"""Mesh-sharded mega-batches through the executor + accumulator (ISSUE 6).

The production multi-chip path end to end, on the 8 virtual CPU devices
conftest provisions (tests/conftest.py): ``device_executor.mesh: true``
upgrades every cached single-chip backend to the SPMD MeshBackend, flush
tails pad to a multiple of the mesh size, per-bucket accumulator buffers
stay SHARDED (one partial-sum row per device, all-reduce only at drain),
the breaker is scoped per MESH (a lost device opens the circuit for every
shape on it), and per-task DRR quotas + per-submission flush child spans
ride along.  Deliberately fast-tier: only the Count shape compiles here;
the heavier mesh parity matrix lives in tests/test_mesh.py (device tier).
"""

import asyncio
import json
import threading
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from janus_tpu.core import faults
from janus_tpu.core.faults import FaultSpec
from janus_tpu.executor import (
    AccumulatorConfig,
    CircuitOpenError,
    DeviceAccumulatorStore,
    DeviceExecutor,
    ExecutorConfig,
    ResidentRef,
    reset_global_executor,
)
from janus_tpu.utils.test_util import det_rng
from janus_tpu.vdaf.backend import MeshBackend, OracleBackend, TpuBackend
from janus_tpu.vdaf.instances import prio3_count


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    yield
    faults.clear()
    reset_global_executor()


def _run(coro, timeout=120.0):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


def _mesh_devices():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest must provision 8 virtual CPU devices"
    return devs[:8]


@pytest.fixture(scope="module")
def mesh_backend():
    return MeshBackend(prio3_count(), devices=_mesh_devices())


def _count_reports(vdaf, n, seed):
    rng = det_rng(seed)
    rows = []
    for i in range(n):
        nonce = rng(vdaf.NONCE_SIZE)
        ps, shares = vdaf.shard(i % 2, nonce, rng(vdaf.RAND_SIZE))
        rows.append((nonce, ps, shares[0]))
    return rows


# -- meshify: the executor upgrade path --------------------------------------


def test_executor_mesh_flag_upgrades_cached_tpu_backends():
    """``device_executor.mesh: true``: backend_for wraps an exact-type
    TpuBackend into MeshBackend over the local mesh before caching; the
    cache returns the SAME upgraded instance to every later caller."""
    vdaf = prio3_count()
    ex = DeviceExecutor(ExecutorConfig(mesh=True))
    b = ex.backend_for(("shape",), lambda: TpuBackend(vdaf))
    assert isinstance(b, MeshBackend)
    assert len(b.mesh.devices) == len(jax.local_devices())
    assert ex.backend_for(("shape",), lambda: TpuBackend(vdaf)) is b
    ex.shutdown()


def test_meshify_passes_through_non_tpu_backends(mesh_backend):
    """Oracle (no SPMD launch) and already-mesh backends are untouched."""
    oracle = OracleBackend(prio3_count())
    assert DeviceExecutor._meshify(oracle) is oracle
    assert DeviceExecutor._meshify(mesh_backend) is mesh_backend


def test_mesh_pad_alignment_multiple_of_mesh_size(mesh_backend):
    """Flush tails pad to a MULTIPLE of the mesh size (so planar_eligible's
    per-shard tiling holds), on top of the pow2 bucketing; explicitly
    requested pads (warmup) are re-aligned too."""
    n = len(mesh_backend.mesh.devices)
    assert n == 8
    for B in (1, 3, 8, 11, 100):
        pad = mesh_backend._pad_to(B)
        assert pad % n == 0 and pad >= B
    assert mesh_backend._align_pad(9) == 16
    vdaf = mesh_backend.vdaf
    staged = mesh_backend.stage_prep_init_multi(
        0, [(b"\x2a" * 16, _count_reports(vdaf, 3, "pad"))], pad_to=9
    )
    assert staged.pad_to % n == 0


# -- sharded submit: parity with the oracle, uneven tails --------------------


def test_mesh_executor_submit_uneven_tail_parity_vs_oracle(mesh_backend):
    """Two tasks coalesce into one sharded mega-batch with B=11 (11 % 8
    != 0: the padded tail crosses shards unevenly) — results byte-equal
    the oracle's, per task."""
    vdaf = mesh_backend.vdaf
    ex = DeviceExecutor(ExecutorConfig(flush_window_s=0.05, flush_max_rows=4096))
    vk_a, vk_b = b"\x0a" * 16, b"\x0b" * 16
    rows_a = _count_reports(vdaf, 7, "tail-a")
    rows_b = _count_reports(vdaf, 4, "tail-b")

    async def go():
        return await asyncio.gather(
            ex.submit(
                ("count",), "prep_init", (vk_a, rows_a),
                backend=mesh_backend, task_ident=b"A",
            ),
            ex.submit(
                ("count",), "prep_init", (vk_b, rows_b),
                backend=mesh_backend, task_ident=b"B",
            ),
        )

    got_a, got_b = _run(go())
    ex.shutdown()
    oracle = OracleBackend(vdaf)
    for got, vk, rows in ((got_a, vk_a, rows_a), (got_b, vk_b, rows_b)):
        want = oracle.prep_init_batch(vk, 0, rows)
        assert len(got) == len(want)
        for (gs, gsh), (ws, wsh) in zip(got, want):
            assert gs.out_share == ws.out_share
            assert gsh.verifiers_share == wsh.verifiers_share


# -- sharded device-resident accumulation ------------------------------------


def test_mesh_resident_flush_masked_accumulate_bit_exact_zero_readback(
    mesh_backend,
):
    """The ISSUE 6 accumulator contract on the mesh: the retained flush
    matrix stays SHARDED, masked accumulate_rows psums per shard with no
    collective, the ONE cross-chip reduction happens at drain — bit-exact
    vs the oracle for a masked subset of an uneven (11-row) flush, with
    ``outshare_readback_rows`` still 0 and the buffer budget accounting
    one partial-sum row per device."""
    vdaf = mesh_backend.vdaf
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True))
    ex = DeviceExecutor(ExecutorConfig(flush_window_s=0.02, flush_max_rows=4096))
    ex.accumulator = store
    vk = b"\x2a" * 16
    reports = _count_reports(vdaf, 11, "mesh-resident")
    mesh_backend.outshare_readback_rows = 0

    async def go():
        return await ex.submit(
            ("count",), "prep_init", (vk, reports),
            backend=mesh_backend, retain_out_shares=True,
        )

    out = _run(go())
    assert mesh_backend.outshare_readback_rows == 0
    refs = [state.out_share for state, _ in out]
    assert all(isinstance(r, ResidentRef) for r in refs)

    # masked commit: only every other row lands in the sharded buffer
    keep = [i for i in range(len(refs)) if i % 2 == 0]
    drop = [i for i in range(len(refs)) if i % 2 == 1]
    store.commit_rows(
        ("bucket",),
        mesh_backend,
        [refs[i] for i in keep],
        job_token=b"job",
        report_ids=[reports[i][0] for i in keep],
    )
    # the sharded buffer carries one (OUT, n) partial row PER DEVICE
    n_dev = len(mesh_backend.mesh.devices)
    assert mesh_backend.accum_buffer_rows == n_dev
    expect_buf = n_dev * vdaf.flp.OUTPUT_LEN * mesh_backend.bp.jf.n * 4
    assert store.stats()["resident_bytes"] >= expect_buf
    store.release_refs([refs[i] for i in drop])

    vector, rids = store.drain(("bucket",), vdaf.flp.field)
    ex.shutdown()
    assert mesh_backend.outshare_readback_rows == 0
    want = vdaf.aggregate(
        [
            state.out_share
            for i, (state, _) in enumerate(
                OracleBackend(vdaf).prep_init_batch(vk, 0, reports)
            )
            if i in set(keep)
        ]
    )
    assert vector == want, "sharded masked accumulation must match the oracle"
    assert rids == {reports[i][0] for i in keep}
    assert store.stats()["flushes_resident"] == 0, "flush must free after use"


def test_mesh_drain_after_device_loss_replays_journal_exactly_once(
    mesh_backend, monkeypatch
):
    """Regression: a device lost AFTER rows were committed into a sharded
    buffer (drain's all-reduce fails) poisons the bucket; discard returns
    the journal EXACTLY ONCE so the oracle replay can re-derive exactly
    the committed reports — never zero times (drop) and never twice
    (double count)."""
    vdaf = mesh_backend.vdaf
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True))
    ex = DeviceExecutor(ExecutorConfig(flush_window_s=0.02, flush_max_rows=4096))
    ex.accumulator = store
    vk = b"\x2a" * 16
    reports = _count_reports(vdaf, 5, "mesh-lost")

    async def go():
        return await ex.submit(
            ("count",), "prep_init", (vk, reports),
            backend=mesh_backend, retain_out_shares=True,
        )

    out = _run(go())
    refs = [state.out_share for state, _ in out]
    rids = [r[0] for r in reports]
    store.commit_rows(
        ("bucket",), mesh_backend, refs, job_token=b"job", report_ids=rids
    )

    def lost(buffer):
        raise RuntimeError("mesh device lost mid-drain")

    monkeypatch.setattr(mesh_backend, "read_accum_buffer", lost)
    from janus_tpu.executor.accumulator import AccumulatorUnavailable

    with pytest.raises(AccumulatorUnavailable):
        store.drain(("bucket",), vdaf.flp.field)

    journal = store.discard(("bucket",))
    assert journal == [(b"job", frozenset(rids))]
    assert store.discard(("bucket",)) == [], "journal must surface exactly once"
    assert store.drain(("bucket",), vdaf.flp.field) is None
    monkeypatch.undo()

    # the replay target: the oracle re-derives exactly the journaled rows
    replay_rids = set().union(*(ids for _job, ids in journal))
    assert replay_rids == set(rids)
    ex.shutdown()


# -- per-mesh breaker ---------------------------------------------------------


class _LostMeshBackend:
    """Stage/launch double that looks mesh-backed (``.mesh.devices``) and
    fires the real ``backend.device_lost`` point on launch."""

    class _V:
        pass

    def __init__(self, devices):
        self.vdaf = self._V()
        self.mesh = SimpleNamespace(devices=np.array(devices, dtype=object))
        self.launches = 0

    def stage_prep_init_multi(self, agg_id, requests, pad_to=None):
        rows = sum(len(r) for _, r in requests)
        return SimpleNamespace(agg_id=agg_id, placed=None, pad_to=rows, rows=rows)

    def launch_prep_init_multi(self, staged, requests):
        self.launches += 1
        faults.fire("backend.device_lost")
        return [[("ok", i) for i in range(len(r))] for _, r in requests]


def test_device_lost_opens_one_breaker_for_every_shape_on_the_mesh():
    """Breaker scope is the MESH, not the shape and not the process: after
    device-lost failures on shape A, shape B (same device set, never
    launched) fails fast with CircuitOpenError — its jobs go straight to
    the oracle — and exactly ONE mesh-labeled breaker exists."""
    devices = ["d0", "d1", "d2", "d3"]
    backend_a = _LostMeshBackend(devices)
    backend_b = _LostMeshBackend(devices)
    ex = DeviceExecutor(
        ExecutorConfig(
            flush_window_s=0.005,
            flush_max_rows=10_000,
            breaker_failure_threshold=2,
            breaker_reset_timeout_s=60.0,
        )
    )
    faults.configure([FaultSpec("backend.device_lost", "error", 1.0)], seed=7)

    async def go():
        for _ in range(2):
            with pytest.raises(Exception) as ei:
                await ex.submit(
                    ("shapeA",), "prep_init", (b"k", [0]), backend=backend_a
                )
            assert "device_lost" in str(ei.value)
        with pytest.raises(CircuitOpenError):
            await ex.submit(
                ("shapeB",), "prep_init", (b"k", [0]), backend=backend_b
            )

    _run(go())
    assert backend_b.launches == 0, "shape B must fail fast, not launch"
    assert ex.circuit_open(("shapeA",)) and ex.circuit_open(("shapeB",))
    circuits = ex.circuit_stats()
    assert len(circuits) == 1, circuits
    (label,) = circuits
    assert label.startswith("mesh[4]#"), label
    ex.shutdown()


def test_mesh_breaker_retires_only_when_every_shape_is_idle():
    """A mesh breaker serves many shapes: bucket retirement may only drop
    it once NO shape on the mesh still has a live bucket."""
    devices = ["d0", "d1"]
    backend = _LostMeshBackend(devices)
    ex = DeviceExecutor(
        ExecutorConfig(flush_window_s=0.005, breaker_failure_threshold=2)
    )

    async def go():
        await ex.submit(("shapeA",), "prep_init", (b"k", [0]), backend=backend)
        await ex.submit(("shapeB",), "prep_init", (b"k", [0]), backend=backend)

    _run(go())
    assert len(ex.circuit_stats()) == 1
    # shape A's bucket idles out; B's stays -> the shared breaker survives
    ex._buckets[(("shapeA",), "prep_init", 0, None)].last_activity -= 1000
    ex.retire_idle_buckets(max_idle_s=600)
    assert len(ex.circuit_stats()) == 1, "breaker retired while B is live"
    ex._buckets[(("shapeB",), "prep_init", 0, None)].last_activity -= 1000
    ex.retire_idle_buckets(max_idle_s=600)
    assert ex.circuit_stats() == {}
    ex.shutdown()


# -- per-task fairness within a bucket ----------------------------------------


class _GatedBackend:
    """Launch-gated double logging the submitting task of each flush."""

    class _V:
        pass

    def __init__(self, gate):
        self.vdaf = self._V()
        self.gate = gate
        self.launch_order = []

    def stage_prep_init_multi(self, agg_id, requests, pad_to=None):
        rows = sum(len(r) for _, r in requests)
        if rows == 0:
            return None
        return SimpleNamespace(agg_id=agg_id, placed=None, pad_to=rows, rows=rows)

    def launch_prep_init_multi(self, staged, requests):
        assert self.gate.wait(10), "test launch gate never opened"
        self.launch_order.append(requests[0][0])
        return [
            [("prep", vk, i) for i in range(len(reports))]
            for vk, reports in requests
        ]


def test_per_task_quota_within_bucket_prevents_starvation():
    """ISSUE 6 satellite (carried from PR 3): tasks sharing ONE VDAF shape
    share its bucket but not its quantum.  A hot task floods the bucket
    with ready flushes before a cold task's lands; deadline-earliest alone
    would serve every hot flush first — the per-task deficit must pull the
    cold task's flush ahead of the hot tail."""
    gate = threading.Event()
    backend = _GatedBackend(gate)
    ex = DeviceExecutor(
        ExecutorConfig(flush_window_s=60.0, flush_max_rows=2, fair_quota_rows=4)
    )

    async def go():
        hot = [
            asyncio.ensure_future(
                ex.submit(
                    ("shape",), "prep_init", (b"h%d" % i, [0, 1]),
                    backend=backend, task_ident=b"hot",
                )
            )
            for i in range(4)
        ]
        await asyncio.sleep(0.05)  # four hot size-flushes ready, same bucket
        cold = asyncio.ensure_future(
            ex.submit(
                ("shape",), "prep_init", (b"c0", [0, 1]),
                backend=backend, task_ident=b"cold",
            )
        )
        await asyncio.sleep(0.05)
        gate.set()
        await asyncio.gather(*hot, cold)

    _run(go())
    ex.shutdown()
    order = backend.launch_order
    assert len(order) == 5
    assert order.index(b"c0") < len(order) - 1, (
        f"cold task starved behind the hot task's flushes: {order}"
    )


# -- per-submission flush child spans -----------------------------------------


def test_flush_share_child_spans_carry_each_submitters_trace(tmp_path):
    """ISSUE 6 satellite (carried from PR 5): one mega-batch flush serving
    two jobs emits one ``flush_share`` child span PER SUBMISSION, stamped
    with the SUBMITTER's trace id — a job's merged Perfetto timeline shows
    its share of the flush it rode."""
    from janus_tpu.core.trace import configure_chrome_trace, trace_scope

    gate = threading.Event()
    gate.set()
    backend = _GatedBackend(gate)
    path = tmp_path / "trace.json"
    configure_chrome_trace(str(path))
    try:
        ex = DeviceExecutor(
            ExecutorConfig(flush_window_s=0.05, flush_max_rows=4096)
        )

        async def submit_with_trace(trace_id, vk):
            with trace_scope(trace_id=trace_id, job_id=vk.decode()):
                return await ex.submit(
                    ("shape",), "prep_init", (vk, [0, 1]), backend=backend
                )

        async def go():
            await asyncio.gather(
                submit_with_trace("a" * 32, b"job-a"),
                submit_with_trace("b" * 32, b"job-b"),
            )

        _run(go())
        ex.shutdown()
    finally:
        configure_chrome_trace(None)

    events = []
    for line in path.read_text().splitlines():
        line = line.strip().rstrip(",")
        if line.startswith("{") and line.endswith("}"):
            events.append(json.loads(line))
    shares = [e for e in events if e.get("name") == "flush_share"]
    assert len(shares) == 2, shares
    by_trace = {e["args"]["trace_id"]: e for e in shares}
    assert set(by_trace) == {"a" * 32, "b" * 32}
    for e in shares:
        assert e["args"]["rows"] == 2
        assert e["args"]["flush_rows"] == 4, "one coalesced flush of 4 rows"
        assert e["args"]["job_id"] in ("job-a", "job-b")
    # both jobs coalesced: exactly one launch served both child spans
    assert len(backend.launch_order) == 1


# -- driver path over the mesh ------------------------------------------------


def test_driver_coalesced_prep_on_mesh_matches_oracle():
    """The leader driver's executor routing with ``mesh: true``: the
    factory-built TpuBackend is upgraded before caching and the coalesced
    prepare stays byte-exact vs the oracle."""
    from janus_tpu.aggregator.aggregation_job_driver import (
        AggregationJobDriver,
        DriverConfig,
    )

    reset_global_executor()
    driver = AggregationJobDriver(
        datastore=None,
        session_factory=None,
        config=DriverConfig(
            vdaf_backend="tpu",
            device_executor=ExecutorConfig(
                enabled=True, mesh=True, flush_window_s=0.02
            ),
        ),
    )
    vdaf = prio3_count()
    key = AggregationJobDriver._vdaf_shape_key(vdaf)
    backend = driver._executor.backend_for(key, lambda: TpuBackend(vdaf))
    assert isinstance(backend, MeshBackend)
    vk = b"\x2a" * 16
    reports = _count_reports(vdaf, 6, "driver-mesh")

    out = _run(
        driver._coalesced_prep_init(backend, vk, reports, task_ident=b"t")
    )
    want = OracleBackend(vdaf).prep_init_batch(vk, 0, reports)
    assert len(out) == len(want)
    for (gs, gsh), (ws, wsh) in zip(out, want):
        assert gs.out_share == ws.out_share
        assert gsh.verifiers_share == wsh.verifiers_share
    reset_global_executor()
