"""Fleet control plane suite (ISSUE 16 tentpole).

Layers, smallest to largest:

* Rendezvous-hash units: determinism, order independence, balance, and
  the minimal-reshuffle property that justifies rendezvous over a ring.
* ``fleet_members`` row plumbing: registration/refresh preserving
  ``started_at``, suspect-set JSON round-trip (publish and un-publish),
  role filtering, delete, prune.
* Ownership routing: two in-process ``FleetRouter``s partition the task
  set disjointly and exhaustively, acquisition filtered through
  ``not_owned_task_ids`` leases every job exactly once to its owner,
  a stale owner's tasks MIGRATE to the survivor behind the takeover
  grace window, and a disabled router filters nothing (the
  ``fleet.enabled: false`` bit-for-bit parity claim).
* Fleet-shared suspects (satellite): a SUSPECT advertisement published
  on one member's heartbeat row is honored by the other replica's
  ``suspect_task_ids``, bounded by advertisement staleness, and
  un-published when the advertiser heals.
* Two real ``JobDriver`` instances with fleet-filtered acquirers in one
  process: every job steps exactly once, ON its rendezvous owner.
* ``test_binary_fleet_sigkill_migration_exactly_once`` (slow) — THE
  ACCEPTANCE CASE: two ``aggregation_job_driver`` BINARIES with
  ``fleet.enabled`` share one datastore; /statusz shows disjoint
  ownership (``tasks_owned == 1`` each) and per-replica compile
  isolation (each warms ONLY its owned task's circuit); one replica is
  SIGKILLed and its task migrates to the survivor within the heartbeat
  TTL (+grace), every job finishes on the survivor, and collection is
  exactly-once with exact Prio3 sums; graceful SIGTERM deregisters the
  survivor's member row while the SIGKILLed row stays as prunable debris.
"""

from __future__ import annotations

import asyncio
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))
from test_datastore import make_task, put_job  # noqa: E402

from janus_tpu.core.fleet import (
    FleetRouter,
    configure_fleet,
    fleet_router,
    fleet_shared_suspects,
    rendezvous_owner,
    reset_fleet,
)
from janus_tpu.core.peer_health import origin_of, reset_peer_health
from janus_tpu.core.time import MockClock
from janus_tpu.datastore import AggregationJobState
from janus_tpu.datastore.test_util import EphemeralDatastore
from janus_tpu.messages import Duration, Time

NOW = Time(1_600_000_000)


@pytest.fixture(autouse=True)
def _clean_process_state():
    reset_fleet()
    reset_peer_health()
    yield
    reset_fleet()
    reset_peer_health()


@pytest.fixture()
def eds():
    e = EphemeralDatastore(MockClock(NOW))
    yield e
    e.cleanup()


def _put_tasks(ds, n):
    tasks = [make_task() for _ in range(n)]
    for t in tasks:
        ds.run_tx("put", lambda tx, t=t: tx.put_aggregator_task(t))
    return tasks


# ---------------------------------------------------------------------------
# rendezvous units


class TestRendezvous:
    def test_deterministic_and_order_independent(self):
        members = ["r0", "r1", "r2"]
        for tid in (b"a" * 32, b"b" * 32, bytes(range(32))):
            owner = rendezvous_owner(tid, members)
            assert owner in members
            assert rendezvous_owner(tid, list(reversed(members))) == owner
            assert rendezvous_owner(tid, members[1:] + members[:1]) == owner

    def test_degenerate_member_sets(self):
        assert rendezvous_owner(b"x" * 32, []) is None
        assert rendezvous_owner(b"x" * 32, ["only"]) == "only"

    def test_balance(self):
        """No member may be starved: over many uniform task ids each of 3
        members owns a healthy share (expected ~1/3; assert >= 1/5)."""
        import hashlib

        members = ["r0", "r1", "r2"]
        counts = {m: 0 for m in members}
        for i in range(1500):
            tid = hashlib.sha256(b"task-%d" % i).digest()
            counts[rendezvous_owner(tid, members)] += 1
        assert all(c >= 300 for c in counts.values()), counts

    def test_minimal_reshuffle_on_member_loss(self):
        """The rendezvous property the router leans on: removing a member
        moves ONLY that member's tasks — every surviving assignment is
        untouched (a ring would reshuffle neighbors too)."""
        import hashlib

        members = ["r0", "r1", "r2"]
        tids = [hashlib.sha256(b"t-%d" % i).digest() for i in range(400)]
        before = {tid: rendezvous_owner(tid, members) for tid in tids}
        after = {tid: rendezvous_owner(tid, ["r0", "r1"]) for tid in tids}
        for tid in tids:
            if before[tid] != "r2":
                assert after[tid] == before[tid], "a surviving assignment moved"
            else:
                assert after[tid] in ("r0", "r1")


# ---------------------------------------------------------------------------
# fleet_members rows


class TestMemberRows:
    def test_upsert_registers_then_refreshes_preserving_started_at(self, eds):
        ds, clock = eds.datastore, eds.datastore.clock
        ds.run_tx("reg", lambda tx: tx.upsert_fleet_member("r0", "aggregation"))
        (m0,) = ds.run_tx("get", lambda tx: tx.get_fleet_members())
        assert m0.replica_id == "r0" and m0.role == "aggregation"
        assert m0.started_at.seconds == m0.heartbeat.seconds == NOW.seconds

        clock.advance(Duration(7))
        ds.run_tx("hb", lambda tx: tx.upsert_fleet_member("r0", "aggregation"))
        (m1,) = ds.run_tx("get", lambda tx: tx.get_fleet_members())
        assert m1.heartbeat.seconds == NOW.seconds + 7
        assert m1.started_at.seconds == NOW.seconds, "refresh must keep started_at"
        assert m1.heartbeat_age(clock.now()) == 0

    def test_suspect_peers_roundtrip_and_unpublish(self, eds):
        ds = eds.datastore
        ds.run_tx(
            "pub",
            lambda tx: tx.upsert_fleet_member(
                "r0", "aggregation", ["peer-b:80", "peer-a:80", "peer-b:80"]
            ),
        )
        (m,) = ds.run_tx("get", lambda tx: tx.get_fleet_members())
        assert m.suspect_peers == ("peer-a:80", "peer-b:80")  # sorted, deduped
        assert m.suspect_updated_at is not None

        # healed: publishing the empty set un-pins
        ds.run_tx("heal", lambda tx: tx.upsert_fleet_member("r0", "aggregation", []))
        (m,) = ds.run_tx("get", lambda tx: tx.get_fleet_members())
        assert m.suspect_peers == ()

    def test_role_filter_delete_and_prune(self, eds):
        ds, clock = eds.datastore, eds.datastore.clock
        ds.run_tx("a", lambda tx: tx.upsert_fleet_member("agg-0", "aggregation"))
        ds.run_tx("c", lambda tx: tx.upsert_fleet_member("coll-0", "collection"))
        aggs = ds.run_tx("get", lambda tx: tx.get_fleet_members("aggregation"))
        assert [m.replica_id for m in aggs] == ["agg-0"]
        assert len(ds.run_tx("all", lambda tx: tx.get_fleet_members())) == 2

        assert ds.run_tx("del", lambda tx: tx.delete_fleet_member("coll-0"))
        assert not ds.run_tx("del2", lambda tx: tx.delete_fleet_member("coll-0"))

        clock.advance(Duration(500))
        ds.run_tx("fresh", lambda tx: tx.upsert_fleet_member("agg-1", "aggregation"))
        # agg-0's heartbeat is 500s old: pruned; agg-1 survives
        assert ds.run_tx(
            "prune", lambda tx: tx.prune_fleet_members(Duration(100))
        ) == 1
        left = ds.run_tx("get", lambda tx: tx.get_fleet_members())
        assert [m.replica_id for m in left] == ["agg-1"]


# ---------------------------------------------------------------------------
# ownership routing + acquisition


class TestOwnershipRouting:
    def _routers(self, n=2, **kw):
        return [FleetRouter(f"ipr-{i}", "aggregation", **kw) for i in range(n)]

    def test_two_routers_partition_tasks_disjoint_and_exhaustive(self, eds):
        ds = eds.datastore
        tasks = _put_tasks(ds, 8)
        r0, r1 = self._routers()
        ds.run_tx("hb0", r0.heartbeat)
        ds.run_tx("hb1", r1.heartbeat)

        def views(tx):
            return (
                set(r0.not_owned_task_ids(tx) or []),
                set(r1.not_owned_task_ids(tx) or []),
                [(t, r0.owns(tx, t.task_id.data), r1.owns(tx, t.task_id.data)) for t in tasks],
                r0.filter_owned(tx, tasks),
                r1.filter_owned(tx, tasks),
            )

        ex0, ex1, owns, own0, own1 = ds.run_tx("views", views)
        all_ids = {t.task_id.data for t in tasks}
        # every task excluded by exactly one of the two replicas
        assert ex0 | ex1 == all_ids and ex0 & ex1 == set()
        for t, o0, o1 in owns:
            assert o0 != o1
            assert o0 == (t.task_id.data not in ex0)
        # warmup filter partitions the registry the same way
        assert {t.task_id.data for t in own0} == all_ids - ex0
        assert {t.task_id.data for t in own1} == all_ids - ex1
        assert r0.stats()["tasks_owned"] + r1.stats()["tasks_owned"] == len(tasks)

    def test_acquisition_filtered_to_owner_exactly_once(self, eds):
        ds = eds.datastore
        tasks = _put_tasks(ds, 6)
        jobs = {t.task_id.data: put_job(ds, t) for t in tasks}
        r0, r1 = self._routers()
        ds.run_tx("hb0", r0.heartbeat)
        ds.run_tx("hb1", r1.heartbeat)

        def acquire(tx, router):
            return tx.acquire_incomplete_aggregation_jobs(
                Duration(600), 10, exclude_task_ids=router.not_owned_task_ids(tx)
            )

        leases0 = ds.run_tx("acq0", lambda tx: acquire(tx, r0))
        leases1 = ds.run_tx("acq1", lambda tx: acquire(tx, r1))
        got0 = {bytes(l.leased.task_id.data) for l in leases0}
        got1 = {bytes(l.leased.task_id.data) for l in leases1}
        assert got0 & got1 == set(), "a job leased by a non-owner"
        assert got0 | got1 == set(jobs), "a job no replica could acquire"
        # and the second poll finds nothing left
        assert ds.run_tx("acq0b", lambda tx: acquire(tx, r0)) == []
        assert ds.run_tx("acq1b", lambda tx: acquire(tx, r1)) == []

    def test_migration_behind_takeover_grace(self, eds):
        ds, clock = eds.datastore, eds.datastore.clock
        tasks = _put_tasks(ds, 8)
        r0, r1 = self._routers(heartbeat_ttl_s=10.0, takeover_grace_s=5.0)
        ds.run_tx("hb0", r0.heartbeat)
        ds.run_tx("hb1", r1.heartbeat)
        ex1 = set(ds.run_tx("v", lambda tx: r1.not_owned_task_ids(tx) or []))
        r0_tasks = ex1  # what r1 must absorb when r0 dies
        assert r0_tasks and r1.stats()["migrations_total"] == 0

        # r0 stops heartbeating; r1 keeps going past the TTL
        clock.advance(Duration(11))
        ds.run_tx("hb1b", r1.heartbeat)
        ex_graced = set(ds.run_tx("v2", lambda tx: r1.not_owned_task_ids(tx) or []))
        # migration DETECTED (counter moves) but the grace window still
        # excludes the absorbed tasks from this acquisition round
        assert r1.stats()["migrations_total"] == len(r0_tasks)
        assert ex_graced == r0_tasks

        clock.advance(Duration(6))  # past takeover_grace_s
        assert ds.run_tx("v3", lambda tx: r1.not_owned_task_ids(tx)) is None
        assert r1.stats()["tasks_owned"] == len(tasks)
        # no double counting on later polls
        assert r1.stats()["migrations_total"] == len(r0_tasks)

    def test_deregister_reroutes_without_waiting_for_ttl(self, eds):
        ds = eds.datastore
        _put_tasks(ds, 5)
        r0, r1 = self._routers(takeover_grace_s=0.0)
        ds.run_tx("hb0", r0.heartbeat)
        ds.run_tx("hb1", r1.heartbeat)
        ds.run_tx("v", r1.not_owned_task_ids)
        ds.run_tx("bye", r0.deregister)
        # immediately (no clock advance): r0's row is gone, r1 owns all
        assert ds.run_tx("v2", r1.not_owned_task_ids) is None
        assert r1.stats()["tasks_owned"] == 5

    def test_self_always_live_despite_stale_own_heartbeat(self, eds):
        ds, clock = eds.datastore, eds.datastore.clock
        _put_tasks(ds, 3)
        (r0,) = self._routers(1)
        ds.run_tx("hb", r0.heartbeat)
        clock.advance(Duration(3600))  # own row long stale, never refreshed
        # a wedged local heartbeat must degrade toward too-much-work,
        # never self-eviction: alone in the fleet, r0 still owns everything
        assert ds.run_tx("v", r0.not_owned_task_ids) is None
        assert r0.stats()["tasks_owned"] == 3

    def test_disabled_router_is_bit_for_bit_no_filter(self, eds):
        ds = eds.datastore
        tasks = _put_tasks(ds, 4)
        r = FleetRouter("off-0", "aggregation", enabled=False)
        ds.run_tx("hb", r.heartbeat)  # must write nothing
        assert ds.run_tx("rows", lambda tx: tx.get_fleet_members()) == []
        assert ds.run_tx("v", r.not_owned_task_ids) is None
        assert ds.run_tx("own", lambda tx: r.owns(tx, tasks[0].task_id.data))
        assert ds.run_tx("f", lambda tx: r.filter_owned(tx, tasks)) == tasks
        assert ds.run_tx("s", r.shared_suspects) == set()


# ---------------------------------------------------------------------------
# fleet-shared suspect set (satellite)


class TestSharedSuspects:
    def test_shared_from_other_members_only_and_unpublish(self, eds):
        ds = eds.datastore
        me = FleetRouter("me", "aggregation")
        other = FleetRouter("other", "collection")  # suspects cross roles
        ds.run_tx("hb_me", me.heartbeat)
        ds.run_tx("hb_o", lambda tx: other.heartbeat(tx, ["peer-x:80"]))
        assert ds.run_tx("s", me.shared_suspects) == {"peer-x:80"}
        # an advertisement is never reflected back at its publisher
        assert ds.run_tx("s_o", other.shared_suspects) == set()
        # heal: the advertiser republishes the empty set
        ds.run_tx("heal", other.heartbeat)
        assert ds.run_tx("s2", me.shared_suspects) == set()

    def test_dead_advertiser_and_stale_advertisement_ignored(self, eds):
        ds, clock = eds.datastore, eds.datastore.clock
        other = FleetRouter("other", "aggregation", heartbeat_ttl_s=10.0)
        ds.run_tx("hb_o", lambda tx: other.heartbeat(tx, ["peer-x:80"]))

        # consumer with a staleness bound TIGHTER than its liveness ttl:
        # the advertiser's row is still "live" but its advertisement has
        # aged out — a dead-ish advertiser must not suspect-pin a healthy
        # peer beyond the bound
        me = FleetRouter(
            "me", "aggregation", heartbeat_ttl_s=100.0, suspect_staleness_s=5.0
        )
        ds.run_tx("hb_me", me.heartbeat)
        assert ds.run_tx("s0", me.shared_suspects) == {"peer-x:80"}
        clock.advance(Duration(8))
        assert ds.run_tx("s1", me.shared_suspects) == set(), "stale advert honored"

        # and a dead advertiser (heartbeat past the ttl) is ignored even
        # with a generous staleness bound
        me2 = FleetRouter(
            "me2", "aggregation", heartbeat_ttl_s=3.0, suspect_staleness_s=3600.0
        )
        assert ds.run_tx("s2", me2.shared_suspects) == set()

    def test_suspect_task_ids_honors_fleet_advertisements(self, eds):
        """The consumption seam: a peer advertised SUSPECT by ANOTHER
        member excludes that peer's tasks from this replica's acquisition
        even though the local tracker never saw a failure."""
        from janus_tpu.aggregator.job_driver import (
            acquisition_exclusions,
            suspect_task_ids,
        )

        ds = eds.datastore
        tasks = _put_tasks(ds, 3)
        peer_origin = origin_of(tasks[0].peer_aggregator_endpoint)

        # fleet off: no shared set, no local suspects -> no filter at all
        assert ds.run_tx("none", lambda tx: suspect_task_ids(tx)) is None
        assert ds.run_tx("none2", lambda tx: acquisition_exclusions(tx)) is None

        me = configure_fleet("me", "aggregation")
        other = FleetRouter("other", "aggregation")
        ds.run_tx("hb_me", me.heartbeat)
        ds.run_tx("hb_o", lambda tx: other.heartbeat(tx, [peer_origin]))
        assert ds.run_tx("fss", fleet_shared_suspects) == {peer_origin}
        # every task points at the same peer endpoint (make_task default),
        # so the advertisement excludes them all
        sus = ds.run_tx("sus", lambda tx: suspect_task_ids(tx))
        assert set(sus) == {t.task_id.data for t in tasks}
        # acquisition_exclusions unions the same ids (owned or not, a
        # suspect peer's task never acquires here)
        excl = ds.run_tx("excl", lambda tx: acquisition_exclusions(tx))
        assert set(excl) >= {t.task_id.data for t in tasks}

    def test_statusz_fleet_section(self, eds):
        from janus_tpu.core.statusz import runtime_status

        assert runtime_status()["fleet"] == {"enabled": False}
        me = configure_fleet("statusz-me", "aggregation")
        eds.datastore.run_tx("hb", me.heartbeat)
        doc = runtime_status()["fleet"]
        assert doc["enabled"] is True
        assert doc["replica_id"] == "statusz-me"
        assert doc["role"] == "aggregation"
        assert [m["replica_id"] for m in doc["members"]] == ["statusz-me"]
        assert doc["members"][0]["live"] is True
        assert fleet_router() is me


# ---------------------------------------------------------------------------
# two real JobDrivers, one process, fleet-routed acquisition


class TestInProcessTwoReplicaDrivers:
    def test_jobs_step_exactly_once_on_their_owner(self, eds):
        from janus_tpu.aggregator.job_driver import JobDriver

        ds = eds.datastore
        tasks = _put_tasks(ds, 6)
        for t in tasks:
            put_job(ds, t)
        routers = {n: FleetRouter(n, "aggregation") for n in ("drv-a", "drv-b")}
        stepped = {n: [] for n in routers}
        # register BOTH members before any driver polls: without this the
        # first poller's live set is just itself and it (safely, but
        # nondeterministically) absorbs the other's tasks for one round
        for r in routers.values():
            ds.run_tx("prereg", r.heartbeat)

        def make_acquirer(router):
            async def acquire(duration, limit):
                def q(tx):
                    router.heartbeat(tx)
                    return tx.acquire_incomplete_aggregation_jobs(
                        duration, limit,
                        exclude_task_ids=router.not_owned_task_ids(tx),
                    )

                return await ds.run_tx_async("acquire", q)

            return acquire

        def make_stepper(name):
            async def step(lease):
                def fin(tx):
                    job = tx.get_aggregation_job(
                        lease.leased.task_id, lease.leased.aggregation_job_id
                    )
                    tx.update_aggregation_job(
                        job.with_state(AggregationJobState.FINISHED)
                    )
                    tx.release_aggregation_job(lease)

                await ds.run_tx_async("fin", fin)
                stepped[name].append(bytes(lease.leased.task_id.data))

            return step

        drivers = [
            JobDriver(
                ds.clock,
                make_acquirer(routers[n]),
                make_stepper(n),
                job_discovery_interval=0.02,
                job_type="aggregation",
            )
            for n in routers
        ]

        def unfinished(tx):
            return sum(
                1
                for t in tasks
                for j in tx.get_aggregation_jobs_for_task(t.task_id)
                if j.state == AggregationJobState.IN_PROGRESS
            )

        async def flow():
            stop = asyncio.Event()
            runs = [asyncio.ensure_future(d.run(stop)) for d in drivers]
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if await ds.run_tx_async("cnt", unfinished) == 0:
                    break
                await asyncio.sleep(0.02)
            stop.set()
            await asyncio.gather(*runs)
            return await ds.run_tx_async("cnt", unfinished)

        loop = asyncio.new_event_loop()
        try:
            remaining = loop.run_until_complete(asyncio.wait_for(flow(), 60))
        finally:
            loop.close()
        assert remaining == 0, "jobs never converged under fleet routing"

        everything = stepped["drv-a"] + stepped["drv-b"]
        assert len(everything) == len(tasks), "a job stepped twice or dropped"
        assert len(set(everything)) == len(tasks)
        members = sorted(routers)
        for name, ids in stepped.items():
            for tid in ids:
                assert rendezvous_owner(tid, members) == name, (
                    "a job stepped on a replica that does not own its task"
                )


# ---------------------------------------------------------------------------
# THE ACCEPTANCE CASE: binary-level fleet, SIGKILL migration, exactly-once


@pytest.mark.slow
def test_binary_fleet_sigkill_migration_exactly_once(tmp_path):
    """Two ``aggregation_job_driver`` BINARIES with ``fleet.enabled``
    share one datastore.  Proves, end to end: (1) disjoint ownership —
    each replica's /statusz fleet section reports ``tasks_owned == 1``;
    (2) per-replica compile isolation — each replica's warmup compiles
    ONLY its owned task's circuit (Count on r0, Sum on r1), observable
    via the /statusz compile ledger; (3) SIGKILLing r0 migrates its task
    to the survivor within the heartbeat TTL (+takeover grace), the
    survivor's migration counter moves, every job finishes on the
    survivor, and collection in this process is exactly-once with exact
    Prio3 count/sum results; (4) graceful SIGTERM deregisters the
    survivor's member row while the SIGKILLed replica's row stays behind
    as prunable debris."""
    import base64
    import json
    import signal
    import sqlite3  # noqa: F401  (via _sql)
    import subprocess
    import urllib.request

    from test_crash_chaos import (
        _BOOT,
        _free_port,
        _metric_total,
        _scrape,
        _sql,
        _wait_http,
        REPO,
        TIME_PRECISION,
    )

    import aiohttp

    from janus_tpu.aggregator import AggregationJobCreator, CreatorConfig
    from janus_tpu.aggregator.collection_job_driver import (
        CollectionDriverConfig,
        CollectionJobDriver,
    )
    from janus_tpu.aggregator.report_writer import ReportWriteBatcher
    from janus_tpu.client import prepare_report
    from janus_tpu.core.auth_tokens import AuthenticationToken
    from janus_tpu.core.hpke import HpkeApplicationInfo, HpkeKeypair, Label, open_
    from janus_tpu.core.time import RealClock
    from janus_tpu.datastore import (
        AggregatorTask,
        CollectionJob,
        CollectionJobState,
        Crypter,
        Datastore,
        LeaderStoredReport,
        TaskQueryType,
        generate_key,
    )
    from janus_tpu.messages import (
        AggregateShareAad,
        BatchSelector,
        CollectionJobId,
        InputShareAad,
        Interval,
        PlaintextInputShare,
        Query,
        Role,
        TaskId,
    )

    REPLICAS = ("fleet-r0", "fleet-r1")
    HB_INTERVAL, HB_TTL, GRACE = 0.3, 2.0, 0.3

    key = generate_key()
    leader_db = str(tmp_path / "leader.sqlite3")
    helper_db = str(tmp_path / "helper.sqlite3")
    helper_port, helper_health = _free_port(), _free_port()
    driver_health = [_free_port(), _free_port()]

    clock = RealClock()
    leader_ds = Datastore(leader_db, Crypter([key]), clock)
    helper_ds = Datastore(helper_db, Crypter([key]), clock)
    agg_token = AuthenticationToken.new_bearer("agg-token-fleet")
    collector_keys = HpkeKeypair.generate(9)
    now = clock.now()
    report_time = Time(now.seconds - now.seconds % TIME_PRECISION.seconds)
    interval = Interval(report_time, TIME_PRECISION)

    def pick_task_id(owner):
        """A task id that rendezvous-routes to ``owner`` — makes the
        ownership split (and the compile-isolation assertion) exact."""
        while True:
            tid = TaskId.random()
            if rendezvous_owner(tid.data, list(REPLICAS)) == owner:
                return tid

    # one distinctly-shaped VDAF per replica: the compile ledgers must
    # stay disjoint BY CIRCUIT, not just by digest
    plans = {
        0: ({"type": "Prio3Count"}, "Count", "fleet-r0", [1, 0, 1, 1]),
        1: ({"type": "Prio3Sum", "bits": 4}, "Sum", "fleet-r1", [3, 5, 2, 7]),
    }
    tasks, keypairs = [], []
    for t, (vdaf, _circuit, owner, _ms) in plans.items():
        task_id = pick_task_id(owner)
        common = dict(
            task_id=task_id,
            query_type=TaskQueryType.time_interval(),
            vdaf=vdaf,
            vdaf_verify_key=bytes([0x60 + t]) * 16,
            min_batch_size=3,
            time_precision=TIME_PRECISION,
            collector_hpke_config=collector_keys.config,
        )
        leader_kp, helper_kp = HpkeKeypair.generate(1), HpkeKeypair.generate(2)
        leader_task = AggregatorTask(
            peer_aggregator_endpoint=f"http://127.0.0.1:{helper_port}/",
            role=Role.LEADER,
            aggregator_auth_token=agg_token,
            hpke_keys=[leader_kp],
            **common,
        )
        helper_task = AggregatorTask(
            peer_aggregator_endpoint="http://127.0.0.1:1/",  # never called
            role=Role.HELPER,
            aggregator_auth_token_hash=agg_token.hash(),
            hpke_keys=[helper_kp],
            **common,
        )
        leader_ds.run_tx("putl", lambda tx, lt=leader_task: tx.put_aggregator_task(lt))
        helper_ds.run_tx("puth", lambda tx, ht=helper_task: tx.put_aggregator_task(ht))
        tasks.append((task_id, leader_task))
        keypairs.append((leader_kp, helper_kp))

    def seed_report(t, m):
        task_id, leader_task = tasks[t]
        leader_kp, helper_kp = keypairs[t]
        vdaf = leader_task.vdaf_instance()
        report = prepare_report(
            vdaf,
            task_id,
            leader_kp.config,
            helper_kp.config,
            TIME_PRECISION,
            m,
            time=report_time,
        )
        aad = InputShareAad(
            task_id, report.metadata, report.public_share
        ).get_encoded()
        info = HpkeApplicationInfo.new(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER)
        plain = PlaintextInputShare.get_decoded(
            open_(leader_kp, info, report.leader_encrypted_input_share, aad)
        )
        stored = LeaderStoredReport(
            task_id=task_id,
            metadata=report.metadata,
            public_share=report.public_share,
            leader_extensions=[],
            leader_input_share=plain.payload,
            helper_encrypted_input_share=report.helper_encrypted_input_share,
        )
        asyncio.run(
            ReportWriteBatcher(leader_ds, max_batch_size=1).write_report(stored)
        )

    for t, (_v, _c, _o, ms) in plans.items():
        for m in ms:
            seed_report(t, m)

    # pre-register BOTH member rows, future-dated past the binaries' slow
    # boot (jax import): the first driver's warmup must already see a
    # 2-member fleet or it would warm (and own) everything for one round.
    # Each driver's synchronous startup registration overwrites its own
    # row with a real-clock heartbeat, so the skew evaporates on boot.
    future = Datastore(
        leader_db, Crypter([key]), MockClock(Time(clock.now().seconds + 600))
    )

    def prereg(tx):
        for r in REPLICAS:
            tx.upsert_fleet_member(r, "aggregation")

    future.run_tx("prereg", prereg)
    future.close()

    def driver_yaml(i):
        return f"""
common:
  database: {{path: {leader_db}}}
  health_check_listen_address: 127.0.0.1:{driver_health[i]}
  status_sample_interval_s: 0.5
  fleet:
    enabled: true
    replica_id: {REPLICAS[i]}
    heartbeat_interval_s: {HB_INTERVAL}
    heartbeat_ttl_s: {HB_TTL}
    takeover_grace_s: {GRACE}
job_driver:
  job_discovery_interval_s: 0.2
  max_concurrent_job_workers: 4
  worker_lease_duration_s: 5
  worker_lease_clock_skew_allowance_s: 1
  maximum_attempts_before_failure: 100000
  max_step_attempts: 100000
  lease_reap_interval_s: 0.1
vdaf_backend: tpu
device_executor:
  enabled: true
  flush_window_ms: 20
  flush_max_rows: 4096
  breaker_failure_threshold: 0
  warmup_rows: 8
"""

    helper_yaml = f"""
common:
  database: {{path: {helper_db}}}
  health_check_listen_address: 127.0.0.1:{helper_health}
listen_address: 127.0.0.1:{helper_port}
"""
    cfg_paths = []
    for i in range(2):
        p = tmp_path / f"driver{i}.yaml"
        p.write_text(driver_yaml(i))
        cfg_paths.append(p)
    helper_cfg = tmp_path / "helper.yaml"
    helper_cfg.write_text(helper_yaml)

    env = dict(os.environ)
    env["DATASTORE_KEYS"] = base64.urlsafe_b64encode(key).decode().rstrip("=")
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    def spawn(binary, cfg, tag):
        log = open(tmp_path / f"{tag}.log", "wb")
        return subprocess.Popen(
            [sys.executable, "-c", _BOOT, binary, "--config-file", str(cfg)],
            env=env,
            cwd=str(REPO),
            stdout=log,
            stderr=subprocess.STDOUT,
        )

    def statusz(port):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statusz", timeout=5
        ) as r:
            return json.loads(r.read().decode())

    def wait_statusz(port, pred, what, deadline_s=120):
        deadline = time.monotonic() + deadline_s
        doc = None
        while time.monotonic() < deadline:
            try:
                doc = statusz(port)
                if pred(doc):
                    return doc
            except Exception:
                pass
            time.sleep(0.2)
        raise TimeoutError(f"{what}: last={doc and doc.get('fleet')}")

    procs = [None, None, None]  # driver0, driver1, helper
    try:
        procs[2] = spawn("aggregator", helper_cfg, "helper")
        _wait_http(f"http://127.0.0.1:{helper_health}/healthz", 120)
        for i in range(2):
            procs[i] = spawn("aggregation_job_driver", cfg_paths[i], f"driver{i}")
        for i in range(2):
            _wait_http(f"http://127.0.0.1:{driver_health[i]}/healthz", 120)

        # -- phase 1: disjoint ownership + compile isolation ---------------
        docs = [
            wait_statusz(
                driver_health[i],
                lambda d: d["fleet"].get("tasks_owned") == 1
                and d["executor"]["compile"],
                f"replica {i} never settled on 1 owned task + a warm ledger",
            )
            for i in range(2)
        ]
        for i, doc in enumerate(docs):
            fleet = doc["fleet"]
            assert fleet["enabled"] is True
            assert fleet["replica_id"] == REPLICAS[i]
            assert fleet["migrations_total"] == 0
            live = [m["replica_id"] for m in fleet["members"] if m["live"]]
            assert sorted(live) == list(REPLICAS), fleet["members"]
            # compile isolation: ONLY the owned task's circuit was warmed
            circuits = {lbl.split("#")[0] for lbl in doc["executor"]["compile"]}
            assert circuits == {plans[i][1]}, (i, circuits)

        # -- phase 2: SIGKILL r0, then create the jobs ---------------------
        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait(timeout=30)
        t_kill = time.monotonic()

        creator = AggregationJobCreator(
            leader_ds,
            CreatorConfig(min_aggregation_job_size=1, max_aggregation_job_size=4),
        )
        n_jobs = asyncio.run(creator.run_once())
        assert n_jobs >= 2, n_jobs

        # migration within the TTL: the survivor's ownership flips once
        # r0's heartbeat ages past HB_TTL and the takeover grace passes.
        # Budget = TTL + grace + heartbeat/discovery/poll cadences, padded
        # generously for CI scheduling jitter — but still the same order
        # of magnitude as the TTL itself.
        doc = wait_statusz(
            driver_health[1],
            lambda d: d["fleet"].get("tasks_owned") == 2,
            "survivor never absorbed the dead replica's task",
            deadline_s=60,
        )
        migrated_after = time.monotonic() - t_kill
        budget = HB_TTL + GRACE + 3 * (HB_INTERVAL + 0.2 + 0.2) + 5.0
        assert migrated_after <= budget, (migrated_after, budget)
        assert doc["fleet"]["migrations_total"] >= 1, doc["fleet"]
        scraped = _scrape(driver_health[1])
        assert _metric_total(scraped, "janus_fleet_migrations_total") >= 1
        # the survivor's live same-role member count is now just itself
        assert _metric_total(scraped, "janus_fleet_members") == 1

        # -- every job finishes on the survivor ----------------------------
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            rows = dict(
                _sql(
                    leader_db,
                    "SELECT state, COUNT(*) FROM aggregation_jobs GROUP BY state",
                )
            )
            if rows.get("InProgress", 0) == 0:
                break
            time.sleep(0.5)
        assert rows.get("InProgress", 0) == 0, rows
        assert rows.get("Finished", 0) == n_jobs, (rows, n_jobs)

        # -- graceful SIGTERM deregisters the survivor's row ---------------
        # (the SIGKILLed replica's debris row is reaped by the survivor's
        # opportunistic prune after PRUNE_TTLS heartbeat TTLs, so by now
        # it may be present or already gone — but never the survivor's)
        procs[1].send_signal(signal.SIGTERM)
        assert procs[1].wait(timeout=120) == 0, "survivor SIGTERM must be clean"
        members = _sql(leader_db, "SELECT replica_id FROM fleet_members")
        assert ("fleet-r1",) not in members, members
        assert members in ([], [("fleet-r0",)]), members

        # -- collection in THIS process: exactly-once, exact sums ----------
        async def collect():
            results = {}
            driver = CollectionJobDriver(
                leader_ds,
                aiohttp.ClientSession,
                CollectionDriverConfig(retry_initial_delay=Duration(1)),
            )
            try:
                for t, (task_id, _lt) in enumerate(tasks):
                    job = CollectionJob(
                        task_id=task_id,
                        collection_job_id=CollectionJobId.random(),
                        query=Query.new_time_interval(interval),
                        aggregation_parameter=b"",
                        batch_identifier=interval.get_encoded(),
                        state=CollectionJobState.START,
                    )
                    leader_ds.run_tx(
                        "putc", lambda tx, j=job: tx.put_collection_job(j)
                    )
                    deadline = time.monotonic() + 120
                    while time.monotonic() < deadline:
                        leases = await leader_ds.run_tx_async(
                            "acqc",
                            lambda tx: tx.acquire_incomplete_collection_jobs(
                                Duration(600), 4
                            ),
                        )
                        for lease in leases:
                            await driver.step_collection_job(lease)
                        got = leader_ds.run_tx(
                            "getc",
                            lambda tx, j=job: tx.get_collection_job(
                                j.task_id, j.collection_job_id, "TimeInterval"
                            ),
                        )
                        if got.state == CollectionJobState.FINISHED:
                            results[t] = got
                            break
                        await asyncio.sleep(0.3)
                    else:
                        raise TimeoutError(f"collection for task {t} never finished")
            finally:
                await driver.close()
            return results

        results = asyncio.run(collect())
        for t, (task_id, leader_task) in enumerate(tasks):
            got = results[t]
            measurements = plans[t][3]
            vdaf = leader_task.vdaf_instance()
            field = vdaf.field_for_agg_param(vdaf.decode_agg_param(b""))
            leader_share = field.decode_vec(got.leader_aggregate_share)
            aad = AggregateShareAad(
                task_id, b"", BatchSelector.new_time_interval(interval)
            ).get_encoded()
            info = HpkeApplicationInfo.new(
                Label.AGGREGATE_SHARE, Role.HELPER, Role.COLLECTOR
            )
            helper_share = field.decode_vec(
                open_(collector_keys, info, got.helper_aggregate_share, aad)
            )
            result = vdaf.unshard([leader_share, helper_share], got.report_count)
            # exactly-once: Prio3 aggregation is exact, so report_count and
            # sum equality ARE the no-double/no-drop proof across the
            # SIGKILL + migration
            assert got.report_count == len(measurements), (t, got.report_count)
            assert result == sum(measurements), (t, result, measurements)
    finally:
        for p in procs:
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=30)
        leader_ds.close()
        helper_ds.close()
