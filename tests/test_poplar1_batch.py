"""Batched Poplar1 prep vs the scalar oracle: byte parity + e2e.

The batched path (janus_tpu/ops/poplar1_batch.py) walks the IDPF tree with
bulk AES over the whole batch and runs the sketch inner products as JField
limb math; every output must equal Poplar1.prep_init exactly
(reference: the accelerated dispatch covers Poplar1 the same as Prio3,
core/src/vdaf.rs:96).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from janus_tpu.ops.poplar1_batch import BatchedPoplar1
from janus_tpu.vdaf.poplar1 import Poplar1, Poplar1AggregationParam


def _shard(vdaf, alpha, rng):
    nonce = rng.bytes(16)
    rand = rng.bytes(vdaf.RAND_SIZE)
    public_share, input_shares = vdaf.shard(alpha, nonce, rand)
    return nonce, public_share, input_shares


@pytest.mark.parametrize("level,prefixes", [
    (0, (0, 1)),
    (2, (0, 3, 5, 6)),
    (7, (0b00000001, 0b10110011, 0b11111111)),  # leaf level: Field255
])
def test_prep_init_batch_matches_oracle(level, prefixes):
    vdaf = Poplar1(bits=8)
    agg_param = Poplar1AggregationParam(level=level, prefixes=tuple(prefixes))
    rng = np.random.default_rng(3)
    rngb = __import__("random").Random(7)

    class R:
        def bytes(self, n):  # deterministic bytes source
            return rngb.randbytes(n)

    r = R()
    vk = b"\x11" * 16
    reports = []
    for i in range(6):
        nonce, pub, shares = _shard(vdaf, i % 256, r)
        reports.append((nonce, pub, shares))

    bp = BatchedPoplar1(vdaf)
    for agg_id in (0, 1):
        rows = [(n, p, s[agg_id]) for (n, p, s) in reports]
        got = bp.prep_init_batch(vk, agg_id, agg_param, rows)
        for (nonce, pub, shares), (st_b, sh_b) in zip(reports, got):
            st_o, sh_o = vdaf.prep_init(
                vk, agg_id, agg_param, nonce, pub, shares[agg_id]
            )
            assert sh_b.encode() == sh_o.encode(), (agg_id, level)
            assert st_b.y_flat == st_o.y_flat
            assert (st_b.a, st_b.b, st_b.c, st_b.zs_share) == (
                st_o.a, st_o.b, st_o.c, st_o.zs_share,
            )


def test_batched_two_party_e2e_decides():
    """Both aggregators prep through the batched path; the combined sketch
    verifies and the aggregate recovers per-prefix counts."""
    vdaf = Poplar1(bits=4)
    agg_param = Poplar1AggregationParam(level=3, prefixes=(0b0010, 0b1011, 0b1111))
    rngb = __import__("random").Random(11)

    class R:
        def bytes(self, n):
            return rngb.randbytes(n)

    r = R()
    vk = b"\x22" * 16
    alphas = [0b0010, 0b1011, 0b0010, 0b0000]
    reports = [_shard(vdaf, a, r) for a in alphas]
    bp = BatchedPoplar1(vdaf)
    outs = {a: bp.prep_init_batch(vk, a, agg_param, [(n, p, s[a]) for (n, p, s) in reports]) for a in (0, 1)}
    field = vdaf.field_for_agg_param(agg_param)
    out_shares = {0: [], 1: []}
    for i in range(len(reports)):
        st0, sh0 = outs[0][i]
        st1, sh1 = outs[1][i]
        z, zs = vdaf.sketch_combine(agg_param, [tuple(sh0.values), tuple(sh1.values)])
        s0 = vdaf.sketch_decide_share(st0, z, zs)
        s1 = vdaf.sketch_decide_share(st1, z, zs)
        vdaf.decide(agg_param, [s0, s1])  # must not raise
        out_shares[0].append(st0.y_flat)
        out_shares[1].append(st1.y_flat)
    agg0 = vdaf.aggregate(agg_param, out_shares[0])
    agg1 = vdaf.aggregate(agg_param, out_shares[1])
    total = [field.add(a, b) for a, b in zip(agg0, agg1)]
    assert total == [2, 1, 0]  # alphas hit 0010 twice, 1011 once, 1111 never
