"""Interop-API end-to-end: client/leader/helper/collector containers'
HTTP control surface, in-process (reference:
interop_binaries/tests/end_to_end.rs over a Docker network)."""

import asyncio
import base64

import aiohttp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from janus_tpu.aggregator import (
    Aggregator,
    AggregationJobCreator,
    AggregationJobDriver,
    CollectionJobDriver,
    Config,
    CreatorConfig,
    aggregator_app,
)
from janus_tpu.core.time import MockClock, RealClock
from janus_tpu.datastore.test_util import EphemeralDatastore
from janus_tpu.interop import (
    interop_aggregator_app,
    interop_client_app,
    interop_collector_app,
)
from janus_tpu.messages import Duration, TaskId, Time


def _b64u(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def test_interop_end_to_end():
    """Drive the whole protocol exclusively through /internal/test/*."""
    clock = RealClock()
    leader_eds = EphemeralDatastore(clock)
    helper_eds = EphemeralDatastore(clock)
    cfg = Config(vdaf_backend="oracle", max_upload_batch_write_delay=0.02)
    leader_agg = Aggregator(leader_eds.datastore, clock, cfg)
    helper_agg = Aggregator(helper_eds.datastore, clock, cfg)

    task_id = TaskId.random()
    vdaf = {"type": "Prio3Count"}
    now = clock.now().seconds
    start = now - now % 3600

    async def flow():
        leader = TestClient(
            TestServer(
                interop_aggregator_app(
                    leader_eds.datastore, leader_agg, aggregator_app(leader_agg)
                )
            )
        )
        helper = TestClient(
            TestServer(
                interop_aggregator_app(
                    helper_eds.datastore, helper_agg, aggregator_app(helper_agg)
                )
            )
        )
        client_api = TestClient(TestServer(interop_client_app()))
        collector_api = TestClient(TestServer(interop_collector_app()))
        for c in (leader, helper, client_api, collector_api):
            await c.start_server()
        try:
            for c in (leader, helper, client_api, collector_api):
                assert (await c.post("/internal/test/ready")).status == 200

            leader_url = str(leader.make_url("/dap/"))
            helper_url = str(helper.make_url("/dap/"))

            # collector add_task first (we need its HPKE config)
            resp = await collector_api.post(
                "/internal/test/add_task",
                json={
                    "task_id": _b64u(task_id.data),
                    "leader": leader_url,
                    "vdaf": vdaf,
                    "collector_authentication_token": "col-tok",
                    "query_type": 1,
                },
            )
            doc = await resp.json()
            assert doc["status"] == "success", doc
            collector_hpke = doc["collector_hpke_config"]

            # add_task on both aggregators
            common = {
                "task_id": _b64u(task_id.data),
                "leader": leader_url,
                "helper": helper_url,
                "vdaf": vdaf,
                "leader_authentication_token": "agg-tok",
                "vdaf_verify_key": _b64u(b"\x2a" * 16),
                "min_batch_size": 1,
                "time_precision": 3600,
                "query_type": 1,
                "collector_hpke_config": collector_hpke,
            }
            resp = await leader.post(
                "/internal/test/add_task",
                json={
                    **common,
                    "role": "Leader",
                    "collector_authentication_token": "col-tok",
                },
            )
            assert (await resp.json())["status"] == "success", await resp.text()
            resp = await helper.post(
                "/internal/test/add_task", json={**common, "role": "Helper"}
            )
            assert (await resp.json())["status"] == "success", await resp.text()

            # uploads through the interop client
            measurements = [1, 1, 0, 1]
            for m in measurements:
                resp = await client_api.post(
                    "/internal/test/upload",
                    json={
                        "task_id": _b64u(task_id.data),
                        "leader": leader_url,
                        "helper": helper_url,
                        "vdaf": vdaf,
                        "measurement": str(m),
                        "time_precision": 3600,
                    },
                )
                doc = await resp.json()
                assert doc["status"] == "success", doc
            await asyncio.sleep(0.1)

            # drive aggregation on the leader
            creator = AggregationJobCreator(
                leader_eds.datastore, CreatorConfig(min_aggregation_job_size=1)
            )
            await creator.run_once()
            driver = AggregationJobDriver(leader_eds.datastore, aiohttp.ClientSession)
            while True:
                leases = await leader_eds.datastore.run_tx_async(
                    "a",
                    lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10),
                )
                if not leases:
                    break
                for lease in leases:
                    await driver.step_aggregation_job(lease)
            await driver.close()

            # collection through the interop collector
            resp = await collector_api.post(
                "/internal/test/collection_start",
                json={
                    "task_id": _b64u(task_id.data),
                    "agg_param": "",
                    "query": {
                        "type": 1,
                        "batch_interval_start": start,
                        "batch_interval_duration": 7200,
                    },
                },
            )
            doc = await resp.json()
            assert doc["status"] == "success", doc
            handle = doc["handle"]

            coll_driver = CollectionJobDriver(
                leader_eds.datastore, aiohttp.ClientSession
            )
            result = None
            for _ in range(50):
                leases = await leader_eds.datastore.run_tx_async(
                    "c",
                    lambda tx: tx.acquire_incomplete_collection_jobs(Duration(600), 10),
                )
                for lease in leases:
                    await coll_driver.step_collection_job(lease)
                resp = await collector_api.post(
                    "/internal/test/collection_poll", json={"handle": handle}
                )
                doc = await resp.json()
                if doc["status"] == "success":
                    result = doc
                    break
                assert doc["status"] == "in progress", doc
                await asyncio.sleep(0.1)
            await coll_driver.close()
            assert result is not None, "collection never completed"
            assert result["report_count"] == len(measurements)
            assert result["result"] == str(sum(measurements))
        finally:
            for c in (leader, helper, client_api, collector_api):
                await c.close()

    asyncio.new_event_loop().run_until_complete(flow())
    leader_eds.cleanup()
    helper_eds.cleanup()
