"""HTTP error-path matrix: one test per DapProblemType mapping plus
malformed-body, auth-failure, role, idempotency, and taskprov edges.

Mirrors the reference's handler-test coverage of failure modes
(reference: aggregator/src/aggregator/http_handlers/tests/*.rs), driven as
full DAP requests against the in-process aiohttp app so the problem-details
wire format (RFC 7807 type/title/status/taskid) is what's asserted.
"""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from janus_tpu.aggregator import Aggregator, Config
from janus_tpu.aggregator.http_handlers import aggregator_app
from janus_tpu.client import prepare_report
from janus_tpu.core.hpke import HpkeKeypair
from janus_tpu.core.time import MockClock
from janus_tpu.datastore.test_util import EphemeralDatastore
from janus_tpu.messages import (
    AggregateShareReq,
    AggregationJobId,
    AggregationJobInitializeReq,
    BatchSelector,
    CollectionJobId,
    CollectionReq,
    Duration,
    Interval,
    PartialBatchSelector,
    Query,
    ReportIdChecksum,
    TaskId,
    Time,
)

from test_aggregator_handlers import (
    AGG_TOKEN,
    COL_TOKEN,
    NOW,
    TIME_PRECISION,
    leader_prep_inits,
    make_pair_tasks,
)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


class Env:
    """One role's app over a real in-process HTTP server."""

    def __init__(self, task=None, clock=None):
        self.eds = EphemeralDatastore(clock or MockClock(NOW))
        if task is not None:
            self.eds.datastore.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        self.agg = Aggregator(self.eds.datastore, self.eds.clock, Config(vdaf_backend="oracle"))
        self.client = None

    async def __aenter__(self):
        self.client = TestClient(TestServer(aggregator_app(self.agg)))
        await self.client.start_server()
        return self.client

    async def __aexit__(self, *exc):
        await self.agg.shutdown()
        await self.client.close()
        self.eds.cleanup()


async def expect_problem(resp, status, suffix):
    assert resp.status == status, await resp.text()
    doc = json.loads(await resp.text())
    assert doc["type"].endswith(suffix), doc
    assert "title" in doc
    return doc


AUTH = {"Authorization": "Bearer " + AGG_TOKEN.token}
COL_AUTH = {"Authorization": "Bearer " + COL_TOKEN.token}


def _report(leader, helper, m=1, time=NOW, config=None):
    vdaf = leader.vdaf_instance()
    return prepare_report(
        vdaf,
        leader.task_id,
        config or leader.hpke_keys[0].config,
        helper.hpke_keys[0].config,
        TIME_PRECISION,
        m,
        time=time,
    )


# ---------------------------------------------------------------- hpke_config


def test_hpke_config_missing_task_id_without_global_keys():
    leader, _, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(leader) as client:
            resp = await client.get("/hpke_config")
            # no global keys provisioned: no config to serve
            assert resp.status in (400, 404)

    run(flow())


def test_hpke_config_unknown_task():
    leader, _, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(leader) as client:
            resp = await client.get("/hpke_config", params={"task_id": str(TaskId.random())})
            await expect_problem(resp, 404, "unrecognizedTask")

    run(flow())


def test_hpke_config_malformed_task_id():
    leader, _, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(leader) as client:
            resp = await client.get("/hpke_config", params={"task_id": "!!notb64!!"})
            assert resp.status == 400

    run(flow())


# -------------------------------------------------------------------- upload


def test_upload_garbage_body():
    leader, _, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(leader) as client:
            resp = await client.put(f"/tasks/{leader.task_id}/reports", data=b"\xffgarbage")
            await expect_problem(resp, 400, "invalidMessage")

    run(flow())


def test_upload_unknown_task():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})
    report = _report(leader, helper)

    async def flow():
        async with Env(leader) as client:
            resp = await client.put(
                f"/tasks/{TaskId.random()}/reports", data=report.get_encoded()
            )
            await expect_problem(resp, 404, "unrecognizedTask")

    run(flow())


def test_upload_to_helper_role_rejected():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})
    report = _report(leader, helper)

    async def flow():
        async with Env(helper) as client:
            resp = await client.put(
                f"/tasks/{leader.task_id}/reports", data=report.get_encoded()
            )
            await expect_problem(resp, 404, "unrecognizedTask")

    run(flow())


def test_upload_outdated_hpke_config():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})
    stale = HpkeKeypair.generate((int(leader.hpke_keys[0].config.id) + 1) % 256)
    report = _report(leader, helper, config=stale.config)

    async def flow():
        async with Env(leader) as client:
            resp = await client.put(
                f"/tasks/{leader.task_id}/reports", data=report.get_encoded()
            )
            await expect_problem(resp, 400, "outdatedConfig")

    run(flow())


def test_upload_report_too_early():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})
    future = Time(NOW.seconds + 3 * 3600)
    report = _report(leader, helper, time=future)

    async def flow():
        async with Env(leader) as client:
            resp = await client.put(
                f"/tasks/{leader.task_id}/reports", data=report.get_encoded()
            )
            await expect_problem(resp, 400, "reportTooEarly")

    run(flow())


# --------------------------------------------------- helper aggregation init


def _init_req(inits):
    return AggregationJobInitializeReq(
        aggregation_parameter=b"",
        partial_batch_selector=PartialBatchSelector.new_time_interval(),
        prepare_inits=inits,
    )


def test_agg_init_no_auth():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})
    vdaf = helper.vdaf_instance()
    inits, _, _ = leader_prep_inits(vdaf, leader, helper, [1])

    async def flow():
        async with Env(helper) as client:
            url = f"/tasks/{helper.task_id}/aggregation_jobs/{AggregationJobId.random()}"
            resp = await client.put(url, data=_init_req(inits).get_encoded())
            await expect_problem(resp, 403, "unauthorizedRequest")

    run(flow())


def test_agg_init_wrong_token():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})
    vdaf = helper.vdaf_instance()
    inits, _, _ = leader_prep_inits(vdaf, leader, helper, [1])

    async def flow():
        async with Env(helper) as client:
            url = f"/tasks/{helper.task_id}/aggregation_jobs/{AggregationJobId.random()}"
            resp = await client.put(
                url,
                data=_init_req(inits).get_encoded(),
                headers={"Authorization": "Bearer wrong-token"},
            )
            await expect_problem(resp, 403, "unauthorizedRequest")

    run(flow())


def test_agg_init_garbage_body():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(helper) as client:
            url = f"/tasks/{helper.task_id}/aggregation_jobs/{AggregationJobId.random()}"
            resp = await client.put(url, data=b"\x01bad", headers=AUTH)
            await expect_problem(resp, 400, "invalidMessage")

    run(flow())


def test_agg_init_unknown_task():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})
    vdaf = helper.vdaf_instance()
    inits, _, _ = leader_prep_inits(vdaf, leader, helper, [1])

    async def flow():
        async with Env(helper) as client:
            url = f"/tasks/{TaskId.random()}/aggregation_jobs/{AggregationJobId.random()}"
            resp = await client.put(url, data=_init_req(inits).get_encoded(), headers=AUTH)
            await expect_problem(resp, 404, "unrecognizedTask")

    run(flow())


def test_agg_init_on_leader_role_rejected():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})
    vdaf = leader.vdaf_instance()
    inits, _, _ = leader_prep_inits(vdaf, leader, helper, [1])

    async def flow():
        async with Env(leader) as client:
            url = f"/tasks/{leader.task_id}/aggregation_jobs/{AggregationJobId.random()}"
            resp = await client.put(url, data=_init_req(inits).get_encoded(), headers=AUTH)
            assert resp.status in (400, 404), await resp.text()

    run(flow())


def test_agg_init_idempotent_replay_and_mutation_conflict():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})
    vdaf = helper.vdaf_instance()
    inits, _, _ = leader_prep_inits(vdaf, leader, helper, [1, 0])
    req = _init_req(inits)

    async def flow():
        async with Env(helper) as client:
            url = f"/tasks/{helper.task_id}/aggregation_jobs/{AggregationJobId.random()}"
            r1 = await client.put(url, data=req.get_encoded(), headers=AUTH)
            assert r1.status == 200, await r1.text()
            body1 = await r1.read()
            # byte-identical replay: same response, no re-processing
            r2 = await client.put(url, data=req.get_encoded(), headers=AUTH)
            assert r2.status == 200
            assert await r2.read() == body1
            # same job id, mutated body: forbidden mutation
            mutated = _init_req(list(reversed(inits)))
            r3 = await client.put(url, data=mutated.get_encoded(), headers=AUTH)
            assert r3.status == 409, await r3.text()

    run(flow())


def test_agg_continue_unknown_job():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(helper) as client:
            url = f"/tasks/{helper.task_id}/aggregation_jobs/{AggregationJobId.random()}"
            from janus_tpu.messages import AggregationJobContinueReq, AggregationJobStep

            req = AggregationJobContinueReq(AggregationJobStep(1), [])
            resp = await client.post(url, data=req.get_encoded(), headers=AUTH)
            await expect_problem(resp, 404, "unrecognizedAggregationJob")

    run(flow())


def test_agg_continue_step_mismatch():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})
    vdaf = helper.vdaf_instance()
    inits, _, _ = leader_prep_inits(vdaf, leader, helper, [1])

    async def flow():
        async with Env(helper) as client:
            job_id = AggregationJobId.random()
            url = f"/tasks/{helper.task_id}/aggregation_jobs/{job_id}"
            r1 = await client.put(url, data=_init_req(inits).get_encoded(), headers=AUTH)
            assert r1.status == 200
            from janus_tpu.messages import AggregationJobContinueReq, AggregationJobStep

            # Prio3 finishes in one round; step 0 on continue is always
            # invalid, and a bogus step number mismatches the job state.
            req = AggregationJobContinueReq(AggregationJobStep(0), [])
            resp = await client.post(url, data=req.get_encoded(), headers=AUTH)
            await expect_problem(resp, 400, "invalidMessage")
            req = AggregationJobContinueReq(AggregationJobStep(5), [])
            resp = await client.post(url, data=req.get_encoded(), headers=AUTH)
            await expect_problem(resp, 400, "stepMismatch")

    run(flow())


def test_agg_delete_requires_auth():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(helper) as client:
            url = f"/tasks/{helper.task_id}/aggregation_jobs/{AggregationJobId.random()}"
            resp = await client.delete(url)
            await expect_problem(resp, 403, "unauthorizedRequest")

    run(flow())


# ------------------------------------------------------- helper agg share


def _share_req(task, count=1, checksum=None, interval_start=None):
    start = interval_start if interval_start is not None else NOW.seconds - NOW.seconds % 3600
    return AggregateShareReq(
        BatchSelector.new_time_interval(Interval(Time(start), TIME_PRECISION)),
        b"",
        count,
        checksum or ReportIdChecksum.zero(),
    )


def test_agg_share_no_auth():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(helper) as client:
            resp = await client.post(
                f"/tasks/{helper.task_id}/aggregate_shares",
                data=_share_req(helper).get_encoded(),
            )
            await expect_problem(resp, 403, "unauthorizedRequest")

    run(flow())


def test_agg_share_unknown_task():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(helper) as client:
            resp = await client.post(
                f"/tasks/{TaskId.random()}/aggregate_shares",
                data=_share_req(helper).get_encoded(),
                headers=AUTH,
            )
            await expect_problem(resp, 404, "unrecognizedTask")

    run(flow())


def test_agg_share_batch_mismatch_on_counts():
    """Helper has aggregated nothing; a leader claiming 5 reports must get
    batchMismatch (checksum/count cross-check)."""
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(helper) as client:
            resp = await client.post(
                f"/tasks/{helper.task_id}/aggregate_shares",
                data=_share_req(helper, count=5).get_encoded(),
                headers=AUTH,
            )
            await expect_problem(resp, 400, "batchMismatch")

    run(flow())


def test_agg_share_garbage_body():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(helper) as client:
            resp = await client.post(
                f"/tasks/{helper.task_id}/aggregate_shares", data=b"zz", headers=AUTH
            )
            await expect_problem(resp, 400, "invalidMessage")

    run(flow())


# --------------------------------------------------------- leader collection


def _collection_req(start=None, duration=None):
    s = start if start is not None else NOW.seconds - NOW.seconds % 3600
    d = duration or 2 * TIME_PRECISION.seconds
    return CollectionReq(
        Query.new_time_interval(Interval(Time(s), Duration(d))), b""
    )


def test_collection_put_no_auth():
    leader, _, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(leader) as client:
            url = f"/tasks/{leader.task_id}/collection_jobs/{CollectionJobId.random()}"
            resp = await client.put(url, data=_collection_req().get_encoded())
            await expect_problem(resp, 403, "unauthorizedRequest")

    run(flow())


def test_collection_put_garbage_body():
    leader, _, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(leader) as client:
            url = f"/tasks/{leader.task_id}/collection_jobs/{CollectionJobId.random()}"
            resp = await client.put(url, data=b"\x00", headers=COL_AUTH)
            await expect_problem(resp, 400, "invalidMessage")

    run(flow())


def test_collection_unaligned_interval_batch_invalid():
    leader, _, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(leader) as client:
            url = f"/tasks/{leader.task_id}/collection_jobs/{CollectionJobId.random()}"
            req = _collection_req(start=NOW.seconds - NOW.seconds % 3600 + 17)
            resp = await client.put(url, data=req.get_encoded(), headers=COL_AUTH)
            await expect_problem(resp, 400, "batchInvalid")

    run(flow())


def test_collection_on_helper_role_rejected():
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(helper) as client:
            url = f"/tasks/{helper.task_id}/collection_jobs/{CollectionJobId.random()}"
            resp = await client.put(url, data=_collection_req().get_encoded(), headers=COL_AUTH)
            assert resp.status in (400, 403, 404), await resp.text()

    run(flow())


def test_collection_poll_unknown_job():
    leader, _, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(leader) as client:
            url = f"/tasks/{leader.task_id}/collection_jobs/{CollectionJobId.random()}"
            resp = await client.post(url, headers=COL_AUTH)
            assert resp.status == 404

    run(flow())


def test_collection_delete_then_poll_gone():
    leader, _, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(leader) as client:
            job_id = CollectionJobId.random()
            url = f"/tasks/{leader.task_id}/collection_jobs/{job_id}"
            resp = await client.put(url, data=_collection_req().get_encoded(), headers=COL_AUTH)
            assert resp.status == 201, await resp.text()
            resp = await client.delete(url, headers=COL_AUTH)
            assert resp.status == 204
            # deleted job: poll reports deletion, not results
            resp = await client.post(url, headers=COL_AUTH)
            assert resp.status == 204

    run(flow())


def test_collection_batch_queried_too_many_times():
    leader, _, _ = make_pair_tasks({"type": "Prio3Count"})

    async def flow():
        async with Env(leader) as client:
            req = _collection_req()
            u1 = f"/tasks/{leader.task_id}/collection_jobs/{CollectionJobId.random()}"
            resp = await client.put(u1, data=req.get_encoded(), headers=COL_AUTH)
            assert resp.status == 201, await resp.text()
            # same interval under a NEW job id: the batch has already been
            # queried max_batch_query_count (=1) times
            u2 = f"/tasks/{leader.task_id}/collection_jobs/{CollectionJobId.random()}"
            resp = await client.put(u2, data=req.get_encoded(), headers=COL_AUTH)
            await expect_problem(resp, 400, "batchQueriedTooManyTimes")

    run(flow())


# -------------------------------------------------------------- taskprov edge


def test_taskprov_advertisement_unknown_peer_rejected():
    """An advertised task config with no configured peer must not be
    provisioned (invalid/unrecognized task), even with a valid auth token."""
    leader, helper, _ = make_pair_tasks({"type": "Prio3Count"})
    vdaf = helper.vdaf_instance()
    inits, _, _ = leader_prep_inits(vdaf, leader, helper, [1])

    async def flow():
        async with Env(helper) as client:
            url = f"/tasks/{TaskId.random()}/aggregation_jobs/{AggregationJobId.random()}"
            headers = dict(AUTH)
            headers["dap-taskprov"] = "AAAA"  # base64url, not a valid TaskConfig
            resp = await client.put(
                url, data=_init_req(inits).get_encoded(), headers=headers
            )
            assert resp.status in (400, 404), await resp.text()

    run(flow())
