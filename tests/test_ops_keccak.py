"""Device Keccak/XOF kernels vs the scalar oracle — byte equality."""

import numpy as np
import pytest

from janus_tpu.fields import Field64, Field128
from janus_tpu.ops.field_jax import JField
from janus_tpu.ops.keccak_jax import turboshake128_batch, xof_turboshake128_batch
from janus_tpu.ops.xof_jax import xof_next_vec_batch
from janus_tpu.xof import XofTurboShake128, turboshake128


# Edge pairs around the 168-byte rate boundary on both axes (one compile
# each); the full cross product adds no new code paths.
@pytest.mark.parametrize(
    "msg_len,out_len",
    [(0, 16), (1, 200), (41, 16), (167, 168), (168, 16), (169, 200), (400, 168)],
)
def test_turboshake_batch_matches_oracle(msg_len, out_len):
    rng = np.random.default_rng(msg_len * 1000 + out_len)
    batch = rng.integers(0, 256, size=(3, msg_len), dtype=np.uint8)
    got = np.asarray(turboshake128_batch(batch, 0x01, out_len))
    for i in range(3):
        want = turboshake128(bytes(batch[i]), 0x01, out_len)
        assert bytes(got[i]) == want, i


def test_xof_batch_matches_oracle():
    rng = np.random.default_rng(7)
    seeds = rng.integers(0, 256, size=(4, 16), dtype=np.uint8)
    binders = rng.integers(0, 256, size=(4, 33), dtype=np.uint8)
    dst = b"\x08\x00\x00\x00\x00\x03\x00\x05"
    got = np.asarray(xof_turboshake128_batch(seeds, dst, binders, 100))
    for i in range(4):
        want = XofTurboShake128(bytes(seeds[i]), dst, bytes(binders[i])).next(100)
        assert bytes(got[i]) == want, i


def test_xof_empty_binder():
    seeds = np.zeros((2, 16), dtype=np.uint8)
    binder = np.zeros((2, 0), dtype=np.uint8)
    got = np.asarray(xof_turboshake128_batch(seeds, b"d", binder, 32))
    want = XofTurboShake128(b"\x00" * 16, b"d", b"").next(32)
    assert bytes(got[0]) == want and bytes(got[1]) == want


def test_pallas_kernels_interpret_mode():
    """Planar Pallas squeeze/absorb kernels vs the scalar oracle (interpret).

    The real Mosaic kernels only compile on TPU; interpret mode runs the
    same kernel logic on CPU so the default suite guards the lane/planar
    bookkeeping and the ping-pong round schedule.
    """
    import os
    from unittest import mock

    with mock.patch.dict(os.environ, {"JANUS_TPU_PALLAS": "interpret"}):
        from janus_tpu.ops.keccak_pallas import pallas_enabled, xof_words_pallas

        assert pallas_enabled(1024) and not pallas_enabled(1000)
        B = 1024
        rng = np.random.default_rng(11)
        seeds = rng.integers(0, 256, size=(B, 16), dtype=np.uint8)
        dst = b"\x08\x00\x00\x00\x00\x03\x00\x01"
        # squeeze: single-block message, multi-block output
        binder = rng.integers(0, 256, size=(B, 1), dtype=np.uint8)
        got = np.asarray(xof_words_pallas(seeds, dst, binder, 100))
        for i in (0, 7, B - 1):
            want = np.frombuffer(
                XofTurboShake128(bytes(seeds[i]), dst, bytes(binder[i])).next(400),
                dtype="<u4",
            )
            assert (got[i] == want).all(), i
        # absorb: multi-block message, seed-sized output
        big = rng.integers(0, 256, size=(B, 500), dtype=np.uint8)
        got = np.asarray(xof_words_pallas(seeds, dst, big, 4))
        for i in (0, B - 1):
            want = np.frombuffer(
                XofTurboShake128(bytes(seeds[i]), dst, bytes(big[i])).next(16),
                dtype="<u4",
            )
            assert (got[i] == want).all(), i


def test_next_vec_flags_rejections():
    """Rows whose stream contains a non-canonical candidate get ok=False.

    Field64/128 rejections are ~2^-32/2^-62 per candidate — unobservable in a
    test — so use a synthetic 31-bit Mersenne field where a candidate is
    rejected with probability ~1/2.  ok must be exactly "all candidates
    canonical", and ok rows must still match the oracle byte-for-byte.
    """

    class TinyField(Field64):
        MODULUS = (1 << 31) - 1
        ENCODED_SIZE = 4

    jf = JField(TinyField)
    rng = np.random.default_rng(5)
    n_rows, length = 64, 1
    seeds = rng.integers(0, 256, size=(n_rows, 16), dtype=np.uint8)
    binder = np.zeros((n_rows, 0), dtype=np.uint8)
    dst = b"tiny"
    got, ok = xof_next_vec_batch(jf, seeds, dst, binder, length)
    got, ok = np.asarray(got), np.asarray(ok)
    assert ok.any() and not ok.all()  # both paths exercised
    for i in range(n_rows):
        stream = XofTurboShake128(bytes(seeds[i]), dst, b"").next(4 * length)
        cands = [int.from_bytes(stream[4 * k : 4 * k + 4], "little") for k in range(length)]
        assert ok[i] == all(c < TinyField.MODULUS for c in cands), i
        if ok[i]:
            assert jf.from_limbs(got[i]) == cands, i


@pytest.mark.parametrize("field", [Field64, Field128])
@pytest.mark.parametrize("length", [1, 7, 100])
def test_next_vec_matches_oracle(field, length):
    jf = JField(field)
    rng = np.random.default_rng(field.ENCODED_SIZE * 100 + length)
    seeds = rng.integers(0, 256, size=(3, 16), dtype=np.uint8)
    binders = rng.integers(0, 256, size=(3, 5), dtype=np.uint8)
    dst = b"\x08\x00\x00\x00\x00\x03\x00\x01"
    got, ok = xof_next_vec_batch(jf, seeds, dst, binders, length)
    got = np.asarray(got)
    assert np.asarray(ok).all()
    for i in range(3):
        want = XofTurboShake128.expand_into_vec(field, bytes(seeds[i]), dst, bytes(binders[i]), length)
        assert jf.from_limbs(got[i]) == want, i
