"""Device Keccak/XOF kernels vs the scalar oracle — byte equality."""

import numpy as np
import pytest

from janus_tpu.fields import Field64, Field128
from janus_tpu.ops.field_jax import JField
from janus_tpu.ops.keccak_jax import turboshake128_batch, xof_turboshake128_batch
from janus_tpu.ops.xof_jax import xof_next_vec_batch
from janus_tpu.xof import XofTurboShake128, turboshake128


# Edge pairs around the 168-byte rate boundary on both axes (one compile
# each); the full cross product adds no new code paths.
@pytest.mark.parametrize(
    "msg_len,out_len",
    [(0, 16), (1, 200), (41, 16), (167, 168), (168, 16), (169, 200), (400, 168)],
)
def test_turboshake_batch_matches_oracle(msg_len, out_len):
    rng = np.random.default_rng(msg_len * 1000 + out_len)
    batch = rng.integers(0, 256, size=(3, msg_len), dtype=np.uint8)
    got = np.asarray(turboshake128_batch(batch, 0x01, out_len))
    for i in range(3):
        want = turboshake128(bytes(batch[i]), 0x01, out_len)
        assert bytes(got[i]) == want, i


def test_xof_batch_matches_oracle():
    rng = np.random.default_rng(7)
    seeds = rng.integers(0, 256, size=(4, 16), dtype=np.uint8)
    binders = rng.integers(0, 256, size=(4, 33), dtype=np.uint8)
    dst = b"\x08\x00\x00\x00\x00\x03\x00\x05"
    got = np.asarray(xof_turboshake128_batch(seeds, dst, binders, 100))
    for i in range(4):
        want = XofTurboShake128(bytes(seeds[i]), dst, bytes(binders[i])).next(100)
        assert bytes(got[i]) == want, i


def test_xof_empty_binder():
    seeds = np.zeros((2, 16), dtype=np.uint8)
    binder = np.zeros((2, 0), dtype=np.uint8)
    got = np.asarray(xof_turboshake128_batch(seeds, b"d", binder, 32))
    want = XofTurboShake128(b"\x00" * 16, b"d", b"").next(32)
    assert bytes(got[0]) == want and bytes(got[1]) == want


@pytest.mark.parametrize("field", [Field64, Field128])
@pytest.mark.parametrize("length", [1, 7, 100])
def test_next_vec_matches_oracle(field, length):
    jf = JField(field)
    rng = np.random.default_rng(field.ENCODED_SIZE * 100 + length)
    seeds = rng.integers(0, 256, size=(3, 16), dtype=np.uint8)
    binders = rng.integers(0, 256, size=(3, 5), dtype=np.uint8)
    dst = b"\x08\x00\x00\x00\x00\x03\x00\x01"
    got, ok = xof_next_vec_batch(jf, seeds, dst, binders, length)
    got = np.asarray(got)
    assert np.asarray(ok).all()
    for i in range(3):
        want = XofTurboShake128.expand_into_vec(field, bytes(seeds[i]), dst, bytes(binders[i]), length)
        assert jf.from_limbs(got[i]) == want, i
