"""Native C++ kernel vs the Python sponge — byte equality, and speed sanity.

The C++ library must produce byte-identical TurboSHAKE streams and field
expansions; the Python path stays as fallback (JANUS_TPU_NATIVE=0).
"""

import os

import pytest

from janus_tpu import native
from janus_tpu.fields import Field64, Field128
from janus_tpu.xof import XofTurboShake128, turboshake128

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native toolchain unavailable"
)


@pytest.mark.parametrize("msg_len", [0, 1, 167, 168, 169, 500])
@pytest.mark.parametrize("out_len", [1, 16, 168, 400])
def test_hash_matches_python(msg_len, out_len):
    msg = bytes((i * 7 + msg_len) % 256 for i in range(msg_len))
    want = turboshake128(msg, 0x1F, out_len)
    got = native.turboshake128(msg, 0x1F, out_len)
    assert got == want


def test_xof_stream_matches_python():
    seed = bytes(range(16))
    dst = b"\x08\x00\x00\x00\x00\x03\x00\x05"
    binder = b"binder-bytes"
    want = XofTurboShake128(seed, dst, binder).next(1000)
    got = native.xof_stream(seed, dst, binder, 1000)
    assert got == want


@pytest.mark.parametrize("field", [Field64, Field128])
@pytest.mark.parametrize("length", [1, 7, 333])
def test_next_vec_matches_python(field, length):
    seed = bytes(reversed(range(16)))
    dst = b"\x08\x00\x00\x00\x00\x03\x00\x01"
    binder = b"nv"
    # force the pure-Python path for the expected value
    want = XofTurboShake128(seed, dst, binder).next_vec(field, length)
    got = native.next_vec(seed, dst, binder, field.ENCODED_SIZE, length)
    assert got == want


def test_expand_into_vec_uses_native_transparently():
    """The public classmethod must agree with the streaming object."""
    seed = b"\x11" * 16
    dst = b"\x08\x00\x00\x00\x00\x00\x00\x01"
    a = XofTurboShake128.expand_into_vec(Field128, seed, dst, b"x", 50)
    b = XofTurboShake128(seed, dst, b"x").next_vec(Field128, 50)
    assert a == b


def test_native_disable_env(monkeypatch):
    monkeypatch.setenv("JANUS_TPU_NATIVE", "0")
    import importlib

    import janus_tpu.native as n

    importlib.reload(n)
    assert n.load() is None
    monkeypatch.delenv("JANUS_TPU_NATIVE")
    importlib.reload(n)


def test_native_path_actually_engaged(monkeypatch):
    """expand_into_vec must take the native path for the supported fields:
    poison the Python fallback so any silent de-engagement fails loudly."""
    from janus_tpu.xof import Xof

    def boom(self, field, length):
        raise AssertionError("python fallback used where native expected")

    monkeypatch.setattr(Xof, "next_vec", boom)
    out = XofTurboShake128.expand_into_vec(
        Field128, b"\x07" * 16, b"\x08" + b"\x00" * 7, b"x", 5
    )
    assert len(out) == 5

    # short seeds must raise exactly like the Python path
    with pytest.raises(ValueError):
        XofTurboShake128.expand_into_vec(Field64, b"", b"d", b"x", 1)
