"""Datastore tests: task/report/job round-trips, leases, crypter, GC.

Mirrors the reference's datastore test strategy (SURVEY.md §4.2; reference:
aggregator_core/src/datastore/tests.rs) against the ephemeral harness.
"""

import threading

import pytest

from janus_tpu.core.auth_tokens import AuthenticationToken
from janus_tpu.core.hpke import HpkeKeypair
from janus_tpu.core.time import MockClock
from janus_tpu.datastore import (
    AggregateShareJob,
    AggregationJob,
    AggregationJobState,
    AggregatorTask,
    BatchAggregation,
    BatchAggregationState,
    CollectionJob,
    CollectionJobState,
    Crypter,
    CrypterError,
    HpkeKeyState,
    LeaderStoredReport,
    ReportAggregation,
    ReportAggregationState,
    TaskQueryType,
    TaskUploadCounter,
    TxConflict,
    generate_key,
)
from janus_tpu.datastore.test_util import EphemeralDatastore
from janus_tpu.messages import (
    AggregationJobId,
    AggregationJobStep,
    BatchId,
    CollectionJobId,
    Duration,
    Extension,
    ExtensionType,
    HpkeCiphertext,
    Interval,
    PrepareError,
    Query,
    ReportId,
    ReportIdChecksum,
    ReportMetadata,
    Role,
    TaskId,
    Time,
)


def make_task(role=Role.LEADER, query_type=None, vdaf=None) -> AggregatorTask:
    from janus_tpu.datastore.task import vdaf_verify_key_length

    vdaf = vdaf or {"type": "Prio3Count"}
    return AggregatorTask(
        task_id=TaskId.random(),
        peer_aggregator_endpoint="https://peer.example.com/",
        query_type=query_type or TaskQueryType.time_interval(),
        vdaf=vdaf,
        role=role,
        vdaf_verify_key=b"\x01" * vdaf_verify_key_length(vdaf),
        min_batch_size=10,
        time_precision=Duration(3600),
        aggregator_auth_token=AuthenticationToken.new_bearer("token-abc")
        if role == Role.LEADER
        else None,
        aggregator_auth_token_hash=AuthenticationToken.new_bearer("token-abc").hash()
        if role == Role.HELPER
        else None,
        collector_auth_token_hash=AuthenticationToken.new_bearer("col-tok").hash()
        if role == Role.LEADER
        else None,
        hpke_keys=[HpkeKeypair.generate(1)],
    )


def make_report(task_id: TaskId, t: int = 1_600_000_000) -> LeaderStoredReport:
    return LeaderStoredReport(
        task_id=task_id,
        metadata=ReportMetadata(ReportId.random(), Time(t)),
        public_share=b"public",
        leader_extensions=[Extension(ExtensionType.TBD, b"ext")],
        leader_input_share=b"leader-share-plaintext",
        helper_encrypted_input_share=HpkeCiphertext(1, b"enc", b"payload"),
    )


@pytest.fixture()
def ds():
    eds = EphemeralDatastore()
    yield eds.datastore
    eds.cleanup()


class TestCrypter:
    def test_round_trip_and_aad_binding(self):
        c = Crypter([generate_key()])
        ct = c.encrypt("tasks", b"row1", "col", b"secret")
        assert c.decrypt("tasks", b"row1", "col", ct) == b"secret"
        with pytest.raises(CrypterError):
            c.decrypt("tasks", b"row2", "col", ct)
        with pytest.raises(CrypterError):
            c.decrypt("tasks", b"row1", "other", ct)
        with pytest.raises(CrypterError):
            c.decrypt("other", b"row1", "col", ct)

    def test_key_rotation(self):
        old, new = generate_key(), generate_key()
        ct = Crypter([old]).encrypt("t", b"r", "c", b"v")
        assert Crypter([new, old]).decrypt("t", b"r", "c", ct) == b"v"
        with pytest.raises(CrypterError):
            Crypter([new]).decrypt("t", b"r", "c", ct)


class TestTasks:
    def test_round_trip(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        got = ds.run_tx("get", lambda tx: tx.get_aggregator_task(task.task_id))
        assert got == task
        assert ds.run_tx("ids", lambda tx: tx.get_task_ids()) == [task.task_id]

    def test_helper_round_trip(self, ds):
        task = make_task(role=Role.HELPER)
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        got = ds.run_tx("get", lambda tx: tx.get_aggregator_task(task.task_id))
        assert got == task
        assert got.aggregator_auth_token is None
        assert got.aggregator_auth_token_hash.validate(
            AuthenticationToken.new_bearer("token-abc")
        )

    def test_duplicate_put_conflicts(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        with pytest.raises(TxConflict):
            ds.run_tx("put2", lambda tx: tx.put_aggregator_task(task))

    def test_delete(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        ds.run_tx("del", lambda tx: tx.delete_task(task.task_id))
        assert ds.run_tx("get", lambda tx: tx.get_aggregator_task(task.task_id)) is None


class TestClientReports:
    def test_round_trip_and_dedup(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        report = make_report(task.task_id)
        ds.run_tx("putr", lambda tx: tx.put_client_report(report))
        got = ds.run_tx(
            "getr", lambda tx: tx.get_client_report(task.task_id, report.report_id)
        )
        assert got == report
        with pytest.raises(TxConflict):
            ds.run_tx("putr2", lambda tx: tx.put_client_report(report))

    def test_claim_and_release(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        reports = [make_report(task.task_id, 1_600_000_000 + i) for i in range(5)]
        for r in reports:
            ds.run_tx("putr", lambda tx, r=r: tx.put_client_report(r))

        claimed = ds.run_tx(
            "claim",
            lambda tx: tx.get_unaggregated_client_reports_for_task(task.task_id, 3),
        )
        assert len(claimed) == 3
        # second claim gets only the remaining two
        claimed2 = ds.run_tx(
            "claim2",
            lambda tx: tx.get_unaggregated_client_reports_for_task(task.task_id, 10),
        )
        assert len(claimed2) == 2
        # release the first three; they become claimable again
        ds.run_tx(
            "rel",
            lambda tx: tx.mark_reports_unaggregated(
                task.task_id, [m.report_id for m in claimed]
            ),
        )
        claimed3 = ds.run_tx(
            "claim3",
            lambda tx: tx.get_unaggregated_client_reports_for_task(task.task_id, 10),
        )
        assert {m.report_id for m in claimed3} == {m.report_id for m in claimed}

    def test_scrub(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        report = make_report(task.task_id)
        ds.run_tx("putr", lambda tx: tx.put_client_report(report))
        ds.run_tx(
            "scrub", lambda tx: tx.scrub_client_report(task.task_id, report.report_id)
        )
        assert (
            ds.run_tx(
                "getr", lambda tx: tx.get_client_report(task.task_id, report.report_id)
            )
            is None
        )
        # still counted as existing (upload dedup)
        assert ds.run_tx(
            "chk",
            lambda tx: tx.check_client_report_exists(task.task_id, report.report_id),
        )

    def test_upload_trace_id_round_trips_and_survives_scrub(self, ds):
        """ISSUE 9: the trace_id column (schema v4) persists the upload
        trace, reads back on every report accessor, survives scrubbing
        (only share payloads are nulled), and the interval query dedups."""
        import dataclasses

        from janus_tpu.messages import Duration, Interval

        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        tid_a, tid_b = "a" * 32, "b" * 32
        reports = [
            dataclasses.replace(make_report(task.task_id, 1_600_000_000), trace_id=tid_a),
            dataclasses.replace(make_report(task.task_id, 1_600_000_001), trace_id=tid_a),
            dataclasses.replace(make_report(task.task_id, 1_600_000_002), trace_id=tid_b),
            make_report(task.task_id, 1_600_000_003),  # pre-v4 shape: no trace
        ]
        for r in reports:
            ds.run_tx("putr", lambda tx, r=r: tx.put_client_report(r))
        got = ds.run_tx(
            "getr",
            lambda tx: tx.get_client_report(task.task_id, reports[0].report_id),
        )
        assert got.trace_id == tid_a
        interval = Interval(Time(1_600_000_000), Duration(100))
        full = ds.run_tx(
            "geti",
            lambda tx: tx.get_client_reports_for_interval(task.task_id, interval, 10),
        )
        assert [r.trace_id for r in full] == [tid_a, tid_a, tid_b, None]
        # pack reports[0] and [2] into aggregation jobs — [2] into a
        # fixed-size batch — leaving [1] and [3] unaggregated, then scrub
        # [2] (what the creator does after packing)
        batch = BatchId.random()
        job_a = put_job(ds, task)
        job_b = put_job(ds, task, batch_id=batch)
        for job, rep in ((job_a, reports[0]), (job_b, reports[2])):
            ra = ReportAggregation(
                task_id=task.task_id,
                aggregation_job_id=job.aggregation_job_id,
                report_id=rep.report_id,
                time=rep.time,
                ord=0,
                state=ReportAggregationState.FINISHED,
            )
            ds.run_tx("putra", lambda tx, ra=ra: tx.put_report_aggregation(ra))
        ds.run_tx(
            "scrub",
            lambda tx: tx.scrub_client_report(task.task_id, reports[2].report_id),
        )
        # link query is membership-scoped: only AGGREGATED reports' traces
        # (tid_a via job_a, tid_b via job_b despite the scrub); the
        # unaggregated tid_a duplicate and the traceless report never leak
        assert ds.run_tx(
            "traces",
            lambda tx: tx.get_aggregated_report_trace_ids(
                task.task_id, interval=interval, limit=10
            ),
        ) == [tid_a, tid_b]
        # batch_id scoping: a fixed-size collection links ONLY its batch
        assert ds.run_tx(
            "traces-batch",
            lambda tx: tx.get_aggregated_report_trace_ids(
                task.task_id, batch_id=batch, limit=10
            ),
        ) == [tid_b]

    def test_counts_and_gc(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        for i in range(4):
            ds.run_tx(
                "putr",
                lambda tx, i=i: tx.put_client_report(
                    make_report(task.task_id, 1_600_000_000 + i * 100)
                ),
            )
        interval = Interval(Time(1_600_000_000), Duration(250))
        assert (
            ds.run_tx(
                "cnt",
                lambda tx: tx.count_client_reports_for_interval(task.task_id, interval),
            )
            == 3
        )
        deleted = ds.run_tx(
            "gc",
            lambda tx: tx.delete_expired_client_reports(
                task.task_id, Time(1_600_000_150), 10
            ),
        )
        assert deleted == 2


def put_job(ds, task, job_id=None, batch_id=None):
    job = AggregationJob(
        task_id=task.task_id,
        aggregation_job_id=job_id or AggregationJobId.random(),
        aggregation_parameter=b"",
        partial_batch_identifier=batch_id,
        client_timestamp_interval=Interval(Time(1_600_000_000), Duration(3600)),
        state=AggregationJobState.IN_PROGRESS,
        step=AggregationJobStep(0),
    )
    ds.run_tx("putj", lambda tx: tx.put_aggregation_job(job))
    return job


class TestAggregationJobs:
    def test_round_trip_update(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        job = put_job(ds, task)
        got = ds.run_tx(
            "getj",
            lambda tx: tx.get_aggregation_job(task.task_id, job.aggregation_job_id),
        )
        assert got == job
        updated = job.with_state(AggregationJobState.FINISHED).with_step(
            AggregationJobStep(1)
        ).with_last_request_hash(b"\x11" * 32)
        ds.run_tx("updj", lambda tx: tx.update_aggregation_job(updated))
        got = ds.run_tx(
            "getj2",
            lambda tx: tx.get_aggregation_job(task.task_id, job.aggregation_job_id),
        )
        assert got == updated

    def test_lease_acquire_release(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        job = put_job(ds, task)

        leases = ds.run_tx(
            "acq",
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10),
        )
        assert len(leases) == 1
        lease = leases[0]
        assert lease.leased.aggregation_job_id == job.aggregation_job_id
        assert lease.leased.vdaf == {"type": "Prio3Count"}
        assert lease.lease_attempts == 1

        # while leased, nothing else can acquire
        assert (
            ds.run_tx(
                "acq2",
                lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10),
            )
            == []
        )
        ds.run_tx("rel", lambda tx: tx.release_aggregation_job(lease))
        leases2 = ds.run_tx(
            "acq3",
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10),
        )
        assert len(leases2) == 1
        assert leases2[0].lease_attempts == 2
        # stale lease token can no longer release
        with pytest.raises(TxConflict):
            ds.run_tx("rel2", lambda tx: tx.release_aggregation_job(lease))

    def test_lease_expiry_reacquire(self, ds):
        clock: MockClock = ds.clock
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        put_job(ds, task)
        leases = ds.run_tx(
            "acq", lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10)
        )
        assert len(leases) == 1
        clock.advance(Duration(601))
        leases2 = ds.run_tx(
            "acq2", lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10)
        )
        assert len(leases2) == 1
        assert leases2[0].lease_attempts == 2

    def test_concurrent_acquirers_no_overlap(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        for _ in range(8):
            put_job(ds, task)

        acquired = []
        lock = threading.Lock()

        def worker():
            got = ds.run_tx(
                "acq",
                lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 3),
            )
            with lock:
                acquired.extend(got)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ids = [l.leased.aggregation_job_id for l in acquired]
        assert len(ids) == len(set(ids)) == 8

    def test_release_with_delay(self, ds):
        clock: MockClock = ds.clock
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        put_job(ds, task)
        (lease,) = ds.run_tx(
            "acq", lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10)
        )
        ds.run_tx(
            "rel", lambda tx: tx.release_aggregation_job(lease, Duration(300))
        )
        assert (
            ds.run_tx(
                "acq2",
                lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10),
            )
            == []
        )
        clock.advance(Duration(301))
        assert (
            len(
                ds.run_tx(
                    "acq3",
                    lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10),
                )
            )
            == 1
        )


class TestReportAggregations:
    def test_all_states_round_trip(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        job = put_job(ds, task)

        ras = [
            ReportAggregation(
                task_id=task.task_id,
                aggregation_job_id=job.aggregation_job_id,
                report_id=ReportId.random(),
                time=Time(1_600_000_000),
                ord=0,
                state=ReportAggregationState.START_LEADER,
                public_share=b"ps",
                leader_extensions=[Extension(ExtensionType.TBD, b"x")],
                leader_input_share=b"lis",
                helper_encrypted_input_share=HpkeCiphertext(2, b"ek", b"pl"),
            ),
            ReportAggregation(
                task_id=task.task_id,
                aggregation_job_id=job.aggregation_job_id,
                report_id=ReportId.random(),
                time=Time(1_600_000_001),
                ord=1,
                state=ReportAggregationState.WAITING_LEADER,
                leader_prep_transition=b"transition-bytes",
            ),
            ReportAggregation(
                task_id=task.task_id,
                aggregation_job_id=job.aggregation_job_id,
                report_id=ReportId.random(),
                time=Time(1_600_000_002),
                ord=2,
                state=ReportAggregationState.WAITING_HELPER,
                helper_prep_state=b"helper-state",
            ),
            ReportAggregation(
                task_id=task.task_id,
                aggregation_job_id=job.aggregation_job_id,
                report_id=ReportId.random(),
                time=Time(1_600_000_003),
                ord=3,
                state=ReportAggregationState.FINISHED,
            ),
            ReportAggregation(
                task_id=task.task_id,
                aggregation_job_id=job.aggregation_job_id,
                report_id=ReportId.random(),
                time=Time(1_600_000_004),
                ord=4,
                state=ReportAggregationState.FAILED,
                error=PrepareError.VDAF_PREP_ERROR,
            ),
        ]
        for ra in ras:
            ds.run_tx("putra", lambda tx, ra=ra: tx.put_report_aggregation(ra))
        got = ds.run_tx(
            "getra",
            lambda tx: tx.get_report_aggregations_for_aggregation_job(
                task.task_id, job.aggregation_job_id
            ),
        )
        assert got == ras

        # state transition: StartLeader -> WaitingLeader clears payloads
        updated = ras[0].with_state(
            ReportAggregationState.WAITING_LEADER, leader_prep_transition=b"t2"
        )
        ds.run_tx("updra", lambda tx: tx.update_report_aggregation(updated))
        got = ds.run_tx(
            "getra2",
            lambda tx: tx.get_report_aggregations_for_aggregation_job(
                task.task_id, job.aggregation_job_id
            ),
        )
        assert got[0] == updated
        assert got[0].public_share is None

    def test_replay_check(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        job1 = put_job(ds, task)
        job2 = put_job(ds, task)
        rid = ReportId.random()
        ra = ReportAggregation(
            task_id=task.task_id,
            aggregation_job_id=job1.aggregation_job_id,
            report_id=rid,
            time=Time(1_600_000_000),
            ord=0,
            state=ReportAggregationState.FINISHED,
        )
        ds.run_tx("putra", lambda tx: tx.put_report_aggregation(ra))
        assert ds.run_tx(
            "chk",
            lambda tx: tx.check_report_aggregation_exists(
                task.task_id, rid, exclude_aggregation_job_id=job2.aggregation_job_id
            ),
        )
        assert not ds.run_tx(
            "chk2",
            lambda tx: tx.check_report_aggregation_exists(
                task.task_id, rid, exclude_aggregation_job_id=job1.aggregation_job_id
            ),
        )


class TestBatchAggregations:
    def test_round_trip(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        ident = Interval(Time(1_600_000_000), Duration(3600)).get_encoded()
        ba = BatchAggregation(
            task_id=task.task_id,
            batch_identifier=ident,
            aggregation_parameter=b"",
            ord=3,
            state=BatchAggregationState.AGGREGATING,
            aggregate_share=b"share-bytes",
            report_count=7,
            checksum=ReportIdChecksum(b"\x05" * 32),
            client_timestamp_interval=Interval(Time(1_600_000_000), Duration(3600)),
            aggregation_jobs_created=2,
            aggregation_jobs_terminated=1,
        )
        ds.run_tx("putba", lambda tx: tx.put_batch_aggregation(ba))
        got = ds.run_tx(
            "getba",
            lambda tx: tx.get_batch_aggregations_for_batch(task.task_id, ident, b""),
        )
        assert got == [ba]
        scrubbed = ba.scrubbed()
        ds.run_tx("updba", lambda tx: tx.update_batch_aggregation(scrubbed))
        got2 = ds.run_tx(
            "getba2",
            lambda tx: tx.get_batch_aggregation(task.task_id, ident, b"", 3),
        )
        assert got2 == scrubbed
        with pytest.raises(TxConflict):
            ds.run_tx("putba2", lambda tx: tx.put_batch_aggregation(ba))


class TestCollectionJobs:
    def test_round_trip_and_leases(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        interval = Interval(Time(1_600_000_000), Duration(3600))
        job = CollectionJob(
            task_id=task.task_id,
            collection_job_id=CollectionJobId.random(),
            query=Query.new_time_interval(interval),
            aggregation_parameter=b"",
            batch_identifier=interval.get_encoded(),
            state=CollectionJobState.START,
        )
        ds.run_tx("putcj", lambda tx: tx.put_collection_job(job))
        got = ds.run_tx(
            "getcj",
            lambda tx: tx.get_collection_job(
                task.task_id, job.collection_job_id, "TimeInterval"
            ),
        )
        assert got == job

        (lease,) = ds.run_tx(
            "acq", lambda tx: tx.acquire_incomplete_collection_jobs(Duration(600), 10)
        )
        assert lease.leased.collection_job_id == job.collection_job_id

        finished = job.finished(
            report_count=12,
            client_timestamp_interval=interval,
            leader_aggregate_share=b"leader-share",
            helper_aggregate_share=HpkeCiphertext(1, b"ek", b"helper-share"),
        )
        ds.run_tx("updcj", lambda tx: tx.update_collection_job(finished))
        ds.run_tx("rel", lambda tx: tx.release_collection_job(lease))
        got2 = ds.run_tx(
            "getcj2",
            lambda tx: tx.get_collection_job(
                task.task_id, job.collection_job_id, "TimeInterval"
            ),
        )
        assert got2 == finished
        # Finished jobs are not acquirable
        assert (
            ds.run_tx(
                "acq2",
                lambda tx: tx.acquire_incomplete_collection_jobs(Duration(600), 10),
            )
            == []
        )


class TestAggregateShareJobs:
    def test_round_trip(self, ds):
        task = make_task(role=Role.HELPER)
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        ident = Interval(Time(1_600_000_000), Duration(3600)).get_encoded()
        job = AggregateShareJob(
            task_id=task.task_id,
            batch_identifier=ident,
            aggregation_parameter=b"",
            helper_aggregate_share=b"helper-share-plain",
            report_count=20,
            checksum=ReportIdChecksum(b"\x07" * 32),
        )
        ds.run_tx("putasj", lambda tx: tx.put_aggregate_share_job(job))
        got = ds.run_tx(
            "getasj",
            lambda tx: tx.get_aggregate_share_job(task.task_id, ident, b""),
        )
        assert got == job
        assert (
            ds.run_tx(
                "cnt",
                lambda tx: tx.count_aggregate_share_jobs_for_batch(task.task_id, ident),
            )
            == 1
        )


class TestOutstandingBatches:
    def test_fill_cycle(self, ds):
        task = make_task(query_type=TaskQueryType.fixed_size(max_batch_size=100))
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        batch_id = BatchId.random()
        ds.run_tx(
            "putob", lambda tx: tx.put_outstanding_batch(task.task_id, batch_id, None)
        )
        got = ds.run_tx(
            "getob",
            lambda tx: tx.get_unfilled_outstanding_batches(task.task_id, None),
        )
        assert len(got) == 1 and got[0].batch_id == batch_id
        assert (got[0].size_min, got[0].size_max) == (0, 0)

        # attach an aggregation job with 3 report aggregations (2 finished)
        job = put_job(ds, task, batch_id=batch_id)
        states = [
            ReportAggregationState.FINISHED,
            ReportAggregationState.FINISHED,
            ReportAggregationState.START_LEADER,
        ]
        for i, st in enumerate(states):
            ra = ReportAggregation(
                task_id=task.task_id,
                aggregation_job_id=job.aggregation_job_id,
                report_id=ReportId.random(),
                time=Time(1_600_000_000),
                ord=i,
                state=st,
            )
            ds.run_tx("putra", lambda tx, ra=ra: tx.put_report_aggregation(ra))
        got = ds.run_tx(
            "getob2",
            lambda tx: tx.get_unfilled_outstanding_batches(task.task_id, None),
        )
        assert (got[0].size_min, got[0].size_max) == (2, 3)

        assert (
            ds.run_tx(
                "acqob", lambda tx: tx.acquire_filled_outstanding_batch(task.task_id, 3)
            )
            is None
        )
        assert (
            ds.run_tx(
                "acqob2", lambda tx: tx.acquire_filled_outstanding_batch(task.task_id, 2)
            )
            == batch_id
        )
        assert (
            ds.run_tx(
                "getob3",
                lambda tx: tx.get_unfilled_outstanding_batches(task.task_id, None),
            )
            == []
        )


class TestGlobalHpkeKeys:
    def test_lifecycle(self, ds):
        kp = HpkeKeypair.generate(7)
        ds.run_tx("putk", lambda tx: tx.put_global_hpke_keypair(kp))
        (got,) = ds.run_tx("getk", lambda tx: tx.get_global_hpke_keypairs())
        assert got.config == kp.config
        assert got.private_key == kp.private_key
        assert got.state == HpkeKeyState.PENDING
        ds.run_tx(
            "setk", lambda tx: tx.set_global_hpke_keypair_state(7, HpkeKeyState.ACTIVE)
        )
        (got,) = ds.run_tx("getk2", lambda tx: tx.get_global_hpke_keypairs())
        assert got.state == HpkeKeyState.ACTIVE
        ds.run_tx("delk", lambda tx: tx.delete_global_hpke_keypair(7))
        assert ds.run_tx("getk3", lambda tx: tx.get_global_hpke_keypairs()) == []


class TestUploadCounters:
    def test_sharded_increment(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        for ord_ in (0, 1, 0):
            ds.run_tx(
                "inc",
                lambda tx, o=ord_: tx.increment_task_upload_counter(
                    task.task_id, o, TaskUploadCounter(task.task_id, report_success=2)
                ),
            )
        got = ds.run_tx("get", lambda tx: tx.get_task_upload_counter(task.task_id))
        assert got.report_success == 6
        assert got.report_decode_failure == 0


class TestSchemaMigrations:
    """Versioned migrations applied on open + the supported-version gate
    (reference: supported_schema_versions!, datastore.rs:77-104; sqlx
    migrations under /db)."""

    _key = generate_key()

    def _open(self, path, clock, **kw):
        from janus_tpu.datastore.datastore import Datastore

        return Datastore(path, Crypter([self._key]), clock, **kw)

    def test_upgrade_applies_only_the_tail(self, tmp_path):
        from janus_tpu.datastore.schema import MIGRATIONS

        clock = MockClock()
        path = str(tmp_path / "m.sqlite3")
        ds1 = self._open(path, clock)
        task = make_task(Role.LEADER)
        ds1.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        ds1.close()

        m2 = "CREATE TABLE IF NOT EXISTS migration_probe (id INTEGER PRIMARY KEY);"
        ds2 = self._open(path, clock, _migrations_override=list(MIGRATIONS) + [m2])
        conn = ds2._conn()
        assert (
            conn.execute("SELECT version FROM schema_version").fetchone()[0]
            == len(MIGRATIONS) + 1
        )
        conn.execute("INSERT INTO migration_probe (id) VALUES (1)")
        # v1 data survives the upgrade
        got = ds2.run_tx("get", lambda tx: tx.get_aggregator_task(task.task_id))
        assert got is not None and got.task_id == task.task_id
        ds2.close()

    def test_future_version_refused(self, tmp_path):
        clock = MockClock()
        path = str(tmp_path / "f.sqlite3")
        ds = self._open(path, clock)
        conn = ds._conn()
        conn.execute("UPDATE schema_version SET version = 99")
        conn.commit()
        ds.close()
        from janus_tpu.datastore.datastore import DatastoreError

        with pytest.raises(DatastoreError, match="newer than this build"):
            self._open(path, clock)

    def test_gate_without_migrate_on_open(self, tmp_path):
        from janus_tpu.datastore.schema import MIGRATIONS

        clock = MockClock()
        path = str(tmp_path / "g.sqlite3")
        from janus_tpu.datastore.datastore import DatastoreError

        # Un-migrated (empty) store: the gating-only open must refuse...
        with pytest.raises(DatastoreError, match="unsupported schema version 0"):
            self._open(path, clock, migrate_on_open=False)
        # ...and after an operator-style migration it opens clean.
        self._open(str(tmp_path / "g2.sqlite3"), clock).close()
        ds = self._open(str(tmp_path / "g2.sqlite3"), clock, migrate_on_open=False)
        ds.run_tx("noop", lambda tx: None)
        ds.close()


class TestLeaseReaper:
    """Expired-without-release leases (a dead holder's) are reaped —
    counted and cleared — while healthy and released leases are not."""

    def test_reap_only_expired_unreleased(self, ds):
        clock: MockClock = ds.clock
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        dead_job = put_job(ds, task)
        live_job = put_job(ds, task)
        released_job = put_job(ds, task)

        # dead: leased for 10s, holder never comes back
        (dead,) = ds.run_tx(
            "acq_dead",
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(10), 1),
        )
        assert dead.leased.aggregation_job_id == dead_job.aggregation_job_id
        # live: long lease, still valid at reap time
        (live,) = ds.run_tx(
            "acq_live",
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1),
        )
        # released: acquired then released cleanly (token already NULL)
        (rel,) = ds.run_tx(
            "acq_rel",
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1),
        )
        ds.run_tx("rel", lambda tx: tx.release_aggregation_job(rel))

        clock.advance(Duration(11))
        assert (
            ds.run_tx("reap", lambda tx: tx.reap_expired_aggregation_job_leases())
            == 1
        )
        # idempotent: nothing left to reap
        assert (
            ds.run_tx("reap2", lambda tx: tx.reap_expired_aggregation_job_leases())
            == 0
        )
        # the dead job is promptly re-acquirable, attempts accounting intact
        leases = ds.run_tx(
            "reacq",
            lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 10),
        )
        by_job = {l.leased.aggregation_job_id: l for l in leases}
        assert dead_job.aggregation_job_id in by_job
        assert by_job[dead_job.aggregation_job_id].lease_attempts == 2
        # the released job is re-acquirable too (that was always true);
        # the LIVE lease must not have been stolen
        assert released_job.aggregation_job_id in by_job
        assert live_job.aggregation_job_id not in by_job
        ds.run_tx("rel_live", lambda tx: tx.release_aggregation_job(live))

    def test_reap_collection_leases(self, ds):
        clock: MockClock = ds.clock
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        interval = Interval(Time(0), Duration(3600))
        job = CollectionJob(
            task_id=task.task_id,
            collection_job_id=CollectionJobId.random(),
            query=Query.new_time_interval(interval),
            aggregation_parameter=b"",
            batch_identifier=interval.get_encoded(),
            state=CollectionJobState.START,
        )
        ds.run_tx("putc", lambda tx: tx.put_collection_job(job))
        (lease,) = ds.run_tx(
            "acq",
            lambda tx: tx.acquire_incomplete_collection_jobs(Duration(10), 1),
        )
        clock.advance(Duration(11))
        assert (
            ds.run_tx("reap", lambda tx: tx.reap_expired_collection_job_leases())
            == 1
        )
        (lease2,) = ds.run_tx(
            "reacq",
            lambda tx: tx.acquire_incomplete_collection_jobs(Duration(600), 1),
        )
        assert lease2.lease_attempts == 2


class TestAccumulatorJournal:
    """Deferred-drain journal rows: same-tx write with the job commit,
    per-batch scans, and the exactly-once DELETE."""

    def _entry_args(self, task, job, rids):
        return (
            task.task_id,
            b"batch-1",
            b"",
            job.aggregation_job_id,
            rids,
        )

    def test_round_trip_and_consume(self, ds):
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        job = put_job(ds, task)
        rids = [bytes([i]) * 16 for i in range(3)]
        ds.run_tx(
            "j_put",
            lambda tx: tx.put_accumulator_journal_entry(*self._entry_args(task, job, rids)),
        )
        entries = ds.run_tx(
            "j_get",
            lambda tx: tx.get_accumulator_journal_entries(task.task_id, b"batch-1"),
        )
        assert len(entries) == 1
        e = entries[0]
        assert e.aggregation_job_id == job.aggregation_job_id
        assert list(e.report_ids) == rids
        assert (
            ds.run_tx(
                "j_count",
                lambda tx: tx.count_accumulator_journal_entries_for_batch(
                    task.task_id, b"batch-1"
                ),
            )
            == 1
        )
        assert (
            ds.run_tx(
                "j_count2",
                lambda tx: tx.count_accumulator_journal_entries_for_batch(
                    task.task_id, b"other"
                ),
            )
            == 0
        )
        # duplicate (job redelivery re-committing) is a conflict, not a
        # silent second row
        with pytest.raises(TxConflict):
            ds.run_tx(
                "j_dup",
                lambda tx: tx.put_accumulator_journal_entry(
                    *self._entry_args(task, job, rids)
                ),
            )
        # exactly-once consumption: first delete wins, second reports it
        assert ds.run_tx(
            "j_del",
            lambda tx: tx.delete_accumulator_journal_entry(
                task.task_id, b"batch-1", b"", job.aggregation_job_id
            ),
        )
        assert not ds.run_tx(
            "j_del2",
            lambda tx: tx.delete_accumulator_journal_entry(
                task.task_id, b"batch-1", b"", job.aggregation_job_id
            ),
        )

    def test_tx_abort_rolls_back_entry(self, ds):
        """The journal row and the job commit are one fact: an aborted tx
        leaves no row behind."""
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        job = put_job(ds, task)

        class Boom(Exception):
            pass

        def tx_fn(tx):
            tx.put_accumulator_journal_entry(
                *self._entry_args(task, job, [b"\x01" * 16])
            )
            raise Boom()

        with pytest.raises(Boom):
            ds.run_tx("j_abort", tx_fn)
        assert (
            ds.run_tx(
                "j_count",
                lambda tx: tx.count_accumulator_journal_entries_for_batch(
                    task.task_id, b"batch-1"
                ),
            )
            == 0
        )

    def test_gc_skips_jobs_with_outstanding_journal_rows(self, ds):
        """GC must not reap a job whose journal row is outstanding: its
        FINISHED rows' retained payloads are the only material the
        replay can re-derive the missing shares from.  Once the row is
        consumed, the next pass collects the job."""
        task = make_task()
        ds.run_tx("put", lambda tx: tx.put_aggregator_task(task))
        job = put_job(ds, task)
        ds.run_tx(
            "finish",
            lambda tx: tx.update_aggregation_job(
                job.with_state(AggregationJobState.FINISHED)
            ),
        )
        ds.run_tx(
            "j_put",
            lambda tx: tx.put_accumulator_journal_entry(
                *self._entry_args(task, job, [b"\x07" * 16])
            ),
        )
        assert (
            ds.run_tx(
                "gc",
                lambda tx: tx.delete_expired_aggregation_artifacts(
                    task.task_id, Time(1_700_000_000), 10
                ),
            )
            == 0
        ), "outstanding journal row must fence the job from GC"
        # replay consumes the row -> the job becomes collectable
        ds.run_tx(
            "j_del",
            lambda tx: tx.delete_accumulator_journal_entry(
                task.task_id, b"batch-1", b"", job.aggregation_job_id
            ),
        )
        assert (
            ds.run_tx(
                "gc2",
                lambda tx: tx.delete_expired_aggregation_artifacts(
                    task.task_id, Time(1_700_000_000), 10
                ),
            )
            == 1
        )
        assert ds.run_tx("cnt", lambda tx: tx.count_accumulator_journal_entries(task.task_id)) == 0
