"""Field oracle tests: parameters, arithmetic laws, NTT, codec."""

import random

import pytest

from janus_tpu.fields import (
    Field64,
    Field128,
    next_power_of_2,
    ntt,
    poly_eval,
    poly_interp,
    poly_mul,
)

FIELDS = [Field64, Field128]


def test_moduli_match_vdaf_spec():
    # draft-irtf-cfrg-vdaf-08 §6.1 field parameter tables.
    assert Field64.MODULUS == 18446744069414584321  # 2^32 * 4294967295 + 1
    assert Field128.MODULUS == 340282366920938462946865773367900766209
    assert Field64.MODULUS == 2**64 - 2**32 + 1
    assert Field128.MODULUS == 2**66 * 4611686018427387897 + 1


@pytest.mark.parametrize("field", FIELDS)
def test_generator_order(field):
    p = field.MODULUS
    g = field.gen()
    assert pow(g, field.gen_order(), p) == 1
    assert pow(g, field.gen_order() // 2, p) != 1


@pytest.mark.parametrize("field", FIELDS)
def test_arithmetic(field):
    rng = random.Random(0)
    p = field.MODULUS
    for _ in range(200):
        a, b = rng.randrange(p), rng.randrange(p)
        assert field.add(a, b) == (a + b) % p
        assert field.sub(a, b) == (a - b) % p
        assert field.mul(a, b) == a * b % p
        if a:
            assert field.mul(a, field.inv(a)) == 1


@pytest.mark.parametrize("field", FIELDS)
def test_codec_roundtrip(field):
    rng = random.Random(1)
    vec = [rng.randrange(field.MODULUS) for _ in range(17)]
    data = field.encode_vec(vec)
    assert len(data) == 17 * field.ENCODED_SIZE
    assert field.decode_vec(data) == vec


def test_decode_rejects_out_of_range():
    data = (Field64.MODULUS).to_bytes(8, "little")
    with pytest.raises(ValueError):
        Field64.decode_vec(data)


@pytest.mark.parametrize("field", FIELDS)
@pytest.mark.parametrize("n", [1, 2, 8, 64])
def test_ntt_roundtrip(field, n):
    rng = random.Random(2)
    coeffs = [rng.randrange(field.MODULUS) for _ in range(n)]
    evals = ntt(field, coeffs)
    # Forward NTT evaluates at powers of the principal n-th root.
    if n > 1:
        w = field.root(n)
        for k in range(n):
            assert evals[k] == poly_eval(field, coeffs, pow(w, k, field.MODULUS))
    assert ntt(field, evals, inverse=True) == coeffs


@pytest.mark.parametrize("field", FIELDS)
def test_poly_interp(field):
    rng = random.Random(3)
    n = 8
    values = [rng.randrange(field.MODULUS) for _ in range(n)]
    coeffs = poly_interp(field, values)
    w = field.root(n)
    for k in range(n):
        assert poly_eval(field, coeffs, pow(w, k, field.MODULUS)) == values[k]


@pytest.mark.parametrize("field", FIELDS)
def test_poly_mul(field):
    rng = random.Random(4)
    a = [rng.randrange(field.MODULUS) for _ in range(5)]
    b = [rng.randrange(field.MODULUS) for _ in range(7)]
    c = poly_mul(field, a, b)
    x = rng.randrange(field.MODULUS)
    assert poly_eval(field, c, x) == field.mul(poly_eval(field, a, x), poly_eval(field, b, x))


def test_next_power_of_2():
    assert next_power_of_2(1) == 1
    assert next_power_of_2(2) == 2
    assert next_power_of_2(3) == 4
    assert next_power_of_2(5) == 8
