"""Process-level crash/restart chaos (ISSUE 4 tentpole).

PR 2/3's chaos harness injects faults *in-process*; these tests kill and
restart whole replica PROCESSES, exercising the recovery machinery the
in-process soak cannot reach: lease expiry under real process death, the
lease reaper's prompt redelivery (``janus_job_leases_expired_total``),
graceful SIGTERM teardown (accumulator spill through the journal
transaction), and the datastore-persisted accumulator journal's
collection-time oracle replay for deltas that died resident on a
SIGKILLed replica's device.

Layers:

* ``test_killed_lease_holder_redelivers_with_attempts_preserved`` — a
  worker process acquires a lease and dies without releasing; after
  expiry the reaper counts it and a survivor reacquires with the
  ``lease_attempts`` accounting intact (the ``max_step_attempts`` budget
  survives holder death).
* ``test_collection_replica_sigkill_mid_replay_exactly_once`` (slow) —
  the COLLECTION driver's crash case (ISSUE 11, carried from the
  ROADMAP): aggregation runs in-process with the accumulator store in
  deferred mode, the executor is torn down drain-less (orphaning every
  job's journal rows), and a real ``collection_job_driver`` BINARY picks
  the collection job up with an ``accumulator.replay`` delay fault armed
  — it is SIGKILLed mid-journal-replay (zero rows consumed), and a
  clean replacement binary replays every orphan exactly once: journal
  drains to empty, the survivor's replay-consumed metric delta equals
  the orphaned row count, the collected result is unchanged, and the
  survivor's trace carries the collection_finish span.
* ``test_crash_restart_soak_exactly_once`` (slow) — THE ACCEPTANCE SOAK:
  a helper aggregator binary plus two aggregation-job-driver binaries
  (device executor + accumulator store in DEFERRED drain mode, device
  backend on a pinned CPU platform) share one datastore; replicas are
  SIGKILLed at seeded random points mid-step and restarted (>= 3
  cycles, ending with a double kill that guarantees a stranded lease);
  after convergence one replica exits via SIGTERM (graceful spill, exit
  code 0) and the other is SIGKILLed (orphaning journal rows), then the
  collection driver replays the orphans from the datastore and every
  seeded report is counted exactly once with aggregates bit-exact
  against the CPU oracle's sums.

Seeded via JANUS_CHAOS_SEED (./ci.sh chaos crash pins it).  The process
soak runs wherever ``cryptography`` is importable — the datastore's
pre-3.35-SQLite fallback paths (backend_sql.py) removed the RETURNING
requirement.
"""

from __future__ import annotations

import base64
import json
import multiprocessing as mp
import os
import pathlib
import random
import signal
import socket
import sqlite3
import subprocess
import sys
import time
import urllib.request

import pytest


from janus_tpu.core.hpke import HpkeApplicationInfo, HpkeKeypair, Label, open_
from janus_tpu.core.time import RealClock
from janus_tpu.datastore import (
    AggregatorTask,
    CollectionJob,
    CollectionJobState,
    Crypter,
    Datastore,
    LeaderStoredReport,
    TaskQueryType,
    generate_key,
)
from janus_tpu.core.auth_tokens import AuthenticationToken
from janus_tpu.messages import (
    AggregationJobId,
    AggregationJobStep,
    BatchSelector,
    CollectionJobId,
    Duration,
    Interval,
    PlaintextInputShare,
    Query,
    Role,
    TaskId,
    Time,
)

SEED = int(os.environ.get("JANUS_CHAOS_SEED", "7"))
REPO = pathlib.Path(__file__).resolve().parents[1]
TIME_PRECISION = Duration(3600)

#: -c bootstrap for replica binaries: pin jax to CPU exactly the way
#: conftest.py does (an ambient out-of-process TPU plugin may win the
#: platform election over the env var alone), then enter the real
#: multi-call entry point.  One TPU cannot be shared by three processes,
#: and CPU-vs-device parity is the backend contract anyway.
_BOOT = (
    "import os, sys;"
    "os.environ['JAX_PLATFORMS'] = 'cpu';"
    "import jax; jax.config.update('jax_platforms', 'cpu');"
    "from janus_tpu.binaries.main import main;"
    "sys.exit(main(sys.argv[1:]))"
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# lease-expiry redelivery across process death (ISSUE 4 satellite)


def _hold_lease_and_die(path: str, key: bytes) -> None:
    """Acquire a short lease, then die WITHOUT releasing (SIGKILL shape:
    os._exit skips every finally/atexit, like a kill -9 mid-step)."""
    ds = Datastore(path, Crypter([key]), RealClock())
    leases = ds.run_tx(
        "acquire",
        lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(2), 1),
    )
    os._exit(0 if len(leases) == 1 else 3)


def test_killed_lease_holder_redelivers_with_attempts_preserved(tmp_path):
    from tests.test_datastore import make_task

    key = generate_key()
    path = str(tmp_path / "lease.sqlite3")
    ds = Datastore(path, Crypter([key]), RealClock())
    task = make_task()
    ds.run_tx("put-task", lambda tx: tx.put_aggregator_task(task))
    from janus_tpu.datastore import AggregationJob, AggregationJobState

    job = AggregationJob(
        task_id=task.task_id,
        aggregation_job_id=AggregationJobId.random(),
        aggregation_parameter=b"",
        partial_batch_identifier=None,
        client_timestamp_interval=Interval(Time(0), Duration(1)),
        state=AggregationJobState.IN_PROGRESS,
        step=AggregationJobStep(0),
    )
    ds.run_tx("put-job", lambda tx: tx.put_aggregation_job(job))

    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_hold_lease_and_die, args=(path, key))
    p.start()
    p.join(timeout=60)
    assert p.exitcode == 0

    # while the dead holder's lease is still valid, nothing to reap or acquire
    assert ds.run_tx("reap0", lambda tx: tx.reap_expired_aggregation_job_leases()) == 0
    assert (
        ds.run_tx(
            "acq0", lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(2), 1)
        )
        == []
    )
    time.sleep(2.5)  # past the 2s lease
    # the survivor's reaper counts exactly the expired-without-release lease
    assert ds.run_tx("reap1", lambda tx: tx.reap_expired_aggregation_job_leases()) == 1
    (lease,) = ds.run_tx(
        "acq1", lambda tx: tx.acquire_incomplete_aggregation_jobs(Duration(600), 1)
    )
    # delivery accounting survives the holder's death: this is attempt 2,
    # so the max_step_attempts budget keeps counting across the crash
    assert lease.lease_attempts == 2
    assert lease.leased.aggregation_job_id == job.aggregation_job_id
    ds.close()


# ---------------------------------------------------------------------------
# THE SOAK


class _Replicas:
    """Spawn/kill/restart the replica binaries of one soak run."""

    def __init__(self, env, driver_cfgs, helper_cfg, log_dir):
        self.env = env
        self.driver_cfgs = driver_cfgs
        self.helper_cfg = helper_cfg
        self.log_dir = log_dir
        self.drivers = [None, None]
        self.helper = None
        self._log_seq = 0

    def _spawn(self, binary, cfg_path, tag):
        self._log_seq += 1
        log = open(self.log_dir / f"{tag}-{self._log_seq}.log", "wb")
        return subprocess.Popen(
            [sys.executable, "-c", _BOOT, binary, "--config-file", str(cfg_path)],
            env=self.env,
            cwd=str(REPO),
            stdout=log,
            stderr=subprocess.STDOUT,
        )

    def start_helper(self):
        self.helper = self._spawn("aggregator", self.helper_cfg, "helper")

    def start_driver(self, i):
        self.drivers[i] = self._spawn(
            "aggregation_job_driver", self.driver_cfgs[i], f"driver{i}"
        )

    def kill_driver(self, i):
        p = self.drivers[i]
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=30)

    def terminate_all(self):
        for p in self.drivers + [self.helper]:
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=30)


def _wait_http(url: str, deadline_s: float) -> None:
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2):
                return
        except Exception:
            time.sleep(0.25)
    raise TimeoutError(f"{url} never came up")


def _scrape(port: int) -> str:
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as r:
            return r.read().decode()
    except Exception:
        return ""


def _metric_total(text: str, name: str) -> float:
    total = 0.0
    for line in text.splitlines():
        if line.startswith(name) and not line.startswith("#"):
            try:
                total += float(line.rsplit(" ", 1)[1])
            except ValueError:
                pass
    return total


def _sql(path: str, query: str):
    conn = sqlite3.connect(path, timeout=10.0)
    try:
        return conn.execute(query).fetchall()
    finally:
        conn.close()


@pytest.mark.slow
def test_collection_replica_sigkill_mid_replay_exactly_once(tmp_path):
    """SIGKILL a collection replica MID-JOURNAL-REPLAY (ISSUE 11): the
    replay's exactly-once fence is the row DELETE inside the merge tx,
    so a replica killed between recompute start and commit must consume
    nothing — and the replacement replica must then consume EVERY
    orphaned row exactly once.  Asserted via the journal gauges (the
    dying replica's /statusz shows the orphans, the survivor's /metrics
    replay counter moves by exactly the orphan count, the table drains
    to empty), the collected result (bit-exact Prio3Count sums), and the
    survivor's merged trace carrying the collection_finish span."""
    import asyncio
    import urllib.parse

    from test_chaos import NOW, TIME_PRECISION, ChaosHarness

    from janus_tpu.core import faults
    from janus_tpu.executor import reset_global_executor

    faults.clear()
    reset_global_executor()
    harness = ChaosHarness(n_tasks=2, deferred=True)
    measurements = {0: [1, 0, 1, 1], 1: [1, 1, 0, 1]}
    coll_health = [_free_port(), _free_port()]

    def _replica_yaml(i, with_fault):
        fault = (
            """
  fault_injection:
    enabled: true
    seed: %d
    points:
      accumulator.replay: {mode: delay, probability: 1.0, delay_s: 600}
"""
            % SEED
        )
        return f"""
common:
  database: {{path: {harness.leader_ds.path}}}
  health_check_listen_address: 127.0.0.1:{coll_health[i]}
  chrome_trace_path: {tmp_path}/trace-coll{i}.json
  status_sample_interval_s: 0.5{fault if with_fault else ''}
job_driver:
  job_discovery_interval_s: 0.2
  max_concurrent_job_workers: 2
  worker_lease_duration_s: 5
  worker_lease_clock_skew_allowance_s: 1
  maximum_attempts_before_failure: 100000
  max_step_attempts: 100000
  lease_reap_interval_s: 0.1
"""

    cfg_paths = []
    for i, with_fault in enumerate((True, False)):
        p = tmp_path / f"coll{i}.yaml"
        p.write_text(_replica_yaml(i, with_fault))
        cfg_paths.append(p)

    env = dict(os.environ)
    env["DATASTORE_KEYS"] = (
        base64.urlsafe_b64encode(harness.leader_ds.key).decode().rstrip("=")
    )
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    def _spawn_coll(i):
        log = open(tmp_path / f"coll{i}.log", "wb")
        return subprocess.Popen(
            [
                sys.executable,
                "-c",
                _BOOT,
                "collection_job_driver",
                "--config-file",
                str(cfg_paths[i]),
            ],
            env=env,
            cwd=str(REPO),
            stdout=log,
            stderr=subprocess.STDOUT,
        )

    def _journal_rows():
        return _sql(
            harness.leader_ds.path, "SELECT COUNT(*) FROM accumulator_journal"
        )[0][0]

    async def _statusz(port):
        def get():
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/statusz", timeout=5
            ) as r:
                return json.loads(r.read().decode())

        return await asyncio.get_running_loop().run_in_executor(None, get)

    procs = [None, None]

    async def flow():
        from janus_tpu.messages import Interval, Query

        await harness.start()
        results = {}
        try:
            # -- in-process aggregation, deferred store -> journal rows -
            for t, ms in measurements.items():
                for m in ms:
                    await harness.upload(t, m)
            await asyncio.sleep(0.1)
            await harness.create_jobs()
            for _ in range(30):
                await harness.drive_round()
                states = harness.agg_job_states()
                if states and all(s == "Finished" for s in states):
                    break
            states = harness.agg_job_states()
            assert states and all(s == "Finished" for s in states), states

            orphans = _journal_rows()
            assert orphans > 0, "deferred store journaled nothing to orphan"
            # CRASH: the executor (and the resident deltas) die drain-less
            # — the journal rows are now recoverable ONLY by replay
            reset_global_executor()

            # -- collection jobs for both tasks -------------------------
            interval = Interval(NOW, TIME_PRECISION)
            jobs = {}
            for t, (task_id, _lt, _ht) in enumerate(harness.tasks):
                job = CollectionJob(
                    task_id=task_id,
                    collection_job_id=CollectionJobId.random(),
                    query=Query.new_time_interval(interval),
                    aggregation_parameter=b"",
                    batch_identifier=interval.get_encoded(),
                    state=CollectionJobState.START,
                )
                harness.leader_ds.datastore.run_tx(
                    "putc", lambda tx, j=job: tx.put_collection_job(j)
                )
                jobs[t] = job

            # -- replica 1: wedged mid-replay, then SIGKILLed -----------
            procs[0] = _spawn_coll(0)
            await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: _wait_http(
                    f"http://127.0.0.1:{coll_health[0]}/healthz", 120
                ),
            )
            deadline = time.monotonic() + 120
            while True:
                doc = await _statusz(coll_health[0])
                if doc["faults"]["hits"].get("accumulator.replay", 0) >= 1:
                    break
                assert time.monotonic() < deadline, "replay fault never fired"
                await asyncio.sleep(0.2)
            # the dying replica's own gauge SEES the orphans (journal
            # section is served straight off the shared datastore)
            assert doc["journal"]["outstanding_rows"] == orphans, doc["journal"]
            # give one step-timeout cycle so the replica completes (and
            # traces) at least one wedged job_step before dying
            await asyncio.sleep(5.0)
            procs[0].send_signal(signal.SIGKILL)
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: procs[0].wait(timeout=30)
            )
            assert _journal_rows() == orphans, (
                "a replica killed mid-replay must consume NOTHING"
            )

            # -- replica 2: clean replay, exactly once ------------------
            procs[1] = _spawn_coll(1)
            await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: _wait_http(
                    f"http://127.0.0.1:{coll_health[1]}/healthz", 120
                ),
            )
            deadline = time.monotonic() + 240
            while time.monotonic() < deadline:
                done = {}
                for t, job in jobs.items():
                    got = await harness.leader_ds.datastore.run_tx_async(
                        "getc",
                        lambda tx, j=job: tx.get_collection_job(
                            j.task_id, j.collection_job_id, "TimeInterval"
                        ),
                    )
                    if got is not None and got.state == CollectionJobState.FINISHED:
                        done[t] = got
                if len(done) == len(jobs):
                    results = done
                    break
                await asyncio.sleep(0.5)
            assert len(results) == len(jobs), "collection never finished"

            # journal drained to empty; the survivor's replay-consumed
            # metric delta equals the orphaned row count
            assert _journal_rows() == 0
            scraped = await asyncio.get_running_loop().run_in_executor(
                None, lambda: _scrape(coll_health[1])
            )
            replayed = _metric_total(
                scraped, 'janus_accumulator_journal_consumed_total{path="replay"}'
            )
            assert replayed == orphans, (replayed, orphans)
            # and the survivor's sampled gauge agrees once a tick lands
            deadline = time.monotonic() + 30
            while True:
                doc = await _statusz(coll_health[1])
                if doc["journal"]["outstanding_rows"] == 0:
                    break
                assert time.monotonic() < deadline, doc["journal"]
                await asyncio.sleep(0.3)
            # graceful SIGTERM for the survivor: _close_tracing flushes
            # its chrome trace (the collection_finish span asserted below)
            procs[1].send_signal(signal.SIGTERM)
            assert (
                await asyncio.get_running_loop().run_in_executor(
                    None, lambda: procs[1].wait(timeout=120)
                )
                == 0
            ), "survivor SIGTERM exit must be clean"
        finally:
            for p in procs:
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait(timeout=30)
            await harness.stop()
        return results

    loop = asyncio.new_event_loop()
    try:
        results = loop.run_until_complete(asyncio.wait_for(flow(), 600))
    finally:
        loop.close()
        reset_global_executor()

    # -- collection results unchanged by the crash/replay dance ---------
    from janus_tpu.messages import AggregateShareAad, Interval as _Interval

    interval = _Interval(NOW, TIME_PRECISION)
    for t, (task_id, leader_task, _h) in enumerate(harness.tasks):
        got = results[t]
        vdaf = leader_task.vdaf_instance()
        field = vdaf.field_for_agg_param(vdaf.decode_agg_param(b""))
        leader_share = field.decode_vec(got.leader_aggregate_share)
        aad = AggregateShareAad(
            task_id, b"", BatchSelector.new_time_interval(interval)
        ).get_encoded()
        info = HpkeApplicationInfo.new(
            Label.AGGREGATE_SHARE, Role.HELPER, Role.COLLECTOR
        )
        helper_share = field.decode_vec(
            open_(harness.collector_keys, info, got.helper_aggregate_share, aad)
        )
        result = vdaf.unshard([leader_share, helper_share], got.report_count)
        assert got.report_count == len(measurements[t]), (t, got.report_count)
        assert result == sum(measurements[t]), (t, result, measurements[t])

    # -- the survivor's trace carries the collection close-out ----------
    from tools.trace_merge import load_events, merge_trace_files

    survivor_trace = str(tmp_path / "trace-coll1.json")
    assert os.path.exists(survivor_trace)
    events = load_events(survivor_trace)
    finishes = [
        e for e in events if e.get("ph") == "X" and e["name"] == "collection_finish"
    ]
    assert len(finishes) == len(harness.tasks), (
        "one collection_finish per task expected",
        [e.get("name") for e in events],
    )
    # each close-out links the collected reports' upload-minted trace ids
    assert all(e["args"].get("links") for e in finishes), finishes
    # both incarnations' files merge onto one timeline (the SIGKILLed
    # replica's partial file must not poison the merge)
    summary = merge_trace_files(
        [str(tmp_path / "trace-coll0.json"), survivor_trace],
        str(tmp_path / "merged-coll-trace.json"),
    )
    assert os.path.exists(tmp_path / "merged-coll-trace.json"), summary


@pytest.mark.slow
def test_crash_restart_soak_exactly_once(tmp_path):
    from janus_tpu.aggregator import AggregationJobCreator, CreatorConfig
    from janus_tpu.client import prepare_report
    from janus_tpu.messages import InputShareAad

    rng = random.Random(SEED)
    key = generate_key()
    leader_db = str(tmp_path / "leader.sqlite3")
    helper_db = str(tmp_path / "helper.sqlite3")
    helper_port = _free_port()
    helper_health = _free_port()
    driver_health = [_free_port(), _free_port()]

    # -- seed both stores ---------------------------------------------------
    clock = RealClock()
    leader_ds = Datastore(leader_db, Crypter([key]), clock)
    helper_ds = Datastore(helper_db, Crypter([key]), clock)
    agg_token = AuthenticationToken.new_bearer("agg-token-crash")
    collector_keys = HpkeKeypair.generate(9)
    now = clock.now()
    report_time = Time(now.seconds - now.seconds % TIME_PRECISION.seconds)
    interval = Interval(report_time, TIME_PRECISION)

    n_tasks = 2
    measurements = {t: [(i + t) % 2 for i in range(12)] for t in range(n_tasks)}
    #: field sum of every seeded report's LEADER out share, straight off
    #: the CPU oracle — the collection's leader aggregate share must be
    #: bit-exact against this no matter which recovery paths fired
    expected_leader_shares = {}
    tasks = []
    keypairs = []
    for t in range(n_tasks):
        task_id = TaskId.random()
        common = dict(
            task_id=task_id,
            query_type=TaskQueryType.time_interval(),
            vdaf={"type": "Prio3Count"},
            vdaf_verify_key=bytes([0x40 + t]) * 16,
            min_batch_size=3,
            time_precision=TIME_PRECISION,
            collector_hpke_config=collector_keys.config,
        )
        leader_kp, helper_kp = HpkeKeypair.generate(1), HpkeKeypair.generate(2)
        leader_task = AggregatorTask(
            peer_aggregator_endpoint=f"http://127.0.0.1:{helper_port}/",
            role=Role.LEADER,
            aggregator_auth_token=agg_token,
            hpke_keys=[leader_kp],
            **common,
        )
        helper_task = AggregatorTask(
            peer_aggregator_endpoint="http://127.0.0.1:1/",  # never called
            role=Role.HELPER,
            aggregator_auth_token_hash=agg_token.hash(),
            hpke_keys=[helper_kp],
            **common,
        )
        leader_ds.run_tx("putl", lambda tx, lt=leader_task: tx.put_aggregator_task(lt))
        helper_ds.run_tx("puth", lambda tx, ht=helper_task: tx.put_aggregator_task(ht))
        tasks.append((task_id, leader_task, helper_task))
        keypairs.append((leader_kp, helper_kp))
        expected_leader_shares[t] = None

    # -- Poplar1 traffic in the soak (ISSUE 10): a heavy-hitters task rides
    # the same kill/restart schedule — its two-round jobs step through the
    # driver binaries' executor-routed poplar_init path, its level-keyed
    # deltas journal in the deferred store, and the SIGKILL orphans replay
    # at collection exactly like Prio3's.
    from janus_tpu.vdaf.poplar1 import Poplar1AggregationParam

    POPLAR_T = n_tasks  # tasks[2]
    poplar_param = Poplar1AggregationParam(1, (0, 1, 2, 3))
    poplar_task_id = TaskId.random()
    poplar_common = dict(
        task_id=poplar_task_id,
        query_type=TaskQueryType.time_interval(),
        vdaf={"type": "Poplar1", "bits": 4},
        vdaf_verify_key=bytes([0x40 + POPLAR_T]) * 16,
        min_batch_size=3,
        time_precision=TIME_PRECISION,
        collector_hpke_config=collector_keys.config,
    )
    poplar_leader_kp, poplar_helper_kp = HpkeKeypair.generate(1), HpkeKeypair.generate(2)
    poplar_leader_task = AggregatorTask(
        peer_aggregator_endpoint=f"http://127.0.0.1:{helper_port}/",
        role=Role.LEADER,
        aggregator_auth_token=agg_token,
        hpke_keys=[poplar_leader_kp],
        **poplar_common,
    )
    poplar_helper_task = AggregatorTask(
        peer_aggregator_endpoint="http://127.0.0.1:1/",
        role=Role.HELPER,
        aggregator_auth_token_hash=agg_token.hash(),
        hpke_keys=[poplar_helper_kp],
        **poplar_common,
    )
    leader_ds.run_tx("putl", lambda tx: tx.put_aggregator_task(poplar_leader_task))
    helper_ds.run_tx("puth", lambda tx: tx.put_aggregator_task(poplar_helper_task))
    tasks.append((poplar_task_id, poplar_leader_task, poplar_helper_task))
    keypairs.append((poplar_leader_kp, poplar_helper_kp))
    expected_leader_shares[POPLAR_T] = None
    measurements[POPLAR_T] = [0b1011, 0b1011, 0b0100, 0b1111, 0b0000, 0b0100]

    def agg_param_enc(t):
        if t == POPLAR_T:
            return tasks[t][1].vdaf_instance().encode_agg_param(poplar_param)
        return b""

    from janus_tpu.core.metrics import GLOBAL_METRICS
    from janus_tpu.core.trace import close_chrome_trace, configure_chrome_trace
    from janus_tpu.vdaf.backend import OracleBackend

    commit_age_count_before = (
        GLOBAL_METRICS.get_sample_value("janus_report_commit_age_seconds_count")
        or 0
    )

    # This process is the soak's CLIENT-INGRESS + COLLECTION replica: the
    # real upload writer and the collection driver both run here, so its
    # trace file carries the upload_commit spans (upload-minted trace
    # ids), the creator's job_create LINK spans, and collection_finish —
    # the pieces trace_merge --stats stitches onto the driver/helper
    # binaries' timelines (ISSUE 9 acceptance).
    client_trace = str(tmp_path / "trace-client.json")
    configure_chrome_trace(client_trace)

    # SLO evaluation plane (ISSUE 9): judge the soak's own traffic.  The
    # commit-age and collection-e2e histograms live in THIS process (the
    # writer and collection driver run here); targets are generous enough
    # that chaos must produce ZERO false breaches.
    from janus_tpu.core.slo import SloEvaluator, targets_from_config

    slo_eval = SloEvaluator(
        targets_from_config(
            {
                "commit_age": {"objective": 0.99, "threshold_s": 3600},
                "collection_e2e": {"objective": 0.95, "threshold_s": 21600},
            }
        )
    )
    slo_eval.tick()  # baseline snapshot before any traffic

    def seed_report(t, m):
        task_id, leader_task, _h = tasks[t]
        leader_kp, helper_kp = keypairs[t]
        vdaf = leader_task.vdaf_instance()
        report = prepare_report(
            vdaf,
            task_id,
            leader_kp.config,
            helper_kp.config,
            TIME_PRECISION,
            m,
            time=report_time,
        )
        # store the leader share the way handle_upload does: HPKE-open
        # our own ciphertext, keep the helper's sealed
        aad = InputShareAad(task_id, report.metadata, report.public_share).get_encoded()
        info = HpkeApplicationInfo.new(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER)
        plain = PlaintextInputShare.get_decoded(
            open_(leader_kp, info, report.leader_encrypted_input_share, aad)
        )
        stored = LeaderStoredReport(
            task_id=task_id,
            metadata=report.metadata,
            public_share=report.public_share,
            leader_extensions=[],
            leader_input_share=plain.payload,
            helper_encrypted_input_share=report.helper_encrypted_input_share,
        )
        # commit through the REAL upload writer (not a bare put): the
        # batch-commit path is what populates the freshness histogram
        # (janus_report_commit_age_seconds) the acceptance asserts on
        import asyncio as _asyncio

        from janus_tpu.aggregator.report_writer import ReportWriteBatcher

        _asyncio.run(
            ReportWriteBatcher(leader_ds, max_batch_size=1).write_report(stored)
        )
        prep_row = (
            report.metadata.report_id.data,
            vdaf.decode_public_share(report.public_share),
            vdaf.decode_input_share(0, plain.payload),
        )
        if t == POPLAR_T:
            # heavy hitters: the leader out share at the collection level
            # is the prefix-value vector (state.y_flat)
            state, _sh = vdaf.prep_init(
                leader_task.vdaf_verify_key, 0, poplar_param, *prep_row
            )
            out_share = list(state.y_flat)
            field = vdaf.field_for_agg_param(poplar_param)
        else:
            (outcome,) = OracleBackend(vdaf).prep_init_batch(
                leader_task.vdaf_verify_key, 0, [prep_row]
            )
            out_share = list(outcome[0].out_share)
            field = vdaf.field_for_agg_param(vdaf.decode_agg_param(b""))
        prev = expected_leader_shares[t]
        expected_leader_shares[t] = (
            out_share if prev is None else field.vec_add(prev, out_share)
        )

    for t in measurements:
        for m in measurements[t]:
            seed_report(t, m)

    import asyncio

    creator = AggregationJobCreator(
        leader_ds,
        CreatorConfig(min_aggregation_job_size=1, max_aggregation_job_size=3),
    )

    def create_poplar_jobs():
        """Agg-param jobs come from collection requests, not the periodic
        creator — drive the production path (_create_agg_param_jobs, job
        size 3) directly so the soak's Poplar1 jobs are created exactly
        the way handle_create_collection_job creates them."""
        from janus_tpu.aggregator import Aggregator, Config
        from janus_tpu.aggregator.aggregator import TaskAggregator

        agg = Aggregator(
            leader_ds, clock, Config(vdaf_backend="oracle", max_agg_param_job_size=3)
        )
        ta = TaskAggregator(poplar_leader_task, "oracle")
        before = len(
            leader_ds.run_tx(
                "jobs",
                lambda tx: tx.get_aggregation_jobs_for_task(poplar_task_id),
            )
        )
        leader_ds.run_tx(
            "poplar_jobs",
            lambda tx: agg._create_agg_param_jobs(
                tx, ta, interval.get_encoded(), agg_param_enc(POPLAR_T)
            ),
        )
        return (
            len(
                leader_ds.run_tx(
                    "jobs",
                    lambda tx: tx.get_aggregation_jobs_for_task(poplar_task_id),
                )
            )
            - before
        )

    n_jobs = asyncio.run(creator.run_once())
    assert n_jobs >= 2 * n_tasks, n_jobs
    n_poplar_jobs = create_poplar_jobs()
    assert n_poplar_jobs == 2, n_poplar_jobs  # 6 reports / job size 3
    n_jobs += n_poplar_jobs

    # -- replica configs ----------------------------------------------------
    def driver_yaml(i):
        return f"""
common:
  database: {{path: {leader_db}}}
  health_check_listen_address: 127.0.0.1:{driver_health[i]}
  chrome_trace_path: {tmp_path}/trace-driver{i}.json
  status_sample_interval_s: 0.5
  otlp_endpoint: http://127.0.0.1:1
  slos:
    job_age_at_acquire: {{objective: 0.9, threshold_s: 1800}}
  # fleet mode ON in the crash soak (ISSUE 16 acceptance): stable
  # per-slot replica ids so a SIGKILL/restart re-owns its tasks (and its
  # warm caches) instead of reshuffling; a short TTL so the kill windows
  # exercise real migrations; routing must never cost exactly-once or
  # convergence
  fleet:
    enabled: true
    replica_id: crash-r{i}
    heartbeat_interval_s: 0.5
    heartbeat_ttl_s: 3.0
    takeover_grace_s: 0.5
job_driver:
  job_discovery_interval_s: 0.2
  max_concurrent_job_workers: 4
  worker_lease_duration_s: 5
  worker_lease_clock_skew_allowance_s: 1
  maximum_attempts_before_failure: 100000
  max_step_attempts: 100000
  retry_initial_delay_s: 1.0
  retry_max_delay_s: 2.0
  lease_reap_interval_s: 0.1
vdaf_backend: tpu
# the drivers walk Poplar1 on the jitted device kernel with DEFERRED
# drains: sketch refs are minted on device, cross the WAITING_LEADER
# persistence hop, and DIE with every SIGKILL — the soak then proves the
# dead-ref recovery story end to end (retained payloads -> per-report
# oracle replay; journal rows -> collection-time replay, exactly once)
poplar_backend: jax
device_executor:
  enabled: true
  flush_window_ms: 20
  flush_max_rows: 4096
  breaker_failure_threshold: 0
  accumulator:
    enabled: true
    byte_budget: 256
    drain_interval_s: 3600
"""

    helper_yaml = f"""
common:
  database: {{path: {helper_db}}}
  health_check_listen_address: 127.0.0.1:{helper_health}
  chrome_trace_path: {tmp_path}/trace-helper.json
  status_sample_interval_s: 0.5
listen_address: 127.0.0.1:{helper_port}
vdaf_backend: tpu
device_executor:
  enabled: true
  flush_window_ms: 20
  flush_max_rows: 4096
  breaker_failure_threshold: 0
  accumulator:
    enabled: true
    byte_budget: 256
"""
    cfg_paths = []
    for i in range(2):
        p = tmp_path / f"driver{i}.yaml"
        p.write_text(driver_yaml(i))
        cfg_paths.append(p)
    helper_cfg = tmp_path / "helper.yaml"
    helper_cfg.write_text(helper_yaml)

    env = dict(os.environ)
    env["DATASTORE_KEYS"] = base64.urlsafe_b64encode(key).decode().rstrip("=")
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    reps = _Replicas(env, cfg_paths, helper_cfg, tmp_path)
    try:
        reps.start_helper()
        _wait_http(f"http://127.0.0.1:{helper_health}/healthz", 120)
        for i in range(2):
            reps.start_driver(i)
        for i in range(2):
            _wait_http(f"http://127.0.0.1:{driver_health[i]}/healthz", 120)

        def leased_count():
            return _sql(
                leader_db,
                "SELECT COUNT(*) FROM aggregation_jobs"
                " WHERE lease_token IS NOT NULL AND state = 'InProgress'",
            )[0][0]

        def unfinished_count():
            return _sql(
                leader_db,
                "SELECT COUNT(*) FROM aggregation_jobs WHERE state = 'InProgress'",
            )[0][0]

        def wait_for_lease(deadline_s=120):
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                if leased_count() > 0:
                    return True
                if unfinished_count() == 0:
                    return False  # converged before a lease appeared
                time.sleep(0.05)
            raise TimeoutError("no lease ever appeared")

        # -- >= 3 seeded SIGKILL/restart cycles mid-step --------------------
        kills = 0
        for cycle in range(2):
            time.sleep(rng.uniform(0.3, 1.2))
            if not wait_for_lease():
                break
            victim = rng.randrange(2)
            reps.kill_driver(victim)
            kills += 1
            reps.start_driver(victim)
        # final cycle: a DOUBLE kill with a lease outstanding guarantees
        # the holder died mid-step — the restarted replicas' reaper must
        # observe at least one expired-without-release lease
        if wait_for_lease():
            reps.kill_driver(0)
            reps.kill_driver(1)
            kills += 2
            reps.start_driver(0)
            reps.start_driver(1)
        assert kills >= 3, f"only {kills} kill/restart cycles ran"
        for i in range(2):
            _wait_http(f"http://127.0.0.1:{driver_health[i]}/healthz", 120)

        # /statusz consistent after recovery: a freshly restarted replica
        # serves every introspection section (ISSUE 5 acceptance).  The
        # health server comes up a beat before the sampler's first tick,
        # so poll briefly until the SLO evaluator has ticked (0.5s cadence).
        deadline = time.monotonic() + 30
        while True:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{driver_health[0]}/statusz", timeout=10
            ) as r:
                statusz = json.loads(r.read().decode())
            if (
                statusz.get("slo", {}).get("ticks", 0) >= 1
                or time.monotonic() > deadline
            ):
                break
            time.sleep(0.2)
        for section in (
            "executor",
            "accumulator",
            "journal",
            "leases",
            "faults",
            "otlp",
            "slo",
        ):
            assert section in statusz, (section, statusz)
        assert statusz["executor"]["enabled"] is True
        assert statusz["leases"]["aggregation"]["active"] >= 0
        # OTLP configured but the SDK is absent on this container: the
        # replica started cleanly and says exactly why it exports nothing
        # (ISSUE 9 acceptance: the no-op path is first-class)
        assert statusz["otlp"]["state"] == "unavailable", statusz["otlp"]
        assert statusz["otlp"]["endpoint"] == "http://127.0.0.1:1"
        # the declarative SLO target from the replica config is armed and
        # its sampler-driven evaluator has ticked
        assert statusz["slo"]["targets"] == 1
        assert statusz["slo"]["ticks"] >= 1, statusz["slo"]
        assert "job_age_at_acquire" in statusz["slo"]["slos"]

        # -- convergence: every job terminal --------------------------------
        deadline = time.monotonic() + 420
        while time.monotonic() < deadline:
            if unfinished_count() == 0:
                break
            time.sleep(0.5)
        states = _sql(leader_db, "SELECT state, COUNT(*) FROM aggregation_jobs GROUP BY state")
        assert dict(states).get("InProgress", 0) == 0, states
        assert dict(states).get("Finished", 0) == n_jobs, (states, n_jobs)

        # acceptance: at least one expired-lease reacquisition observed
        expired = sum(
            _metric_total(_scrape(driver_health[i]), "janus_job_leases_expired_total")
            for i in range(2)
        )
        assert expired > 0, "no expired-lease reacquisition observed"

        # deferred drains (interval 1h) never fired: the journal must hold
        # outstanding rows for the committed-but-unspilled resident deltas
        journal_before = _sql(leader_db, "SELECT COUNT(*) FROM accumulator_journal")[0][0]
        assert journal_before > 0, "no outstanding journal rows to replay"

        # the live replica's /statusz journal section agrees with the
        # datastore (nothing is committing post-convergence, so the
        # outstanding-row count is stable)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{driver_health[1]}/statusz", timeout=10
        ) as r:
            statusz = json.loads(r.read().decode())
        assert statusz["journal"]["outstanding_rows"] == journal_before, statusz[
            "journal"
        ]

        # -- teardown: graceful SIGTERM (spill), then a GUARANTEED orphan ---
        reps.drivers[0].send_signal(signal.SIGTERM)
        assert reps.drivers[0].wait(timeout=120) == 0, "SIGTERM exit must be clean"

        # second wave: only driver1 remains, so every wave-2 job's journal
        # row is owned by driver1's live store — SIGKILLing it afterwards
        # deterministically orphans rows for the collection replay
        for t in range(n_tasks):
            for m in [1, 1, 0]:
                measurements[t].append(m)
                seed_report(t, m)
        # wave-2 Poplar1 reports: _create_agg_param_jobs' conflict-key
        # dedup must pick up ONLY the fresh reports for the new level job
        for m in [0b0100, 0b1111, 0b1011]:
            measurements[POPLAR_T].append(m)
            seed_report(POPLAR_T, m)
        n_jobs += asyncio.run(creator.run_once())
        wave2_poplar = create_poplar_jobs()
        assert wave2_poplar == 1, wave2_poplar
        n_jobs += wave2_poplar
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if unfinished_count() == 0:
                break
            time.sleep(0.5)
        assert unfinished_count() == 0, "wave-2 jobs never converged"
        reps.kill_driver(1)
        journal_after = _sql(leader_db, "SELECT COUNT(*) FROM accumulator_journal")[0][0]
        assert journal_after > 0, "the SIGKILLed replica must orphan journal rows"
    except BaseException:
        reps.terminate_all()
        configure_chrome_trace(None)
        raise

    # -- collection: replay the orphans, then exactness ---------------------
    import aiohttp

    from janus_tpu.aggregator.collection_job_driver import (
        CollectionDriverConfig,
        CollectionJobDriver,
    )

    async def collect():
        results = {}
        driver = CollectionJobDriver(
            leader_ds,
            aiohttp.ClientSession,
            CollectionDriverConfig(retry_initial_delay=Duration(1)),
        )
        try:
            for t, (task_id, leader_task, _h) in enumerate(tasks):
                job = CollectionJob(
                    task_id=task_id,
                    collection_job_id=CollectionJobId.random(),
                    query=Query.new_time_interval(interval),
                    aggregation_parameter=agg_param_enc(t),
                    batch_identifier=interval.get_encoded(),
                    state=CollectionJobState.START,
                )
                leader_ds.run_tx("putc", lambda tx, j=job: tx.put_collection_job(j))
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    leases = await leader_ds.run_tx_async(
                        "acqc",
                        lambda tx: tx.acquire_incomplete_collection_jobs(
                            Duration(600), 4
                        ),
                    )
                    for lease in leases:
                        await driver.step_collection_job(lease)
                    got = leader_ds.run_tx(
                        "getc",
                        lambda tx, j=job: tx.get_collection_job(
                            j.task_id, j.collection_job_id, "TimeInterval"
                        ),
                    )
                    if got.state == CollectionJobState.FINISHED:
                        results[t] = got
                        break
                    await asyncio.sleep(0.3)
                else:
                    raise TimeoutError(f"collection for task {t} never finished")
        finally:
            await driver.close()
        return results

    replay_before = (
        GLOBAL_METRICS.get_sample_value(
            "janus_accumulator_journal_consumed_total", {"path": "replay"}
        )
        or 0
    )
    e2e_before = (
        GLOBAL_METRICS.get_sample_value("janus_collection_e2e_seconds_count") or 0
    )

    try:
        results = asyncio.run(collect())

        from janus_tpu.messages import AggregateShareAad

        for t, (task_id, leader_task, _h) in enumerate(tasks):
            got = results[t]
            vdaf = leader_task.vdaf_instance()
            agg_param = vdaf.decode_agg_param(agg_param_enc(t))
            field = vdaf.field_for_agg_param(agg_param)
            leader_share = field.decode_vec(got.leader_aggregate_share)
            aad = AggregateShareAad(
                task_id, agg_param_enc(t), BatchSelector.new_time_interval(interval)
            ).get_encoded()
            info = HpkeApplicationInfo.new(
                Label.AGGREGATE_SHARE, Role.HELPER, Role.COLLECTOR
            )
            helper_share = field.decode_vec(
                open_(collector_keys, info, got.helper_aggregate_share, aad)
            )
            result = vdaf.unshard_with_param(
                agg_param, [leader_share, helper_share], got.report_count
            )
            # exactly-once: Prio3Count aggregation is exact, so equality
            # with the true count and sum IS the no-double/no-drop proof;
            # the leader share is additionally checked BIT-EXACT against
            # the CPU oracle's field sum (splits a leader-side recovery
            # bug from a helper-side one on failure)
            assert got.report_count == len(measurements[t]), (t, got.report_count)
            assert leader_share == expected_leader_shares[t], (
                t,
                "leader share deviates from the CPU oracle sum",
                leader_share,
                expected_leader_shares[t],
            )
            if t == POPLAR_T:
                # heavy-hitter counts: per-prefix totals at level 1
                expect = [0, 0, 0, 0]
                for m in measurements[t]:
                    expect[m >> 2] += 1
            else:
                expect = sum(measurements[t])
            assert result == expect, (t, result, expect, "helper side")

        # every orphaned journal row was consumed by the replay
        assert _sql(leader_db, "SELECT COUNT(*) FROM accumulator_journal")[0][0] == 0

        # -- ISSUE 5 acceptance: metric invariants + the merged trace -------
        # journal written == consumed, from metrics: the rows the SIGKILLed
        # replica wrote and never drained (journal_after of them) were each
        # consumed via the replay path — the replay counter moved by exactly
        # the orphan count, and with the table empty above, every row any
        # incarnation ever wrote was consumed by its drain or this replay.
        replay_delta = (
            GLOBAL_METRICS.get_sample_value(
                "janus_accumulator_journal_consumed_total", {"path": "replay"}
            )
            or 0
        ) - replay_before
        assert replay_delta == journal_after, (replay_delta, journal_after)

        # freshness histograms populated: one commit-age sample per seeded
        # report (the soak uploads through the real writer), and an
        # upload->collectable end-to-end sample per finished collection
        commit_age_delta = (
            GLOBAL_METRICS.get_sample_value("janus_report_commit_age_seconds_count")
            or 0
        ) - commit_age_count_before
        total_reports = sum(len(m) for m in measurements.values())
        assert commit_age_delta == total_reports, (commit_age_delta, total_reports)
        e2e_delta = (
            GLOBAL_METRICS.get_sample_value("janus_collection_e2e_seconds_count")
            or 0
        ) - e2e_before
        assert e2e_delta >= n_tasks, (e2e_delta, n_tasks)

        # -- ISSUE 9 acceptance: SLO self-evaluation over the soak ----------
        # The evaluator ticked a baseline before traffic; this tick sees
        # every commit-age and collection-e2e sample the soak produced.
        # Burn-rate samples must EXIST for both SLOs (the evaluator is
        # live) and read 0.0 — at these targets, chaos must not cost SLO
        # budget, so any breach is a false positive.
        slo_verdict = slo_eval.tick()
        for slo in ("commit_age", "collection_e2e"):
            st = slo_verdict[slo]
            assert st["events_total"] > 0, (slo, st)
            for window in ("fast", "slow"):
                sample = GLOBAL_METRICS.get_sample_value(
                    "janus_slo_burn_rate", {"slo": slo, "window": window}
                )
                assert sample is not None, (slo, window)
                assert sample == 0.0, (slo, window, sample)
            assert st["breaches"] == 0, (slo, st)
            assert (
                GLOBAL_METRICS.get_sample_value(
                    "janus_slo_breach_total", {"slo": slo}
                )
                or 0
            ) == 0
        # the evaluator saw every sample the soak committed (events_total
        # is the histogram's absolute count; the soak added exactly
        # commit_age_delta of them)
        assert slo_verdict["commit_age"]["events_total"] >= commit_age_delta

        # upload->commit latency recorded for every seeded report
        assert (
            GLOBAL_METRICS.get_sample_value(
                "janus_report_upload_to_commit_seconds_count"
            )
            or 0
        ) >= total_reports

        # merged chrome trace: one aggregation job's spans visible from >= 2
        # processes (a leader driver binary AND the helper binary) under a
        # single trace id — the cross-process correlation the trace ids
        # persisted on job rows + the traceparent header exist to provide
        from tools.trace_merge import load_events, merge_trace_files, trace_stats

        close_chrome_trace()  # flush this process's client/collection spans
        helper_trace = str(tmp_path / "trace-helper.json")
        trace_files = [
            str(tmp_path / f"trace-driver{i}.json") for i in range(2)
        ] + [helper_trace, client_trace]
        for f in trace_files:
            assert os.path.exists(f), f"replica never wrote its trace: {f}"
        summary = merge_trace_files(
            trace_files, str(tmp_path / "merged-trace.json")
        )
        helper_pids = {
            e.get("pid") for e in load_events(helper_trace) if e.get("ph") == "X"
        }
        cross_process = {
            t: pids
            for t, pids in summary["traces"].items()
            if set(pids) & helper_pids and set(pids) - helper_pids
        }
        assert cross_process, (
            "no trace id spans both a driver and the helper",
            summary["traces"],
        )

        # -- ISSUE 9 acceptance: the MERGED timeline runs client ingress ->
        # collection.  Upload-minted trace ids (this process's writer) are
        # linked to job trace ids by job_create spans and closed out by
        # collection_finish, so trace_merge --stats must report >= 1 merged
        # trace whose critical path is COMPLETE (upload span -> batch
        # commit -> a driver binary's flush -> collection) and whose spans
        # come from an upload process, a driver binary, AND the helper.
        driver_pids = set()
        for i in range(2):
            driver_pids |= {
                e.get("pid")
                for e in load_events(str(tmp_path / f"trace-driver{i}.json"))
                if e.get("ph") == "X"
            }
        stats = trace_stats(trace_files)
        assert stats["complete_paths"] >= 1, stats
        end_to_end = [
            g
            for g in stats["merged_traces"]
            if g["complete"]
            and set(g["pids"]) & driver_pids
            and set(g["pids"]) & helper_pids
        ]
        assert end_to_end, (
            "no complete upload->collection path crosses a driver binary "
            "and the helper",
            stats,
        )
        durations = end_to_end[0]["durations_s"]
        assert durations["upload_to_collection"] > 0, durations
    finally:
        reps.terminate_all()
        leader_ds.close()
        helper_ds.close()
        configure_chrome_trace(None)


# ---------------------------------------------------------------------------
# zero-copy ingest: SIGKILL between ACK and materialization (ISSUE 18), with
# the GC loop live through the whole replay window (ROADMAP direction 4)


@pytest.mark.slow
def test_journaled_ingest_sigkill_replay_exactly_once_with_gc(tmp_path):
    """THE INGEST CRASH CASE (ISSUE 18 acceptance): an aggregator binary
    in journaled mode ACKs uploads off the report-journal write alone
    (materializer and staged consumer are parked far out, so every
    admitted report sits in the replay window), is SIGKILLed there, and
    the restarted incarnation's startup replay materializes every row —
    zero admitted-then-lost.  The GC loop runs at 0.2s the WHOLE time
    (ROADMAP direction 4's GC-mid-SIGKILL case): it provably executes
    deletions (an aged decoy report is reaped) yet never touches a
    journal row inside the replay window.  Re-uploading every ACKed
    report after recovery changes nothing (cross-crash, cross-path
    dedup), the upload-success counter reads exactly N, and the creator
    then packs each report into exactly one aggregation job."""
    import asyncio

    from janus_tpu.aggregator import AggregationJobCreator, CreatorConfig
    from janus_tpu.aggregator.report_writer import ReportWriteBatcher
    from janus_tpu.client import prepare_report
    from janus_tpu.messages import InputShareAad

    key = generate_key()
    leader_db = str(tmp_path / "leader.sqlite3")
    agg_port, agg_health = _free_port(), _free_port()

    clock = RealClock()
    leader_ds = Datastore(leader_db, Crypter([key]), clock)
    agg_token = AuthenticationToken.new_bearer("agg-token-ingest")
    collector_keys = HpkeKeypair.generate(9)
    now = clock.now()
    report_time = Time(now.seconds - now.seconds % TIME_PRECISION.seconds)

    task_id = TaskId.random()
    leader_kp, helper_kp = HpkeKeypair.generate(1), HpkeKeypair.generate(2)
    leader_task = AggregatorTask(
        task_id=task_id,
        peer_aggregator_endpoint="http://127.0.0.1:1/",  # never called
        role=Role.LEADER,
        aggregator_auth_token=agg_token,
        hpke_keys=[leader_kp],
        query_type=TaskQueryType.time_interval(),
        vdaf={"type": "Prio3Count"},
        vdaf_verify_key=bytes([0x60]) * 16,
        min_batch_size=1,
        time_precision=TIME_PRECISION,
        collector_hpke_config=collector_keys.config,
        report_expiry_age=Duration(2 * 3600),
    )
    leader_ds.run_tx("putl", lambda tx: tx.put_aggregator_task(leader_task))

    vdaf = leader_task.vdaf_instance()

    def _sealed(m, time):
        return prepare_report(
            vdaf,
            task_id,
            leader_kp.config,
            helper_kp.config,
            TIME_PRECISION,
            m,
            time=time,
        )

    # the GC BAIT: an aged report written straight into client_reports
    # (the upload path would reject it as expired) — its disappearance is
    # the proof that the 0.2s GC loop is executing real deletions while
    # the journal rows sit in the replay window beside it
    decoy = _sealed(1, Time(report_time.seconds - 3 * 3600))
    aad = InputShareAad(task_id, decoy.metadata, decoy.public_share).get_encoded()
    info = HpkeApplicationInfo.new(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER)
    plain = PlaintextInputShare.get_decoded(
        open_(leader_kp, info, decoy.leader_encrypted_input_share, aad)
    )
    asyncio.run(
        ReportWriteBatcher(leader_ds, max_batch_size=1).write_report(
            LeaderStoredReport(
                task_id=task_id,
                metadata=decoy.metadata,
                public_share=decoy.public_share,
                leader_extensions=[],
                leader_input_share=plain.payload,
                helper_encrypted_input_share=decoy.helper_encrypted_input_share,
            )
        )
    )

    measurements = [1, 0, 1, 1, 0, 1, 1, 1]
    N = len(measurements)
    encodeds = [_sealed(m, report_time).get_encoded() for m in measurements]

    def _success_total():
        return _sql(
            leader_db,
            "SELECT COALESCE(SUM(report_success), 0) FROM task_upload_counters",
        )[0][0]

    success_before = _success_total()  # the decoy's seed write counted one

    cfg = tmp_path / "ingest-agg.yaml"
    cfg.write_text(
        f"""
common:
  database: {{path: {leader_db}}}
  health_check_listen_address: 127.0.0.1:{agg_health}
  status_sample_interval_s: 0.5
listen_address: 127.0.0.1:{agg_port}
vdaf_backend: oracle
upload_open_batch_delay_ms: 2
garbage_collection_interval_s: 0.2
ingest:
  mode: journaled
  journal_write_delay_ms: 5
  materialize_interval_ms: 600000
  staged_consume_interval_ms: 600000
"""
    )

    env = dict(os.environ)
    env["DATASTORE_KEYS"] = base64.urlsafe_b64encode(key).decode().rstrip("=")
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    def _spawn(tag):
        log = open(tmp_path / f"{tag}.log", "wb")
        return subprocess.Popen(
            [sys.executable, "-c", _BOOT, "aggregator", "--config-file", str(cfg)],
            env=env,
            cwd=str(REPO),
            stdout=log,
            stderr=subprocess.STDOUT,
        )

    def _put_report(encoded):
        req = urllib.request.Request(
            f"http://127.0.0.1:{agg_port}/tasks/{task_id}/reports",
            data=encoded,
            method="PUT",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status

    def _journal():
        return _sql(leader_db, "SELECT COUNT(*) FROM report_journal")[0][0]

    def _reports_rows():
        return _sql(leader_db, "SELECT COUNT(*) FROM client_reports")[0][0]

    proc = _spawn("ingest-agg-1")
    try:
        _wait_http(f"http://127.0.0.1:{agg_health}/healthz", 120)
        for enc in encodeds:
            assert _put_report(enc) == 201
        # ACK semantics: every 201 above returned only after its journal
        # row committed — and with the materializer parked, the journal
        # IS the only durable home of the admitted reports
        assert _journal() == N
        # GC provably executes during the window: the aged decoy goes...
        deadline = time.monotonic() + 60
        while _reports_rows() > 0:
            assert time.monotonic() < deadline, "GC never reaped the aged decoy"
            time.sleep(0.2)
        # ...while several more GC passes never touch a journal row
        time.sleep(1.0)
        assert _journal() == N
        # the replica's own /statusz sees the replay window (shared
        # datastore section) and reports the journaled ingest plane
        with urllib.request.urlopen(
            f"http://127.0.0.1:{agg_health}/statusz", timeout=10
        ) as r:
            doc = json.loads(r.read().decode())
        assert doc["report_journal"]["outstanding_rows"] == N, doc["report_journal"]
        assert doc["ingest"]["mode"] == "journaled", doc["ingest"]

        # -- SIGKILL between ACK and materialization ------------------------
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        assert _journal() == N, "journal rows must survive the SIGKILL"
        assert _reports_rows() == 0, "nothing materialized before the crash"

        # -- restart: startup replay drains the journal, GC still live ------
        proc = _spawn("ingest-agg-2")
        _wait_http(f"http://127.0.0.1:{agg_health}/healthz", 120)
        deadline = time.monotonic() + 120
        while _journal() > 0:
            assert time.monotonic() < deadline, "startup replay never drained"
            time.sleep(0.2)
        assert _reports_rows() == N, "zero admitted-then-lost after replay"
        # several GC cycles post-replay: fresh reports stay put
        time.sleep(1.0)
        assert _reports_rows() == N

        # -- duplicate re-uploads after the crash change NOTHING ------------
        for enc in encodeds:
            assert _put_report(enc) == 201
        assert _journal() == 0
        assert _reports_rows() == N
        # exactly-once admission accounting across crash + duplicates
        assert _success_total() - success_before == N
        # the survivor's replay counter moved by exactly the orphan count
        scraped = _scrape(agg_health)
        assert (
            _metric_total(scraped, "janus_ingest_journal_replayed_total") == N
        ), scraped

        # graceful close-out: SIGTERM drains the (empty) plane cleanly
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == 0, "SIGTERM exit must be clean"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    # -- exactly-once collection: each report lands in ONE job --------------
    creator = AggregationJobCreator(
        leader_ds,
        CreatorConfig(min_aggregation_job_size=1, max_aggregation_job_size=3),
    )
    n_jobs = asyncio.run(creator.run_once())
    assert n_jobs >= 1, n_jobs
    total, distinct = _sql(
        leader_db,
        "SELECT COUNT(*), COUNT(DISTINCT report_id) FROM report_aggregations",
    )[0]
    assert total == N and distinct == N, (total, distinct)
    leader_ds.close()


# ---------------------------------------------------------------------------
# flight recorder SIGKILL semantics + per-task cost attribution (ISSUE 12)


@pytest.mark.slow
def test_flight_recorder_sigkill_semantics_and_per_task_cost(tmp_path):
    """The flight recorder is deliberately in-memory: a fresh binary
    starts an EMPTY ring (probed on a just-started driver before any job
    exists), a SIGKILLed binary's records die with it (the survivor's
    ring carries only its OWN flushes), and the survivor's breaker trip
    dumps the ring EXACTLY ONCE into its log.  After recovery, the
    per-task cost series prove the failure-domain shift: every seeded
    task has device-seconds > 0, attributed on the oracle path the open
    breaker degraded it to."""
    import asyncio

    from janus_tpu.aggregator import AggregationJobCreator, CreatorConfig
    from janus_tpu.aggregator.report_writer import ReportWriteBatcher
    from janus_tpu.client import prepare_report
    from janus_tpu.executor.flight_recorder import DUMP_MARKER
    from janus_tpu.messages import InputShareAad

    key = generate_key()
    leader_db = str(tmp_path / "leader.sqlite3")
    helper_db = str(tmp_path / "helper.sqlite3")
    helper_port, helper_health = _free_port(), _free_port()
    driver_health = [_free_port(), _free_port()]

    clock = RealClock()
    leader_ds = Datastore(leader_db, Crypter([key]), clock)
    helper_ds = Datastore(helper_db, Crypter([key]), clock)
    agg_token = AuthenticationToken.new_bearer("agg-token-flights")
    collector_keys = HpkeKeypair.generate(9)
    now = clock.now()
    report_time = Time(now.seconds - now.seconds % TIME_PRECISION.seconds)

    n_tasks = 2
    tasks = []
    for t in range(n_tasks):
        task_id = TaskId.random()
        common = dict(
            task_id=task_id,
            query_type=TaskQueryType.time_interval(),
            vdaf={"type": "Prio3Count"},
            vdaf_verify_key=bytes([0x50 + t]) * 16,
            min_batch_size=3,
            time_precision=TIME_PRECISION,
            collector_hpke_config=collector_keys.config,
        )
        leader_kp, helper_kp = HpkeKeypair.generate(1), HpkeKeypair.generate(2)
        leader_task = AggregatorTask(
            peer_aggregator_endpoint=f"http://127.0.0.1:{helper_port}/",
            role=Role.LEADER,
            aggregator_auth_token=agg_token,
            hpke_keys=[leader_kp],
            **common,
        )
        helper_task = AggregatorTask(
            peer_aggregator_endpoint="http://127.0.0.1:1/",
            role=Role.HELPER,
            aggregator_auth_token_hash=agg_token.hash(),
            hpke_keys=[helper_kp],
            **common,
        )
        leader_ds.run_tx("putl", lambda tx, lt=leader_task: tx.put_aggregator_task(lt))
        helper_ds.run_tx("puth", lambda tx, ht=helper_task: tx.put_aggregator_task(ht))
        tasks.append((task_id, leader_task, leader_kp, helper_kp))

    def seed_report(t, m):
        task_id, leader_task, leader_kp, helper_kp = tasks[t]
        vdaf = leader_task.vdaf_instance()
        report = prepare_report(
            vdaf,
            task_id,
            leader_kp.config,
            helper_kp.config,
            TIME_PRECISION,
            m,
            time=report_time,
        )
        aad = InputShareAad(
            task_id, report.metadata, report.public_share
        ).get_encoded()
        info = HpkeApplicationInfo.new(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER)
        plain = PlaintextInputShare.get_decoded(
            open_(leader_kp, info, report.leader_encrypted_input_share, aad)
        )
        stored = LeaderStoredReport(
            task_id=task_id,
            metadata=report.metadata,
            public_share=report.public_share,
            leader_extensions=[],
            leader_input_share=plain.payload,
            helper_encrypted_input_share=report.helper_encrypted_input_share,
        )
        asyncio.run(
            ReportWriteBatcher(leader_ds, max_batch_size=1).write_report(stored)
        )

    for t in range(n_tasks):
        for m in (1, 0, 1):
            seed_report(t, m)

    # -- replica configs ----------------------------------------------------
    def driver_yaml(i):
        if i == 0:  # the WEDGER: every flush parks for 600s mid-step
            fault_point = "executor.flush: {mode: delay, probability: 1.0, delay_s: 600}"
        else:  # the SURVIVOR: every device launch fails -> breaker trip
            fault_point = "backend.launch: {mode: error, probability: 1.0}"
        return f"""
common:
  database: {{path: {leader_db}}}
  health_check_listen_address: 127.0.0.1:{driver_health[i]}
  status_sample_interval_s: 0.5
  fault_injection:
    enabled: true
    seed: {SEED}
    points:
      {fault_point}
job_driver:
  job_discovery_interval_s: 0.2
  max_concurrent_job_workers: 2
  worker_lease_duration_s: 5
  worker_lease_clock_skew_allowance_s: 1
  maximum_attempts_before_failure: 100000
  max_step_attempts: 100000
  retry_initial_delay_s: 0.5
  retry_max_delay_s: 1.0
  lease_reap_interval_s: 0.1
vdaf_backend: tpu
device_executor:
  enabled: true
  flush_window_ms: 20
  flush_max_rows: 4096
  breaker_failure_threshold: 1
  breaker_reset_timeout_s: 3600
"""

    helper_yaml = f"""
common:
  database: {{path: {helper_db}}}
  health_check_listen_address: 127.0.0.1:{helper_health}
listen_address: 127.0.0.1:{helper_port}
vdaf_backend: oracle
"""
    cfg_paths = []
    for i in range(2):
        p = tmp_path / f"driver{i}.yaml"
        p.write_text(driver_yaml(i))
        cfg_paths.append(p)
    helper_cfg = tmp_path / "helper.yaml"
    helper_cfg.write_text(helper_yaml)

    env = dict(os.environ)
    env["DATASTORE_KEYS"] = base64.urlsafe_b64encode(key).decode().rstrip("=")
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    def _statusz(port):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statusz", timeout=10
        ) as r:
            return json.loads(r.read().decode())

    def _unfinished():
        return _sql(
            leader_db,
            "SELECT COUNT(*) FROM aggregation_jobs WHERE state = 'InProgress'",
        )[0][0]

    def _task_seconds_from_scrape(text, label):
        total = 0.0
        for line in text.splitlines():
            if line.startswith("janus_task_device_seconds_total{") and (
                f'task="{label}"' in line
            ):
                total += float(line.rsplit(" ", 1)[1])
        return total

    reps = _Replicas(env, cfg_paths, helper_cfg, tmp_path)
    try:
        reps.start_helper()
        _wait_http(f"http://127.0.0.1:{helper_health}/healthz", 120)

        # -- binary #1 starts BEFORE any job exists: a fresh binary's
        # flight ring is EMPTY (deterministic probe, nothing to flush yet)
        reps.start_driver(0)
        _wait_http(f"http://127.0.0.1:{driver_health[0]}/healthz", 120)
        doc = _statusz(driver_health[0])
        flights = doc["executor"]["flights"]
        assert flights["recorded"] == 0 and flights["records"] == [], flights
        assert doc["executor"]["cost_attribution"]["tracked"] == 0

        # jobs appear; the wedger acquires and parks mid-flush (the
        # injected 600s executor.flush delay) — a wedged flush never
        # COMPLETES, so its ring stays empty right up to the SIGKILL
        creator = AggregationJobCreator(
            leader_ds,
            CreatorConfig(min_aggregation_job_size=1, max_aggregation_job_size=3),
        )
        n_jobs = asyncio.run(creator.run_once())
        assert n_jobs == n_tasks, n_jobs

        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if _statusz(driver_health[0])["faults"]["hits"].get("executor.flush", 0):
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("the wedger never reached its flush fault")
        assert _statusz(driver_health[0])["executor"]["flights"]["recorded"] == 0

        # -- SIGKILL the wedger; its in-memory ring dies with it --------
        reps.kill_driver(0)

        # -- binary #2 (the survivor): launch faults trip the breaker,
        # jobs degrade to the per-task-attributed oracle, and converge
        reps.start_driver(1)
        _wait_http(f"http://127.0.0.1:{driver_health[1]}/healthz", 120)
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if _unfinished() == 0:
                break
            time.sleep(0.3)
        assert _unfinished() == 0, "survivor never converged on the oracle path"

        # the survivor's ring carries ONLY its own flushes (SIGKILL
        # semantics: nothing leaked over from binary #1's incarnation),
        # and each is the error-outcome record of its own launch faults
        doc = _statusz(driver_health[1])
        records = doc["executor"]["flights"]["records"]
        assert records, "survivor must have recorded its failing flushes"
        assert all(r["outcome"] == "error" and r["fault"] for r in records), records
        assert doc["executor"]["flights"]["dumps"] == {"breaker_trip": 1}, doc[
            "executor"
        ]["flights"]

        # per-task device-seconds > 0 for EVERY seeded task after
        # recovery — and specifically on the ORACLE path (the breaker
        # cost shift the series exist to show)
        scraped = _scrape(driver_health[1])
        for task_id, _lt, _lk, _hk in tasks:
            label = str(task_id)
            assert _task_seconds_from_scrape(scraped, label) > 0, label
            oracle_line = [
                line
                for line in scraped.splitlines()
                if line.startswith("janus_task_device_seconds_total{")
                and f'task="{label}"' in line
                and 'path="oracle"' in line
            ]
            assert oracle_line, f"task {label} has no oracle-path attribution"
    finally:
        reps.terminate_all()

    # -- the dump appears EXACTLY ONCE in the survivor's log ------------
    def _dump_lines(tag):
        lines = []
        for log in sorted(tmp_path.glob(f"{tag}-*.log")):
            lines += [
                line
                for line in log.read_text(errors="replace").splitlines()
                if DUMP_MARKER in line
            ]
        return lines

    survivor_dumps = _dump_lines("driver1")
    assert len(survivor_dumps) == 1, survivor_dumps
    payload = json.loads(survivor_dumps[0].split(DUMP_MARKER, 1)[1])
    assert payload["reason"] == "breaker_trip"
    assert payload["flights"], "the dump must carry the ring that led to the trip"
    assert all(f["outcome"] == "error" for f in payload["flights"])
    # the wedger never completed a flush, never tripped: zero dumps
    assert _dump_lines("driver0") == []
    leader_ds.close()
    helper_ds.close()
