"""Device-resident accumulator store (ISSUE 3, janus_tpu/executor/accumulator.py).

Layers, cheapest first:

* store semantics against a fake numpy backend — commit/drain round trip,
  flush-matrix lifecycle, LRU eviction under a tiny byte budget, poisoned-
  bucket discard (the mirror-delta journal's exactly-once contract),
  injected mid-spill faults;
* the fair flush scheduler: a hot bucket cannot starve a second bucket's
  flush past its deadline slot;
* writer-side delta resolution: StaleAccumulatorDelta on any mismatch
  between the drained delta and the reports surviving the tx;
* the real-backend acceptance path (TpuBackend on Prio3Count): executor
  flushes with the store attached perform ZERO device->host out-share
  readbacks (``outshare_readback_rows`` stays 0), commit-time spill is
  bit-exact vs the CPU oracle, and the breaker/launch-failure replay
  re-derives the journaled reports on the oracle without double-counting.

The end-to-end chaos condition (spill/evict faults firing during a 2-replica
soak, aggregates exact) rides tests/test_chaos.py's soak, which now runs
with the accumulator enabled and a 256-byte budget.
"""

import asyncio
import threading

import numpy as np
import pytest

from janus_tpu.core import faults
from janus_tpu.core.faults import FaultSpec
from janus_tpu.executor import (
    AccumulatorConfig,
    AccumulatorUnavailable,
    DeviceAccumulatorStore,
    DeviceExecutor,
    ExecutorConfig,
    ResidentRef,
    StaleAccumulatorDelta,
    reset_global_executor,
)
from janus_tpu.utils.test_util import det_rng
from janus_tpu.vdaf.instances import prio3_count


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    yield
    faults.clear()
    reset_global_executor()


def _run(coro, timeout=120.0):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


# -- fake backend ------------------------------------------------------------


class _Field:
    """Tiny exact field double: plain integer adds (values stay small)."""

    @staticmethod
    def vec_add(a, b):
        return [x + y for x, y in zip(a, b)]


class _FakeFlp:
    OUTPUT_LEN = 2
    field = _Field


class _FakeVdaf:
    flp = _FakeFlp


class _AccumBackend:
    """Store seam double: numpy matrices, integer sums, no jax."""

    supports_resident_out_shares = True

    def __init__(self):
        self.vdaf = _FakeVdaf()
        self.accum_launches = 0
        self.fail_accumulate = False
        self.fail_read = False

    def accumulate_rows(self, buffer, matrix, mask):
        self.accum_launches += 1
        if self.fail_accumulate:
            raise RuntimeError("device on fire")
        delta = np.asarray(matrix)[mask].sum(axis=0)
        return delta if buffer is None else buffer + delta

    def read_accum_buffer(self, buffer):
        if self.fail_read:
            raise RuntimeError("device on fire")
        return [int(x) for x in np.asarray(buffer)]


def _matrix(rows, width=2, base=1):
    """Row r holds [base*(r+1), base*(r+1)*10] — distinct, easy sums."""
    return np.array(
        [[base * (r + 1), base * (r + 1) * 10] for r in range(rows)], dtype=np.int64
    )


# -- store semantics ---------------------------------------------------------


def test_commit_drain_round_trip_and_flush_lifecycle():
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True))
    backend = _AccumBackend()
    m = _matrix(4)
    fid = store.retain_flush(backend, m, rows=4, nbytes=m.nbytes)

    store.commit_rows(
        ("bucket-a",),
        backend,
        [ResidentRef(fid, 0), ResidentRef(fid, 2)],
        job_token=b"job1",
        report_ids=[b"r0", b"r2"],
    )
    # rows 1 and 3 never finish: released, which frees the flush matrix
    store.release_refs([ResidentRef(fid, 1), ResidentRef(fid, 3)])
    assert store.stats()["flushes_resident"] == 0

    vector, rids = store.drain(("bucket-a",), _Field)
    assert vector == [1 + 3, 10 + 30]
    assert rids == {b"r0", b"r2"}
    assert store.stats()["buckets"] == 0
    # a second drain has nothing: the delta can never merge twice
    assert store.drain(("bucket-a",), _Field) is None


def test_cross_flush_residency_accumulates_across_commits():
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True))
    backend = _AccumBackend()
    f1 = store.retain_flush(backend, _matrix(2), rows=2, nbytes=32)
    f2 = store.retain_flush(backend, _matrix(2, base=100), rows=2, nbytes=32)
    store.commit_rows(
        ("b",), backend, [ResidentRef(f1, 0)], job_token=b"j1", report_ids=[b"a"]
    )
    store.commit_rows(
        ("b",), backend, [ResidentRef(f2, 1)], job_token=b"j2", report_ids=[b"b"]
    )
    store.release_refs([ResidentRef(f1, 1), ResidentRef(f2, 0)])
    vector, rids = store.drain(("b",), _Field)
    assert vector == [1 + 200, 10 + 2000]
    assert rids == {b"a", b"b"}


def test_eviction_under_tiny_byte_budget_stays_exact():
    """LRU eviction spills flush matrices and bucket buffers to host
    mirrors; sums stay exact and the eviction counter moves."""
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True, byte_budget=40))
    backend = _AccumBackend()
    m1 = _matrix(2)
    f1 = store.retain_flush(backend, m1, rows=2, nbytes=32)
    f2 = store.retain_flush(backend, _matrix(2, base=100), rows=2, nbytes=32)
    # the budget is now blown; the next store op evicts LRU state (f1's
    # matrix spills to host) BEFORE mutating anything
    store.commit_rows(
        ("b",),
        backend,
        [ResidentRef(f1, 0), ResidentRef(f1, 1)],
        job_token=b"j1",
        report_ids=[b"a", b"b"],
    )
    assert store.evictions >= 1
    store.commit_rows(
        ("b",), backend, [ResidentRef(f2, 0)], job_token=b"j2", report_ids=[b"c"]
    )
    store.release_refs([ResidentRef(f2, 1)])
    vector, rids = store.drain(("b",), _Field)
    assert vector == [1 + 2 + 100, 10 + 20 + 1000]
    assert rids == {b"a", b"b", b"c"}


def test_bucket_buffer_eviction_merges_host_and_device_state():
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True, byte_budget=0))
    backend = _AccumBackend()
    f1 = store.retain_flush(backend, _matrix(1), rows=1, nbytes=8)
    store.commit_rows(
        ("b",), backend, [ResidentRef(f1, 0)], job_token=b"j1", report_ids=[b"a"]
    )
    bucket = store._buckets[("b",)]
    store._evict(bucket)  # force the buffer to its host mirror
    assert bucket.buffer is None and bucket.spilled_host == [1, 10]
    f2 = store.retain_flush(backend, _matrix(1, base=7), rows=1, nbytes=8)
    store.commit_rows(
        ("b",), backend, [ResidentRef(f2, 0)], job_token=b"j2", report_ids=[b"b"]
    )
    vector, rids = store.drain(("b",), _Field)
    assert vector == [1 + 7, 10 + 70]
    assert rids == {b"a", b"b"}


def test_poisoned_bucket_discard_returns_journal_exactly_once():
    """The mirror-delta journal contract: a failed accumulate poisons the
    bucket; discard() hands back the journaled (job, rids) ONCE and drops
    the device delta so nothing can double-count."""
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True))
    backend = _AccumBackend()
    fid = store.retain_flush(backend, _matrix(2), rows=2, nbytes=32)
    store.commit_rows(
        ("b",), backend, [ResidentRef(fid, 0)], job_token=b"j1", report_ids=[b"a"]
    )
    backend.fail_accumulate = True
    with pytest.raises(AccumulatorUnavailable):
        store.commit_rows(
            ("b",), backend, [ResidentRef(fid, 1)], job_token=b"j2", report_ids=[b"b"]
        )
    # the bucket is poisoned: drains refuse rather than return a half sum
    with pytest.raises(AccumulatorUnavailable):
        store.drain(("b",), _Field)
    journal = store.discard(("b",))
    assert [(tok, set(ids)) for tok, ids in journal] == [(b"j1", {b"a"})]
    assert store.discard(("b",)) == []  # exactly once
    assert store.stats()["buckets"] == 0


def test_injected_spill_fault_surfaces_as_unavailable():
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True))
    backend = _AccumBackend()
    fid = store.retain_flush(backend, _matrix(1), rows=1, nbytes=8)
    store.commit_rows(
        ("b",), backend, [ResidentRef(fid, 0)], job_token=b"j", report_ids=[b"a"]
    )
    faults.configure([FaultSpec("accumulator.spill", "error", 1.0)], seed=7)
    with pytest.raises(AccumulatorUnavailable):
        store.drain(("b",), _Field)
    faults.clear()
    # recovery path: discard + journal replay (no partial drain escaped)
    journal = store.discard(("b",))
    assert [set(ids) for _tok, ids in journal] == [{b"a"}]


def test_injected_evict_fault_fires_before_any_mutation():
    """An eviction fault must leave the commit cleanly un-applied (no
    journal entry, no half-updated buffer) — exactly-once recovery
    depends on failures never firing after state mutated."""
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True, byte_budget=8))
    backend = _AccumBackend()
    fid = store.retain_flush(backend, _matrix(2), rows=2, nbytes=32)
    faults.configure([FaultSpec("accumulator.evict", "error", 1.0)], seed=7)
    with pytest.raises(faults.FaultInjectedError):
        store.commit_rows(
            ("b",), backend, [ResidentRef(fid, 0)], job_token=b"j", report_ids=[b"a"]
        )
    faults.clear()
    assert ("b",) not in store._buckets, "failed commit must not journal"
    assert store._flushes[fid].consumed == set(), "refs must stay live"


# -- agg-param-keyed host buckets (ISSUE 10) ---------------------------------


def test_host_rows_level_keyed_buckets_never_merge_and_drain_all_spills():
    """The agg-param element of the bucket key is the level fence: one
    task's level-k and level-(k+1) deltas live in distinct buckets with
    independent journals; two jobs at ONE level share a bucket (one
    drained vector covering both journal rows); and drain_all reaches
    host buckets through their stored field (no minting backend)."""
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True))
    base = ("leader", b"task", ("Poplar1", None), b"batch")
    k_lvl1 = base + (b"\x00\x01prefixes",)
    k_lvl2 = base + (b"\x00\x02prefixes",)
    store.commit_host_rows(
        k_lvl1, _Field, [[1, 10], [2, 20]], job_token=b"j1", report_ids=[b"a", b"b"]
    )
    store.commit_host_rows(
        k_lvl1, _Field, [[3, 30]], job_token=b"j2", report_ids=[b"c"]
    )
    store.commit_host_rows(
        k_lvl2, _Field, [[100, 1]], job_token=b"j3", report_ids=[b"a"]
    )
    assert store.stats()["buckets"] == 2, "levels must never share a bucket"

    spilled = {}
    store.drain_all(
        lambda key, vector, journal: spilled.update({key: (vector, journal)})
    )
    assert set(spilled) == {k_lvl1, k_lvl2}
    v1, journal1 = spilled[k_lvl1]
    assert v1 == [6, 60], "same-level jobs merge into ONE vector"
    assert [j for j, _ in journal1] == [b"j1", b"j2"]
    v2, journal2 = spilled[k_lvl2]
    assert v2 == [100, 1] and [j for j, _ in journal2] == [b"j3"]
    assert store.stats()["buckets"] == 0


def test_host_rows_commit_after_poison_raises_and_journal_survives_discard():
    """Exactly-once plumbing parity with device buckets: a poisoned host
    bucket refuses commits, and discard returns the journal so the caller
    can replay from the datastore."""
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True))
    key = ("leader", b"t", ("Poplar1", None), b"b", b"\x00\x05p")
    store.commit_host_rows(key, _Field, [[5, 50]], job_token=b"j1", report_ids=[b"r"])
    with store._lock:
        store._buckets[key].poisoned = True
    with pytest.raises(AccumulatorUnavailable):
        store.commit_host_rows(
            key, _Field, [[7, 70]], job_token=b"j2", report_ids=[b"q"]
        )
    journal = store.discard(key)
    assert [(j, set(r)) for j, r in journal] == [(b"j1", {b"r"})]


def test_host_rows_vector_report_mismatch_rejected():
    from janus_tpu.executor import AccumulatorError

    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True))
    with pytest.raises(AccumulatorError):
        store.commit_host_rows(
            ("k",), _Field, [[1]], job_token=b"j", report_ids=[b"a", b"b"]
        )


# -- fair flush scheduling ---------------------------------------------------


class _GatedPrepBackend:
    """test_executor-style stage/launch double with a launch gate and an
    order log, for scheduler-order assertions."""

    class _V:
        pass

    def __init__(self, gate):
        self.vdaf = self._V()
        self.gate = gate
        self.launch_order = []

    def stage_prep_init_multi(self, agg_id, requests, pad_to=None):
        from types import SimpleNamespace

        rows = sum(len(r) for _, r in requests)
        if rows == 0:
            return None
        return SimpleNamespace(agg_id=agg_id, placed=None, pad_to=rows, rows=rows)

    def launch_prep_init_multi(self, staged, requests):
        assert self.gate.wait(10), "test launch gate never opened"
        self.launch_order.append(requests[0][0])
        return [
            [("prep", vk, i) for i in range(len(reports))]
            for vk, reports in requests
        ]


def test_fair_scheduler_hot_bucket_cannot_starve_cold_flush():
    """Four hot-bucket flushes are ready before the cold bucket's one; FIFO
    would launch the cold flush LAST, the deficit round-robin must
    interleave it ahead of the hot tail."""
    gate = threading.Event()
    backend = _GatedPrepBackend(gate)
    ex = DeviceExecutor(
        ExecutorConfig(flush_window_s=60.0, flush_max_rows=2, fair_quota_rows=4)
    )

    async def go():
        hot = [
            asyncio.ensure_future(
                ex.submit(
                    ("hot",), "prep_init", (b"h%d" % i, [0, 1]), backend=backend
                )
            )
            for i in range(4)
        ]
        await asyncio.sleep(0.05)  # all four hot size-flushes are ready
        cold = asyncio.ensure_future(
            ex.submit(("cold",), "prep_init", (b"c0", [0, 1]), backend=backend)
        )
        await asyncio.sleep(0.05)
        gate.set()
        await asyncio.gather(*hot, cold)

    _run(go())
    ex.shutdown()
    order = backend.launch_order
    assert len(order) == 5
    assert order.index(b"c0") < len(order) - 1, (
        f"cold flush starved to the back of the line: {order}"
    )


def test_legacy_fifo_mode_still_available():
    gate = threading.Event()
    gate.set()
    backend = _GatedPrepBackend(gate)
    ex = DeviceExecutor(
        ExecutorConfig(flush_window_s=0.005, flush_max_rows=1024, fair_flush=False)
    )

    async def go():
        return await ex.submit(("s",), "prep_init", (b"k", [0, 1]), backend=backend)

    out = _run(go())
    ex.shutdown()
    assert len(out) == 2


# -- writer-side delta resolution -------------------------------------------


def test_writer_resolves_delta_and_rejects_stale_sets():
    from janus_tpu.aggregator.aggregation_job_writer import AggregationJobWriter

    writer = AggregationJobWriter(
        task=None,
        vdaf=None,
        accumulator_deltas={b"ident": ([5, 50], frozenset({b"r1", b"r2"}))},
    )
    refs = [ResidentRef(0, 0), ResidentRef(0, 1)]
    got = writer._resolve_shares(_Field, b"ident", refs, [b"r1", b"r2"])
    assert got == [5, 50]
    # mixed host + resident rows: delta and host vectors add
    got = writer._resolve_shares(
        _Field, b"ident", refs + [[1, 1]], [b"r1", b"r2", b"r3"]
    )
    assert got == [6, 51]
    # a report failed in-tx after its row was drained -> abort the tx
    with pytest.raises(StaleAccumulatorDelta):
        writer._resolve_shares(_Field, b"ident", [refs[0]], [b"r1"])
    # unknown batch ident -> no delta at all
    with pytest.raises(StaleAccumulatorDelta):
        writer._resolve_shares(_Field, b"other", refs, [b"r1", b"r2"])


# -- real backend: zero-readback flushes + bit-exact spill + oracle replay ---


@pytest.fixture(scope="module")
def count_backend():
    from janus_tpu.vdaf.backend import TpuBackend

    return TpuBackend(prio3_count())


def _count_reports(vdaf, n, seed):
    rng = det_rng(seed)
    rows = []
    for i in range(n):
        nonce = rng(vdaf.NONCE_SIZE)
        ps, shares = vdaf.shard(i % 2, nonce, rng(vdaf.RAND_SIZE))
        rows.append((nonce, ps, shares[0]))
    return rows


def test_resident_flush_zero_readback_and_bit_exact_drain(count_backend):
    """THE ACCEPTANCE PATH: with the store attached, executor flushes read
    back zero out-share rows; the commit-time spill equals the CPU
    oracle's field sum exactly."""
    from janus_tpu.vdaf.backend import OracleBackend

    vdaf = count_backend.vdaf
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True))
    ex = DeviceExecutor(ExecutorConfig(flush_window_s=0.02, flush_max_rows=1024))
    ex.accumulator = store
    vk = b"\x2a" * vdaf.VERIFY_KEY_SIZE
    reports = _count_reports(vdaf, 5, "resident")
    count_backend.outshare_readback_rows = 0

    async def go():
        return await ex.submit(
            ("count",),
            "prep_init",
            (vk, reports),
            backend=count_backend,
            retain_out_shares=True,
        )

    out = _run(go())
    assert count_backend.outshare_readback_rows == 0, (
        "device-resident flush must not read out shares back"
    )
    refs = [state.out_share for state, _share in out]
    assert all(isinstance(r, ResidentRef) for r in refs)

    rids = [r[0] for r in reports]
    store.commit_rows(
        ("bucket",), count_backend, refs, job_token=b"job", report_ids=rids
    )
    field = vdaf.flp.field
    vector, drained_rids = store.drain(("bucket",), field)
    ex.shutdown()
    want = vdaf.aggregate(
        [
            state.out_share
            for state, _ in OracleBackend(vdaf).prep_init_batch(vk, 0, reports)
        ]
    )
    assert vector == want, "spill-on-commit must be bit-exact vs the oracle"
    assert drained_rids == set(rids)
    assert store.stats()["flushes_resident"] == 0


def test_driver_breaker_replay_recovers_via_oracle(count_backend):
    """Launch-failure recovery at the DRIVER layer: commit_rows dies, the
    journal replays through the CPU oracle, out_shares become host
    vectors, and nothing is left resident to double-count."""
    from janus_tpu.aggregator.aggregation_job_driver import (
        AggregationJobDriver,
        DriverConfig,
    )
    from janus_tpu.datastore import (
        AggregationJob,
        AggregationJobState,
        ReportAggregation,
        ReportAggregationState,
    )
    from janus_tpu.datastore.task import AggregatorTask, TaskQueryType
    from janus_tpu.messages import (
        AggregationJobId,
        AggregationJobStep,
        Duration,
        Interval,
        ReportId,
        Role,
        TaskId,
        Time,
    )
    from janus_tpu.vdaf import pingpong as pp
    from janus_tpu.vdaf.backend import OracleBackend

    vdaf = count_backend.vdaf
    reset_global_executor()
    driver = AggregationJobDriver(
        datastore=None,
        session_factory=None,
        config=DriverConfig(
            vdaf_backend="tpu",
            device_executor=ExecutorConfig(
                enabled=True,
                flush_window_s=0.02,
                flush_max_rows=1024,
                accumulator=AccumulatorConfig(enabled=True),
            ),
        ),
    )
    store = driver._executor.accumulator
    assert store is not None
    key = AggregationJobDriver._vdaf_shape_key(vdaf)
    driver._backends[key] = count_backend

    task = AggregatorTask(
        task_id=TaskId.random(),
        peer_aggregator_endpoint="http://helper.invalid/",
        query_type=TaskQueryType.time_interval(),
        vdaf={"type": "Prio3Count"},
        role=Role.LEADER,
        vdaf_verify_key=b"\x2a" * 16,
        min_batch_size=1,
        time_precision=Duration(3600),
    )
    now = Time(1_600_000_000)
    job = AggregationJob(
        task_id=task.task_id,
        aggregation_job_id=AggregationJobId.random(),
        aggregation_parameter=b"",
        partial_batch_identifier=None,
        client_timestamp_interval=Interval(now, Duration(3600)),
        state=AggregationJobState.IN_PROGRESS,
        step=AggregationJobStep(1),
    )
    reports = _count_reports(vdaf, 3, "replay")
    ras = [
        ReportAggregation(
            task_id=task.task_id,
            aggregation_job_id=job.aggregation_job_id,
            report_id=ReportId(nonce),
            time=now,
            ord=i,
            state=ReportAggregationState.START_LEADER,
            public_share=vdaf.encode_public_share(ps),
            leader_input_share=share.encode(vdaf),
        )
        for i, (nonce, ps, share) in enumerate(reports)
    ]

    async def go():
        prep_in = [(ra.report_id.data, ps, share) for ra, (_n, ps, share) in zip(ras, reports)]
        out = await driver._coalesced_prep_init(
            count_backend, task.vdaf_verify_key, prep_in
        )
        assert count_backend.outshare_readback_rows == 0
        states, out_shares = {}, {}
        for ra, (state, _share) in zip(ras, out):
            assert isinstance(state.out_share, ResidentRef)
            states[ra.report_id.data] = pp.PingPongContinued(state, 0)
            out_shares[ra.report_id.data] = state.out_share

        # the device dies between flush and commit
        orig = count_backend.accumulate_rows
        count_backend.accumulate_rows = lambda *a, **kw: (_ for _ in ()).throw(
            RuntimeError("device on fire")
        )
        try:
            deltas, _journal, _touched = await driver._commit_resident_shares(
                task, vdaf, job, ras, states, out_shares
            )
        finally:
            count_backend.accumulate_rows = orig
        return deltas, out_shares

    count_backend.outshare_readback_rows = 0
    deltas, out_shares = _run(go())
    assert deltas is None, "replay path yields host vectors, not deltas"
    want = {
        ra.report_id.data: state.out_share
        for ra, (state, _) in zip(
            ras,
            OracleBackend(vdaf).prep_init_batch(
                task.vdaf_verify_key, 0, reports
            ),
        )
    }
    assert out_shares == want, "oracle replay must be bit-exact"
    assert store.stats()["buckets"] == 0, "discarded delta must never drain"
    assert store.stats()["flushes_resident"] == 0
    reset_global_executor()


# -- helper-side executor routing (satellite) --------------------------------


def _helper_decoded_rows(vdaf, n, seed):
    """(idx, (nonce, public, helper_share, leader INITIALIZE msg)) rows,
    exactly what handle_aggregate_init hands _helper_prepare_batch."""
    from janus_tpu.vdaf import pingpong as pp

    vk = b"\x2a" * vdaf.VERIFY_KEY_SIZE
    rng = det_rng(seed)
    decoded = []
    for i in range(n):
        nonce = rng(vdaf.NONCE_SIZE)
        public, shares = vdaf.shard(i % 2, nonce, rng(vdaf.RAND_SIZE))
        _state, l_share = vdaf.prep_init(vk, 0, nonce, public, shares[0])
        msg = pp.PingPongMessage(
            pp.PingPongMessage.INITIALIZE,
            prep_share=vdaf.ping_pong_encode_prep_share(l_share),
        )
        decoded.append((i, (nonce, public, shares[1], msg)))
    return vk, decoded


class _AggStub:
    """Just the Aggregator surface the helper prep path touches."""

    from janus_tpu.aggregator.aggregator import Aggregator as _A

    _helper_decode_leader_shares = staticmethod(_A._helper_decode_leader_shares)
    _helper_finish_prio3 = staticmethod(_A._helper_finish_prio3)
    _helper_prepare_batch_prio3 = _A._helper_prepare_batch_prio3
    _helper_prep_rows_prio3 = _A._helper_prep_rows_prio3
    _helper_prepare_batch_prio3_executor = _A._helper_prepare_batch_prio3_executor
    _executor_backend_for = _A._executor_backend_for
    _release_helper_refs = _A._release_helper_refs
    _release_unfinished_helper_refs = _A._release_unfinished_helper_refs

    def __init__(self, executor):
        self._executor = executor


def test_helper_prep_routes_through_executor_and_matches_oracle(count_backend):
    """aggregator.py's prep_init_batch / prep_shares_to_prep_batch calls
    submit through the executor (prep_init a1 + combine buckets) and the
    outcomes match the direct oracle path bit for bit."""
    from types import SimpleNamespace

    from janus_tpu.vdaf.backend import OracleBackend

    vdaf = count_backend.vdaf
    vk, decoded = _helper_decoded_rows(vdaf, 3, "helper-route")
    ex = DeviceExecutor(ExecutorConfig(flush_window_s=0.02, flush_max_rows=1024))
    agg = _AggStub(ex)
    ta = SimpleNamespace(
        vdaf=vdaf, backend=count_backend, task=SimpleNamespace(vdaf_verify_key=vk)
    )

    got = _run(agg._helper_prepare_batch_prio3_executor(ta, decoded))
    ex.shutdown()
    want = agg._helper_prepare_batch_prio3(
        ta, decoded, backend=OracleBackend(vdaf)
    )
    assert set(got) == set(want)
    for idx in want:
        gk, g_out, g_msg = got[idx]
        wk, w_out, w_msg = want[idx]
        assert (gk, g_out) == (wk, w_out)
        assert (g_msg.variant, g_msg.prep_msg) == (w_msg.variant, w_msg.prep_msg)
    stats = ex.stats()
    assert any("/a1/prep_init" in k for k in stats), stats
    assert any("/a1/combine" in k for k in stats), stats


def test_helper_prep_degrades_to_oracle_when_circuit_open(count_backend):
    """Breaker-aware helper path: an open circuit skips the executor
    entirely (no submissions) and serves the request on the oracle."""
    from types import SimpleNamespace

    vdaf = count_backend.vdaf
    vk, decoded = _helper_decoded_rows(vdaf, 2, "helper-breaker")
    ex = DeviceExecutor(ExecutorConfig(flush_window_s=0.02, flush_max_rows=1024))
    ex.circuit_open = lambda shape_key: True  # breaker peek says: open
    agg = _AggStub(ex)
    ta = SimpleNamespace(
        vdaf=vdaf, backend=count_backend, task=SimpleNamespace(vdaf_verify_key=vk)
    )
    got = _run(agg._helper_prepare_batch_prio3_executor(ta, decoded))
    ex.shutdown()
    assert ex.stats() == {}, "open circuit must not submit to the device"
    assert all(v[0] == "finished" for v in got.values())


def test_driver_precheck_skips_submit_when_circuit_open():
    """Breaker-aware acquisition on the DRIVER side: circuit_open short-
    circuits to the oracle with no submission and no CircuitOpenError."""
    from janus_tpu.aggregator.aggregation_job_driver import (
        AggregationJobDriver,
        DriverConfig,
    )

    reset_global_executor()
    driver = AggregationJobDriver(
        datastore=None,
        session_factory=None,
        config=DriverConfig(
            vdaf_backend="tpu", device_executor=ExecutorConfig(enabled=True)
        ),
    )
    driver._executor.circuit_open = lambda shape_key: True

    class _Oracle:
        def prep_init_batch(self, vk, agg_id, rows):
            return [("oracle", vk, i) for i in range(len(rows))]

    class _B:
        class _V:
            pass

        vdaf = _V()
        oracle = _Oracle()

        def stage_prep_init_multi(self, *a, **kw):  # pragma: no cover
            raise AssertionError("device path reached despite open circuit")

    out = _run(driver._coalesced_prep_init(_B(), b"vk", [0, 1]))
    assert out == [("oracle", b"vk", 0), ("oracle", b"vk", 1)]
    assert driver._executor.stats() == {}
    reset_global_executor()


def test_accumulator_config_yaml_round_trip():
    from janus_tpu.binaries.config import JobDriverBinaryConfig, load_config

    cfg = load_config(
        JobDriverBinaryConfig,
        text="""
device_executor:
  enabled: true
  fair_quota_rows: 4096
  accumulator:
    enabled: true
    byte_budget: 1048576
""",
    )
    ec = cfg.device_executor.to_executor_config()
    assert ec.fair_quota_rows == 4096
    assert ec.accumulator is not None and ec.accumulator.byte_budget == 1048576


# -- deferred drains (ISSUE 4): journal-granular drain + shutdown spill ------


def test_drain_with_journal_returns_per_job_entries():
    """Deferred drains consume persisted journal rows at JOB granularity:
    the store must hand back the per-job entry list, not just the flat
    report-id set."""
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True))
    backend = _AccumBackend()
    m = _matrix(4)
    fid = store.retain_flush(backend, m, rows=4, nbytes=m.nbytes)
    store.commit_rows(
        ("b",), backend, [ResidentRef(fid, 0)], job_token=b"jobA", report_ids=[b"r0"]
    )
    store.commit_rows(
        ("b",),
        backend,
        [ResidentRef(fid, 1), ResidentRef(fid, 2)],
        job_token=b"jobB",
        report_ids=[b"r1", b"r2"],
    )
    store.release_refs([ResidentRef(fid, 3)])
    vector, journal = store.drain_with_journal(("b",), _Field)
    assert vector == [1 + 2 + 3, 10 + 20 + 30]
    assert journal == [
        (b"jobA", frozenset({b"r0"})),
        (b"jobB", frozenset({b"r1", b"r2"})),
    ]


def test_due_buckets_age_scan():
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True, drain_interval_s=30))
    backend = _AccumBackend()
    m = _matrix(1)
    fid = store.retain_flush(backend, m, rows=1, nbytes=m.nbytes)
    store.commit_rows(
        ("b",), backend, [ResidentRef(fid, 0)], job_token=b"j", report_ids=[b"r"]
    )
    assert store.due_buckets(3600.0) == []  # too young
    assert store.due_buckets(0.0) == [("b",)]  # everything is due at age 0
    assert AccumulatorConfig(enabled=True, drain_interval_s=30).deferred
    assert not AccumulatorConfig(enabled=True).deferred


def test_maintenance_pass_drains_due_deferred_buckets_and_rebalances():
    """ISSUE 6 satellite (carried from PR 4): the dedicated maintenance
    pass the binaries run on ``accumulator.maintenance_interval_s`` —
    due deferred buckets drain WITHOUT waiting for a committing driver,
    and the occupancy rebalance (eviction pass) runs off the hot path."""
    import time as _time

    from janus_tpu.aggregator.aggregation_job_driver import (
        AggregationJobDriver,
        DriverConfig,
    )

    reset_global_executor()
    driver = AggregationJobDriver(
        datastore=None,
        session_factory=None,
        config=DriverConfig(
            vdaf_backend="oracle",
            device_executor=ExecutorConfig(
                enabled=True,
                accumulator=AccumulatorConfig(
                    enabled=True,
                    drain_interval_s=0.01,
                    maintenance_interval_s=0.01,
                ),
            ),
        ),
    )
    store = driver._executor.accumulator
    backend = _AccumBackend()
    m = _matrix(2)
    fid = store.retain_flush(backend, m, rows=2, nbytes=m.nbytes)
    key = ("leader", b"task", ("shape",), b"ident", b"param")  # deferred key
    store.commit_rows(
        key,
        backend,
        [ResidentRef(fid, 0), ResidentRef(fid, 1)],
        job_token=b"job",
        report_ids=[b"r0", b"r1"],
    )
    drained_keys = []

    def fake_drain(k):  # consume the bucket like the real drain's journal tx
        drained_keys.append(k)
        store.discard(k)

    driver._drain_due_bucket = fake_drain
    _time.sleep(0.02)  # past drain_interval_s: the bucket is due
    n = _run(driver.run_accumulator_maintenance())
    assert n == 1 and drained_keys == [key]
    # nothing due -> a quiet pass; the loop must be safe to run forever
    assert _run(driver.run_accumulator_maintenance()) == 0
    reset_global_executor()


def test_shutdown_drain_spills_through_sink_exactly_once():
    """SIGTERM path (ISSUE 4 satellite): shutdown(drain=True) — the
    default — spills committed-but-unspilled deltas through the
    registered sink before discarding; the sink sees the vector AND the
    per-job journal so it can consume the persisted rows."""
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True, drain_interval_s=60))
    ex = DeviceExecutor(ExecutorConfig())
    ex.accumulator = store
    backend = _AccumBackend()
    m = _matrix(2)
    fid = store.retain_flush(backend, m, rows=2, nbytes=m.nbytes)
    store.commit_rows(
        ("bucket",),
        backend,
        [ResidentRef(fid, 0), ResidentRef(fid, 1)],
        job_token=b"jobA",
        report_ids=[b"r0", b"r1"],
    )
    spilled = []
    ex.set_spill_sink(lambda key, vector, journal: spilled.append((key, vector, journal)))
    ex.shutdown()  # drain=True is the default
    assert spilled == [
        (("bucket",), [1 + 2, 10 + 20], [(b"jobA", frozenset({b"r0", b"r1"}))])
    ]
    assert store.stats()["buckets"] == 0
    # drained exactly once: nothing left for a second teardown to spill
    spilled.clear()
    ex.shutdown()
    assert spilled == []


def test_undrained_shutdown_discards_and_redelivery_rederives():
    """Regression (ISSUE 4 satellite): shutdown(drain=False) — the crash
    shape — discards the delta WITHOUT spilling; the journaled reports
    are still rederivable (here: recomputing the same rows into a fresh
    store yields the identical vector, which is what lease redelivery /
    the datastore replay does with real report shares)."""
    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True))
    ex = DeviceExecutor(ExecutorConfig())
    ex.accumulator = store
    backend = _AccumBackend()
    m = _matrix(2)
    fid = store.retain_flush(backend, m, rows=2, nbytes=m.nbytes)
    store.commit_rows(
        ("bucket",),
        backend,
        [ResidentRef(fid, 0), ResidentRef(fid, 1)],
        job_token=b"jobA",
        report_ids=[b"r0", b"r1"],
    )
    spilled = []
    ex.set_spill_sink(lambda *a: spilled.append(a))
    ex.shutdown(drain=False)
    assert spilled == [] and store.stats()["buckets"] == 0
    # "redelivery": the same rows recommit into a fresh store, bit-exact
    store2 = DeviceAccumulatorStore(AccumulatorConfig(enabled=True))
    m2 = _matrix(2)
    fid2 = store2.retain_flush(backend, m2, rows=2, nbytes=m2.nbytes)
    store2.commit_rows(
        ("bucket",),
        backend,
        [ResidentRef(fid2, 0), ResidentRef(fid2, 1)],
        job_token=b"jobA",
        report_ids=[b"r0", b"r1"],
    )
    vector, rids = store2.drain(("bucket",), _Field)
    assert vector == [3, 30] and rids == {b"r0", b"r1"}


def test_writer_journal_entries_defer_shares_and_persist_rows():
    """Deferred mode at the writer: journaled rows contribute NO share in
    this tx (None when everything is deferred) and one journal row per
    (job, ident) is persisted; a journaled report failed in-tx aborts."""
    from types import SimpleNamespace

    from janus_tpu.aggregator.aggregation_job_writer import AggregationJobWriter

    writer = AggregationJobWriter(
        task=None,
        vdaf=None,
        journal_entries={b"ident": frozenset({b"r1", b"r2"})},
    )
    refs = [ResidentRef(0, 0), ResidentRef(0, 1)]
    # all resident rows journaled: no delta required, no share merged now
    assert writer._resolve_shares(_Field, b"ident", refs, [b"r1", b"r2"]) is None
    # mixed: host rows still merge
    assert writer._resolve_shares(
        _Field, b"ident", refs + [[1, 1]], [b"r1", b"r2", b"r3"]
    ) == [1, 1]
    # resident rows NOT covered by the journal still need a drained delta
    with pytest.raises(StaleAccumulatorDelta):
        writer._resolve_shares(_Field, b"other-ident", refs, [b"r1", b"r2"])

    calls = []
    tx = SimpleNamespace(
        put_accumulator_journal_entry=lambda *a: calls.append(a)
    )
    task = SimpleNamespace(task_id=b"task")
    writer.task = task
    job = SimpleNamespace(aggregation_parameter=b"", aggregation_job_id=b"job")
    writer._write_journal(tx, job, failures={})
    assert calls == [(b"task", b"ident", b"", b"job", [b"r1", b"r2"])]
    with pytest.raises(StaleAccumulatorDelta):
        writer._write_journal(tx, job, failures={b"r2": "collected"})


def test_concurrent_same_job_deliveries_use_disjoint_buckets():
    """Regression (found by the crash soak): two CONCURRENT deliveries of
    one aggregation job (helper: a leader redelivers while the first
    request is still being served; leader: two in-process driver replicas
    overlap on an expired lease) must never share a drain-at-commit
    bucket — both commits landing before either drain yields a DOUBLED
    vector whose report-id set still matches, which StaleAccumulatorDelta
    cannot catch.  Keys carry a per-delivery nonce, so interleaved
    commit/commit/drain/drain stays exact."""
    from types import SimpleNamespace

    from janus_tpu.aggregator.aggregator import Aggregator

    store = DeviceAccumulatorStore(AccumulatorConfig(enabled=True))
    backend = _AccumBackend()

    commit_keys = []
    orig_commit = store.commit_rows

    def recording_commit(key, *a, **kw):
        commit_keys.append(key)
        return orig_commit(key, *a, **kw)

    store.commit_rows = recording_commit

    from janus_tpu.datastore import TaskQueryType
    from janus_tpu.messages import AggregationJobId, TaskId, Time
    from janus_tpu.vdaf.instances import prio3_count

    vdaf = prio3_count()
    task = SimpleNamespace(
        task_id=TaskId.random(),
        query_type=TaskQueryType.time_interval(),
        time_precision=__import__("janus_tpu.messages", fromlist=["Duration"]).Duration(3600),
    )
    job = SimpleNamespace(
        aggregation_parameter=b"",
        aggregation_job_id=AggregationJobId.random(),
        partial_batch_identifier=None,
    )
    ta = SimpleNamespace(task=task, vdaf=vdaf, backend=backend)
    agg = SimpleNamespace(
        _executor=SimpleNamespace(accumulator=store),
        datastore=None,
    )

    def deliver():
        m = _matrix(2)
        fid = store.retain_flush(backend, m, rows=2, nbytes=m.nbytes)
        ras = [
            SimpleNamespace(report_id=SimpleNamespace(data=bytes([i]) * 16), time=Time(0))
            for i in range(2)
        ]
        out_shares = {
            ras[0].report_id.data: ResidentRef(fid, 0),
            ras[1].report_id.data: ResidentRef(fid, 1),
        }
        return _run(
            Aggregator._commit_helper_resident_shares(
                agg, ta, job, ras, out_shares, decoded_by_rid={}
            )
        )

    d1 = deliver()
    d2 = deliver()
    # each delivery drains exactly its OWN rows — no doubling
    (v1, rids1), = d1.values()
    (v2, rids2), = d2.values()
    assert v1 == [1 + 2, 10 + 20] and v2 == [1 + 2, 10 + 20]
    # the fence: bucket keys differ per delivery even for the same job
    assert len(commit_keys) == 2
    assert commit_keys[0] != commit_keys[1]
