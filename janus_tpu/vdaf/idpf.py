"""Incremental Distributed Point Function (IdpfPoplar).

The IDPF underlying Poplar1 (draft-irtf-cfrg-vdaf-08 §8; consumed by the
reference through the prio crate's ``idpf`` module, SURVEY.md §2.2): a
two-party sharing of the function that is ``beta_inner[l]`` on every prefix
of ``alpha`` at inner level ``l``, ``beta_leaf`` at the leaf, and zero
everywhere else.  Inner nodes live in Field64, leaves in Field255.

Tree walk per level: ``extend`` (seed → two child seeds + control bits) and
``convert`` (seed → next seed + value-share vector), both via the fixed-key
AES XOF keyed by the nonce.  Key generation emits one correction word per
level; evaluation applies it gated on the evaluator's control bit.

Protocol-correctness tests (tests/test_poplar1.py) check the defining
property: the two parties' evaluations sum to beta exactly on the prefix
path and to zero off it.  Byte-level anchoring to libprio-rs awaits vendored
test vectors (no network access in this environment).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from ..fields import Field64, Field255
from ..xof import XofFixedKeyAes128
from .prio3 import VdafError

KEY_SIZE = 16


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


def _dst(usage: int) -> bytes:
    # (version 8, algorithm class 1 = IDPF, usage)
    return bytes([8, 1, 0, 0, 0, 0, 0, usage])


@dataclass
class IdpfCorrectionWord:
    seed_cw: bytes
    ctrl_cw: Tuple[int, int]
    w_cw: List[int]


class IdpfPoplar:
    """Two-party IDPF with VALUE_LEN-element payloads."""

    SHARES = 2

    def __init__(self, bits: int, value_len: int = 1):
        if bits < 1:
            raise ValueError("bits must be >= 1")
        self.BITS = bits
        self.VALUE_LEN = value_len
        self.RAND_SIZE = 2 * KEY_SIZE

    def field_at(self, level: int) -> type:
        return Field255 if level == self.BITS - 1 else Field64

    # ------------------------------------------------------------------
    def _extend(self, seed: bytes, nonce: bytes):
        """seed -> ([seed_L, seed_R], [ctrl_L, ctrl_R])"""
        xof = XofFixedKeyAes128(seed, _dst(0), nonce)
        s = [bytearray(xof.next(KEY_SIZE)) for _ in range(2)]
        ctrl = [s[0][0] & 1, s[1][0] & 1]
        s[0][0] &= 0xFE
        s[1][0] &= 0xFE
        return [bytes(s[0]), bytes(s[1])], ctrl

    def _convert(self, level: int, seed: bytes, nonce: bytes):
        """seed -> (next_seed, value-share vector at this level's field)"""
        xof = XofFixedKeyAes128(seed, _dst(1), nonce)
        next_seed = xof.next(KEY_SIZE)
        field = self.field_at(level)
        return next_seed, xof.next_vec(field, self.VALUE_LEN)

    # ------------------------------------------------------------------
    def gen(
        self,
        alpha: int,
        beta_inner: Sequence[Sequence[int]],
        beta_leaf: Sequence[int],
        nonce: bytes,
        rand: bytes,
    ) -> Tuple[List[IdpfCorrectionWord], List[bytes]]:
        """Returns (public_share = correction words, [key_0, key_1])."""
        if alpha >> self.BITS:
            raise VdafError("alpha out of range")
        if len(rand) != self.RAND_SIZE:
            raise VdafError("bad idpf rand size")
        if len(beta_inner) != self.BITS - 1:
            raise VdafError("wrong number of inner beta values")
        init_seed = [rand[0:KEY_SIZE], rand[KEY_SIZE : 2 * KEY_SIZE]]
        seed = list(init_seed)
        ctrl = [0, 1]
        correction_words: List[IdpfCorrectionWord] = []
        for level in range(self.BITS):
            field = self.field_at(level)
            bit = (alpha >> (self.BITS - 1 - level)) & 1
            keep, lose = bit, 1 - bit
            s0, t0 = self._extend(seed[0], nonce)
            s1, t1 = self._extend(seed[1], nonce)
            seed_cw = _xor(s0[lose], s1[lose])
            ctrl_cw = (t0[0] ^ t1[0] ^ bit ^ 1, t0[1] ^ t1[1] ^ bit)

            x0 = _xor(s0[keep], seed_cw) if ctrl[0] else s0[keep]
            x1 = _xor(s1[keep], seed_cw) if ctrl[1] else s1[keep]
            next_ctrl0 = t0[keep] ^ (ctrl[0] & ctrl_cw[keep])
            next_ctrl1 = t1[keep] ^ (ctrl[1] & ctrl_cw[keep])
            seed[0], w0 = self._convert(level, x0, nonce)
            seed[1], w1 = self._convert(level, x1, nonce)
            ctrl = [next_ctrl0, next_ctrl1]

            beta = beta_leaf if level == self.BITS - 1 else beta_inner[level]
            if len(beta) != self.VALUE_LEN:
                raise VdafError("bad beta length")
            # w_cw = beta - w0 + w1, negated if party 1's control bit is set
            w_cw = [
                field.sub(field.add(b, y1), y0) for b, y0, y1 in zip(beta, w0, w1)
            ]
            if ctrl[1]:
                w_cw = [field.neg(x) for x in w_cw]
            correction_words.append(IdpfCorrectionWord(seed_cw, ctrl_cw, w_cw))
        return correction_words, list(init_seed)

    # ------------------------------------------------------------------
    def eval(
        self,
        agg_id: int,
        public_share: Sequence[IdpfCorrectionWord],
        key: bytes,
        level: int,
        prefixes: Sequence[int],
        nonce: bytes,
    ) -> List[List[int]]:
        """Evaluate this party's share at each ``level``-bit prefix."""
        if agg_id not in (0, 1):
            raise VdafError("bad aggregator id")
        if not 0 <= level < self.BITS:
            raise VdafError("level out of range")
        for prefix in prefixes:
            if prefix >> (level + 1):
                raise VdafError("prefix out of range for level")

        # Shared-prefix path memoization: sibling prefixes reuse every
        # ancestor's extend/convert, so evaluating P prefixes costs ~O(P)
        # tree nodes instead of O(P * level).
        memo = {}

        def node(l: int, p: int):
            """State after level ``l`` on prefix ``p``: (seed, ctrl, value)."""
            hit = memo.get((l, p))
            if hit is not None:
                return hit
            if l == 0:
                parent_seed, parent_ctrl = key, agg_id  # party 1 starts set
            else:
                parent_seed, parent_ctrl, _ = node(l - 1, p >> 1)
            cw = public_share[l]
            s, t = self._extend(parent_seed, nonce)
            if parent_ctrl:
                s = [_xor(s[0], cw.seed_cw), _xor(s[1], cw.seed_cw)]
                t = [t[0] ^ cw.ctrl_cw[0], t[1] ^ cw.ctrl_cw[1]]
            bit = p & 1
            seed, w = self._convert(l, s[bit], nonce)
            ctrl = t[bit]
            field = self.field_at(l)
            if ctrl:
                w = [field.add(x, c) for x, c in zip(w, cw.w_cw)]
            value = [field.neg(x) for x in w] if agg_id == 1 else w
            memo[(l, p)] = (seed, ctrl, value)
            return memo[(l, p)]

        return [list(node(level, p)[2]) for p in prefixes]

    # ------------------------------------------------------------------
    # codec (public share <-> bytes; key is raw 16 bytes)

    def encode_public_share(self, correction_words: Sequence[IdpfCorrectionWord]) -> bytes:
        # packed control bits first, then per-level seed + value words
        # (mirrors the spec's packed encoding shape)
        out = bytearray()
        bits = []
        for cw in correction_words:
            bits.extend(cw.ctrl_cw)
        for i in range(0, len(bits), 8):
            byte = 0
            for j, b in enumerate(bits[i : i + 8]):
                byte |= b << j
            out.append(byte)
        for level, cw in enumerate(correction_words):
            field = self.field_at(level)
            out += cw.seed_cw
            out += field.encode_vec(cw.w_cw)
        return bytes(out)

    def decode_public_share(self, data: bytes) -> List[IdpfCorrectionWord]:
        nbits = 2 * self.BITS
        nbytes = (nbits + 7) // 8
        if len(data) < nbytes:
            raise VdafError("truncated idpf public share")
        bits = []
        for i in range(nbits):
            bits.append((data[i // 8] >> (i % 8)) & 1)
        # trailing bits in the last byte must be zero (canonical encoding)
        for i in range(nbits, nbytes * 8):
            if (data[i // 8] >> (i % 8)) & 1:
                raise VdafError("non-canonical idpf public share")
        pos = nbytes
        out = []
        for level in range(self.BITS):
            field = self.field_at(level)
            if len(data) < pos + KEY_SIZE + field.ENCODED_SIZE * self.VALUE_LEN:
                raise VdafError("truncated idpf public share")
            seed_cw = data[pos : pos + KEY_SIZE]
            pos += KEY_SIZE
            w_cw = field.decode_vec(
                data[pos : pos + field.ENCODED_SIZE * self.VALUE_LEN]
            )
            pos += field.ENCODED_SIZE * self.VALUE_LEN
            out.append(
                IdpfCorrectionWord(
                    seed_cw, (bits[2 * level], bits[2 * level + 1]), w_cw
                )
            )
        if pos != len(data):
            raise VdafError("trailing idpf public share bytes")
        return out
