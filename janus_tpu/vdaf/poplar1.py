"""Poplar1: heavy-hitters VDAF over the IDPF.

The analog of the reference's ``Poplar1{bits}`` instance (reference:
core/src/vdaf.rs:96, served by the prio crate; draft-irtf-cfrg-vdaf-08 §9):
clients shard a ``bits``-bit string through an IDPF; aggregators, given an
aggregation parameter (level, prefixes), evaluate their IDPF shares at each
prefix and run a two-round sketch to verify the client's contribution is a
one-hot unit vector before accumulating prefix counts.

Sketch (Boneh et al. secure-sketching as used by Poplar): with verifier
randomness r_i per prefix and client-supplied correlated randomness
(A, B, C=A²) additively shared — helper's shares derived from a seed, the
leader's carried explicitly so the relation C = A² holds — the aggregators
broadcast

    z_b  = Σ r_i·y_b(i) + a_b,      z*_b = Σ r_i²·y_b(i) + b_b,

then verify  σ = (z−A)² − (z*−B) = (Σ r_i y_i)² − Σ r_i² y_i = 0,  which
holds exactly when y is one-hot with value 1 (up to the r-randomized check).

Multi-round state flows through the stored-transition ping-pong model
(janus_tpu.vdaf.pingpong), so the driver layer persists Poplar1 exactly as
the reference persists prio's PingPongTransition (models.rs:898).

Protocol correctness (completeness, one-hotness soundness, prefix-count
aggregation, wire round-trips) is tested in tests/test_poplar1.py;
byte-level anchoring to libprio-rs awaits vendored vectors.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from ..fields import Field64, Field255
from ..xof import XofTurboShake128
from .idpf import IdpfPoplar
from .prio3 import VdafError

USAGE_SHARD_RANDOMNESS = 1
USAGE_CORR_INNER = 2
USAGE_CORR_LEAF = 3
USAGE_VERIFY_RANDOMNESS = 4

ALG_POPLAR1 = 0x00000006

_FIELD_TAGS = {0: Field64, 1: Field255}


def _field_tag(field: type) -> int:
    return 1 if field is Field255 else 0


@dataclass(frozen=True)
class Poplar1AggregationParam:
    """(level, sorted distinct prefixes) — reference analog:
    prio's Poplar1AggregationParam."""

    level: int
    prefixes: Tuple[int, ...]

    def __post_init__(self):
        if list(self.prefixes) != sorted(set(self.prefixes)):
            raise VdafError("prefixes must be sorted and distinct")

    def encode(self, bits: int) -> bytes:
        if not 0 <= self.level < bits:
            raise VdafError("level out of range")
        prefix_bytes = (self.level + 1 + 7) // 8
        out = struct.pack(">HI", self.level, len(self.prefixes))
        for p in self.prefixes:
            if p >> (self.level + 1):
                raise VdafError("prefix out of range")
            out += int(p).to_bytes(prefix_bytes, "big")
        return out

    @classmethod
    def decode(cls, bits: int, data: bytes) -> "Poplar1AggregationParam":
        if len(data) < 6:
            raise VdafError("truncated aggregation parameter")
        level, count = struct.unpack(">HI", data[:6])
        if level >= bits:
            raise VdafError("level out of range")
        prefix_bytes = (level + 1 + 7) // 8
        if len(data) != 6 + count * prefix_bytes:
            raise VdafError("bad aggregation parameter length")
        prefixes = tuple(
            int.from_bytes(data[6 + i * prefix_bytes : 6 + (i + 1) * prefix_bytes], "big")
            for i in range(count)
        )
        for p in prefixes:
            if p >> (level + 1):
                raise VdafError("prefix out of range for level")
        return cls(level, prefixes)


@dataclass
class Poplar1InputShare:
    idpf_key: bytes
    #: helper: 16-byte seed the corr randomness expands from; leader: None
    corr_seed: Optional[bytes]
    #: leader: explicit (a, b, c) triples per level; helper: None
    corr_inner: Optional[List[Tuple[int, int, int]]] = None
    corr_leaf: Optional[Tuple[int, int, int]] = None

    def encode(self, vdaf: "Poplar1") -> bytes:
        if self.corr_seed is not None:
            return b"\x01" + self.idpf_key + self.corr_seed
        out = bytearray(b"\x00" + self.idpf_key)
        for triple in self.corr_inner:
            out += Field64.encode_vec(list(triple))
        out += Field255.encode_vec(list(self.corr_leaf))
        return bytes(out)

    @staticmethod
    def decode(vdaf: "Poplar1", agg_id: int, data: bytes) -> "Poplar1InputShare":
        if not data:
            raise VdafError("empty input share")
        kind, rest = data[0], data[1:]
        if kind == 1:
            if agg_id == 0:
                raise VdafError("leader share must carry explicit correlation")
            if len(rest) != 32:
                raise VdafError("bad helper input share length")
            return Poplar1InputShare(rest[:16], rest[16:])
        if kind != 0 or agg_id != 0:
            raise VdafError("bad input share")
        key, rest = rest[:16], rest[16:]
        inner_len = 3 * Field64.ENCODED_SIZE * (vdaf.bits - 1)
        leaf_len = 3 * Field255.ENCODED_SIZE
        if len(rest) != inner_len + leaf_len:
            raise VdafError("bad leader input share length")
        inner_vals = Field64.decode_vec(rest[:inner_len])
        leaf_vals = Field255.decode_vec(rest[inner_len:])
        corr_inner = [
            (inner_vals[3 * i], inner_vals[3 * i + 1], inner_vals[3 * i + 2])
            for i in range(vdaf.bits - 1)
        ]
        return Poplar1InputShare(
            key, None, corr_inner, (leaf_vals[0], leaf_vals[1], leaf_vals[2])
        )


@dataclass
class Poplar1PrepareShare:
    """Round 0: values = [z, zs]; round 1: values = [sigma].  Field-tagged
    so wire decoding needs no agg-param context."""

    field_tag: int
    values: List[int]

    def encode(self) -> bytes:
        return bytes([self.field_tag]) + _FIELD_TAGS[self.field_tag].encode_vec(
            self.values
        )

    @staticmethod
    def decode(data: bytes) -> "Poplar1PrepareShare":
        if not data or data[0] not in _FIELD_TAGS:
            raise VdafError("bad prepare share")
        vals = _FIELD_TAGS[data[0]].decode_vec(data[1:])
        if len(vals) not in (1, 2):
            raise VdafError("bad prepare share length")
        return Poplar1PrepareShare(data[0], vals)


@dataclass
class Poplar1PrepareState:
    agg_id: int
    level: int
    round: int  # 0 = sketch broadcast pending, 1 = decision pending
    #: this party's prefix value shares — a List[int], or (device-resident
    #: IDPF) an executor.accumulator.ResidentRef naming the row of a
    #: retained (B, P, n) sketch matrix; the ping-pong layer passes it
    #: through untouched, exactly like Prio3's resident out shares
    y_flat: object
    a: int
    b: int
    c: int
    zs_share: int


class Poplar1:
    """Two-party Poplar1 with ``bits``-bit inputs; 2 prepare rounds."""

    NONCE_SIZE = 16
    VERIFY_KEY_SIZE = 16
    ROUNDS = 2
    REQUIRES_AGG_PARAM = True
    num_shares = 2

    def __init__(self, bits: int):
        self.bits = bits
        self.idpf = IdpfPoplar(bits, value_len=1)
        # idpf keys + helper corr seed + joint (a, b) seed
        self.RAND_SIZE = self.idpf.RAND_SIZE + 16 + 16

    # -- uniform VDAF surface -------------------------------------------
    @property
    def field(self) -> type:
        return Field255  # leaf field; level-dependent via field_for_agg_param

    def field_for_agg_param(self, agg_param) -> type:
        if agg_param is None:
            raise VdafError("Poplar1 requires an aggregation parameter")
        return self.idpf.field_at(agg_param.level)

    def encode_agg_param(self, agg_param: Poplar1AggregationParam) -> bytes:
        return agg_param.encode(self.bits)

    def decode_agg_param(self, data: bytes) -> Poplar1AggregationParam:
        return Poplar1AggregationParam.decode(self.bits, data)

    def decode_input_share(self, agg_id: int, data: bytes) -> Poplar1InputShare:
        return Poplar1InputShare.decode(self, agg_id, data)

    def agg_param_conflict_key(self, data: bytes) -> bytes:
        """A report may be aggregated at most ONCE PER LEVEL: the sketch's
        correlated randomness is keyed by (nonce, level), so two different
        prefix sets at one level would reuse one-time randomness and leak
        relations among the helper's shares."""
        return data[:2]  # the big-endian level prefix of the encoded param

    def encode_public_share(self, public_share) -> bytes:
        return self.idpf.encode_public_share(public_share)

    def decode_public_share(self, data: bytes):
        return self.idpf.decode_public_share(data)

    # -- correlated randomness ------------------------------------------
    def _dst(self, usage: int) -> bytes:
        return struct.pack(">BIBH", 8, ALG_POPLAR1, 0, usage)

    def _corr_triples(self, seed: bytes, nonce: bytes, who: int):
        """Expand (a, b, c)-shares per level from a seed (helper side)."""
        binder = bytes([who]) + nonce
        inner_vals = XofTurboShake128(
            seed, self._dst(USAGE_CORR_INNER), binder
        ).next_vec(Field64, 3 * (self.bits - 1)) if self.bits > 1 else []
        leaf_vals = XofTurboShake128(
            seed, self._dst(USAGE_CORR_LEAF), binder
        ).next_vec(Field255, 3)
        inner = [
            (inner_vals[3 * i], inner_vals[3 * i + 1], inner_vals[3 * i + 2])
            for i in range(self.bits - 1)
        ]
        return inner, (leaf_vals[0], leaf_vals[1], leaf_vals[2])

    # -- shard -----------------------------------------------------------
    def shard(self, measurement: int, nonce: bytes, rand: bytes):
        """Returns (public_share, [leader_share, helper_share])."""
        if len(rand) != self.RAND_SIZE:
            raise VdafError("bad rand size")
        if measurement >> self.bits:
            raise VdafError("measurement out of range")
        idpf_rand = rand[: self.idpf.RAND_SIZE]
        helper_corr_seed = rand[self.idpf.RAND_SIZE : self.idpf.RAND_SIZE + 16]
        joint_seed = rand[self.idpf.RAND_SIZE + 16 :]

        beta_inner = [[1] for _ in range(self.bits - 1)]
        public_share, keys = self.idpf.gen(
            measurement, beta_inner, [1], nonce, idpf_rand
        )

        # helper (a1,b1,c1) from its seed; joint (A,B) from the joint seed;
        # leader gets a0 = A-a1, b0 = B-b1, c0 = A²-c1 so C = A² holds.
        h_inner, h_leaf = self._corr_triples(helper_corr_seed, nonce, 1)
        j_inner, j_leaf = self._corr_triples(joint_seed, nonce, 2)
        corr_inner = []
        for lvl in range(self.bits - 1):
            A, B, _ = j_inner[lvl]
            a1, b1, c1 = h_inner[lvl]
            corr_inner.append(
                (
                    Field64.sub(A, a1),
                    Field64.sub(B, b1),
                    Field64.sub(Field64.mul(A, A), c1),
                )
            )
        A, B, _ = j_leaf
        a1, b1, c1 = h_leaf
        corr_leaf = (
            Field255.sub(A, a1),
            Field255.sub(B, b1),
            Field255.sub(Field255.mul(A, A), c1),
        )
        leader = Poplar1InputShare(keys[0], None, corr_inner, corr_leaf)
        helper = Poplar1InputShare(keys[1], helper_corr_seed)
        return public_share, [leader, helper]

    # -- prepare ---------------------------------------------------------
    def _verify_rands(
        self, verify_key: bytes, nonce: bytes, agg_param: Poplar1AggregationParam
    ) -> List[int]:
        field = self.field_for_agg_param(agg_param)
        binder = nonce + struct.pack(">H", agg_param.level)
        return XofTurboShake128(
            verify_key, self._dst(USAGE_VERIFY_RANDOMNESS), binder
        ).next_vec(field, len(agg_param.prefixes))

    def prep_init(
        self,
        verify_key: bytes,
        agg_id: int,
        agg_param: Poplar1AggregationParam,
        nonce: bytes,
        public_share,
        input_share: Poplar1InputShare,
    ):
        field = self.field_for_agg_param(agg_param)
        level = agg_param.level
        y = self.idpf.eval(
            agg_id, public_share, input_share.idpf_key, level, agg_param.prefixes, nonce
        )
        y_flat = [row[0] for row in y]
        if input_share.corr_seed is not None:
            inner, leaf = self._corr_triples(input_share.corr_seed, nonce, 1)
        else:
            inner, leaf = input_share.corr_inner, input_share.corr_leaf
        a, b, c = leaf if level == self.bits - 1 else inner[level]
        r = self._verify_rands(verify_key, nonce, agg_param)
        z = a
        zs = b
        for r_i, y_i in zip(r, y_flat):
            z = field.add(z, field.mul(r_i, y_i))
            zs = field.add(zs, field.mul(field.mul(r_i, r_i), y_i))
        state = Poplar1PrepareState(
            agg_id=agg_id, level=level, round=0, y_flat=y_flat,
            a=a, b=b, c=c, zs_share=zs,
        )
        return state, Poplar1PrepareShare(_field_tag(field), [z, zs])

    def sketch_combine(self, agg_param, shares: Sequence[Tuple[int, int]]):
        """Round-0 combine: broadcast (z, z*)."""
        field = self.field_for_agg_param(agg_param)
        z = zs = 0
        for z_b, zs_b in shares:
            z = field.add(z, z_b)
            zs = field.add(zs, zs_b)
        return z, zs

    def sketch_decide_share(self, state: Poplar1PrepareState, z: int, zs: int) -> int:
        """Round-1 share:  σ_b = [z²]_{b=0} − 2z·a_b + c_b + b_b − z*_b."""
        field = self.idpf.field_at(state.level)
        sigma = field.sub(
            field.add(field.add(state.c, state.b), 0 if state.agg_id else field.mul(z, z)),
            field.add(field.mul(field.add(z, z), state.a), state.zs_share),
        )
        return sigma

    def decide(self, agg_param, sigma_shares: Sequence[int]) -> None:
        field = self.field_for_agg_param(agg_param)
        total = 0
        for s in sigma_shares:
            total = field.add(total, s)
        if total != 0:
            raise VdafError("sketch verification failed")

    # -- aggregation -----------------------------------------------------
    def aggregate(self, agg_param, out_shares: Sequence[Sequence[int]]) -> List[int]:
        field = self.field_for_agg_param(agg_param)
        agg = [0] * len(agg_param.prefixes)
        for s in out_shares:
            agg = field.vec_add(agg, s)
        return agg

    def unshard_with_param(
        self, agg_param, agg_shares: Sequence[Sequence[int]], num_measurements: int
    ) -> List[int]:
        field = self.field_for_agg_param(agg_param)
        agg = [0] * len(agg_param.prefixes)
        for s in agg_shares:
            agg = field.vec_add(agg, s)
        return agg

    # -- ping-pong adapter surface --------------------------------------
    # Encodings are field-tagged so they decode without agg-param context.

    def ping_pong_prep_init(
        self, verify_key, agg_id, agg_param, nonce, public_share, input_share
    ):
        return self.prep_init(
            verify_key, agg_id, agg_param, nonce, public_share, input_share
        )

    def ping_pong_prep_shares_to_prep(self, agg_param, prep_shares, round=0) -> bytes:
        field = self.field_for_agg_param(agg_param)
        tag = _field_tag(field)
        for sh in prep_shares:
            if sh.field_tag != tag:
                raise VdafError("prepare share field mismatch")
        if round == 0:
            z, zs = self.sketch_combine(
                agg_param, [(sh.values[0], sh.values[1]) for sh in prep_shares]
            )
            return bytes([tag]) + field.encode_vec([z, zs])
        self.decide(agg_param, [sh.values[0] for sh in prep_shares])
        return b""

    def ping_pong_prep_next(self, prep_state: Poplar1PrepareState, prep_msg: bytes, round=0):
        field = self.idpf.field_at(prep_state.level)
        if prep_state.round == 0:
            if not prep_msg or prep_msg[0] != _field_tag(field):
                raise VdafError("bad sketch message")
            vals = field.decode_vec(prep_msg[1:])
            if len(vals) != 2:
                raise VdafError("bad sketch message length")
            sigma = self.sketch_decide_share(prep_state, vals[0], vals[1])
            next_state = Poplar1PrepareState(
                agg_id=prep_state.agg_id, level=prep_state.level, round=1,
                y_flat=prep_state.y_flat, a=0, b=0, c=0, zs_share=0,
            )
            share = Poplar1PrepareShare(_field_tag(field), [sigma])
            return ("continue", next_state, share.encode())
        if prep_msg:
            raise VdafError("unexpected decision payload")
        if isinstance(prep_state.y_flat, list):
            return ("finish", list(prep_state.y_flat))
        # device-resident sketch: the ref travels out verbatim; only the
        # accumulator store can resolve it (commit psums the row in place)
        return ("finish", prep_state.y_flat)

    def ping_pong_encode_prep_share(self, share: Poplar1PrepareShare) -> bytes:
        return share.encode()

    def ping_pong_decode_prep_share(self, data: bytes, round=0) -> Poplar1PrepareShare:
        share = Poplar1PrepareShare.decode(data)
        expected = 2 if round == 0 else 1
        if len(share.values) != expected:
            raise VdafError("bad prepare share length for round")
        return share

    #: y-count sentinel marking a persisted state whose sketch vector is a
    #: device-resident ref (flush id + row) instead of inline field
    #: elements.  The value is unreachable for real prefix counts (the
    #: encoded agg param caps count at u32, and a 2^32-prefix frontier
    #: cannot exist), so legacy states decode unchanged.
    _RESIDENT_Y = 0xFFFFFFFF

    def ping_pong_encode_state(self, state: Poplar1PrepareState) -> bytes:
        field = self.idpf.field_at(state.level)
        if not isinstance(state.y_flat, list):
            # device-resident sketch: persist the ref, not the vector —
            # the WAITING_LEADER -> FINISHED hop never round-trips the
            # y values through host memory.  A ref that outlives its
            # process decodes fine and fails closed at commit time
            # (AccumulatorUnavailable -> per-report oracle replay from the
            # retained report payloads).
            ref = state.y_flat
            head = struct.pack(
                ">BHBI", state.agg_id, state.level, state.round, self._RESIDENT_Y
            )
            return (
                head
                + struct.pack(">qI", int(ref.flush_id), int(ref.row))
                + field.encode_vec(
                    [state.a, state.b, state.c, state.zs_share]
                )
            )
        head = struct.pack(
            ">BHBI", state.agg_id, state.level, state.round, len(state.y_flat)
        )
        return head + field.encode_vec(
            state.y_flat + [state.a, state.b, state.c, state.zs_share]
        )

    def ping_pong_decode_state(self, data: bytes) -> Poplar1PrepareState:
        if len(data) < 8:
            raise VdafError("truncated prepare state")
        agg_id, level, round_, n = struct.unpack(">BHBI", data[:8])
        field = self.idpf.field_at(level)
        if n == self._RESIDENT_Y:
            from ..executor.accumulator import ResidentRef

            if len(data) < 20:
                raise VdafError("truncated resident prepare state")
            flush_id, row = struct.unpack(">qI", data[8:20])
            vals = field.decode_vec(data[20:])
            if len(vals) != 4:
                raise VdafError("bad resident prepare state length")
            return Poplar1PrepareState(
                agg_id=agg_id, level=level, round=round_,
                y_flat=ResidentRef(flush_id, row),
                a=vals[0], b=vals[1], c=vals[2], zs_share=vals[3],
            )
        vals = field.decode_vec(data[8:])
        if len(vals) != n + 4:
            raise VdafError("bad prepare state length")
        return Poplar1PrepareState(
            agg_id=agg_id, level=level, round=round_, y_flat=vals[:n],
            a=vals[n], b=vals[n + 1], c=vals[n + 2], zs_share=vals[n + 3],
        )
