"""Execution backends for Prio3 preparation: CPU oracle vs batched TPU.

This is the real dispatch seam the reference expresses as ``vdaf_dispatch!`` /
``VdafOps`` (reference: core/src/vdaf.rs:516-532,
aggregator/src/aggregator.rs:1168-1340): one switch routes a whole aggregation
job's prepare work either through the scalar oracle (janus_tpu.vdaf.prio3) or
through one jitted device launch (janus_tpu.ops.prepare), with identical
results — the agreement is asserted in tests/test_backend.py.

Both backends speak oracle-level types (Prio3InputShare / Prio3PrepareShare /
Prio3PrepareState), so role logic above the seam is backend-agnostic.  The
device backend pads batches to power-of-two buckets to bound recompilation,
and falls back to the oracle for any row whose XOF rejection-sampling margin
overflowed (``ok`` mask — astronomically rare, but exact).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..core import faults
from ..fields import next_power_of_2
from ..xof import XofTurboShake128
from .prio3 import (
    Prio3,
    Prio3InputShare,
    Prio3PrepareShare,
    Prio3PrepareState,
    VdafError,
)

#: A per-report prepare outcome: either a result or the error that rejected it.
PrepOutcome = Union[Tuple[Prio3PrepareState, Prio3PrepareShare], VdafError]


@dataclass
class StagedPrepInit:
    """Device-resident half of a prepare launch.

    Produced by ``TpuBackend.stage_prep_init_multi`` (host marshal +
    device_put), consumed by ``launch_prep_init_multi`` (compiled launch +
    readback).  The split lets the device executor double-buffer: batch
    k+1 stages on the host while batch k's launch occupies the chip.
    """

    agg_id: int
    placed: Dict[str, object]
    #: padded batch size the compiled executable was (or will be) built for
    pad_to: int
    #: real rows in the batch (readbacks slice to this)
    rows: int


def _observe_prepare(backend: str, phase: str, reports: int, seconds: float) -> None:
    """Per-backend steady-state throughput/latency metrics (VERDICT r4 #6).

    Also the oracle-path COST ATTRIBUTION hook (ISSUE 12): when the
    calling thread carries a task scope (core/costs.run_in_task_scope —
    the drivers and the helper bind it around oracle fallbacks and direct
    backend batches), the same measured duration lands on
    ``janus_task_device_seconds_total{task,phase,path}``, path derived
    from the backend name — so an open breaker's cost shift to the CPU
    oracle is visible per task.  Conservation is exact by construction:
    one measurement, observed once here and attributed once there."""
    from ..core import costs
    from ..core.metrics import GLOBAL_METRICS

    if GLOBAL_METRICS.registry is not None:
        GLOBAL_METRICS.observe_prepare(backend, phase, reports, seconds)
    costs.attribute_prepare(backend, phase, seconds)


class OracleBackend:
    """Scalar per-report loop — the analog of the reference's rayon hop
    (reference: aggregator/src/aggregator.rs:2101)."""

    name = "oracle"

    def __init__(self, vdaf: Prio3):
        self.vdaf = vdaf

    def prep_init_batch(
        self,
        verify_key: bytes,
        agg_id: int,
        reports: Sequence[Tuple[bytes, Optional[List[bytes]], Prio3InputShare]],
    ) -> List[PrepOutcome]:
        t0 = time.monotonic()
        out: List[PrepOutcome] = []
        for nonce, public_share, input_share in reports:
            try:
                out.append(
                    self.vdaf.prep_init(verify_key, agg_id, nonce, public_share, input_share)
                )
            except VdafError as e:
                out.append(e)
        _observe_prepare(self.name, "init", len(out), time.monotonic() - t0)
        return out

    def prep_shares_to_prep_batch(
        self, prep_shares: Sequence[Sequence[Prio3PrepareShare]]
    ) -> List[Union[Optional[bytes], VdafError]]:
        t0 = time.monotonic()
        out: List[Union[Optional[bytes], VdafError]] = []
        for shares in prep_shares:
            try:
                out.append(self.vdaf.prep_shares_to_prep(shares))
            except VdafError as e:
                out.append(e)
        _observe_prepare(self.name, "combine", len(out), time.monotonic() - t0)
        return out


#: Field-arithmetic layouts for the device backends (ops/prepare.py):
#: "vpu" = scalar-lane CIOS multiply chains + limb-planar Pallas kernels;
#: "mxu" = limb-plane dot_general contractions (JField.mat_mul_mont) so the
#: FLP wire/gadget math runs on the matrix units.  Bit-exact either way —
#: the CPU oracle stays the correctness fence for both.
FIELD_BACKENDS = ("vpu", "mxu")


def default_field_backend() -> str:
    """Process default, overridable via JANUS_TPU_FIELD_BACKEND (the A/B
    knob for bench runs that don't thread a config file)."""
    import os

    return os.environ.get("JANUS_TPU_FIELD_BACKEND", "vpu")


def _resolve_field_backend(field_backend: Optional[str]) -> str:
    fb = field_backend or default_field_backend()
    if fb not in FIELD_BACKENDS:
        raise VdafError(f"unknown field_backend {fb!r}")
    return fb


def _req_parts(req):
    """A prepare request is ``(verify_key, reports)`` or — on a CANONICAL
    backend (vdaf/canonical.py) — ``(verify_key, reports, actual_vdaf)``,
    the third element naming the task's true (unpadded) VDAF so marshal
    can pad its rows to the bucket shape and unmarshal can slice back."""
    return req[0], req[1], (req[2] if len(req) > 2 else None)


def oracle_backend_for(backend, vdaf):
    """The bit-exact CPU oracle for serving ``vdaf``'s reports when
    ``backend`` cannot (circuit open, executable warming, replay).  The
    single chokepoint for canonical routing: a canonical backend's own
    ``.oracle`` computes the bucket twin's padded circuit, so it must
    resolve through ``oracle_for(vdaf)``; plain backends fall back to
    their ``.oracle`` (or None when there is none)."""
    if hasattr(backend, "oracle_for"):
        return backend.oracle_for(vdaf)
    return getattr(backend, "oracle", None)


class TpuBackend:
    """Batched device prepare: one XLA launch per aggregation job."""

    name = "tpu"
    #: this backend can keep a flush's out shares resident on device and
    #: hand back ResidentRefs (executor/accumulator.py) instead of limbs
    supports_resident_out_shares = True
    #: leading-axis rows of an accumulator buffer (accumulate_rows):
    #: 1 on a single chip; the mesh backend keeps one partial-sum row PER
    #: DEVICE so the accumulator store can account resident bytes honestly
    accum_buffer_rows = 1

    def __init__(
        self,
        vdaf: Prio3,
        field_backend: Optional[str] = None,
        canonical: bool = False,
    ):
        if vdaf.xof is not XofTurboShake128:
            raise VdafError("TPU backend requires the TurboSHAKE XOF")
        import jax

        from ..ops.prepare import BatchedPrio3

        self.vdaf = vdaf
        #: CANONICAL mode (vdaf/canonical.py): ``vdaf`` is a bucket's
        #: padded twin shared by every task in the bucket.  Requests carry
        #: the task's actual vdaf (3-tuples), marshal pads measurement
        #: columns and emits the per-row ``meas_len_u32`` mask input, and
        #: the graphs run row-major (the planar Pallas kernels take no
        #: masks).  The graph SIGNATURE is mode-fixed, so one executable
        #: serves every task mix.
        self.canonical = canonical
        #: "vpu" | "mxu" — see FIELD_BACKENDS; carried so the executor's
        #: mesh upgrade (_meshify) preserves the layout choice.
        self.field_backend = _resolve_field_backend(field_backend)
        self.bp = BatchedPrio3(vdaf, field_backend=self.field_backend)
        self.oracle = OracleBackend(vdaf)
        #: actual-shape oracles for canonical-mode fallback rows, keyed by
        #: vdaf_shape_key (a row that overflowed the device margin must be
        #: recomputed by ITS task's oracle, not the bucket twin's)
        self._oracles: Dict[tuple, OracleBackend] = {}
        self._jax = jax
        self._prep_fns: Dict[int, object] = {}
        self._combine_fn = None
        self._agg_fn = None
        self._accum_fn = None
        #: out-share rows transferred device->host by prepare launches —
        #: the flush-readback counter the accumulator acceptance tests
        #: assert stays 0 in the device-resident steady state
        self.outshare_readback_rows = 0

    def oracle_for(self, vdaf=None) -> OracleBackend:
        """The bit-exact CPU oracle for ``vdaf`` (None/own = this
        backend's).  Canonical-mode callers MUST route fallbacks through
        this — the bucket twin's oracle computes a different circuit."""
        if vdaf is None or vdaf is self.vdaf:
            return self.oracle
        key = vdaf_shape_key(vdaf)
        o = self._oracles.get(key)
        if o is None:
            o = self._oracles[key] = OracleBackend(vdaf)
        return o

    # -- jit caches ------------------------------------------------------
    #: Gate for the limb-planar fast path.  Pallas custom calls do not
    #: partition under SHARDED jit, but MeshBackend routes its launches
    #: through shard_map (manual partitioning), where each chip runs the
    #: planar kernels on its own shard — so both backends keep this True;
    #: it remains a seam for environments whose compiler lacks the kernels.
    _planar_capable = True

    def _prep_fn(self, agg_id: int):
        # verify_key flows as a traced input (it is per-task data), so one
        # compilation per agg_id serves every task.
        fn = self._prep_fns.get(agg_id)
        if fn is None:

            def prep(kw):
                vk = kw.pop("verify_key_u8")
                B = kw["nonces_u8"].shape[0]
                # Canonical-mode batches carry the per-row mask input and
                # run row-major only (the planar kernels take no masks).
                if (
                    self._planar_capable
                    and "meas_len_u32" not in kw
                    and self.bp.planar_eligible(agg_id, B)
                ):
                    # Limb-planar fast path (the bench pipeline), both
                    # sides: helpers expand share seeds through the planar
                    # XOF, the leader transposes its explicit shares in.
                    # Outputs are identical; out_share transposes back to
                    # row-major for the unmarshal/aggregate interfaces.
                    out = self.bp.prep_init_planar(
                        agg_id,
                        vk,
                        kw["nonces_u8"],
                        share_seeds_u8=kw.get("share_seeds_u8"),
                        meas_limbs=kw.get("meas_limbs"),
                        proofs_limbs=kw.get("proofs_limbs"),
                        blinds_u8=kw.get("blinds_u8"),
                        public_parts_u8=kw.get("public_parts_u8"),
                    )
                    out = dict(
                        out,
                        out_share=self.bp.planar_out_share_to_rows(out["out_share"]),
                    )
                    return out
                return self.bp.prep_init(agg_id, verify_key=vk, **kw)

            fn = self._jax.jit(prep)
            self._prep_fns[agg_id] = fn
        return fn

    def _combine(self):
        if self._combine_fn is None:
            has_jr = self.vdaf.flp.JOINT_RAND_LEN > 0
            if has_jr:
                self._combine_fn = self._jax.jit(
                    lambda vs, parts: self.bp.prep_shares_to_prep(vs, parts)
                )
            else:
                self._combine_fn = self._jax.jit(
                    lambda vs, parts: self.bp.prep_shares_to_prep(vs)
                )
        return self._combine_fn

    # -- marshaling ------------------------------------------------------
    def _marshal(
        self, agg_id, reports, pad_to: int, segments=None
    ) -> Dict[str, np.ndarray]:
        """``segments`` (canonical mode): ``[(rows, actual_meas_len)]``
        per contiguous same-task run of ``reports`` — leader measurement
        limbs land in the leading ``actual_meas_len`` columns of the
        bucket-width matrix (the pad columns STAY ZERO; the graph's mask
        and the select-absorb's pad construction both require it) and
        every row gets its ``meas_len_u32`` mask input."""
        vdaf, flp, jf = self.vdaf, self.vdaf.flp, self.bp.jf
        B = len(reports)
        seed_size = vdaf.xof.SEED_SIZE

        def stack_bytes(rows, width) -> np.ndarray:
            arr = np.frombuffer(b"".join(rows), dtype=np.uint8).reshape(B, width)
            return np.concatenate([arr, np.repeat(arr[-1:], pad_to - B, axis=0)])

        kw: Dict[str, np.ndarray] = {
            "nonces_u8": stack_bytes([r[0] for r in reports], vdaf.NONCE_SIZE)
        }
        if flp.JOINT_RAND_LEN > 0:
            kw["public_parts_u8"] = stack_bytes(
                [b"".join(r[1]) for r in reports], vdaf.num_shares * seed_size
            ).reshape(pad_to, vdaf.num_shares, seed_size)
            kw["blinds_u8"] = stack_bytes(
                [r[2].joint_rand_blind for r in reports], seed_size
            )
        if agg_id == 0:
            if segments is None:
                meas = jf.to_limbs(
                    [x for r in reports for x in r[2].meas_share]
                ).reshape(B, flp.MEAS_LEN, jf.n)
            else:
                meas = np.zeros((B, flp.MEAS_LEN, jf.n), dtype=np.uint32)
                limbs = jf.to_limbs([x for r in reports for x in r[2].meas_share])
                row = off = 0
                for rows, mlen in segments:
                    meas[row : row + rows, :mlen] = limbs[
                        off : off + rows * mlen
                    ].reshape(rows, mlen, jf.n)
                    row += rows
                    off += rows * mlen
            proofs = jf.to_limbs(
                [x for r in reports for x in r[2].proofs_share]
            ).reshape(B, flp.PROOF_LEN * vdaf.num_proofs, jf.n)
            kw["meas_limbs"] = np.concatenate(
                [meas, np.repeat(meas[-1:], pad_to - B, axis=0)]
            )
            kw["proofs_limbs"] = np.concatenate(
                [proofs, np.repeat(proofs[-1:], pad_to - B, axis=0)]
            )
        else:
            kw["share_seeds_u8"] = stack_bytes([r[2].share_seed for r in reports], seed_size)
        if segments is not None:
            lens = np.concatenate(
                [np.full(rows, mlen, dtype=np.uint32) for rows, mlen in segments]
            )
            kw["meas_len_u32"] = np.concatenate(
                [lens, np.repeat(lens[-1:], pad_to - B, axis=0)]
            )
        return kw

    # -- placement hooks (MeshBackend shards these over the device mesh) --
    def _pad_to(self, B: int) -> int:
        """Power-of-two bucketing bounds recompiles to log2 distinct shapes."""
        return next_power_of_2(B)

    def _align_pad(self, pad_to: int) -> int:
        """Final alignment applied to an explicitly requested pad (warmup's
        target mega-batch shape); the mesh backend rounds it up so the
        batch axis divides evenly across the mesh."""
        return pad_to

    def _place(self, kw: Dict[str, np.ndarray]) -> Dict[str, object]:
        """Commit marshaled inputs to device(s); identity on a single chip."""
        return kw

    def _place_batch(self, arr: np.ndarray):
        """Commit one batch-axis array to device(s)."""
        return arr

    # -- batch APIs ------------------------------------------------------
    def prep_init_batch(
        self,
        verify_key: bytes,
        agg_id: int,
        reports: Sequence[Tuple[bytes, Optional[List[bytes]], Prio3InputShare]],
    ) -> List[PrepOutcome]:
        """Single-task launch: the one-request form of prep_init_multi
        (same compiled graph — the verify key is a per-row traced input
        either way)."""
        if not reports:
            return []
        return self.prep_init_multi(agg_id, [(verify_key, reports)])[0]

    def _unmarshal_prep(
        self, verify_key, agg_id, reports, out, resident=None, actual_vdaf=None
    ) -> List[PrepOutcome]:
        """``resident=(flush_id, start_row)`` means the out-share matrix
        stayed on device (accumulator store): states carry ResidentRefs
        instead of limb vectors and no out-share bytes cross the PCIe.
        ``actual_vdaf`` (canonical mode) slices the bucket-width out share
        back to the task's OUTPUT_LEN — the pad tail is provably zero —
        and routes margin-overflow fallback rows to the TASK's oracle."""
        flp, jf = self.vdaf.flp, self.bp.jf
        out_len = (actual_vdaf or self.vdaf).flp.OUTPUT_LEN
        oracle = self.oracle_for(actual_vdaf)
        B = len(reports)
        ok = np.asarray(out["ok"])[:B]
        verifiers = np.asarray(out["verifiers"])[:B]
        if resident is None:
            out_shares = np.asarray(out["out_share"])[:B]
        else:
            from ..executor.accumulator import ResidentRef

            flush_id, start_row = resident
        has_jr = flp.JOINT_RAND_LEN > 0
        if has_jr:
            parts = np.asarray(out["joint_rand_part"])[:B]
            corrected = np.asarray(out["corrected_seed"])[:B]

        results: List[PrepOutcome] = []
        for b in range(B):
            if not ok[b]:
                # Exact-path fallback: the device margin overflowed for this row.
                results.extend(
                    oracle.prep_init_batch(verify_key, agg_id, [reports[b]])
                )
                continue
            state = Prio3PrepareState(
                out_share=jf.from_limbs(out_shares[b, :out_len])
                if resident is None
                else ResidentRef(flush_id, start_row + b),
                corrected_joint_rand_seed=corrected[b].tobytes() if has_jr else None,
            )
            share = Prio3PrepareShare(
                verifiers_share=jf.from_limbs(verifiers[b]),
                joint_rand_part=parts[b].tobytes() if has_jr else None,
            )
            results.append((state, share))
        return results

    def prep_shares_to_prep_batch(
        self, prep_shares: Sequence[Sequence[Prio3PrepareShare]]
    ) -> List[Union[Optional[bytes], VdafError]]:
        if not prep_shares:
            return []
        faults.fire("backend.combine")
        vdaf, flp, jf = self.vdaf, self.vdaf.flp, self.bp.jf
        S = vdaf.num_shares
        # Rows with the wrong share count must fail exactly like the oracle
        # ("wrong number of prepare shares"), not be truncated or crash.
        bad_rows = {i for i, row in enumerate(prep_shares) if len(row) != S}
        if bad_rows:
            results = []
            good = [row for i, row in enumerate(prep_shares) if i not in bad_rows]
            good_iter = iter(self.prep_shares_to_prep_batch(good))
            for i in range(len(prep_shares)):
                if i in bad_rows:
                    results.append(VdafError("wrong number of prepare shares"))
                else:
                    results.append(next(good_iter))
            return results
        B = len(prep_shares)
        pad_to = self._pad_to(B)
        has_jr = flp.JOINT_RAND_LEN > 0

        ver_len = flp.VERIFIER_LEN * vdaf.num_proofs
        vs = []
        parts = []
        for a in range(S):
            limbs = jf.to_limbs(
                [x for row in prep_shares for x in row[a].verifiers_share]
            ).reshape(B, ver_len, jf.n)
            vs.append(
                self._place_batch(
                    np.concatenate([limbs, np.repeat(limbs[-1:], pad_to - B, axis=0)])
                )
            )
            if has_jr:
                arr = np.frombuffer(
                    b"".join(row[a].joint_rand_part for row in prep_shares), dtype=np.uint8
                ).reshape(B, vdaf.xof.SEED_SIZE)
                parts.append(
                    self._place_batch(
                        np.concatenate([arr, np.repeat(arr[-1:], pad_to - B, axis=0)])
                    )
                )

        t0 = time.monotonic()
        out = self._combine()(vs, parts)
        decide = np.asarray(out["decide"])[:B]
        seeds = np.asarray(out["prep_msg_seed"])[:B] if has_jr else None
        _observe_prepare(self.name, "combine", B, time.monotonic() - t0)

        results: List[Union[Optional[bytes], VdafError]] = []
        for b in range(B):
            if not decide[b]:
                results.append(VdafError("proof verification failed"))
            elif has_jr:
                results.append(seeds[b].tobytes())
            else:
                results.append(None)
        return results

    def stage_prep_init_multi(
        self,
        agg_id: int,
        requests: Sequence[
            Tuple[bytes, Sequence[Tuple[bytes, Optional[List[bytes]], Prio3InputShare]]]
        ],
        pad_to: Optional[int] = None,
    ) -> Optional[StagedPrepInit]:
        """Host half of a multi-request launch: flatten, marshal, pow2-pad,
        and commit to device.  Returns None when no request carries rows.

        ``pad_to`` overrides the power-of-two bucket (the executor's warmup
        uses it to compile a target mega-batch shape from a handful of
        synthetic rows)."""
        flat: List = []
        vk_rows: List[np.ndarray] = []
        segments: Optional[List] = [] if self.canonical else None
        for req in requests:
            verify_key, reports, actual = _req_parts(req)
            flat.extend(reports)
            vk = np.frombuffer(verify_key, dtype=np.uint8)
            vk_rows.extend([vk] * len(reports))
            if segments is not None and reports:
                # a 2-tuple request (warmup's synthetic rows) is shaped for
                # the canonical twin itself: its mask is the full width
                mlen = (actual or self.vdaf).flp.MEAS_LEN
                segments.append((len(reports), mlen))
        if not flat:
            return None
        B = len(flat)
        pad_to = self._align_pad(max(pad_to or 0, self._pad_to(B)))
        kw = self._marshal(agg_id, flat, pad_to, segments=segments)
        vk_mat = np.stack(vk_rows)
        kw["verify_key_u8"] = np.concatenate(
            [vk_mat, np.repeat(vk_mat[-1:], pad_to - B, axis=0)]
        )
        return StagedPrepInit(
            agg_id=agg_id, placed=self._place(kw), pad_to=pad_to, rows=B
        )

    def launch_prep_init_multi(
        self,
        staged: StagedPrepInit,
        requests: Sequence[
            Tuple[bytes, Sequence[Tuple[bytes, Optional[List[bytes]], Prio3InputShare]]]
        ],
        retain_store=None,
    ) -> List[List[PrepOutcome]]:
        """Device half: run the compiled prepare on a staged batch, read
        back once, and slice results per request.

        ``retain_store`` (a DeviceAccumulatorStore) is the accumulate-into-
        buffer variant: the (pad, OUT, n) out-share matrix stays RESIDENT on
        device (adopted by the store) and each ok row's state carries a
        ResidentRef; only the small verdict outputs (ok / verifiers /
        joint-rand) are read back, so the flush pays zero out-share
        readback."""
        # Failure-domain boundary: an injected launch fault impersonates
        # XLA OOM / plugin loss; callers (executor breaker, driver retry
        # budget) must degrade gracefully.  The oracle has no such point —
        # it is the fallback truth.  backend.device_lost is the mesh-
        # flavored twin: a chip dropping out of the mesh mid-launch, which
        # the executor's per-MESH breaker must answer by opening the
        # circuit for EVERY mesh-backed shape (./ci.sh chaos exercises it).
        faults.fire("backend.launch")
        faults.fire("backend.device_lost")
        agg_id, B = staged.agg_id, staged.rows
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.device_launches.labels(backend=self.name).inc()
            GLOBAL_METRICS.device_reports.labels(backend=self.name).inc(B)
        from ..core.trace import trace_span

        t0 = time.monotonic()
        resident = None
        try:
            with trace_span("prep_launch", cat="device", backend=self.name, batch=B):
                out = dict(self._prep_fn(agg_id)(staged.placed))
                if retain_store is not None:
                    matrix = out.pop("out_share")
                    nbytes = int(np.prod(matrix.shape)) * 4
                    flush_id = retain_store.retain_flush(self, matrix, B, nbytes)
                    resident = (flush_id, 0)
                else:
                    self.outshare_readback_rows += B
                # One readback for the whole launch, then slice per request.
                outputs = {k: np.asarray(v)[:B] for k, v in out.items()}
            _observe_prepare(self.name, "init", B, time.monotonic() - t0)
            start = 0
            results: List[List[PrepOutcome]] = []
            for req in requests:
                verify_key, reports, actual = _req_parts(req)
                n = len(reports)
                view = {k: v[start : start + n] for k, v in outputs.items()}
                results.append(
                    self._unmarshal_prep(
                        verify_key,
                        agg_id,
                        reports,
                        view,
                        resident=None
                        if resident is None
                        else (resident[0], start),
                        actual_vdaf=actual,
                    )
                )
                start += n
        except Exception:
            if resident is not None:
                # a failure after the store adopted the matrix (verdict
                # readback, unmarshal) must not strand the flush: release
                # every row so it frees (release is idempotent)
                from ..executor.accumulator import ResidentRef

                retain_store.release_refs(
                    [ResidentRef(resident[0], r) for r in range(B)]
                )
            raise
        if resident is not None:
            # rows the oracle fallback served (device margin overflow)
            # never minted a ref; release them so the flush can free
            from ..executor.accumulator import ResidentRef

            ok_all = np.asarray(outputs["ok"])
            dead = [ResidentRef(resident[0], r) for r in range(B) if not ok_all[r]]
            if dead:
                retain_store.release_refs(dead)
        return results

    def prep_init_multi(
        self,
        agg_id: int,
        requests: Sequence[
            Tuple[bytes, Sequence[Tuple[bytes, Optional[List[bytes]], Prio3InputShare]]]
        ],
    ) -> List[List[PrepOutcome]]:
        """ONE device launch preparing reports from MULTIPLE tasks.

        ``requests``: (verify_key, reports) per task, all sharing this
        backend's VDAF shape.  The verify key is a traced per-ROW input, so
        the same compiled graph serves any task mix (BASELINE configs[4]'s
        16-concurrent-task shape on a single chip; the mesh backend shards
        the concatenated batch across chips).  Results are returned
        per-request, byte-identical to separate launches.
        """
        if not requests:
            return []
        staged = self.stage_prep_init_multi(agg_id, requests)
        if staged is None:
            return [[] for _ in requests]
        return self.launch_prep_init_multi(staged, requests)

    # -- device-resident accumulation (executor/accumulator.py) ----------
    def accumulate_rows(self, buffer, matrix, mask: np.ndarray):
        """Accumulate-into-buffer launch: psum the ``mask``-selected rows
        of a resident (pad, OUT, n) out-share matrix into ``buffer`` (an
        (OUT, n) limb accumulator; None starts one).  Pure device work —
        no readback; the result is the new resident buffer."""
        if self._accum_fn is None:
            jnp = self._jax.numpy
            jf = self.bp.jf

            def accum(buf, m, msk):
                masked = jnp.where(msk[:, None, None], m, jnp.zeros_like(m))
                delta = jf.sum(masked, axis=0)
                return jf.add(buf, delta)

            self._accum_fn = self._jax.jit(accum)
        if buffer is None:
            jf = self.bp.jf
            buffer = np.zeros((self.vdaf.flp.OUTPUT_LEN, jf.n), dtype=np.uint32)
        return self._accum_fn(buffer, matrix, mask)

    def read_accum_buffer(self, buffer) -> List[int]:
        """Spill readback: ONE (OUT,) field vector — the commit-time drain."""
        return self.bp.jf.from_limbs(np.asarray(buffer))

    def aggregate_batch(self, out_shares_limbs, mask) -> List[int]:
        """Masked out-share aggregation on-device.

        out_shares_limbs (B, OUT, n) canonical, mask (B,) bool -> aggregate
        share as field integers.  On MeshBackend the inputs are sharded over
        the batch axis and the reduction crosses shard boundaries, so XLA
        lowers it to per-device partial sums + an all-reduce over the mesh —
        the collective replacing the reference's DB shard merge
        (reference: aggregator/src/aggregator/aggregation_job_writer.rs:591-698).
        """
        if self._agg_fn is None:
            self._agg_fn = self._jax.jit(self.bp.aggregate)
        shares = np.asarray(out_shares_limbs)
        m = np.asarray(mask)
        B = shares.shape[0]
        pad_to = self._pad_to(B)
        if pad_to != B:  # zero rows masked False: no effect on the sum
            shares = np.concatenate(
                [shares, np.zeros((pad_to - B,) + shares.shape[1:], shares.dtype)]
            )
            m = np.concatenate([m, np.zeros(pad_to - B, dtype=bool)])
        return self.bp.jf.from_limbs(
            np.asarray(self._agg_fn(self._place_batch(shares), self._place_batch(m)))
        )


class MeshBackend(TpuBackend):
    """SPMD batched prepare over a ``jax.sharding.Mesh``.

    The product form of the multi-chip path (not just the dryrun): every
    prepare / combine launch is sharded over the mesh's ``batch`` axis, so
    on a v5e-8 slice each chip prepares 1/8 of the job's reports, and
    ``aggregate_batch`` reduces out shares ACROSS chips on-device — XLA
    inserts the all-reduce over ICI for the sum along the sharded axis.
    This replaces the reference's write-contention DB shard merge
    (reference: aggregator/src/aggregator/aggregation_job_writer.rs:591-698)
    with a collective, exactly the psum re-design named in SURVEY §2.3 P4.

    Selected via the service config ``vdaf_backend: mesh``.  On a single
    device it degrades to TpuBackend behavior (mesh of 1).
    """

    name = "mesh"

    def __init__(
        self,
        vdaf: Prio3,
        devices=None,
        field_backend: Optional[str] = None,
        canonical: bool = False,
    ):
        super().__init__(vdaf, field_backend=field_backend, canonical=canonical)
        import os

        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        if devices is not None:
            devs = list(devices)
        elif os.environ.get("JANUS_TPU_MESH_SPAN", "local") == "global":
            # Multi-controller SPMD: ONLY sound when every process runs the
            # same launch sequence in lockstep (gang-scheduled deployments;
            # a lease-driven daemon must NOT set this — its launches are
            # per-replica and a cross-host collective would deadlock).
            devs = jax.devices()
        else:
            # Per-replica mesh over this host's chips (ICI); cross-host
            # scale-out is the N-replica shared-datastore model, exactly
            # the reference's deployment shape (docs/DEPLOYING.md:29-31).
            devs = jax.local_devices()
        self.mesh = Mesh(np.array(devs), ("batch",))
        self._batch_sharding = NamedSharding(self.mesh, PartitionSpec("batch"))
        self._replicated = NamedSharding(self.mesh, PartitionSpec())
        #: accumulator buffers keep one (OUT, n) partial-sum row per device
        self.accum_buffer_rows = len(devs)
        self._accum_read_fn = None

    # -- sharded launches -------------------------------------------------
    # prepare/combine run under shard_map (manual partitioning): each chip
    # executes the SAME per-shard program TpuBackend runs — including the
    # limb-planar Pallas kernels, which do not partition under sharded jit
    # but run fine per-shard — on its 1/N of the batch.  No cross-shard
    # dataflow exists in prepare, so out_specs are batch-sharded
    # everywhere; the cross-chip psum stays in aggregate_batch (sharded
    # jit, XLA inserts the all-reduce).  planar_eligible is evaluated on
    # the LOCAL (per-shard) batch during tracing, so planar engages exactly
    # when each chip's shard satisfies the kernels' tiling.

    def _shard_wrap(self, per_shard):
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec

        return jax.jit(
            shard_map(
                per_shard,
                mesh=self.mesh,
                in_specs=(PartitionSpec("batch"),),
                out_specs=PartitionSpec("batch"),
                check_rep=False,
            )
        )

    def _prep_fn(self, agg_id: int):
        fn = self._prep_fns.get(agg_id)
        if fn is None:

            def per_shard(kw):
                vk = kw.pop("verify_key_u8")
                B = kw["nonces_u8"].shape[0]
                if (
                    self._planar_capable
                    and "meas_len_u32" not in kw
                    and self.bp.planar_eligible(agg_id, B)
                ):
                    out = self.bp.prep_init_planar(
                        agg_id,
                        vk,
                        kw["nonces_u8"],
                        share_seeds_u8=kw.get("share_seeds_u8"),
                        meas_limbs=kw.get("meas_limbs"),
                        proofs_limbs=kw.get("proofs_limbs"),
                        blinds_u8=kw.get("blinds_u8"),
                        public_parts_u8=kw.get("public_parts_u8"),
                    )
                    return dict(
                        out,
                        out_share=self.bp.planar_out_share_to_rows(out["out_share"]),
                    )
                return self.bp.prep_init(agg_id, verify_key=vk, **kw)

            fn = self._shard_wrap(per_shard)
            self._prep_fns[agg_id] = fn
        return fn

    def _combine(self):
        if self._combine_fn is None:
            has_jr = self.vdaf.flp.JOINT_RAND_LEN > 0

            def per_shard(args):
                vs, parts = args
                return self.bp.prep_shares_to_prep(vs, parts if has_jr else None)

            wrapped = self._shard_wrap(per_shard)
            self._combine_fn = lambda vs, parts: wrapped((vs, parts))
        return self._combine_fn

    # The batch APIs are inherited: only padding and placement differ.
    def _pad_to(self, B: int) -> int:
        # Power-of-two bucketing (bounds recompiles) rounded up to a
        # MULTIPLE of the mesh size, so the batch axis divides evenly and
        # every shard sees the same local batch — the flush-tail guarantee
        # planar_eligible's per-shard tiling check relies on.  (For a
        # power-of-two mesh the pow2 pad is already a multiple; the
        # rounding matters on odd-sized meshes, e.g. after a chip is
        # cordoned out.)
        n = len(self.mesh.devices)
        return self._align_pad(max(next_power_of_2(B), n))

    def _align_pad(self, pad_to: int) -> int:
        n = len(self.mesh.devices)
        return -(-pad_to // n) * n

    def _place(self, kw: Dict[str, np.ndarray]) -> Dict[str, object]:
        """Commit per-report arrays shard-per-device.

        Every marshaled array — including verify_key_u8, which
        prep_init_multi expands to one row per report — has the batch as
        its leading axis, matching _shard_wrap's in_specs."""
        return {
            k: self._jax.device_put(v, self._batch_sharding) for k, v in kw.items()
        }

    def _place_batch(self, arr: np.ndarray):
        return self._jax.device_put(arr, self._batch_sharding)

    # -- sharded device-resident accumulation -----------------------------
    # The accumulator store's per-bucket buffers stay SHARDED: one
    # (OUT, n) partial-sum row per device, batch-sharded over the mesh.
    # accumulate_rows is pure per-shard work (each chip psums the
    # mask-selected rows of ITS shard of the retained out-share matrix
    # into ITS partial row — no collective, no readback), and the ONE
    # cross-chip reduction happens at drain/spill time in
    # read_accum_buffer, where XLA lowers the sum over the device-sharded
    # axis to an all-reduce.  Bucket placement decision: one bucket spans
    # the LOCAL mesh (the same ICI domain its flush matrices live on);
    # hashing buckets across meshes on multi-slice hosts stays a ROADMAP
    # item.

    def accumulate_rows(self, buffer, matrix, mask: np.ndarray):
        """Per-shard psum of the mask-selected rows of a batch-sharded
        (pad, OUT, n) out-share matrix into a (n_dev, OUT, n) sharded
        buffer (None starts one).  Zero cross-chip traffic."""
        if self._accum_fn is None:
            jnp = self._jax.numpy
            jf = self.bp.jf

            def per_shard(buf, m, msk):
                masked = jnp.where(msk[:, None, None], m, jnp.zeros_like(m))
                delta = jf.sum(masked, axis=0)
                return jf.add(buf, delta[None])

            self._accum_fn = self._shard_wrap3(per_shard)
        if buffer is None:
            jf = self.bp.jf
            buffer = self._jax.device_put(
                np.zeros(
                    (len(self.mesh.devices), self.vdaf.flp.OUTPUT_LEN, jf.n),
                    dtype=np.uint32,
                ),
                self._batch_sharding,
            )
        return self._accum_fn(buffer, matrix, np.asarray(mask))

    def _shard_wrap3(self, per_shard):
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec

        return jax.jit(
            shard_map(
                per_shard,
                mesh=self.mesh,
                in_specs=(
                    PartitionSpec("batch"),
                    PartitionSpec("batch"),
                    PartitionSpec("batch"),
                ),
                out_specs=PartitionSpec("batch"),
                check_rep=False,
            )
        )

    def read_accum_buffer(self, buffer) -> List[int]:
        """Spill readback: the one point where the accumulated shards
        cross chips — a modular tree-sum over the device-sharded leading
        axis (XLA inserts the all-reduce), then ONE (OUT,) vector to the
        host.  (A raw integer psum over u32 limb arrays would be wrong —
        the carry chain must run inside the modular sum.)"""
        if self._accum_read_fn is None:
            jf = self.bp.jf
            self._accum_read_fn = self._jax.jit(lambda b: jf.sum(b, axis=0))
        return self.bp.jf.from_limbs(np.asarray(self._accum_read_fn(buffer)))


class HybridXofBackend:
    """Host-XOF + device-FLP hybrid for non-TurboSHAKE Prio3 instances.

    The HMAC-SHA256-AES128 multiproof VDAF (reference:
    core/src/vdaf.rs:178-195) keeps its XOF on the host — HMAC/AES have no
    TPU kernels worth writing, and the multiproof circuits' XOF volume is
    tiny — while the FLP queries (num_proofs of them) and the decide run
    as one batched device launch (BatchedPrio3.query_batch/decide_batch).
    Byte parity with the oracle is the same contract as TpuBackend's
    (tests/test_backend.py)."""

    name = "tpu-hybrid"

    def __init__(self, vdaf: Prio3, field_backend: Optional[str] = None):
        import jax

        from ..ops.prepare import BatchedPrio3

        self.vdaf = vdaf
        self.field_backend = _resolve_field_backend(field_backend)
        self.bp = BatchedPrio3(
            vdaf, require_device_xof=False, field_backend=self.field_backend
        )
        self.oracle = OracleBackend(vdaf)
        self._jax = jax
        self._query_fn = None
        self._decide_fn = None

    def _pad_to(self, B: int) -> int:
        return next_power_of_2(B)

    def prep_init_batch(self, verify_key, agg_id, reports):
        if not reports:
            return []
        vdaf, flp, jf = self.vdaf, self.vdaf.flp, self.bp.jf
        t0 = time.monotonic()
        B = len(reports)
        has_jr = flp.JOINT_RAND_LEN > 0
        meas_rows: List[int] = []
        proof_rows: List[int] = []
        qr_rows: List[int] = []
        jr_rows: List[int] = []
        parts: List[Optional[bytes]] = []
        corrected: List[Optional[bytes]] = []
        for nonce, public_share, input_share in reports:
            # host XOF stage — mirrors Prio3.prep_init element for element
            if agg_id == 0:
                meas = input_share.meas_share
                proofs = input_share.proofs_share
            else:
                meas = vdaf._helper_meas_share(agg_id, input_share.share_seed)
                proofs = vdaf._helper_proofs_share(agg_id, input_share.share_seed)
            meas_rows.extend(meas)
            proof_rows.extend(proofs)
            qr_rows.extend(vdaf._query_rands(verify_key, nonce))
            if has_jr:
                part = vdaf._joint_rand_part(
                    agg_id, input_share.joint_rand_blind, meas, nonce
                )
                ps = list(public_share)
                ps[agg_id] = part
                cs = vdaf._joint_rand_seed(ps)
                jr_rows.extend(vdaf._joint_rands(cs))
                parts.append(part)
                corrected.append(cs)
            else:
                parts.append(None)
                corrected.append(None)

        pad_to = self._pad_to(B)

        def limb_mat(vals, width):
            arr = jf.to_limbs(vals).reshape(B, width, jf.n)
            return np.concatenate([arr, np.repeat(arr[-1:], pad_to - B, axis=0)])

        meas_l = limb_mat(meas_rows, flp.MEAS_LEN)
        proofs_l = limb_mat(proof_rows, flp.PROOF_LEN * vdaf.num_proofs)
        qr_l = limb_mat(qr_rows, flp.QUERY_RAND_LEN * vdaf.num_proofs)
        jr_l = (
            limb_mat(jr_rows, flp.JOINT_RAND_LEN * vdaf.num_proofs)
            if has_jr
            else None
        )
        if self._query_fn is None:
            self._query_fn = self._jax.jit(self.bp.query_batch)
        out = self._query_fn(meas_l, proofs_l, jr_l, qr_l)
        ok = np.asarray(out["ok"])[:B]
        verifiers = np.asarray(out["verifiers"])[:B]
        out_shares = np.asarray(out["out_share"])[:B]

        results: List[PrepOutcome] = []
        for b in range(B):
            if not ok[b]:
                # Per-row oracle rescue is an INTERNAL detail of this
                # device batch: the enclosing _observe_prepare below
                # already spans it, so the nested oracle call must not
                # ALSO attribute its slice to the task's cost scope (the
                # conservation invariant is one measurement, attributed
                # once) — clear the scope around the rescue.
                from ..core import costs

                results.extend(
                    costs.run_in_task_scope(
                        None,
                        lambda b=b: self.oracle.prep_init_batch(
                            verify_key, agg_id, [reports[b]]
                        ),
                    )
                )
                continue
            state = Prio3PrepareState(
                out_share=jf.from_limbs(out_shares[b]),
                corrected_joint_rand_seed=corrected[b],
            )
            share = Prio3PrepareShare(
                verifiers_share=jf.from_limbs(verifiers[b]),
                joint_rand_part=parts[b],
            )
            results.append((state, share))
        _observe_prepare(self.name, "init", B, time.monotonic() - t0)
        return results

    def prep_shares_to_prep_batch(self, prep_shares):
        if not prep_shares:
            return []
        vdaf, flp, jf = self.vdaf, self.vdaf.flp, self.bp.jf
        t0 = time.monotonic()
        S = vdaf.num_shares
        bad_rows = {i for i, row in enumerate(prep_shares) if len(row) != S}
        if bad_rows:
            results = []
            good = [row for i, row in enumerate(prep_shares) if i not in bad_rows]
            good_iter = iter(self.prep_shares_to_prep_batch(good))
            for i in range(len(prep_shares)):
                results.append(
                    VdafError("wrong number of prepare shares")
                    if i in bad_rows
                    else next(good_iter)
                )
            return results
        B = len(prep_shares)
        pad_to = self._pad_to(B)
        ver_len = flp.VERIFIER_LEN * vdaf.num_proofs
        acc_rows = [row[0].verifiers_share for row in prep_shares]
        for a in range(1, S):
            acc_rows = [
                flp.field.vec_add(prev, row[a].verifiers_share)
                for prev, row in zip(acc_rows, prep_shares)
            ]
        comb_l = jf.to_limbs([x for row in acc_rows for x in row]).reshape(
            B, ver_len, jf.n
        )
        comb_l = np.concatenate(
            [comb_l, np.repeat(comb_l[-1:], pad_to - B, axis=0)]
        )
        if self._decide_fn is None:
            self._decide_fn = self._jax.jit(self.bp.decide_batch)
        decide = np.asarray(self._decide_fn(comb_l))[:B]
        results = []
        has_jr = flp.JOINT_RAND_LEN > 0
        for b in range(B):
            if not decide[b]:
                results.append(VdafError("proof verification failed"))
            elif has_jr:
                results.append(
                    vdaf._joint_rand_seed(
                        [row.joint_rand_part for row in prep_shares[b]]
                    )
                )
            else:
                results.append(None)
        _observe_prepare(self.name, "combine", B, time.monotonic() - t0)
        return results


class Poplar1Oracle:
    """Scalar per-report Poplar1 prepare — the bit-exact CPU fallback the
    executor-routed heavy-hitters path degrades to (circuit open, journal
    replay), mirroring OracleBackend's role for Prio3."""

    name = "poplar1-oracle"

    def __init__(self, vdaf):
        self.vdaf = vdaf

    def prep_init_batch_poplar(self, verify_key, agg_id, agg_param, reports):
        t0 = time.monotonic()
        out = []
        for nonce, public_share, input_share in reports:
            try:
                out.append(
                    self.vdaf.prep_init(
                        verify_key, agg_id, agg_param, nonce, public_share, input_share
                    )
                )
            except VdafError as e:
                out.append(e)
        _observe_prepare(self.name, "init", len(out), time.monotonic() - t0)
        return out


class Poplar1Backend:
    """Batched prepare for Poplar1 (heavy hitters): bulk-AES IDPF tree walk
    on the host (AES-NI territory) + JField sketch inner products on the
    accelerator — see ops/poplar1_batch.py.  Exposed through the same
    dispatch seam as the Prio3 backends so the role logic stays
    VDAF-agnostic (reference: core/src/vdaf.rs:96 — Poplar1 rides the same
    accelerated dispatch as Prio3).  Through the device executor this
    backend serves the ``poplar_init`` submission kind: mega-batches whose
    bucket identity carries the aggregation parameter's tree LEVEL, so
    ping-pong rounds from different jobs at one IDPF level coalesce into
    one walk + one sketch launch (``prep_init_multi_poplar``)."""

    name = "poplar1-batch"

    def __init__(self, vdaf, poplar_backend: Optional[str] = None):
        from ..ops.poplar1_batch import BatchedPoplar1

        self.vdaf = vdaf
        #: AES-walk backend seam ("host" | "jax"; None = process default)
        self.bp = BatchedPoplar1(vdaf, poplar_backend=poplar_backend)
        #: bit-exact per-report CPU fallback (breaker open / replay), the
        #: same contract as the Prio3 backends' .oracle
        self.oracle = Poplar1Oracle(vdaf)

    @property
    def poplar_backend(self) -> str:
        return self.bp.walk_backend

    @property
    def supports_resident_sketch(self) -> bool:
        """Whether flushes may retain the sketch y matrices on device and
        hand back ResidentRefs: requires the jax walk (host-walked values
        are born in host memory — retaining them would be a readback in
        reverse)."""
        return self.bp.walk_backend == "jax"

    @property
    def sketch_readback_rows(self) -> int:
        """Device-walked rows whose y vectors were materialized to host
        (the acceptance counter: 0 on the device-resident path)."""
        return self.bp.sketch_readback_rows

    def oracle_for(self, vdaf=None) -> "Poplar1Oracle":
        """Uniform fallback-resolution face (oracle_backend_for): Poplar1
        backends are never canonicalized, so the answer is always this
        backend's own oracle."""
        return self.oracle

    def prep_init_batch_poplar(self, verify_key, agg_id, agg_param, reports):
        """Batched round-0 prep: per-report (state, share), oracle parity."""
        return self.prep_init_multi_poplar(
            agg_id, [(verify_key, agg_param, reports)]
        )[0]

    def stage_poplar_init_multi(self, agg_id, requests):
        """The WALK half of a poplar flush: bulk-AES IDPF eval per
        agg-param group, value shares staged (device-resident under the
        jax walk).  Runs on the executor's STAGING thread so walk k+1
        overlaps sketch launch k (the stage/launch double buffering).  A
        walk failure surfaces through the flush like a stage failure on
        the Prio3 path — the breaker counts it."""
        return self.bp.stage_init_multi(agg_id, requests)

    def launch_poplar_init_multi(self, staged, retain_store=None):
        """The SKETCH half: device inner products + state assembly over a
        staged walk.  The named fault points fire here so the per-shape
        circuit breaker (and chaos coverage) treats a sick sketch/walk
        path exactly like a sick XLA launch.  ``retain_store`` (the
        device accumulator store) adopts device-walked y matrices: states
        then carry ResidentRefs and the flush pays zero sketch readback."""
        faults.fire("backend.launch")
        faults.fire("backend.device_lost")
        rows = sum(len(r) for _p, _i, _c, _v, r, _w in staged.groups)
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.device_launches.labels(backend=self.name).inc()
            GLOBAL_METRICS.device_reports.labels(backend=self.name).inc(rows)
        from ..core.trace import trace_span

        t0 = time.monotonic()
        with trace_span("prep_launch", cat="device", backend=self.name, batch=rows):
            out = self.bp.launch_init_multi(staged, retain_store=retain_store)
        _observe_prepare(self.name, "init", rows, time.monotonic() - t0)
        return out

    def prep_init_multi_poplar(self, agg_id, requests, retain_store=None):
        """ONE bulk-AES walk + sketch launch for rows from MULTIPLE jobs
        (``requests``: (verify_key, agg_param, reports) per submission —
        the executor's poplar_init flush form).  Composed from the
        stage/launch halves; direct (non-executor) callers pay them
        back-to-back."""
        return self.launch_poplar_init_multi(
            self.stage_poplar_init_multi(agg_id, requests),
            retain_store=retain_store,
        )


BACKENDS = {"oracle": OracleBackend, "tpu": TpuBackend, "mesh": MeshBackend}


def vdaf_shape_key(vdaf) -> tuple:
    """Key a VDAF by its FULL parameterization: tasks sharing it share one
    backend instance — and therefore one set of compiled device graphs
    (verify_key is a traced input, so one compilation serves every task).
    Every scalar circuit parameter participates — derived lengths alone
    are ambiguous (SumVec(length=100, bits=2) and SumVec(length=200,
    bits=1) share MEAS_LEN but not truncate/OUTPUT_LEN).  Shared by the
    driver and the helper aggregator so both sides of the protocol land in
    the same executor buckets and breaker domains."""
    flp = getattr(vdaf, "flp", None)
    valid = getattr(flp, "valid", None)
    circuit_params = None
    if valid is not None:
        circuit_params = tuple(
            sorted(
                (k, v if isinstance(v, (int, str, bool)) else getattr(v, "__name__", str(v)))
                for k, v in vars(valid).items()
                if not k.startswith("_") and not isinstance(v, (list, dict))
            )
        )
    return (
        type(vdaf).__name__,
        type(valid).__name__ if valid is not None else None,
        circuit_params,
        getattr(vdaf, "algorithm_id", None),
        getattr(vdaf, "num_shares", None),
        getattr(vdaf, "num_proofs", None),
        getattr(getattr(vdaf, "xof", None), "__name__", None),
        # FLP-less VDAFs parameterize outside a `valid` circuit: Poplar1's
        # whole shape is its input bit width (two Poplar1 tasks with
        # different `bits` must never share a backend, bucket, or breaker)
        getattr(vdaf, "bits", None) if valid is None else None,
    )


# Circuits with a device twin in ops/prepare.py _device_circuit.  Kept as a
# name set so capability checks (driver dispatch, provisioning warnings) do
# NOT import the jax-backed kernels — a control-plane process must be able
# to classify a VDAF without pulling in jax.  tests/test_backend_fallback.py
# asserts this set matches _device_circuit's dispatch table.
# FixedPointBoundedL2VecSum (ISSUE 15) rides the multi-gadget device plane:
# every TurboSHAKE Prio3 family now has a device arm — there is no
# oracle-only Prio3 family left.
DEVICE_CIRCUITS = {"Count", "Sum", "SumVec", "Histogram", "FixedPointBoundedL2VecSum"}


def device_supported(vdaf) -> Tuple[bool, str]:
    """Whether the device (tpu/mesh) prepare path serves this VDAF.

    Returns (ok, reason).  Used to make oracle fallback LOUD: a task whose
    VDAF silently ran ~100x slower than the flagship path was VERDICT r3
    weak #3 (reference analog: every VdafInstance monomorphizes onto the
    same rayon path, core/src/vdaf.rs:178-195 — there is no silent tier
    split to begin with).  jax-free by design.
    """
    if not isinstance(vdaf, Prio3):
        if type(vdaf).__name__ == "Poplar1":
            return True, ""  # batched host-AES + device-sketch path
        return False, f"{type(vdaf).__name__} is not a Prio3 VDAF"
    circuit = type(vdaf.flp.valid).__name__
    if circuit not in DEVICE_CIRCUITS:
        return False, f"no device circuit for {circuit}"
    # Non-TurboSHAKE XOFs (HMAC multiproof) ride the hybrid backend: host
    # XOF, device FLP query/decide (HybridXofBackend).
    return True, ""


def device_path_label(vdaf) -> str:
    """Human-readable routing status for provisioning surfaces (task-API
    responses, startup logs): WHICH accelerated path serves this VDAF and
    which executor submission plane it batches through.  Poplar1 used to
    read as a silent "supported" while actually riding a per-job path
    outside the executor — this label makes the tier explicit, and names
    the oracle reason when there is no device path at all.  jax-free."""
    ok, reason = device_supported(vdaf)
    if not ok:
        return f"cpu-oracle ({reason})"
    if type(vdaf).__name__ == "Poplar1":
        return (
            "poplar1-batch: bulk-AES IDPF walk + device sketch, "
            "executor kind=poplar_init (agg-param/level-keyed buckets)"
        )
    if isinstance(vdaf, Prio3) and vdaf.xof is not XofTurboShake128:
        return "tpu-hybrid: host XOF + device FLP, executor kind=prep_init/combine"
    if type(getattr(vdaf.flp, "valid", None)).__name__ == "FixedPointBoundedL2VecSum":
        return (
            "tpu: multi-gadget batched device prepare (gradient "
            "aggregation), executor kind=prep_init/combine"
        )
    return "tpu: batched device prepare, executor kind=prep_init/combine"


def make_backend(
    vdaf,
    backend: str = "oracle",
    field_backend: Optional[str] = None,
    canonical: bool = False,
    poplar_backend: Optional[str] = None,
):
    """Backend factory — the dispatch gate named in the north star.

    ``field_backend`` ("vpu" | "mxu", None = JANUS_TPU_FIELD_BACKEND or
    "vpu") selects the device backends' field-arithmetic layout; the
    oracle and Poplar1 paths have no device field layer and ignore it.
    ``poplar_backend`` ("host" | "jax", None = JANUS_TPU_POPLAR_BACKEND
    or "host") selects the Poplar1 AES-walk backend; only the Poplar1
    path reads it.  ``canonical`` marks ``vdaf`` as a bucket's padded
    twin (vdaf/canonical.py) — device backends then expect 3-tuple
    requests and emit the per-row mask input; only device Prio3 backends
    honor it (the oracle/hybrid/Poplar1 paths are never canonicalized).
    """
    try:
        cls = BACKENDS[backend]
    except KeyError:
        raise VdafError(f"unknown backend {backend!r}")
    if backend != "oracle" and type(vdaf).__name__ == "Poplar1":
        # Heavy hitters: the device configs route Poplar1 through the
        # batched AES/sketch path instead of the Prio3-shaped backends.
        return Poplar1Backend(vdaf, poplar_backend=poplar_backend)
    if (
        backend != "oracle"
        and isinstance(vdaf, Prio3)
        and vdaf.xof is not XofTurboShake128
    ):
        # Host-XOF VDAFs (HMAC multiproof): device FLP, host XOF.
        return HybridXofBackend(vdaf, field_backend=field_backend)
    if cls is OracleBackend:
        return cls(vdaf)
    return cls(vdaf, field_backend=field_backend, canonical=canonical)
