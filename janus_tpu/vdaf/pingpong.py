"""Ping-pong topology for two-aggregator VDAF preparation.

draft-irtf-cfrg-vdaf-08 §5.8; the reference consumes this from
``prio::topology::ping_pong`` (SURVEY.md §2.2 "prio crate surface":
PingPongTopology::{leader_initialized, helper_initialized, leader_continued},
PingPongState::{Continued, Finished}, PingPongMessage), driven from
aggregator/src/aggregator/aggregation_job_driver.rs:397-414,677-711 on the
leader and aggregator/src/aggregator.rs:2022-2040 on the helper.

Prio3 is one-round: leader emits Initialize{prep_share}; the helper combines
both prepare shares into the prepare message, finishes, and replies
Finish{prep_msg}; the leader checks the message and finishes.  The message
wire format (tagged union with u32-length-prefixed opaques) matches the DAP
encoding embedded in PrepareResp/PrepareContinue.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import List, Optional, Tuple, Union

from .prio3 import Prio3, Prio3InputShare, Prio3PrepareShare, Prio3PrepareState, VdafError


@dataclass
class PingPongMessage:
    """Tagged union: 0 = initialize, 1 = continue, 2 = finish."""

    INITIALIZE = 0
    CONTINUE = 1
    FINISH = 2

    variant: int
    prep_share: Optional[bytes] = None  # initialize / continue
    prep_msg: Optional[bytes] = None  # continue / finish

    def encode(self) -> bytes:
        out = bytes([self.variant])
        if self.variant == self.INITIALIZE:
            out += struct.pack(">I", len(self.prep_share)) + self.prep_share
        elif self.variant == self.CONTINUE:
            out += struct.pack(">I", len(self.prep_msg)) + self.prep_msg
            out += struct.pack(">I", len(self.prep_share)) + self.prep_share
        elif self.variant == self.FINISH:
            out += struct.pack(">I", len(self.prep_msg)) + self.prep_msg
        else:
            raise VdafError("bad ping-pong variant")
        return out

    @staticmethod
    def decode(data: bytes) -> "PingPongMessage":
        if not data:
            raise VdafError("empty ping-pong message")
        variant = data[0]
        rest = data[1:]

        def take(buf: bytes) -> Tuple[bytes, bytes]:
            if len(buf) < 4:
                raise VdafError("truncated ping-pong message")
            (n,) = struct.unpack(">I", buf[:4])
            if len(buf) < 4 + n:
                raise VdafError("truncated ping-pong message")
            return buf[4 : 4 + n], buf[4 + n :]

        if variant == PingPongMessage.INITIALIZE:
            share, rest = take(rest)
            if rest:
                raise VdafError("trailing bytes")
            return PingPongMessage(variant, prep_share=share)
        if variant == PingPongMessage.CONTINUE:
            msg, rest = take(rest)
            share, rest = take(rest)
            if rest:
                raise VdafError("trailing bytes")
            return PingPongMessage(variant, prep_share=share, prep_msg=msg)
        if variant == PingPongMessage.FINISH:
            msg, rest = take(rest)
            if rest:
                raise VdafError("trailing bytes")
            return PingPongMessage(variant, prep_msg=msg)
        raise VdafError("bad ping-pong variant")


@dataclass
class PingPongContinued:
    """Waiting for the peer; holds our prepare state."""

    prep_state: Prio3PrepareState


@dataclass
class PingPongFinished:
    out_share: List[int]


PingPongState = Union[PingPongContinued, PingPongFinished]


def leader_initialized(
    vdaf: Prio3,
    verify_key: bytes,
    nonce: bytes,
    public_share: Optional[List[bytes]],
    input_share: Prio3InputShare,
) -> Tuple[PingPongContinued, PingPongMessage]:
    prep_state, prep_share = vdaf.prep_init(verify_key, 0, nonce, public_share, input_share)
    msg = PingPongMessage(PingPongMessage.INITIALIZE, prep_share=prep_share.encode(vdaf))
    return PingPongContinued(prep_state), msg


def helper_initialized(
    vdaf: Prio3,
    verify_key: bytes,
    nonce: bytes,
    public_share: Optional[List[bytes]],
    input_share: Prio3InputShare,
    inbound: PingPongMessage,
) -> Tuple[PingPongFinished, PingPongMessage]:
    if inbound.variant != PingPongMessage.INITIALIZE:
        raise VdafError("expected initialize message")
    leader_share = Prio3PrepareShare.decode(vdaf, inbound.prep_share)
    prep_state, helper_share = vdaf.prep_init(verify_key, 1, nonce, public_share, input_share)
    prep_msg = vdaf.prep_shares_to_prep([leader_share, helper_share])
    out_share = vdaf.prep_next(prep_state, prep_msg)
    msg = PingPongMessage(PingPongMessage.FINISH, prep_msg=prep_msg if prep_msg is not None else b"")
    return PingPongFinished(out_share), msg


def leader_continued(
    vdaf: Prio3, state: PingPongContinued, inbound: PingPongMessage
) -> PingPongFinished:
    if inbound.variant != PingPongMessage.FINISH:
        raise VdafError("expected finish message")
    if vdaf.flp.JOINT_RAND_LEN > 0:
        prep_msg = inbound.prep_msg
    else:
        # Prep message must be empty for VDAFs without joint randomness.
        if inbound.prep_msg:
            raise VdafError("unexpected prepare message payload")
        prep_msg = None
    out_share = vdaf.prep_next(state.prep_state, prep_msg)
    return PingPongFinished(out_share)
