"""Ping-pong topology for two-aggregator VDAF preparation.

draft-irtf-cfrg-vdaf-08 §5.8, generalized to multi-round VDAFs with the
stored-transition model the reference persists between driver steps
(reference consumes ``prio::topology::ping_pong``:
``PingPongTopology::{leader_initialized, helper_initialized, leader_continued}``,
``PingPongState::{Continued, Finished}``, ``PingPongTransition::evaluate``;
driver storage of serialized transitions at
aggregator_core/src/datastore/models.rs:898-1105 ``WaitingLeader``).

A ``PingPongTransition`` is the deferred tail of one protocol step: the
party's *pre-message* prepare state plus the combined prepare message.  It is
serializable, so a driver can persist it in the datastore and evaluate it in
a later process — "the DB is the checkpoint" (SURVEY.md §5).

VDAFs plug in via the small ``ping_pong_*`` adapter surface implemented by
``Prio3`` (1 round) and the dummy test VDAFs (any rounds; vdaf/dummy.py).

Message wire format (tagged union with u32-length-prefixed opaques) matches
the DAP embedding used inside PrepareResp/PrepareContinue — anchored to the
reference's own hex in tests/test_messages.py.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Any, List, Optional, Tuple, Union

from .prio3 import Prio3, Prio3InputShare, Prio3PrepareShare, Prio3PrepareState, VdafError


class PingPongError(VdafError):
    pass


@dataclass
class PingPongMessage:
    """Tagged union: 0 = initialize, 1 = continue, 2 = finish."""

    INITIALIZE = 0
    CONTINUE = 1
    FINISH = 2

    variant: int
    prep_share: Optional[bytes] = None  # initialize / continue
    prep_msg: Optional[bytes] = None  # continue / finish

    def encode(self) -> bytes:
        out = bytes([self.variant])
        if self.variant == self.INITIALIZE:
            out += struct.pack(">I", len(self.prep_share)) + self.prep_share
        elif self.variant == self.CONTINUE:
            out += struct.pack(">I", len(self.prep_msg)) + self.prep_msg
            out += struct.pack(">I", len(self.prep_share)) + self.prep_share
        elif self.variant == self.FINISH:
            out += struct.pack(">I", len(self.prep_msg)) + self.prep_msg
        else:
            raise VdafError("bad ping-pong variant")
        return out

    @staticmethod
    def decode(data: bytes) -> "PingPongMessage":
        if not data:
            raise VdafError("empty ping-pong message")
        variant = data[0]
        rest = data[1:]

        def take(buf: bytes) -> Tuple[bytes, bytes]:
            if len(buf) < 4:
                raise VdafError("truncated ping-pong message")
            (n,) = struct.unpack(">I", buf[:4])
            if len(buf) < 4 + n:
                raise VdafError("truncated ping-pong message")
            return buf[4 : 4 + n], buf[4 + n :]

        if variant == PingPongMessage.INITIALIZE:
            share, rest = take(rest)
            if rest:
                raise VdafError("trailing bytes")
            return PingPongMessage(variant, prep_share=share)
        if variant == PingPongMessage.CONTINUE:
            msg, rest = take(rest)
            share, rest = take(rest)
            if rest:
                raise VdafError("trailing bytes")
            return PingPongMessage(variant, prep_share=share, prep_msg=msg)
        if variant == PingPongMessage.FINISH:
            msg, rest = take(rest)
            if rest:
                raise VdafError("trailing bytes")
            return PingPongMessage(variant, prep_msg=msg)
        raise VdafError("bad ping-pong variant")


@dataclass
class PingPongContinued:
    """Waiting for the peer; holds our prepare state (+ current round)."""

    prep_state: Any
    round: int = 0


@dataclass
class PingPongFinished:
    out_share: Any


PingPongState = Union[PingPongContinued, PingPongFinished]


@dataclass
class PingPongTransition:
    """Deferred evaluation of one prepare step: (pre-message state, combined
    prepare message).  Mirrors ``prio::topology::ping_pong::PingPongTransition``;
    serialized into driver state between steps (reference:
    aggregator_core/src/datastore/models.rs:898)."""

    previous_prepare_state: Any
    current_prepare_message: bytes  # encoded prep message
    round: int  # round of previous_prepare_state

    def evaluate(self, vdaf) -> Tuple[PingPongState, PingPongMessage]:
        kind, *rest = vdaf.ping_pong_prep_next(
            self.previous_prepare_state, self.current_prepare_message, self.round
        )
        if kind == "finish":
            (out_share,) = rest
            return (
                PingPongFinished(out_share),
                PingPongMessage(PingPongMessage.FINISH, prep_msg=self.current_prepare_message),
            )
        next_state, next_share = rest
        return (
            PingPongContinued(next_state, self.round + 1),
            PingPongMessage(
                PingPongMessage.CONTINUE,
                prep_msg=self.current_prepare_message,
                prep_share=next_share,
            ),
        )

    # -- persistence ----------------------------------------------------
    def encode(self, vdaf) -> bytes:
        state = vdaf.ping_pong_encode_state(self.previous_prepare_state)
        return (
            struct.pack(">H", self.round)
            + struct.pack(">I", len(self.current_prepare_message))
            + self.current_prepare_message
            + state
        )

    @classmethod
    def decode(cls, vdaf, data: bytes) -> "PingPongTransition":
        if len(data) < 6:
            raise PingPongError("truncated transition")
        (rnd,) = struct.unpack(">H", data[:2])
        (n,) = struct.unpack(">I", data[2:6])
        if len(data) < 6 + n:
            raise PingPongError("truncated transition")
        msg = data[6 : 6 + n]
        state = vdaf.ping_pong_decode_state(data[6 + n :])
        return cls(state, msg, rnd)


def leader_initialized(
    vdaf,
    verify_key: bytes,
    agg_param,
    nonce: bytes,
    public_share,
    input_share,
) -> Tuple[PingPongContinued, PingPongMessage]:
    """Leader's first move: prep_init, send Initialize{prep_share}."""
    prep_state, prep_share = vdaf.ping_pong_prep_init(
        verify_key, 0, agg_param, nonce, public_share, input_share
    )
    msg = PingPongMessage(
        PingPongMessage.INITIALIZE, prep_share=vdaf.ping_pong_encode_prep_share(prep_share)
    )
    return PingPongContinued(prep_state, 0), msg


def helper_initialized(
    vdaf,
    verify_key: bytes,
    agg_param,
    nonce: bytes,
    public_share,
    input_share,
    inbound: PingPongMessage,
) -> PingPongTransition:
    """Helper's first move: prep_init, combine with the leader's share, and
    return the (storable) transition whose evaluation yields the reply."""
    if inbound.variant != PingPongMessage.INITIALIZE:
        raise PingPongError("expected initialize message")
    leader_share = vdaf.ping_pong_decode_prep_share(inbound.prep_share, round=0)
    prep_state, helper_share = vdaf.ping_pong_prep_init(
        verify_key, 1, agg_param, nonce, public_share, input_share
    )
    prep_msg = vdaf.ping_pong_prep_shares_to_prep(
        agg_param, [leader_share, helper_share], round=0
    )
    return PingPongTransition(prep_state, prep_msg, 0)


@dataclass
class PingPongContinuedValue:
    """Either a new transition (reply pending) or a message-less finish."""

    transition: Optional[PingPongTransition] = None
    out_share: Optional[Any] = None


def continued(
    vdaf,
    is_leader: bool,
    state: PingPongContinued,
    inbound: PingPongMessage,
    agg_param=None,
) -> PingPongContinuedValue:
    """Apply the peer's message to our continued state.

    Mirrors prio's ``leader_continued``/``helper_continued``: evaluate our
    deferred prep_next with the inbound prepare message; on Continue, combine
    the new prepare shares into the next transition; on Finish, we are done.
    """
    if inbound.variant == PingPongMessage.INITIALIZE:
        raise PingPongError("unexpected initialize message")
    kind, *rest = vdaf.ping_pong_prep_next(state.prep_state, inbound.prep_msg, state.round)
    if kind == "finish":
        if inbound.variant != PingPongMessage.FINISH:
            raise PingPongError("round mismatch: we finished, peer continued")
        (out_share,) = rest
        return PingPongContinuedValue(out_share=out_share)
    if inbound.variant != PingPongMessage.CONTINUE:
        raise PingPongError("round mismatch: we continued, peer finished")
    next_state, our_share_enc = rest
    next_round = state.round + 1
    our_share = vdaf.ping_pong_decode_prep_share(our_share_enc, round=next_round)
    peer_share = vdaf.ping_pong_decode_prep_share(inbound.prep_share, round=next_round)
    shares = [our_share, peer_share] if is_leader else [peer_share, our_share]
    prep_msg = vdaf.ping_pong_prep_shares_to_prep(agg_param, shares, round=next_round)
    return PingPongContinuedValue(
        transition=PingPongTransition(next_state, prep_msg, next_round)
    )


def leader_continued(vdaf, state: PingPongContinued, inbound: PingPongMessage):
    """One-round convenience (Prio3): the FINISH reply completes the leader.

    Multi-round flows should use ``continued`` and transition evaluation.
    """
    value = continued(vdaf, True, state, inbound)
    if value.out_share is None:
        raise PingPongError("expected finish message")
    return PingPongFinished(value.out_share)


# ---------------------------------------------------------------------------
# Prio3 adapter surface (1-round).  The encoded prepare message for Prio3 is
# the joint-rand seed confirmation (or empty when the circuit has none).
# ---------------------------------------------------------------------------


def _prio3_prep_init(self, verify_key, agg_id, agg_param, nonce, public_share, input_share):
    if agg_param is not None:
        raise VdafError("Prio3 takes no aggregation parameter")
    return self.prep_init(verify_key, agg_id, nonce, public_share, input_share)


def _prio3_prep_shares_to_prep(self, agg_param, prep_shares, round=0):
    msg = self.prep_shares_to_prep(prep_shares)
    return msg if msg is not None else b""


def _prio3_prep_next(self, prep_state, prep_msg: bytes, round=0):
    if self.flp.JOINT_RAND_LEN > 0:
        out = self.prep_next(prep_state, prep_msg)
    else:
        if prep_msg:
            raise VdafError("unexpected prepare message payload")
        out = self.prep_next(prep_state, None)
    return ("finish", out)


def _prio3_encode_prep_share(self, share: Prio3PrepareShare) -> bytes:
    return share.encode(self)


def _prio3_decode_prep_share(self, data: bytes, round=0) -> Prio3PrepareShare:
    return Prio3PrepareShare.decode(self, data)


def _prio3_encode_state(self, state: Prio3PrepareState) -> bytes:
    f = self.flp.field
    out = f.encode_vec(state.out_share)
    if state.corrected_joint_rand_seed is not None:
        out += state.corrected_joint_rand_seed
    return out


def _prio3_decode_state(self, data: bytes) -> Prio3PrepareState:
    f = self.flp.field
    seed = None
    if self.flp.JOINT_RAND_LEN > 0:
        if len(data) < self.xof.SEED_SIZE:
            raise VdafError("truncated prepare state")
        seed = data[len(data) - self.xof.SEED_SIZE :]
        data = data[: len(data) - self.xof.SEED_SIZE]
    out_share = f.decode_vec(data)
    if len(out_share) != self.flp.OUTPUT_LEN:
        raise VdafError("bad prepare state length")
    return Prio3PrepareState(out_share=out_share, corrected_joint_rand_seed=seed)


Prio3.ping_pong_prep_init = _prio3_prep_init
Prio3.ping_pong_prep_shares_to_prep = _prio3_prep_shares_to_prep
Prio3.ping_pong_prep_next = _prio3_prep_next
Prio3.ping_pong_encode_prep_share = _prio3_encode_prep_share
Prio3.ping_pong_decode_prep_share = _prio3_decode_prep_share
Prio3.ping_pong_encode_state = _prio3_encode_state
Prio3.ping_pong_decode_state = _prio3_decode_state
