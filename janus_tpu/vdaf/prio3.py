"""Prio3 VDAF composition — draft-irtf-cfrg-vdaf-08 §7.2, CPU oracle.

Implements shard / prepare (init, shares-to-prep, next) / aggregate / unshard
generically over an FLP and an XOF, including the joint-randomness derivation
and the multi-proof generalization used by libprio-rs for the custom
``Prio3SumVecField64MultiproofHmacSha256Aes128`` VDAF the reference registers
(reference: core/src/vdaf.rs:178-195; algorithm id 0xFFFF1003).

This is the protocol oracle the TPU batched path (janus_tpu.ops.prepare) must
match byte-for-byte; the reference runs the equivalent per-report loop on a
rayon pool (reference: aggregator/src/aggregator/aggregation_job_driver.rs:449,
aggregator/src/aggregator.rs:2101).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from ..flp.generic import FlpError, FlpGeneric
from ..xof import Xof, XofTurboShake128

# Domain-separation usage constants (§7.2).
USAGE_MEAS_SHARE = 1
USAGE_PROOF_SHARE = 2
USAGE_JOINT_RANDOMNESS = 3
USAGE_PROVE_RANDOMNESS = 4
USAGE_QUERY_RANDOMNESS = 5
USAGE_JOINT_RAND_SEED = 6
USAGE_JOINT_RAND_PART = 7

VDAF_VERSION = 8  # draft-irtf-cfrg-vdaf-08

# Algorithm identifiers (§10; reference custom id at core/src/vdaf.rs:178-195).
ALG_PRIO3_COUNT = 0x00000000
ALG_PRIO3_SUM = 0x00000001
ALG_PRIO3_SUMVEC = 0x00000002
ALG_PRIO3_HISTOGRAM = 0x00000003
ALG_PRIO3_SUMVEC_FIELD64_MULTIPROOF_HMACSHA256_AES128 = 0xFFFF1003
# libprio's private codepoint for the fpvec_bounded_l2 family.
ALG_PRIO3_FIXEDPOINT_BOUNDED_L2_VEC_SUM = 0xFFFF1002


class VdafError(Exception):
    pass


@dataclass
class Prio3InputShare:
    """Leader share carries explicit vectors; helpers carry seeds."""

    meas_share: Optional[List[int]] = None  # leader only
    proofs_share: Optional[List[int]] = None  # leader only (all proofs, concatenated)
    share_seed: Optional[bytes] = None  # helpers only
    joint_rand_blind: Optional[bytes] = None  # present iff circuit uses joint rand

    def encode(self, prio3: "Prio3") -> bytes:
        f = prio3.flp.field
        if self.share_seed is None:
            out = f.encode_vec(self.meas_share) + f.encode_vec(self.proofs_share)
        else:
            out = self.share_seed
        if self.joint_rand_blind is not None:
            out += self.joint_rand_blind
        return out

    @staticmethod
    def decode(prio3: "Prio3", agg_id: int, data: bytes) -> "Prio3InputShare":
        f = prio3.flp.field
        blind = None
        if prio3.flp.JOINT_RAND_LEN > 0:
            if len(data) < prio3.xof.SEED_SIZE:
                raise VdafError("input share too short")
            blind = data[len(data) - prio3.xof.SEED_SIZE :]
            data = data[: len(data) - prio3.xof.SEED_SIZE]
        if agg_id == 0:
            meas_len = prio3.flp.MEAS_LEN * f.ENCODED_SIZE
            proofs_len = prio3.flp.PROOF_LEN * prio3.num_proofs * f.ENCODED_SIZE
            if len(data) != meas_len + proofs_len:
                raise VdafError("bad leader input share length")
            return Prio3InputShare(
                meas_share=f.decode_vec(data[:meas_len]),
                proofs_share=f.decode_vec(data[meas_len:]),
                joint_rand_blind=blind,
            )
        if len(data) != prio3.xof.SEED_SIZE:
            raise VdafError("bad helper input share length")
        return Prio3InputShare(share_seed=data, joint_rand_blind=blind)


@dataclass
class Prio3PrepareState:
    out_share: List[int]
    corrected_joint_rand_seed: Optional[bytes]


@dataclass
class Prio3PrepareShare:
    verifiers_share: List[int]  # VERIFIER_LEN * num_proofs elements
    joint_rand_part: Optional[bytes]

    def encode(self, prio3: "Prio3") -> bytes:
        out = prio3.flp.field.encode_vec(self.verifiers_share)
        if self.joint_rand_part is not None:
            out += self.joint_rand_part
        return out

    @staticmethod
    def decode(prio3: "Prio3", data: bytes) -> "Prio3PrepareShare":
        f = prio3.flp.field
        n = prio3.flp.VERIFIER_LEN * prio3.num_proofs * f.ENCODED_SIZE
        part = None
        if prio3.flp.JOINT_RAND_LEN > 0:
            if len(data) != n + prio3.xof.SEED_SIZE:
                raise VdafError("bad prepare share length")
            part = data[n:]
        elif len(data) != n:
            raise VdafError("bad prepare share length")
        return Prio3PrepareShare(f.decode_vec(data[:n]), part)


class Prio3:
    """A Prio3 instance: FLP + XOF + share/proof counts + algorithm id."""

    ROUNDS = 1
    NONCE_SIZE = 16
    REQUIRES_AGG_PARAM = False

    def __init__(
        self,
        flp: FlpGeneric,
        algorithm_id: int,
        num_shares: int = 2,
        num_proofs: int = 1,
        xof: type = XofTurboShake128,
    ):
        if not 2 <= num_shares < 256:
            raise ValueError("num_shares out of range")
        if num_proofs < 1:
            raise ValueError("need at least one proof")
        self.flp = flp
        self.algorithm_id = algorithm_id
        self.num_shares = num_shares
        self.num_proofs = num_proofs
        self.xof = xof
        self.VERIFY_KEY_SIZE = xof.SEED_SIZE
        if flp.JOINT_RAND_LEN > 0:
            self.RAND_SIZE = (2 * (num_shares - 1) + 2) * xof.SEED_SIZE
        else:
            self.RAND_SIZE = num_shares * xof.SEED_SIZE

    # ------------------------------------------------------------------
    def _dst(self, usage: int) -> bytes:
        return (
            VDAF_VERSION.to_bytes(1, "big")
            + b"\x00"  # algorithm class: VDAF
            + self.algorithm_id.to_bytes(4, "big")
            + usage.to_bytes(2, "big")
        )

    def _helper_meas_share(self, agg_id: int, seed: bytes) -> List[int]:
        return self.xof.expand_into_vec(
            self.flp.field, seed, self._dst(USAGE_MEAS_SHARE), bytes([agg_id]), self.flp.MEAS_LEN
        )

    def _helper_proofs_share(self, agg_id: int, seed: bytes) -> List[int]:
        return self.xof.expand_into_vec(
            self.flp.field,
            seed,
            self._dst(USAGE_PROOF_SHARE),
            bytes([agg_id]),
            self.flp.PROOF_LEN * self.num_proofs,
        )

    def _joint_rand_part(self, agg_id: int, blind: bytes, meas_share: Sequence[int], nonce: bytes) -> bytes:
        x = self.xof(
            blind,
            self._dst(USAGE_JOINT_RAND_PART),
            bytes([agg_id]) + nonce + self.flp.field.encode_vec(meas_share),
        )
        return x.next(self.xof.SEED_SIZE)

    def _joint_rand_seed(self, parts: Sequence[bytes]) -> bytes:
        x = self.xof(b"\x00" * self.xof.SEED_SIZE, self._dst(USAGE_JOINT_RAND_SEED), b"".join(parts))
        return x.next(self.xof.SEED_SIZE)

    def _joint_rands(self, seed: bytes) -> List[int]:
        return self.xof.expand_into_vec(
            self.flp.field,
            seed,
            self._dst(USAGE_JOINT_RANDOMNESS),
            b"",
            self.flp.JOINT_RAND_LEN * self.num_proofs,
        )

    def _prove_rands(self, seed: bytes) -> List[int]:
        return self.xof.expand_into_vec(
            self.flp.field,
            seed,
            self._dst(USAGE_PROVE_RANDOMNESS),
            b"",
            self.flp.PROVE_RAND_LEN * self.num_proofs,
        )

    def _query_rands(self, verify_key: bytes, nonce: bytes) -> List[int]:
        return self.xof.expand_into_vec(
            self.flp.field,
            verify_key,
            self._dst(USAGE_QUERY_RANDOMNESS),
            nonce,
            self.flp.QUERY_RAND_LEN * self.num_proofs,
        )

    # ------------------------------------------------------------------
    def shard(
        self, measurement, nonce: bytes, rand: bytes
    ) -> Tuple[Optional[List[bytes]], List[Prio3InputShare]]:
        """Returns (public_share = joint rand parts or None, input shares)."""
        if len(nonce) != self.NONCE_SIZE:
            raise VdafError("bad nonce size")
        if len(rand) != self.RAND_SIZE:
            raise VdafError("bad rand size")
        l = self.xof.SEED_SIZE
        seeds = [rand[i : i + l] for i in range(0, len(rand), l)]
        meas = self.flp.encode(measurement)
        if self.flp.JOINT_RAND_LEN > 0:
            return self._shard_with_joint_rand(meas, nonce, seeds)
        return self._shard_without_joint_rand(meas, seeds)

    def _shard_without_joint_rand(self, meas, seeds):
        f = self.flp.field
        helper_seeds, (prove_seed,) = seeds[: self.num_shares - 1], seeds[self.num_shares - 1 :]
        leader_meas_share = list(meas)
        for j in range(self.num_shares - 1):
            leader_meas_share = f.vec_sub(leader_meas_share, self._helper_meas_share(j + 1, helper_seeds[j]))
        prove_rands = self._prove_rands(prove_seed)
        proofs: List[int] = []
        for i in range(self.num_proofs):
            pr = prove_rands[i * self.flp.PROVE_RAND_LEN : (i + 1) * self.flp.PROVE_RAND_LEN]
            proofs += self.flp.prove(meas, pr, [])
        leader_proofs_share = list(proofs)
        for j in range(self.num_shares - 1):
            leader_proofs_share = f.vec_sub(leader_proofs_share, self._helper_proofs_share(j + 1, helper_seeds[j]))
        shares = [Prio3InputShare(meas_share=leader_meas_share, proofs_share=leader_proofs_share)]
        shares += [Prio3InputShare(share_seed=s) for s in helper_seeds]
        return None, shares

    def _shard_with_joint_rand(self, meas, nonce, seeds):
        f = self.flp.field
        k_helper_seeds = seeds[: 2 * (self.num_shares - 1)]
        k_helper_meas_shares = k_helper_seeds[0::2]
        k_helper_blinds = k_helper_seeds[1::2]
        k_leader_blind = seeds[2 * (self.num_shares - 1)]
        k_prove = seeds[2 * (self.num_shares - 1) + 1]

        leader_meas_share = list(meas)
        joint_rand_parts: List[bytes] = []
        for j in range(self.num_shares - 1):
            helper_share = self._helper_meas_share(j + 1, k_helper_meas_shares[j])
            leader_meas_share = f.vec_sub(leader_meas_share, helper_share)
            joint_rand_parts.append(self._joint_rand_part(j + 1, k_helper_blinds[j], helper_share, nonce))
        leader_part = self._joint_rand_part(0, k_leader_blind, leader_meas_share, nonce)
        joint_rand_parts.insert(0, leader_part)
        joint_rand_seed = self._joint_rand_seed(joint_rand_parts)
        joint_rands = self._joint_rands(joint_rand_seed)
        prove_rands = self._prove_rands(k_prove)
        proofs: List[int] = []
        for i in range(self.num_proofs):
            pr = prove_rands[i * self.flp.PROVE_RAND_LEN : (i + 1) * self.flp.PROVE_RAND_LEN]
            jr = joint_rands[i * self.flp.JOINT_RAND_LEN : (i + 1) * self.flp.JOINT_RAND_LEN]
            proofs += self.flp.prove(meas, pr, jr)
        leader_proofs_share = list(proofs)
        for j in range(self.num_shares - 1):
            leader_proofs_share = f.vec_sub(
                leader_proofs_share, self._helper_proofs_share(j + 1, k_helper_meas_shares[j])
            )
        shares = [
            Prio3InputShare(
                meas_share=leader_meas_share,
                proofs_share=leader_proofs_share,
                joint_rand_blind=k_leader_blind,
            )
        ]
        for j in range(self.num_shares - 1):
            shares.append(
                Prio3InputShare(share_seed=k_helper_meas_shares[j], joint_rand_blind=k_helper_blinds[j])
            )
        return joint_rand_parts, shares

    # ------------------------------------------------------------------
    def prep_init(
        self,
        verify_key: bytes,
        agg_id: int,
        nonce: bytes,
        public_share: Optional[List[bytes]],
        input_share: Prio3InputShare,
    ) -> Tuple[Prio3PrepareState, Prio3PrepareShare]:
        flp = self.flp
        if agg_id == 0:
            meas_share = input_share.meas_share
            proofs_share = input_share.proofs_share
        else:
            meas_share = self._helper_meas_share(agg_id, input_share.share_seed)
            proofs_share = self._helper_proofs_share(agg_id, input_share.share_seed)

        query_rands = self._query_rands(verify_key, nonce)
        joint_rands: List[int] = []
        joint_rand_part = None
        corrected_seed = None
        if flp.JOINT_RAND_LEN > 0:
            joint_rand_part = self._joint_rand_part(agg_id, input_share.joint_rand_blind, meas_share, nonce)
            parts = list(public_share)
            parts[agg_id] = joint_rand_part
            corrected_seed = self._joint_rand_seed(parts)
            joint_rands = self._joint_rands(corrected_seed)

        verifiers: List[int] = []
        for i in range(self.num_proofs):
            qr = query_rands[i * flp.QUERY_RAND_LEN : (i + 1) * flp.QUERY_RAND_LEN]
            jr = joint_rands[i * flp.JOINT_RAND_LEN : (i + 1) * flp.JOINT_RAND_LEN]
            ps = proofs_share[i * flp.PROOF_LEN : (i + 1) * flp.PROOF_LEN]
            verifiers += flp.query(meas_share, ps, qr, jr, self.num_shares)

        out_share = flp.truncate(meas_share)
        return (
            Prio3PrepareState(out_share=out_share, corrected_joint_rand_seed=corrected_seed),
            Prio3PrepareShare(verifiers_share=verifiers, joint_rand_part=joint_rand_part),
        )

    def prep_shares_to_prep(self, prep_shares: Sequence[Prio3PrepareShare]) -> Optional[bytes]:
        """Combine prepare shares; verify every proof; return the joint-rand
        seed confirmation message (or None when the circuit has no joint rand)."""
        if len(prep_shares) != self.num_shares:
            raise VdafError("wrong number of prepare shares")
        f = self.flp.field
        verifiers = [0] * (self.flp.VERIFIER_LEN * self.num_proofs)
        parts: List[bytes] = []
        for ps in prep_shares:
            verifiers = f.vec_add(verifiers, ps.verifiers_share)
            if self.flp.JOINT_RAND_LEN > 0:
                parts.append(ps.joint_rand_part)
        for i in range(self.num_proofs):
            v = verifiers[i * self.flp.VERIFIER_LEN : (i + 1) * self.flp.VERIFIER_LEN]
            if not self.flp.decide(v):
                raise VdafError("proof verification failed")
        if self.flp.JOINT_RAND_LEN > 0:
            return self._joint_rand_seed(parts)
        return None

    def prep_next(self, prep_state: Prio3PrepareState, prep_msg: Optional[bytes]) -> List[int]:
        if self.flp.JOINT_RAND_LEN > 0:
            if prep_msg != prep_state.corrected_joint_rand_seed:
                raise VdafError("joint randomness check failed")
        return prep_state.out_share

    # ------------------------------------------------------------------
    def aggregate(self, out_shares: Sequence[Sequence[int]]) -> List[int]:
        f = self.flp.field
        agg = [0] * self.flp.OUTPUT_LEN
        for s in out_shares:
            agg = f.vec_add(agg, s)
        return agg

    def unshard(self, agg_shares: Sequence[Sequence[int]], num_measurements: int):
        f = self.flp.field
        agg = [0] * self.flp.OUTPUT_LEN
        for s in agg_shares:
            agg = f.vec_add(agg, s)
        return self.flp.decode(agg, num_measurements)

    # ------------------------------------------------------------------
    def encode_public_share(self, public_share: Optional[List[bytes]]) -> bytes:
        if public_share is None:
            return b""
        return b"".join(public_share)

    def decode_public_share(self, data: bytes) -> Optional[List[bytes]]:
        if self.flp.JOINT_RAND_LEN == 0:
            if data:
                raise VdafError("unexpected public share")
            return None
        l = self.xof.SEED_SIZE
        if len(data) != self.num_shares * l:
            raise VdafError("bad public share length")
        return [data[i : i + l] for i in range(0, len(data), l)]

    # Uniform VDAF surface consumed by role logic (the analog of the
    # prio::vdaf::Aggregator assoc-type codecs, SURVEY.md §2.2).
    @property
    def field(self):
        return self.flp.field

    def field_for_agg_param(self, agg_param):
        return self.flp.field

    def unshard_with_param(self, agg_param, agg_shares, num_measurements: int):
        return self.unshard(agg_shares, num_measurements)

    def decode_input_share(self, agg_id: int, data: bytes) -> Prio3InputShare:
        return Prio3InputShare.decode(self, agg_id, data)

    def encode_agg_param(self, agg_param) -> bytes:
        if agg_param is not None:
            raise VdafError("Prio3 takes no aggregation parameter")
        return b""

    def decode_agg_param(self, data: bytes):
        if data:
            raise VdafError("Prio3 takes no aggregation parameter")
        return None

    def agg_param_conflict_key(self, data: bytes) -> bytes:
        """Reports may be aggregated once, period (no aggregation parameter)."""
        return b""
