"""VDAF instance registry — the analog of the reference's ``VdafInstance`` enum
and ``vdaf_dispatch!`` gate (reference: core/src/vdaf.rs:65-108, 516-532).

Each constructor returns a configured ``Prio3``.  The registry maps the
serialized instance description (as stored in the task model / DB ``tasks.vdaf``
column in the reference) to a constructor, and is the seam where the execution
backend (CPU oracle vs batched TPU ops) is selected.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

from ..fields import Field64, Field128
from ..flp import Count, FixedPointBoundedL2VecSum, FlpGeneric, Histogram, Sum, SumVec
from ..xof import XofHmacSha256Aes128, XofTurboShake128
from .prio3 import (
    ALG_PRIO3_COUNT,
    ALG_PRIO3_HISTOGRAM,
    ALG_PRIO3_SUM,
    ALG_PRIO3_SUMVEC,
    ALG_PRIO3_FIXEDPOINT_BOUNDED_L2_VEC_SUM,
    ALG_PRIO3_SUMVEC_FIELD64_MULTIPROOF_HMACSHA256_AES128,
    Prio3,
)

# Verify-key sizes, as in the reference (core/src/vdaf.rs:16,24).
VERIFY_KEY_LENGTH = 16
VERIFY_KEY_LENGTH_HMACSHA256_AES128 = 32


def prio3_count(num_shares: int = 2) -> Prio3:
    return Prio3(FlpGeneric(Count()), ALG_PRIO3_COUNT, num_shares=num_shares)


def prio3_sum(bits: int, num_shares: int = 2) -> Prio3:
    return Prio3(FlpGeneric(Sum(bits)), ALG_PRIO3_SUM, num_shares=num_shares)


def prio3_sum_vec(length: int, bits: int, chunk_length: int, num_shares: int = 2) -> Prio3:
    return Prio3(
        FlpGeneric(SumVec(length=length, bits=bits, chunk_length=chunk_length, field=Field128)),
        ALG_PRIO3_SUMVEC,
        num_shares=num_shares,
    )


def prio3_histogram(length: int, chunk_length: int, num_shares: int = 2) -> Prio3:
    return Prio3(
        FlpGeneric(Histogram(length=length, chunk_length=chunk_length)),
        ALG_PRIO3_HISTOGRAM,
        num_shares=num_shares,
    )


def prio3_sum_vec_field64_multiproof_hmacsha256_aes128(
    proofs: int, length: int, bits: int, chunk_length: int, num_shares: int = 2
) -> Prio3:
    """The custom Daphne-interop VDAF (reference: core/src/vdaf.rs:178-195)."""
    if proofs < 2:
        raise ValueError("multiproof variant requires at least 2 proofs")
    return Prio3(
        FlpGeneric(SumVec(length=length, bits=bits, chunk_length=chunk_length, field=Field64)),
        ALG_PRIO3_SUMVEC_FIELD64_MULTIPROOF_HMACSHA256_AES128,
        num_shares=num_shares,
        num_proofs=proofs,
        xof=XofHmacSha256Aes128,
    )


def prio3_fixedpoint_bounded_l2_vec_sum(
    bitsize, length: int, num_shares: int = 2, chunk_length: int = None
) -> Prio3:
    """Fixed-point bounded-L2 vector sum (reference: core/src/vdaf.rs:88-91).

    ``bitsize``: 16 | 32 | "BitSize16" | "BitSize32" (the reference's enum).
    A ``dp_strategy`` key in the instance description is handled by the DP
    layer (janus_tpu/core/dp.py), not the circuit — vdaf_from_instance
    strips it before construction, mirroring the reference's per-instance
    dp_strategy dispatch.
    """
    bits = {16: 16, 32: 32, "BitSize16": 16, "BitSize32": 32}.get(bitsize)
    if bits is None:
        raise ValueError(f"unsupported bitsize {bitsize!r}")
    return Prio3(
        FlpGeneric(
            FixedPointBoundedL2VecSum(
                bits_per_entry=bits, entries=length, chunk_length=chunk_length
            )
        ),
        ALG_PRIO3_FIXEDPOINT_BOUNDED_L2_VEC_SUM,
        num_shares=num_shares,
    )


def _poplar1(bits: int):
    from .poplar1 import Poplar1

    return Poplar1(bits)


def _fake(rounds: int = 1):
    from .dummy import DummyVdaf

    return DummyVdaf(rounds)


def _fake_fails_prep_init(rounds: int = 1):
    from .dummy import FakeFailsPrepInit

    return FakeFailsPrepInit(rounds)


def _fake_fails_prep_step(rounds: int = 1):
    from .dummy import FakeFailsPrepStep

    return FakeFailsPrepStep(rounds)


# Serializable registry keyed the way the reference names instances
# (core/src/vdaf.rs:65-108).  Values: constructor taking the instance's
# params.  The Fake* test VDAFs mirror the reference's test-util instances
# (core/src/vdaf.rs:96-108): no real crypto, configurable round count,
# fault injection.
VDAF_INSTANCES: Dict[str, Callable[..., Prio3]] = {
    "Prio3Count": prio3_count,
    "Prio3Sum": prio3_sum,
    "Prio3SumVec": prio3_sum_vec,
    "Prio3Histogram": prio3_histogram,
    "Prio3SumVecField64MultiproofHmacSha256Aes128": prio3_sum_vec_field64_multiproof_hmacsha256_aes128,
    "Prio3FixedPointBoundedL2VecSum": prio3_fixedpoint_bounded_l2_vec_sum,
    "Poplar1": _poplar1,
    "Fake": _fake,
    "FakeFailsPrepInit": _fake_fails_prep_init,
    "FakeFailsPrepStep": _fake_fails_prep_step,
}


def vdaf_from_instance(instance: Dict[str, Any], backend: str = None) -> Prio3:
    """Instantiate from a serialized description, e.g.
    ``{"type": "Prio3Histogram", "length": 1024, "chunk_length": 316}``.

    ``backend`` selects the prepare execution path ("oracle" | "tpu") and
    attaches it as ``vdaf.backend`` — the analog of ``vdaf_dispatch!``
    monomorphizing over the instance (reference: core/src/vdaf.rs:516-532).
    """
    kind = instance["type"]
    if kind not in VDAF_INSTANCES:
        raise ValueError(f"unknown VDAF instance: {kind}")
    # dp_strategy rides inside the instance description (the reference keeps
    # it in the VdafInstance variants and dispatches it alongside the vdaf,
    # aggregator/src/aggregator/collection_job_driver.rs:98); it is not a
    # circuit parameter.
    params = {k: v for k, v in instance.items() if k not in ("type", "dp_strategy")}
    vdaf = VDAF_INSTANCES[kind](**params)
    vdaf.instance = dict(instance)
    if backend is not None:
        from .backend import make_backend

        vdaf.backend = make_backend(vdaf, backend)
    return vdaf
