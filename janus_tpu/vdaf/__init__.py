"""Prio3 VDAF composition, instance registry, and ping-pong topology."""

from .prio3 import (
    Prio3,
    Prio3InputShare,
    Prio3PrepareShare,
    Prio3PrepareState,
    VdafError,
)
from .instances import (
    VDAF_INSTANCES,
    prio3_count,
    prio3_histogram,
    prio3_sum,
    prio3_sum_vec,
    prio3_sum_vec_field64_multiproof_hmacsha256_aes128,
    vdaf_from_instance,
)

__all__ = [
    "Prio3",
    "Prio3InputShare",
    "Prio3PrepareShare",
    "Prio3PrepareState",
    "VdafError",
    "VDAF_INSTANCES",
    "prio3_count",
    "prio3_histogram",
    "prio3_sum",
    "prio3_sum_vec",
    "prio3_sum_vec_field64_multiproof_hmacsha256_aes128",
    "vdaf_from_instance",
]
