"""Pow2 shape canonicalization: N task shapes -> O(log N) compiled graphs.

Compile time is the dominant cold-start cost of the device path (37-286 s
per VDAF shape, BENCH_r04 ``compile_s``), and every DISTINCT circuit
parameterization — Histogram(length=1000) vs (length=1024), Sum(bits=17)
vs (bits=20) — is a distinct XLA executable even though the circuits are
structurally identical.  In the many-task world taskprov enables, a fresh
task therefore stalls its first mega-batch behind a minute of compile.

This module maps a task's VDAF to a CANONICAL twin whose parameter axes
are rounded up to a small bucket set, so that every task in a bucket
shares ONE backend instance and ONE set of compiled graphs.  The contract
is strict bit-exactness: the canonical graph, given a task's reports plus
a per-row ``meas_len`` input, produces byte-identical prepare outputs to
the task's own (unpadded) CPU oracle — for ARBITRARY (adversarial)
report content, not just honest reports.  That works because:

* Wire polynomials in the FLP are already interpolated over the P = 2^k
  roots of unity with ZERO values at unused gadget calls, so padding the
  call axis within one P class and zero-masking the padded calls'
  barycentric coefficients reproduces the exact polynomial.
* The gadget polynomial's length (``glen = DEGREE*(P-1)+1``) and the
  verifier layout (``VERIFIER_LEN = 2 + ARITY``) depend only on (P,
  chunk), not on the measurement length — the wire formats of proofs and
  prepare shares are IDENTICAL across a bucket.
* XOF expansions are prefix-stable: expanding MORE elements from a
  TurboSHAKE stream yields the same leading elements (rejection sampling
  only widens the ``ok=False`` oracle-fallback window, which is already
  bit-exact by construction).
* The one length-dependent XOF *message* (the joint-randomness part,
  whose binder embeds ``enc(meas)``) is absorbed with a per-row
  length-selected sponge (ops/keccak_jax.turboshake128_batch_select)
  that is byte-identical to absorbing the row's true message.

The bucket set per (circuit, chunk) class is {2^k} ∪ {2^k - 1} gadget
calls: ``calls`` rounds up to ``min(next_pow2(calls), P-1)``, which is
the largest padding that provably preserves P (P = next_pow2(1+calls)
must not change — the roots of unity ARE the circuit).  Shapes where any
parity precondition cannot be verified — multiproof instances (their
joint/query-rand streams interleave per proof, breaking prefix
stability), non-TurboSHAKE XOFs, circuits without a padded twin — fall
back to exact-shape compile: ``canonical_vdaf_for`` returns None and the
executor keys the backend by the exact ``vdaf_shape_key``.  Parity is
ASSERTED by tests/test_shape_canonical.py, never assumed.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..fields import next_power_of_2
from ..xof import XofTurboShake128

__all__ = [
    "canonical_vdaf_for",
    "canonicalization_reason",
    "clip_agg_vector",
    "clip_drained_vector",
    "executor_shape",
]


def _canonical_calls(calls: int) -> int:
    """Round a gadget-call count up within its P class.

    P = next_pow2(1 + calls) is load-bearing (the wire polynomials live
    on the P-th roots of unity), so the bucket ceiling is P - 1; below
    it, calls round to the next power of two.  Bucket set per class:
    {2^k, 2^k - 1} — O(log N) buckets over N lengths."""
    P = next_power_of_2(1 + calls)
    return min(next_power_of_2(calls), P - 1)


def _build_canonical(vdaf):
    """The padded circuit twin, or None when no padding applies."""
    from ..flp import (
        FixedPointBoundedL2VecSum,
        FlpGeneric,
        Histogram,
        Sum,
        SumVec,
    )
    from .prio3 import Prio3

    valid = vdaf.flp.valid
    calls = valid.GADGET_CALLS[0]
    c_calls = _canonical_calls(calls)
    if isinstance(valid, FixedPointBoundedL2VecSum):
        # TWO gadgets chunk over different axes: bit checks over MEAS_LEN
        # = entries*n + (2n-2), entry squares over entries.  Pad ENTRIES
        # to the largest count that keeps BOTH gadgets' call counts within
        # their P classes (the per-gadget rounding of _canonical_calls);
        # _parity_preconditions then re-verifies every P from the built
        # twin — the bucket set stays O(log N) over N entry counts.
        chunk = valid.chunk_length
        nb = valid.bits_per_entry
        c_sq = _canonical_calls(valid.GADGET_CALLS[1])
        by_bits = (c_calls * chunk - valid.bits_for_norm) // nb
        by_sq = c_sq * chunk
        entries = min(by_bits, by_sq)
        if entries <= valid.entries:
            return None
        twin = FixedPointBoundedL2VecSum(
            bits_per_entry=nb,
            entries=entries,
            chunk_length=chunk,
            field=valid.field,
        )
    elif isinstance(valid, Histogram):
        length = c_calls * valid.chunk_length
        if length == valid.length:
            return None  # already canonical: keep the exact backend
        twin = Histogram(length, valid.chunk_length, field=valid.field)
    elif isinstance(valid, SumVec):
        # MEAS_LEN = length*bits must stay a multiple of bits, so the
        # padded length is the largest one whose call count fits the
        # bucket; the validator below re-derives P and rejects any edge
        # case where flooring dropped out of the class.
        length = (c_calls * valid.chunk_length) // valid.bits
        if length == valid.length:
            return None
        twin = SumVec(length, valid.bits, valid.chunk_length, field=valid.field)
    elif isinstance(valid, Sum):
        if c_calls == valid.bits:
            return None
        twin = Sum(c_calls)
    else:
        return None  # Count has no parameter axis; others have no twin
    return Prio3(
        FlpGeneric(twin),
        vdaf.algorithm_id,
        num_shares=vdaf.num_shares,
        num_proofs=vdaf.num_proofs,
        xof=vdaf.xof,
    )


def _parity_preconditions(vdaf, canon) -> Tuple[bool, str]:
    """Verify — never assume — that the canonical graph can be bit-exact
    for this task.  Every check here guards a concrete mechanism the
    masked graph relies on; any failure means exact-shape compile."""
    a, c = vdaf.flp, canon.flp
    av, cv = a.valid, c.valid
    if len(av.GADGET_CALLS) != len(cv.GADGET_CALLS):
        return False, "gadget count differs across the bucket"
    for ac, cc in zip(av.GADGET_CALLS, cv.GADGET_CALLS):
        if next_power_of_2(1 + ac) != next_power_of_2(1 + cc):
            return False, "padding changed P (the interpolation roots)"
    if a.PROOF_LEN != c.PROOF_LEN or a.VERIFIER_LEN != c.VERIFIER_LEN:
        return False, "proof/verifier wire width differs across the bucket"
    if a.QUERY_RAND_LEN != c.QUERY_RAND_LEN:
        return False, "query-rand stream width differs across the bucket"
    if getattr(av, "chunk_length", None) != getattr(cv, "chunk_length", None):
        return False, "chunk_length differs (gadget arity is the wire format)"
    if getattr(av, "bits_per_entry", None) != getattr(cv, "bits_per_entry", None):
        return False, "bits_per_entry differs (the entry layout is the wire format)"
    if a.MEAS_LEN > c.MEAS_LEN or a.OUTPUT_LEN > c.OUTPUT_LEN:
        return False, "canonical shape smaller than actual"
    if a.JOINT_RAND_LEN > c.JOINT_RAND_LEN:
        return False, "joint-rand stream would truncate"
    if av.field is not cv.field:
        return False, "field differs"
    return True, ""


#: shape_key -> (canonical twin | None, reason).  The plan is a pure
#: function of the shape, and drain consumers ask per merge — memoizing
#: makes the steady-state cost a dict hit (twin instances are stateless
#: parameter records, safe to share).
_PLAN_CACHE: dict = {}


def _plan(vdaf):
    """(canonical twin or None, fallback reason) — memoized by shape."""
    from .backend import vdaf_shape_key
    from .prio3 import Prio3

    if not isinstance(vdaf, Prio3):
        return None, f"{type(vdaf).__name__} is not Prio3"
    key = vdaf_shape_key(vdaf)
    hit = _PLAN_CACHE.get(key)
    if hit is not None:
        return hit
    if vdaf.xof is not XofTurboShake128:
        plan = (None, "length-selected absorb requires the TurboSHAKE XOF")
    elif vdaf.num_proofs != 1:
        plan = (None, "multiproof rand streams are not prefix-stable")
    else:
        try:
            canon = _build_canonical(vdaf)
        except Exception as e:  # e.g. Sum(bits) ceiling past the field width
            canon, reason = None, f"no canonical twin: {e}"
        else:
            if canon is None:
                reason = "shape is its own bucket ceiling"
            else:
                ok, reason = _parity_preconditions(vdaf, canon)
                if not ok:
                    canon = None
        plan = (canon, "" if canon is not None else reason)
    _PLAN_CACHE[key] = plan
    return plan


def canonicalization_reason(vdaf) -> str:
    """Why this VDAF serves from an exact-shape compile ("" when it
    canonicalizes).  Introspection for tests / provisioning logs."""
    return _plan(vdaf)[1]


def plan_stats() -> dict:
    """Counted plan outcomes across every shape this process has resolved
    (the memoized _PLAN_CACHE): how many canonicalized, and the per-reason
    counts of shapes that kept exact-shape compiles.  Surfaced in the
    /statusz "compile" neighborhood (ISSUE 9 satellite) so an operator
    can see at a glance WHY a fleet's shape count is not collapsing."""
    reasons: dict = {}
    canonicalized = 0
    for canon, reason in list(_PLAN_CACHE.values()):
        if canon is not None:
            canonicalized += 1
        else:
            reasons[reason] = reasons.get(reason, 0) + 1
    return {
        "planned": len(_PLAN_CACHE),
        "canonicalized": canonicalized,
        "exact_reasons": reasons,
    }


def canonical_vdaf_for(vdaf):
    """The canonical Prio3 twin this task's prepare graphs compile for,
    or None when the task must keep an exact-shape backend (including
    when the task already sits on its bucket ceiling — a ceiling shape
    keeps its maskless exact graphs, and with them the planar Pallas
    fast path that the masked canonical layout forgoes)."""
    return _plan(vdaf)[0]


def executor_shape(vdaf, enabled: bool = True):
    """(backend cache key, canonical vdaf or None) for the device
    executor.  Tasks mapping to one canonical twin share the key — one
    backend instance, one set of compiled graphs, one mega-batch bucket.
    Shared by the job drivers and the helper aggregator so both protocol
    sides keep landing in the same buckets and breaker domains.

    Canonical keys carry a distinguishing tag: a bucket-CEILING task
    (its own twin — e.g. Histogram(6,2) in the {5,6} bucket) keeps the
    EXACT key and an exact maskless backend, and that key must never
    collide with the bucket's canonical entry — whichever task resolved
    first would otherwise decide the backend mode for every bucket
    member (a maskless exact backend served to a shorter member computes
    the wrong circuit)."""
    from .backend import vdaf_shape_key

    canon = canonical_vdaf_for(vdaf) if enabled else None
    if canon is None:
        return vdaf_shape_key(vdaf), None
    return ("canon",) + vdaf_shape_key(canon), canon


def backend_shape_key(backend):
    """The executor cache/bucket/warmup-ledger key a RESOLVED backend
    serves under — derived from the backend ITSELF, so the submit key can
    never diverge from the cache entry.  This matters on the fallback
    path: when a canonical twin build fails, the driver caches an
    exact-shape backend under the exact key, and re-deriving the key from
    the task's vdaf would aim submissions at the (empty) canonical bucket
    — binding a wrong-shaped backend to it for every later bucket member."""
    from .backend import vdaf_shape_key

    key = vdaf_shape_key(backend.vdaf)
    if getattr(backend, "canonical", False):
        return ("canon",) + key
    return key


def clip_agg_vector(vdaf, vector):
    """Clip a drained accumulator vector from canonical OUTPUT_LEN back to
    the task's.  The canonical pad tail is provably zero (padded
    measurement columns are zero-masked through truncate), so clipping is
    exact — and a nonzero tail means the parity contract broke, which
    must fail LOUDLY, never aggregate."""
    out_len = vdaf.flp.OUTPUT_LEN
    if vector is None or len(vector) <= out_len:
        return vector
    if any(vector[out_len:]):
        from .prio3 import VdafError

        raise VdafError(
            "canonical accumulator pad tail is nonzero "
            f"({len(vector)} drained, {out_len} expected)"
        )
    return list(vector[:out_len])


def clip_drained_vector(vdaf, vector):
    """:func:`clip_agg_vector` gated to shapes that actually canonicalize
    — the drain consumers' form.  A task that never canonicalizes keeps
    its vector untouched (exact backends already produce exact lengths;
    test fakes may produce anything)."""
    if vector is None or canonical_vdaf_for(vdaf) is None:
        return vector
    return clip_agg_vector(vdaf, vector)
