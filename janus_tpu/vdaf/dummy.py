"""Dummy VDAFs for protocol-layer tests — no real crypto, configurable round
count, and fault-injection variants.

The analog of ``prio::vdaf::dummy`` consumed by the reference's
``VdafInstance::Fake{rounds}/FakeFailsPrepInit/FakeFailsPrepStep``
(reference: core/src/vdaf.rs:96-108); lets job-driver and handler tests
exercise multi-round ping-pong and failure paths without FLP work
(SURVEY.md §4 item 5).

Measurement: one small integer.  Every party's output share is the
measurement (shares are not actually secret — this is a test double); the
aggregate is the sum over reports, "unshard" halves the doubled sum so
transcripts stay shaped like a two-party VDAF.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import List, Optional, Tuple

from .prio3 import VdafError


@dataclass
class DummyInputShare:
    measurement: int

    def encode(self, vdaf) -> bytes:
        return struct.pack(">I", self.measurement)

    @staticmethod
    def decode(vdaf, agg_id: int, data: bytes) -> "DummyInputShare":
        if len(data) != 4:
            raise VdafError("bad dummy input share")
        return DummyInputShare(struct.unpack(">I", data)[0])


@dataclass
class DummyPrepState:
    measurement: int
    round: int


class DummyField:
    """Minimal field surface for out-share accumulation (u64 counters)."""

    ENCODED_SIZE = 8
    MODULUS = 1 << 64

    @classmethod
    def vec_add(cls, a, b):
        return [(x + y) % cls.MODULUS for x, y in zip(a, b)]

    @classmethod
    def encode_vec(cls, vec) -> bytes:
        return b"".join(int(x).to_bytes(8, "little") for x in vec)

    @classmethod
    def decode_vec(cls, data: bytes):
        if len(data) % 8:
            raise VdafError("bad dummy vector length")
        return [int.from_bytes(data[i : i + 8], "little") for i in range(0, len(data), 8)]


class DummyVdaf:
    """Test VDAF with ``rounds`` ping-pong prepare rounds (>= 1)."""

    NONCE_SIZE = 16
    VERIFY_KEY_SIZE = 0
    RAND_SIZE = 0
    ROUNDS: int
    REQUIRES_AGG_PARAM = False
    field = DummyField

    def __init__(self, rounds: int = 1):
        if rounds < 1:
            raise ValueError("rounds must be >= 1")
        self.ROUNDS = rounds

    # -- sharding / aggregation ----------------------------------------
    def shard(self, measurement: int, nonce: bytes, rand: bytes):
        share = DummyInputShare(int(measurement))
        return None, [share, share]

    def aggregate(self, out_shares) -> List[int]:
        return [sum(s[0] for s in out_shares)]

    def unshard(self, agg_shares, num_measurements: int) -> int:
        return sum(s[0] for s in agg_shares) // 2

    def encode_public_share(self, public_share) -> bytes:
        return b""

    def decode_public_share(self, data: bytes):
        if data:
            raise VdafError("unexpected public share")
        return None

    # Uniform VDAF surface consumed by role logic.
    def field_for_agg_param(self, agg_param):
        return self.field

    def unshard_with_param(self, agg_param, agg_shares, num_measurements: int):
        return self.unshard(agg_shares, num_measurements)

    def decode_input_share(self, agg_id: int, data: bytes) -> DummyInputShare:
        return DummyInputShare.decode(self, agg_id, data)

    def encode_agg_param(self, agg_param) -> bytes:
        return b"" if agg_param is None else struct.pack(">I", int(agg_param))

    def decode_agg_param(self, data: bytes):
        if not data:
            return None
        if len(data) != 4:
            raise VdafError("bad dummy aggregation parameter")
        return struct.unpack(">I", data)[0]

    def agg_param_conflict_key(self, data: bytes) -> bytes:
        return data

    # -- ping-pong adapter surface --------------------------------------
    def ping_pong_prep_init(self, verify_key, agg_id, agg_param, nonce, public_share, input_share):
        state = DummyPrepState(input_share.measurement, 0)
        share = struct.pack(">IB", input_share.measurement, 0)
        return state, share

    def ping_pong_prep_shares_to_prep(self, agg_param, prep_shares, round=0) -> bytes:
        vals = set()
        for s in prep_shares:
            try:
                m, r = struct.unpack(">IB", s)
            except struct.error:
                raise VdafError("bad dummy prepare share")
            if r != round:
                raise VdafError("prepare share round mismatch")
            vals.add(m)
        if len(vals) != 1:
            raise VdafError("dummy prepare disagreement")
        return struct.pack(">IB", vals.pop(), round)

    def ping_pong_prep_next(self, prep_state: DummyPrepState, prep_msg: bytes, round=0):
        try:
            m, r = struct.unpack(">IB", prep_msg)
        except struct.error:
            raise VdafError("bad dummy prepare message")
        if m != prep_state.measurement or r != prep_state.round:
            raise VdafError("dummy prepare message mismatch")
        if prep_state.round + 1 >= self.ROUNDS:
            return ("finish", [prep_state.measurement])
        next_state = DummyPrepState(prep_state.measurement, prep_state.round + 1)
        next_share = struct.pack(">IB", prep_state.measurement, next_state.round)
        return ("continue", next_state, next_share)

    def ping_pong_encode_prep_share(self, share: bytes) -> bytes:
        return share

    def ping_pong_decode_prep_share(self, data: bytes, round=0) -> bytes:
        if len(data) != 5:
            raise VdafError("bad dummy prepare share")
        return data

    def ping_pong_encode_state(self, state: DummyPrepState) -> bytes:
        return struct.pack(">IB", state.measurement, state.round)

    def ping_pong_decode_state(self, data: bytes) -> DummyPrepState:
        try:
            m, r = struct.unpack(">IB", data)
        except struct.error:
            raise VdafError("bad dummy prepare state")
        return DummyPrepState(m, r)


class FakeFailsPrepInit(DummyVdaf):
    """Every prep_init errors (reference: core/src/vdaf.rs:101)."""

    def ping_pong_prep_init(self, *args, **kwargs):
        raise VdafError("FakeFailsPrepInit")


class FakeFailsPrepStep(DummyVdaf):
    """prep_init succeeds; every prepare step errors
    (reference: core/src/vdaf.rs:105)."""

    def ping_pong_prep_shares_to_prep(self, agg_param, prep_shares, round=0):
        raise VdafError("FakeFailsPrepStep")

    def ping_pong_prep_next(self, prep_state, prep_msg, round=0):
        raise VdafError("FakeFailsPrepStep")
