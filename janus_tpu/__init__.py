"""janus_tpu — a TPU-native DAP aggregation framework.

A ground-up re-design of the capabilities of divviup/janus (v0.7.4) for TPU:
the Prio3 VDAF prepare step (FLP proof verification over Field64/Field128 plus
TurboSHAKE128 XOF expansion) runs as jax.vmap'd modular-arithmetic tensor ops
batched across whole aggregation jobs, with output-share accumulation as
lax.psum over a device mesh.  A bit-exact CPU oracle (fields/xof/flp/vdaf
modules) mirrors the pure-Rust ``prio`` path.

Layout (see SURVEY.md for the reference layer map this re-expresses):
  fields, xof     — bit-exact scalar oracle for the crypto kernel
  flp/            — FLP proof system: gadgets, circuits, prove/query/decide
  vdaf/           — Prio3 composition, ping-pong topology, instance registry
  ops/            — JAX/TPU kernels (u32-limb field ops, vmapped Keccak,
                    batched prepare)
  parallel/       — device-mesh sharding and collective accumulation
  messages/       — DAP wire-format codec
"""

__version__ = "0.1.0"
