"""janus_tpu — a TPU-native DAP aggregation framework.

A ground-up re-design of the capabilities of divviup/janus (v0.7.4) for TPU:
the VDAF prepare step (FLP proof verification over Field64/Field128 plus
TurboSHAKE128 XOF expansion) runs as jax.vmap'd modular-arithmetic tensor ops
batched across whole aggregation jobs, with output-share accumulation reduced
over a device mesh.  A bit-exact CPU oracle (fields/xof/flp/vdaf modules)
mirrors the pure-Rust ``prio`` path.

Layout (see SURVEY.md for the reference layer map this re-expresses):
  fields, xof     — bit-exact scalar oracle for the crypto kernel
  flp/            — FLP proof system: gadgets, circuits, prove/query/decide
  vdaf/           — Prio3 + Poplar1 (IDPF, sketch), ping-pong topology,
                    instance registry, execution backends (oracle | tpu),
                    fake test VDAFs with fault injection
  ops/            — JAX/TPU kernels: u32-limb field ops, scanned Keccak,
                    batched XOF sampling, the batched prepare pipeline
  messages/       — DAP wire messages + TLS-syntax codec, taskprov, problems
  core/           — HPKE (RFC 9180), auth tokens, checksums, clock/time math,
                    HTTP retries, metrics, tracing
  native/         — C++ TurboSHAKE host kernel (ctypes)
  datastore/      — the database-is-the-checkpoint persistence layer: run_tx,
                    leases, column crypto, models, task model, query types
  aggregator/     — role logic, DAP HTTP API, job drivers, writers, taskprov
  binaries/       — multi-call entry: daemons, janus_cli, interop servers
  client, collector, aggregator_api, interop — SDKs and auxiliary APIs
  utils/          — transcript/test helpers, shared JAX setup
"""

__version__ = "0.3.0"
