"""janus_tpu — a TPU-native DAP aggregation framework.

A ground-up re-design of the capabilities of divviup/janus (v0.7.4) for TPU:
the Prio3 VDAF prepare step (FLP proof verification over Field64/Field128 plus
TurboSHAKE128 XOF expansion) runs as jax.vmap'd modular-arithmetic tensor ops
batched across whole aggregation jobs, with output-share accumulation reduced
over a device mesh.  A bit-exact CPU oracle (fields/xof/flp/vdaf modules)
mirrors the pure-Rust ``prio`` path.

Layout (see SURVEY.md for the reference layer map this re-expresses):
  fields, xof     — bit-exact scalar oracle for the crypto kernel
  flp/            — FLP proof system: gadgets, circuits, prove/query/decide
  vdaf/           — Prio3 composition, ping-pong topology, instance registry,
                    execution backends (oracle | tpu), dummy test VDAFs
  ops/            — JAX/TPU kernels: u32-limb field ops, scanned Keccak,
                    batched XOF sampling, the batched prepare pipeline
  messages/       — DAP wire messages + TLS-syntax codec, taskprov, problems
  core/           — HPKE (RFC 9180), auth tokens, checksums, clock/time math
  utils/          — transcript/test helpers, shared JAX setup
"""

__version__ = "0.2.0"
