"""Pure-Python ECDH curves: the `cryptography`-less KEM fallback.

core/hpke.py's two KEMs need exactly two primitives from the
`cryptography` package: X25519 (RFC 7748) and P-256 ECDH.  This module
supplies both in plain Python ints so the HPKE tier — and everything
downstream of it (client report sealing, upload opens, the RFC 9180 KAT
suite) — runs on hosts without the wheel.

* :func:`x25519` — the RFC 7748 §5 Montgomery ladder (constant
  structure, not constant time).
* :func:`p256_ecdh` / :func:`p256_public` — short-Weierstrass scalar
  multiplication in Jacobian coordinates with a single final inversion,
  X9.62 uncompressed-point encoding, and on-curve validation of peer
  points (an off-curve point must fail exactly like the real library's
  ``from_encoded_point``).

Performance posture: a scalar multiplication costs single-digit
milliseconds — fine for tests, soaks, and scaled bench rows; production
hosts install `cryptography` and never reach this path.  NONE of this is
constant-time; the functional-probe seam in core/hpke.py prefers the
real library whenever it actually works.

Correctness is anchored by the RFC 7748 §5.2 and NIST CAVP ECDH vectors
in tests/test_hpke.py's KAT suite (every supported HPKE suite exercises
decap/encap through whichever backend the seam picks).
"""

from __future__ import annotations

# -- X25519 (RFC 7748) --------------------------------------------------------

_P25519 = 2**255 - 19
_A24 = 121665


def _clamp(k: bytes) -> int:
    a = bytearray(k)
    a[0] &= 248
    a[31] &= 127
    a[31] |= 64
    return int.from_bytes(a, "little")


def x25519(scalar: bytes, u: bytes) -> bytes:
    """RFC 7748 §5 X25519(k, u): the Montgomery ladder."""
    if len(scalar) != 32 or len(u) != 32:
        raise ValueError("X25519 scalar and u-coordinate must be 32 bytes")
    k = _clamp(scalar)
    # mask the high bit of u (RFC 7748: the top bit is ignored)
    x1 = int.from_bytes(u, "little") & ((1 << 255) - 1)
    p = _P25519
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in range(254, -1, -1):
        k_t = (k >> t) & 1
        if swap ^ k_t:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % p
        aa = a * a % p
        b = (x2 - z2) % p
        bb = b * b % p
        e = (aa - bb) % p
        c = (x3 + z3) % p
        d = (x3 - z3) % p
        da = d * a % p
        cb = c * b % p
        x3 = (da + cb) % p
        x3 = x3 * x3 % p
        z3 = (da - cb) % p
        z3 = x1 * (z3 * z3) % p
        x2 = aa * bb % p
        z2 = e * (aa + _A24 * e) % p
    if swap:
        x2, z2 = x3, z3
    return (x2 * pow(z2, p - 2, p) % p).to_bytes(32, "little")


def x25519_public(scalar: bytes) -> bytes:
    """Public key = X25519(k, 9)."""
    return x25519(scalar, (9).to_bytes(32, "little"))


# -- P-256 (secp256r1) --------------------------------------------------------

_P256_P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
_P256_A = _P256_P - 3
_P256_B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
_P256_N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
_P256_G = (
    0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296,
    0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5,
)


def _jac_double(X, Y, Z, p=_P256_P):
    if Y == 0 or Z == 0:
        return 0, 1, 0
    # a = -3 doubling (dbl-2001-b)
    delta = Z * Z % p
    gamma = Y * Y % p
    beta = X * gamma % p
    alpha = 3 * (X - delta) * (X + delta) % p
    X3 = (alpha * alpha - 8 * beta) % p
    Z3 = ((Y + Z) * (Y + Z) - gamma - delta) % p
    Y3 = (alpha * (4 * beta - X3) - 8 * gamma * gamma) % p
    return X3, Y3, Z3


def _jac_add_affine(X1, Y1, Z1, x2, y2, p=_P256_P):
    """Mixed Jacobian + affine addition."""
    if Z1 == 0:
        return x2, y2, 1
    Z1Z1 = Z1 * Z1 % p
    U2 = x2 * Z1Z1 % p
    S2 = y2 * Z1 * Z1Z1 % p
    H = (U2 - X1) % p
    r = (S2 - Y1) % p
    if H == 0:
        if r == 0:
            return _jac_double(X1, Y1, Z1, p)
        return 0, 1, 0  # inverse points: infinity
    HH = H * H % p
    HHH = H * HH % p
    V = X1 * HH % p
    X3 = (r * r - HHH - 2 * V) % p
    Y3 = (r * (V - X3) - Y1 * HHH) % p
    Z3 = Z1 * H % p
    return X3, Y3, Z3


def _p256_scalar_mult(k: int, point):
    """k * point (affine in, affine out; None = infinity)."""
    x2, y2 = point
    X, Y, Z = 0, 1, 0
    for bit in range(k.bit_length() - 1, -1, -1):
        X, Y, Z = _jac_double(X, Y, Z)
        if (k >> bit) & 1:
            X, Y, Z = _jac_add_affine(X, Y, Z, x2, y2)
    if Z == 0:
        return None
    p = _P256_P
    zinv = pow(Z, p - 2, p)
    z2 = zinv * zinv % p
    return X * z2 % p, Y * z2 * zinv % p


def _p256_check_on_curve(x: int, y: int) -> None:
    p = _P256_P
    if not (0 <= x < p and 0 <= y < p) or (
        y * y - (x * x * x + _P256_A * x + _P256_B)
    ) % p != 0:
        raise ValueError("point is not on P-256")


def p256_decode_point(data: bytes):
    """X9.62 uncompressed point -> (x, y), validated on-curve."""
    if len(data) != 65 or data[0] != 4:
        raise ValueError("expected a 65-byte uncompressed P-256 point")
    x = int.from_bytes(data[1:33], "big")
    y = int.from_bytes(data[33:], "big")
    _p256_check_on_curve(x, y)
    return x, y


def p256_encode_point(point) -> bytes:
    x, y = point
    return b"\x04" + x.to_bytes(32, "big") + y.to_bytes(32, "big")


def p256_public(scalar: bytes) -> bytes:
    """Uncompressed public point for a 32-byte big-endian scalar."""
    k = int.from_bytes(scalar, "big") % _P256_N
    if k == 0:
        raise ValueError("P-256 private scalar is zero mod n")
    pt = _p256_scalar_mult(k, _P256_G)
    return p256_encode_point(pt)


def p256_ecdh(scalar: bytes, peer_point: bytes) -> bytes:
    """ECDH shared secret: the x-coordinate of k * peer, 32 bytes."""
    k = int.from_bytes(scalar, "big") % _P256_N
    if k == 0:
        raise ValueError("P-256 private scalar is zero mod n")
    pt = _p256_scalar_mult(k, p256_decode_point(peer_point))
    if pt is None:
        raise ValueError("P-256 ECDH produced the point at infinity")
    return pt[0].to_bytes(32, "big")
