"""Protocol transcript helper, mirroring the reference's ``run_vdaf`` test
utility (reference: core/src/test_util/mod.rs:48-100): run the full sharding /
ping-pong preparation / aggregation / unsharding flow in-process and expose
every intermediate artifact as ground truth for backend tests.
"""

from __future__ import annotations

import secrets
import zlib
from dataclasses import dataclass, field
from typing import Any, List, Optional


def det_rng(name: str):
    """Deterministic byte stream keyed by a test name via crc32 (reproducible
    across processes — PYTHONHASHSEED-independent)."""
    state = {"ctr": 0, "seed": zlib.crc32(name.encode())}

    def rng(n: int) -> bytes:
        out = b""
        while len(out) < n:
            out += zlib.crc32(
                state["seed"].to_bytes(4, "big") + state["ctr"].to_bytes(8, "big")
            ).to_bytes(4, "big")
            state["ctr"] += 1
        return out[:n]

    return rng

from ..vdaf.pingpong import (
    PingPongMessage,
    helper_initialized,
    leader_continued,
    leader_initialized,
)
from ..vdaf.prio3 import Prio3, Prio3InputShare


@dataclass
class ReportTranscript:
    nonce: bytes
    public_share: Optional[List[bytes]]
    input_shares: List[Prio3InputShare]
    leader_message: PingPongMessage
    helper_message: PingPongMessage
    leader_out_share: List[int]
    helper_out_share: List[int]


@dataclass
class VdafTranscript:
    verify_key: bytes
    reports: List[ReportTranscript] = field(default_factory=list)
    leader_agg_share: List[int] = field(default_factory=list)
    helper_agg_share: List[int] = field(default_factory=list)
    aggregate_result: Any = None


def run_vdaf(
    vdaf: Prio3,
    measurements: List[Any],
    verify_key: Optional[bytes] = None,
    rng=secrets.token_bytes,
) -> VdafTranscript:
    """Run the two-party protocol end-to-end over the given measurements."""
    if verify_key is None:
        verify_key = rng(vdaf.VERIFY_KEY_SIZE)
    t = VdafTranscript(verify_key=verify_key)
    leader_out_shares, helper_out_shares = [], []
    for m in measurements:
        nonce = rng(vdaf.NONCE_SIZE)
        rand = rng(vdaf.RAND_SIZE)
        public_share, input_shares = vdaf.shard(m, nonce, rand)
        state, leader_msg = leader_initialized(
            vdaf, verify_key, None, nonce, public_share, input_shares[0]
        )
        transition = helper_initialized(
            vdaf, verify_key, None, nonce, public_share, input_shares[1], leader_msg
        )
        helper_state, helper_msg = transition.evaluate(vdaf)
        leader_fin = leader_continued(vdaf, state, helper_msg)
        t.reports.append(
            ReportTranscript(
                nonce=nonce,
                public_share=public_share,
                input_shares=input_shares,
                leader_message=leader_msg,
                helper_message=helper_msg,
                leader_out_share=leader_fin.out_share,
                helper_out_share=helper_state.out_share,
            )
        )
        leader_out_shares.append(leader_fin.out_share)
        helper_out_shares.append(helper_state.out_share)
    t.leader_agg_share = vdaf.aggregate(leader_out_shares)
    t.helper_agg_share = vdaf.aggregate(helper_out_shares)
    t.aggregate_result = vdaf.unshard([t.leader_agg_share, t.helper_agg_share], len(measurements))
    return t
