"""Shared JAX runtime configuration for tests, bench, and driver entries."""

from __future__ import annotations

import hashlib
import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _host_fingerprint() -> str:
    """Identify the host microarchitecture for the cache key.

    Persisted executables embed AOT-compiled machine code; an entry built on
    a host with a different CPU feature set can hang or SIGILL when loaded
    (observed: a cache populated on an avx512fp16 host made a 12-second
    Field128 graph hang its *execution* for 9+ minutes on this one).  Keying
    the cache directory by the CPU flags makes foreign entries invisible
    instead of trusting XLA's partial feature check.
    """
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha256(line.encode()).hexdigest()[:12]
    except OSError:
        pass
    import platform

    return platform.machine()


def enable_compile_cache(cache_dir: str = None) -> None:
    """Point XLA's persistent compilation cache at <repo>/.jax_cache/<config>.

    The limb-arithmetic graphs are large; caching makes every re-run of the
    same (circuit, batch) shape start in milliseconds instead of minutes.

    The cache is scoped per (JAX_PLATFORMS, XLA_FLAGS) configuration:
    executables AOT-compiled under one configuration (e.g. the real TPU
    platform, or a different host-feature set) must never be loaded under
    another — XLA logs machine-feature mismatches and can hang or SIGILL
    executing them.  XLA-internal AOT kernel caches are disabled for the
    same reason; only the JAX-level executable cache is persisted.
    """
    import jax

    platforms = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
    if platforms.split(",")[0] == "cpu" or platforms == "":
        # XLA:CPU persists executables as AOT objects whose recorded target
        # machine includes compile-time pseudo-features (+prefer-no-scatter,
        # +prefer-no-gather) that never appear in the loader's host-feature
        # probe.  Every cross-process load then fails the feature check
        # (cpu_aot_loader: "Machine type used for XLA:CPU compilation
        # doesn't match...") and falls into a pathological slow path —
        # observed turning a 68 s cold-compile test into a 26+ minute hang.
        # Cold compiles are cheaper than poisoned loads: no persistent
        # cache on CPU.
        return

    config_key = (
        os.environ.get("JAX_PLATFORMS", "default")
        + "|"
        + os.environ.get("XLA_FLAGS", "")
        + "|"
        + _host_fingerprint()
    )
    sub = hashlib.sha256(config_key.encode()).hexdigest()[:12]
    path = cache_dir or os.path.join(_REPO_ROOT, ".jax_cache", sub)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    try:
        jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
    except AttributeError:
        pass
