"""Shared JAX runtime configuration for tests, bench, and driver entries."""

from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def enable_compile_cache(cache_dir: str = None) -> None:
    """Point XLA's persistent compilation cache at <repo>/.jax_cache.

    The limb-arithmetic graphs are large; caching makes every re-run of the
    same (circuit, batch) shape start in milliseconds instead of minutes.
    """
    import jax

    jax.config.update(
        "jax_compilation_cache_dir", cache_dir or os.path.join(_REPO_ROOT, ".jax_cache")
    )
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
