"""Shared JAX runtime configuration for tests, bench, and driver entries."""

from __future__ import annotations

import hashlib
import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def enable_compile_cache(cache_dir: str = None) -> None:
    """Point XLA's persistent compilation cache at <repo>/.jax_cache/<config>.

    The limb-arithmetic graphs are large; caching makes every re-run of the
    same (circuit, batch) shape start in milliseconds instead of minutes.

    The cache is scoped per (JAX_PLATFORMS, XLA_FLAGS) configuration:
    executables AOT-compiled under one configuration (e.g. the real TPU
    platform, or a different host-feature set) must never be loaded under
    another — XLA logs machine-feature mismatches and can hang or SIGILL
    executing them.  XLA-internal AOT kernel caches are disabled for the
    same reason; only the JAX-level executable cache is persisted.
    """
    import jax

    config_key = (
        os.environ.get("JAX_PLATFORMS", "default")
        + "|"
        + os.environ.get("XLA_FLAGS", "")
    )
    sub = hashlib.sha256(config_key.encode()).hexdigest()[:12]
    path = cache_dir or os.path.join(_REPO_ROOT, ".jax_cache", sub)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    try:
        jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
    except AttributeError:
        pass
