"""Shared JAX runtime configuration for tests, bench, and driver entries."""

from __future__ import annotations

import hashlib
import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _host_fingerprint() -> str:
    """Identify the host microarchitecture for the cache key.

    Persisted executables embed AOT-compiled machine code; an entry built on
    a host with a different CPU feature set can hang or SIGILL when loaded
    (observed: a cache populated on an avx512fp16 host made a 12-second
    Field128 graph hang its *execution* for 9+ minutes on this one).  Keying
    the cache directory by the CPU flags makes foreign entries invisible
    instead of trusting XLA's partial feature check.
    """
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha256(line.encode()).hexdigest()[:12]
    except OSError:
        pass
    import platform

    return platform.machine()


def resolve_cache_dir(cache_dir: str = None) -> str:
    """The configuration-scoped cache path: ``<root>/<config-digest>``.

    ``cache_dir`` overrides only the ROOT (the fleet-shared location, e.g.
    a persistent volume every replica mounts) — the per-(JAX_PLATFORMS,
    XLA_FLAGS, host-fingerprint) subdirectory is kept even then, so a
    replica restarting on a different host or platform config never loads
    a foreign executable (see _host_fingerprint)."""
    config_key = (
        os.environ.get("JAX_PLATFORMS", "default")
        + "|"
        + os.environ.get("XLA_FLAGS", "")
        + "|"
        + _host_fingerprint()
    )
    sub = hashlib.sha256(config_key.encode()).hexdigest()[:12]
    return os.path.join(cache_dir or os.path.join(_REPO_ROOT, ".jax_cache"), sub)


def enable_compile_cache(cache_dir: str = None) -> bool:
    """Point XLA's persistent compilation cache at the config-scoped dir.

    The limb-arithmetic graphs are large; caching makes every re-run of the
    same (circuit, batch) shape start in milliseconds instead of minutes —
    a RESTARTED replica (crash recovery, rollout) recovers warm instead of
    re-paying every shape's compile.  Wired into every binary's startup
    behind ``common.compile_cache_dir`` (binaries/main._bootstrap) and
    into bench.py.  Returns True when the cache was enabled.

    The cache is scoped per (JAX_PLATFORMS, XLA_FLAGS, host fingerprint)
    configuration: executables AOT-compiled under one configuration (e.g.
    the real TPU platform, or a different host-feature set) must never be
    loaded under another — XLA logs machine-feature mismatches and can
    hang or SIGILL executing them.  XLA-internal AOT kernel caches are
    disabled for the same reason; only the JAX-level executable cache is
    persisted.
    """
    import jax

    platforms = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
    if platforms.split(",")[0] == "cpu" or platforms == "":
        # XLA:CPU persists executables as AOT objects whose recorded target
        # machine includes compile-time pseudo-features (+prefer-no-scatter,
        # +prefer-no-gather) that never appear in the loader's host-feature
        # probe.  Every cross-process load then fails the feature check
        # (cpu_aot_loader: "Machine type used for XLA:CPU compilation
        # doesn't match...") and falls into a pathological slow path —
        # observed turning a 68 s cold-compile test into a 26+ minute hang.
        # Cold compiles are cheaper than poisoned loads: no persistent
        # cache on CPU.  This guard applies even to an explicitly
        # configured cache_dir.
        return False

    jax.config.update("jax_compilation_cache_dir", resolve_cache_dir(cache_dir))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    try:
        jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
    except AttributeError:
        pass
    return True
