"""Pure-Python/numpy AEADs: the `cryptography`-less fallback tier.

core/hpke.py and datastore/crypter.py need exactly three AEAD
constructions from the `cryptography` package — AES-128-GCM, AES-256-GCM
and ChaCha20-Poly1305 — plus nothing else from its hazmat layer that the
soft fallbacks in this package cannot provide (softaes supplies AES, and
utils/purecurves.py supplies the DH curves).  Dev containers without
`cryptography` (or with a nonfunctional test shim) used to lose the whole
HPKE tier, the datastore's column encryption, and with them most of the
service/chaos suites, to those imports.  This module is the
gate-don't-skip answer for the AEAD half:

* :class:`SoftAesGcm` — AES-GCM (128- and 256-bit keys) over the
  vectorized table AES in utils/softaes.py, with a 4-bit-table GHASH in
  plain Python ints (SP 800-38D right-shift construction).
* :class:`SoftChaCha20Poly1305` — RFC 8439 ChaCha20-Poly1305 in plain
  Python.
* :func:`aesgcm` / :func:`chacha20poly1305` — the backend seam: prefer
  `cryptography`'s implementations whenever they are importable AND
  functional (the functional probe matters: dev-container crypto shims
  import fine but compute garbage), soft fallbacks otherwise.

Performance posture: the fallbacks run at ~0.1-1 ms per small message —
plenty for tests, soak harnesses and scaled bench rows.  Production
hosts install `cryptography` (AES-NI / vectorized ChaCha at GB/s) and
never reach this path.  None of the fallback code is constant-time; it
must never be preferred over a functional `cryptography`.

Correctness is anchored at import time to NIST GCM test case 4 and the
RFC 8439 §2.8.2 vector (a table or rotation bug must fail loudly, never
silently mis-seal a share), and the RFC 9180 KAT suite in tests/test_hpke.py
runs every supported HPKE suite through whichever backend this seam picks.
"""

from __future__ import annotations

import struct
from typing import Optional

import numpy as np

from .softaes import encrypt_blocks, expand_key_any


class InvalidTagError(Exception):
    """AEAD authentication failed (the fallback's InvalidTag analog)."""


#: Exception types that mean "authentication failed" across both AEAD
#: backends — catch sites (Crypter key rotation, HPKE open) must treat
#: the real library's InvalidTag and the fallback's identically.
try:  # pragma: no cover - exercised only where cryptography is installed
    from cryptography.exceptions import InvalidTag as _RealInvalidTag

    INVALID_TAG_EXCEPTIONS = (InvalidTagError, _RealInvalidTag)
except ImportError:  # pragma: no cover
    INVALID_TAG_EXCEPTIONS = (InvalidTagError,)


# -- GHASH (SP 800-38D §6.3, right-shift table construction) -----------------

_R = 0xE1 << 120  # the GCM reduction polynomial, string-order


def _gf_shift_right(v: int) -> int:
    """Multiply by x in the GCM bit order (one right shift + reduce)."""
    if v & 1:
        return (v >> 1) ^ _R
    return v >> 1


class GhashKey:
    """H with a 16-entry (4-bit Shoup) multiplication table: ~32 table
    lookups + shifts per block, all plain Python ints."""

    def __init__(self, h: int):
        self.h = h
        # table[n] = (n as a 4-bit string-order prefix) * H: bit 3 of n is
        # the FIRST string bit, so table[0b1000] == H and each lower bit
        # is H shifted one further right.
        table = [0] * 16
        table[0b1000] = h
        for i in (0b0100, 0b0010, 0b0001):
            table[i] = _gf_shift_right(table[i << 1])
        for n in range(16):
            if n not in (0, 1, 2, 4, 8):
                table[n] = table[n & 8] ^ table[n & 4] ^ table[n & 2] ^ table[n & 1]
        self._table = table
        # Horner-by-nibble shifts the accumulated product right by 4 each
        # step; red[n] is the reduction term for a dropped low nibble n.
        red = [0] * 16
        for n in range(1, 16):
            v = n
            for _ in range(4):
                v = _gf_shift_right(v)
            red[n] = v
        self._red = red

    def mult(self, x: int) -> int:
        """x * H in GF(2^128).  The STRING-order head nibble (the
        integer's top bits) carries x^0 and the tail x^124, so Horner
        runs from the integer's low bits upward — each step multiplies
        the accumulated tail-side sum by x^4 (a 4-bit right shift with
        reduction) before adding the next nibble's table entry."""
        table, red = self._table, self._red
        z = 0
        for shift in range(0, 128, 4):
            if shift:
                z = (z >> 4) ^ red[z & 0xF]
            z ^= table[(x >> shift) & 0xF]
        return z

    def ghash(self, data: bytes) -> int:
        """GHASH over ``data`` (length must be a block multiple)."""
        assert len(data) % 16 == 0
        y = 0
        for off in range(0, len(data), 16):
            y = self.mult(y ^ int.from_bytes(data[off : off + 16], "big"))
        return y


def _gcm_pad(aad: bytes, ct: bytes) -> bytes:
    """aad || pad || ct || pad || bitlen(aad) || bitlen(ct)."""
    out = aad + b"\x00" * (-len(aad) % 16) + ct + b"\x00" * (-len(ct) % 16)
    return out + struct.pack(">QQ", 8 * len(aad), 8 * len(ct))


class SoftAesGcm:
    """Duck-type of ``cryptography``'s AESGCM over softaes + GhashKey.
    Accepts 16- or 32-byte keys; nonces must be 12 bytes (the only length
    HPKE/DAP and the datastore Crypter ever use)."""

    def __init__(self, key: bytes):
        if len(key) not in (16, 32):
            raise ValueError("AES-GCM key must be 16 or 32 bytes")
        self._rk = expand_key_any(key)
        h = encrypt_blocks(self._rk, np.zeros((1, 16), dtype=np.uint8)).tobytes()
        self._ghash = GhashKey(int.from_bytes(h, "big"))

    def _keystream(self, j0: bytes, nblocks: int) -> bytes:
        """E(K, J0), E(K, inc32(J0)), ...: block 0 is the tag mask."""
        prefix = j0[:12]
        ctr0 = struct.unpack(">I", j0[12:])[0]
        blocks = np.frombuffer(
            b"".join(
                prefix + struct.pack(">I", (ctr0 + i) & 0xFFFFFFFF)
                for i in range(nblocks)
            ),
            dtype=np.uint8,
        ).reshape(-1, 16)
        return encrypt_blocks(self._rk, blocks).tobytes()

    def _tag(self, j0: bytes, aad: bytes, ct: bytes, tag_mask: bytes) -> bytes:
        s = self._ghash.ghash(_gcm_pad(aad, ct))
        return (s ^ int.from_bytes(tag_mask, "big")).to_bytes(16, "big")

    def encrypt(self, nonce: bytes, data: bytes, aad: Optional[bytes]) -> bytes:
        if len(nonce) != 12:
            raise ValueError("soft AES-GCM supports 12-byte nonces only")
        aad = aad or b""
        nblocks = (len(data) + 15) // 16
        j0 = nonce + b"\x00\x00\x00\x01"
        stream = self._keystream(j0, 1 + nblocks)
        ct = bytes(a ^ b for a, b in zip(data, stream[16:]))
        return ct + self._tag(j0, aad, ct, stream[:16])

    def decrypt(self, nonce: bytes, data: bytes, aad: Optional[bytes]) -> bytes:
        if len(nonce) != 12:
            raise ValueError("soft AES-GCM supports 12-byte nonces only")
        if len(data) < 16:
            raise InvalidTagError("ciphertext shorter than the tag")
        aad = aad or b""
        ct, tag = data[:-16], data[-16:]
        nblocks = (len(ct) + 15) // 16
        j0 = nonce + b"\x00\x00\x00\x01"
        stream = self._keystream(j0, 1 + nblocks)
        if self._tag(j0, aad, ct, stream[:16]) != tag:
            raise InvalidTagError("AES-GCM tag mismatch")
        return bytes(a ^ b for a, b in zip(ct, stream[16:]))


# -- ChaCha20-Poly1305 (RFC 8439) --------------------------------------------


def _rotl32(v: int, n: int) -> int:
    return ((v << n) | (v >> (32 - n))) & 0xFFFFFFFF


def _chacha20_block(key_words, counter: int, nonce_words) -> bytes:
    state = [
        0x61707865, 0x3320646E, 0x79622D32, 0x6B206574,
        *key_words,
        counter, *nonce_words,
    ]
    x = list(state)

    def qr(a, b, c, d):
        x[a] = (x[a] + x[b]) & 0xFFFFFFFF; x[d] = _rotl32(x[d] ^ x[a], 16)
        x[c] = (x[c] + x[d]) & 0xFFFFFFFF; x[b] = _rotl32(x[b] ^ x[c], 12)
        x[a] = (x[a] + x[b]) & 0xFFFFFFFF; x[d] = _rotl32(x[d] ^ x[a], 8)
        x[c] = (x[c] + x[d]) & 0xFFFFFFFF; x[b] = _rotl32(x[b] ^ x[c], 7)

    for _ in range(10):
        qr(0, 4, 8, 12); qr(1, 5, 9, 13); qr(2, 6, 10, 14); qr(3, 7, 11, 15)
        qr(0, 5, 10, 15); qr(1, 6, 11, 12); qr(2, 7, 8, 13); qr(3, 4, 9, 14)
    return struct.pack(
        "<16I", *(((a + b) & 0xFFFFFFFF) for a, b in zip(x, state))
    )


def _chacha20_xor(key: bytes, counter: int, nonce: bytes, data: bytes) -> bytes:
    key_words = struct.unpack("<8I", key)
    nonce_words = struct.unpack("<3I", nonce)
    out = bytearray()
    for off in range(0, len(data), 64):
        block = _chacha20_block(key_words, counter + off // 64, nonce_words)
        chunk = data[off : off + 64]
        out += bytes(a ^ b for a, b in zip(chunk, block))
    return bytes(out)


def _poly1305(key: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key[:16], "little") & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key[16:], "little")
    p = (1 << 130) - 5
    acc = 0
    for off in range(0, len(msg), 16):
        chunk = msg[off : off + 16]
        n = int.from_bytes(chunk, "little") + (1 << (8 * len(chunk)))
        acc = ((acc + n) * r) % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


class SoftChaCha20Poly1305:
    """Duck-type of ``cryptography``'s ChaCha20Poly1305 (RFC 8439 AEAD)."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20-Poly1305 key must be 32 bytes")
        self._key = key

    def _mac(self, nonce: bytes, aad: bytes, ct: bytes) -> bytes:
        otk = _chacha20_block(
            struct.unpack("<8I", self._key), 0, struct.unpack("<3I", nonce)
        )[:32]
        msg = (
            aad + b"\x00" * (-len(aad) % 16)
            + ct + b"\x00" * (-len(ct) % 16)
            + struct.pack("<QQ", len(aad), len(ct))
        )
        return _poly1305(otk, msg)

    def encrypt(self, nonce: bytes, data: bytes, aad: Optional[bytes]) -> bytes:
        if len(nonce) != 12:
            raise ValueError("ChaCha20-Poly1305 nonce must be 12 bytes")
        aad = aad or b""
        ct = _chacha20_xor(self._key, 1, nonce, data)
        return ct + self._mac(nonce, aad, ct)

    def decrypt(self, nonce: bytes, data: bytes, aad: Optional[bytes]) -> bytes:
        if len(nonce) != 12:
            raise ValueError("ChaCha20-Poly1305 nonce must be 12 bytes")
        if len(data) < 16:
            raise InvalidTagError("ciphertext shorter than the tag")
        aad = aad or b""
        ct, tag = data[:-16], data[-16:]
        if self._mac(nonce, aad, ct) != tag:
            raise InvalidTagError("Poly1305 tag mismatch")
        return _chacha20_xor(self._key, 1, nonce, ct)


# -- the backend seam ---------------------------------------------------------


def _probe_real_cryptography() -> bool:
    """Is a FUNCTIONAL `cryptography` present?  Known-answer probed for
    EVERY primitive this flag gates — AES-GCM (NIST test case 1),
    ChaCha20-Poly1305 (RFC 8439), X25519 (RFC 7748 §6.1), and P-256
    (NIST CAVP ECDH) — because a dev-container shim may fake them
    independently; one real primitive must not vouch for a garbage
    curve.  All-or-nothing: any failing probe lands the whole suite on
    the soft fallbacks."""
    try:
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.hazmat.primitives.asymmetric.x25519 import (
            X25519PrivateKey,
            X25519PublicKey,
        )
        from cryptography.hazmat.primitives.ciphers.aead import (
            AESGCM,
            ChaCha20Poly1305,
        )

        if AESGCM(b"\x00" * 16).encrypt(b"\x00" * 12, b"", b"") != bytes.fromhex(
            "58e2fccefa7e3061367f1d57a4e7455a"
        ):
            return False
        if ChaCha20Poly1305(b"\x00" * 32).encrypt(
            b"\x00" * 12, b"", b""
        ) != bytes.fromhex("4eb972c9a8fb3a1b382bb4d36f5ffad1"):
            return False
        # X25519: RFC 7748 §6.1 — K = X25519(a, X25519(b, 9))
        a = bytes.fromhex(
            "77076d0a7318a57d3c16c17251b26645df4c2f87ebc0992ab177fba51db92c2a"
        )
        b_pub = bytes.fromhex(
            "de9edb7d7b7dc1b4d35b61c2ece435373f8343c85b78674dadfc7e146f882b4f"
        )
        if X25519PrivateKey.from_private_bytes(a).exchange(
            X25519PublicKey.from_public_bytes(b_pub)
        ) != bytes.fromhex(
            "4a5d9d5ba4ce2de1728e3bf480350f25e07e21c947d19e3376f09b3c1e161742"
        ):
            return False
        # P-256: NIST CAVP ECDH vector (count 0)
        d = 0x7D7DC5F71EB29DDAF80D6214632EEAE03D9058AF1FB6D22ED80BADB62BC1A534
        qx = 0x700C48F77F56584C5CC632CA65640DB91B6BACCE3A4DF6B42CE7CC838833D287
        qy = 0xDB71E509E3FD9B060DDB20BA5C51DCC5948D46FBF640DFE0441782CAB85FA4AC
        peer = ec.EllipticCurvePublicNumbers(qx, qy, ec.SECP256R1()).public_key()
        shared = ec.derive_private_key(d, ec.SECP256R1()).exchange(ec.ECDH(), peer)
        return shared == (
            0x46FC62106420FF012E54A434FBDD2D25CCC5852060561E68040DD7778997BD7B
        ).to_bytes(32, "big")
    except Exception:
        return False


HAVE_FUNCTIONAL_CRYPTOGRAPHY = _probe_real_cryptography()


def aesgcm(key: bytes):
    """An AES-GCM AEAD (.encrypt/.decrypt(nonce, data, aad)): the real
    library when functional, the soft fallback otherwise."""
    if HAVE_FUNCTIONAL_CRYPTOGRAPHY:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM

        return AESGCM(key)
    return SoftAesGcm(key)


def chacha20poly1305(key: bytes):
    """A ChaCha20-Poly1305 AEAD, same seam as :func:`aesgcm`."""
    if HAVE_FUNCTIONAL_CRYPTOGRAPHY:
        from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

        return ChaCha20Poly1305(key)
    return SoftChaCha20Poly1305(key)


# -- import-time anchors ------------------------------------------------------
# NIST GCM test case 4 (AES-128): a GHASH table or counter bug must fail
# loudly at import, never mis-open a share.
_k = bytes.fromhex("feffe9928665731c6d6a8f9467308308")
_gcm = SoftAesGcm(_k)
_ct = _gcm.encrypt(
    bytes.fromhex("cafebabefacedbaddecaf888"),
    bytes.fromhex(
        "d9313225f88406e5a55909c5aff5269a86a7a9531534f7da2e4c303d8a318a72"
        "1c3c0c95956809532fcf0e2449a6b525b16aedf5aa0de657ba637b39"
    ),
    bytes.fromhex("feedfacedeadbeeffeedfacedeadbeefabaddad2"),
)
if _ct != bytes.fromhex(
    "42831ec2217774244b7221b784d0d49ce3aa212f2c02a4e035c17e2329aca12e"
    "21d514b25466931c7d8f6a5aac84aa051ba30b396a0aac973d58e091"
    "5bc94fbc3221a5db94fae95ae7121a47"
):  # pragma: no cover
    raise AssertionError("soft AES-GCM self-test failed (GHASH/CTR corruption)")
# RFC 8439 §2.8.2
_cc = SoftChaCha20Poly1305(bytes(range(0x80, 0xA0)))
_ct = _cc.encrypt(
    bytes.fromhex("070000004041424344454647"),
    b"Ladies and Gentlemen of the class of '99: If I could offer you "
    b"only one tip for the future, sunscreen would be it.",
    bytes.fromhex("50515253c0c1c2c3c4c5c6c7"),
)
if _ct[-16:] != bytes.fromhex("1ae10b594f09e26a7e902ecbd0600691"):  # pragma: no cover
    raise AssertionError("soft ChaCha20-Poly1305 self-test failed")
del _k, _gcm, _cc, _ct
