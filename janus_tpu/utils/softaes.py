"""Pure-numpy AES-128-ECB encryption: the `cryptography`-less fallback.

The IDPF tree walk (vdaf/idpf.py, ops/poplar1_batch.py) needs exactly one
primitive from the `cryptography` package: a fixed-key AES-128-ECB
*encryptor* (the Davies-Meyer-style hash_block of XofFixedKeyAes128,
draft-irtf-cfrg-vdaf-08 §6.2.2 — no decryption, no other modes).  Dev
containers without `cryptography` (or with the test shim that stubs it
out) used to lose the whole Poplar1 tier to that one import.  This module
is the gate-don't-skip answer: a vectorized table-based AES-128 encryptor
over (N, 16) u8 blocks, API-compatible with the ``encryptor().update``
call sites.

Performance posture: numpy table lookups run the whole batch per round
(~20 vector ops per 10-round block set), plenty for tests and scaled
bench rows.  Production hosts install `cryptography` (AES-NI at GB/s) and
never reach this path — `aes128_ecb_encryptor` prefers it whenever its
Cipher actually works.

Correctness is anchored to the FIPS-197 appendix C.1 vector at import
time (a table typo must fail loudly, never walk a wrong tree).
"""

from __future__ import annotations

import numpy as np

# FIPS-197 S-box, generated from the GF(2^8) inverse + affine map so the
# table cannot drift from the spec by a transcription typo.
def _build_sbox() -> np.ndarray:
    # multiplicative inverse in GF(2^8) mod x^8+x^4+x^3+x+1
    exp = [0] * 512
    log = [0] * 256
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        # multiply by generator 0x03
        x ^= ((x << 1) ^ (0x1B if x & 0x80 else 0)) & 0xFF
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    sbox = np.zeros(256, dtype=np.uint8)
    for v in range(256):
        inv = 0 if v == 0 else exp[255 - log[v]]
        b = inv
        res = 0x63
        for _ in range(4):
            b = ((b << 1) | (b >> 7)) & 0xFF
            res ^= b
        sbox[v] = res ^ inv
    return sbox


_SBOX = _build_sbox()
_MUL2 = np.array(
    [((v << 1) ^ (0x1B if v & 0x80 else 0)) & 0xFF for v in range(256)],
    dtype=np.uint8,
)
_MUL3 = _MUL2 ^ np.arange(256, dtype=np.uint8)
#: ShiftRows as a flat-index permutation: byte i sits at (row=i%4,
#: col=i//4); row r rotates left by r columns.
_SHIFT = np.array(
    [(i % 4) + 4 * (((i // 4) + (i % 4)) % 4) for i in range(16)], dtype=np.intp
)
_RCON = (0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36)


def _expand_key(key: bytes) -> np.ndarray:
    """(11, 16) u8 round keys."""
    if len(key) != 16:
        raise ValueError("AES-128 key must be 16 bytes")
    words = [list(key[4 * i : 4 * i + 4]) for i in range(4)]
    sbox = _SBOX
    for i in range(4, 44):
        t = list(words[i - 1])
        if i % 4 == 0:
            t = t[1:] + t[:1]
            t = [int(sbox[b]) for b in t]
            t[0] ^= _RCON[i // 4 - 1]
        words.append([a ^ b for a, b in zip(words[i - 4], t)])
    flat = [b for w in words for b in w]
    return np.array(flat, dtype=np.uint8).reshape(11, 16)


def _expand_key_256(key: bytes) -> np.ndarray:
    """(15, 16) u8 round keys (AES-256: 8-word key, 14 rounds)."""
    if len(key) != 32:
        raise ValueError("AES-256 key must be 32 bytes")
    words = [list(key[4 * i : 4 * i + 4]) for i in range(8)]
    sbox = _SBOX
    for i in range(8, 60):
        t = list(words[i - 1])
        if i % 8 == 0:
            t = t[1:] + t[:1]
            t = [int(sbox[b]) for b in t]
            t[0] ^= _RCON[i // 8 - 1]
        elif i % 8 == 4:
            t = [int(sbox[b]) for b in t]
        words.append([a ^ b for a, b in zip(words[i - 8], t)])
    flat = [b for w in words for b in w]
    return np.array(flat, dtype=np.uint8).reshape(15, 16)


def expand_key_any(key: bytes) -> np.ndarray:
    """Round keys for a 16- or 32-byte key; ``encrypt_blocks`` derives the
    round count from the schedule's first-axis length."""
    return _expand_key(key) if len(key) == 16 else _expand_key_256(key)


def _mix_columns(s: np.ndarray) -> np.ndarray:
    """(N, 16) -> (N, 16); state reshaped (N, 4 cols, 4 rows)."""
    a = s.reshape(-1, 4, 4)
    a0, a1, a2, a3 = a[..., 0], a[..., 1], a[..., 2], a[..., 3]
    out = np.empty_like(a)
    out[..., 0] = _MUL2[a0] ^ _MUL3[a1] ^ a2 ^ a3
    out[..., 1] = a0 ^ _MUL2[a1] ^ _MUL3[a2] ^ a3
    out[..., 2] = a0 ^ a1 ^ _MUL2[a2] ^ _MUL3[a3]
    out[..., 3] = _MUL3[a0] ^ a1 ^ a2 ^ _MUL2[a3]
    return out.reshape(-1, 16)


def encrypt_blocks(round_keys: np.ndarray, blocks: np.ndarray) -> np.ndarray:
    """AES encrypt (N, 16) u8 blocks with precomputed round keys; the
    round count comes from the schedule (11 keys = AES-128, 15 = AES-256)."""
    rounds = len(round_keys) - 1
    s = blocks ^ round_keys[0]
    for rnd in range(1, rounds):
        s = _SBOX[s][:, _SHIFT]
        s = _mix_columns(s) ^ round_keys[rnd]
    return _SBOX[s][:, _SHIFT] ^ round_keys[rounds]


class SoftAesCtr:
    """Duck-type of ``Cipher(AES(key), CTR(iv)).encryptor()``: stateful
    keystream over a big-endian 128-bit counter starting at ``iv`` —
    exactly the construction XofHmacSha256Aes128 streams from."""

    def __init__(self, key: bytes, iv: bytes):
        if len(iv) != 16:
            raise ValueError("CTR IV must be 16 bytes")
        self._rk = expand_key_any(key)
        self._counter = int.from_bytes(iv, "big")
        self._buf = b""

    def update(self, data: bytes) -> bytes:
        need = len(data) - len(self._buf)
        if need > 0:
            nblocks = (need + 15) // 16
            ctrs = np.frombuffer(
                b"".join(
                    ((self._counter + i) % (1 << 128)).to_bytes(16, "big")
                    for i in range(nblocks)
                ),
                dtype=np.uint8,
            ).reshape(-1, 16)
            self._counter = (self._counter + nblocks) % (1 << 128)
            self._buf += encrypt_blocks(self._rk, ctrs).tobytes()
        stream, self._buf = self._buf[: len(data)], self._buf[len(data) :]
        return bytes(a ^ b for a, b in zip(data, stream))


#: cached functional-Cipher probe (None = not yet probed): the
#: dev-container crypto shim imports fine but miscomputes, so the real
#: library is trusted only after a known-answer check, paid once.
_CTR_FUNCTIONAL = None


def _ctr_functional() -> bool:
    global _CTR_FUNCTIONAL
    if _CTR_FUNCTIONAL is None:
        try:
            from cryptography.hazmat.primitives.ciphers import (
                Cipher,
                algorithms,
                modes,
            )

            probe = Cipher(
                algorithms.AES(b"\x00" * 16), modes.CTR(b"\x00" * 16)
            ).encryptor()
            # AES-128-CTR of zeros at iv=0 starts with E(K, 0) (FIPS-197)
            _CTR_FUNCTIONAL = probe.update(b"\x00" * 16) == bytes.fromhex(
                "66e94bd4ef8a2c3b884cfa59ca342b2e"
            )
        except Exception:
            _CTR_FUNCTIONAL = False
    return _CTR_FUNCTIONAL


def aes128_ctr_encryptor(key: bytes, iv: bytes):
    """An AES-128-CTR encryptor: `cryptography`'s Cipher when functional
    (AES-NI), the numpy fallback otherwise — the seam XofHmacSha256Aes128
    streams through, so HMAC-XOF VDAFs run on cryptography-less hosts."""
    if _ctr_functional():
        from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

        return Cipher(algorithms.AES(key), modes.CTR(iv)).encryptor()
    return SoftAesCtr(key, iv)


class SoftAes128Ecb:
    """Duck-type of ``Cipher(AES(key), ECB()).encryptor()``: stateless ECB,
    so ``update`` just encrypts every 16-byte block of its input."""

    def __init__(self, key: bytes):
        self._rk = _expand_key(key)

    def update(self, data: bytes) -> bytes:
        if len(data) % 16:
            raise ValueError("ECB input must be a multiple of 16 bytes")
        if not data:
            return b""
        blocks = np.frombuffer(data, dtype=np.uint8).reshape(-1, 16)
        return encrypt_blocks(self._rk, blocks).tobytes()


#: Process default for the Poplar1 AES-walk backend ("host" | "jax"),
#: resolved lazily from JANUS_TPU_POPLAR_BACKEND.  "host" is the legacy
#: path: `cryptography` (AES-NI) when functional, numpy soft-AES
#: otherwise.  "jax" routes through the jitted kernel in ops/aes_jax.py —
#: the device-resident IDPF walk — and falls back to host loudly if the
#: jax kernel cannot import.  The binaries' `poplar_backend` config is
#: threaded PER BACKEND (make_backend -> Poplar1Backend), deliberately
#: leaving this process default alone: the per-report oracle and
#: XofFixedKeyAes128 keep the host path regardless of how the batched
#: walk is configured.  set_poplar_backend exists for tests and for
#: operators who want the env-equivalent programmatically.
_POPLAR_BACKEND = None
POPLAR_BACKENDS = ("host", "jax")


def poplar_backend() -> str:
    global _POPLAR_BACKEND
    if _POPLAR_BACKEND is None:
        import os

        env = os.environ.get("JANUS_TPU_POPLAR_BACKEND", "host")
        _POPLAR_BACKEND = env if env in POPLAR_BACKENDS else "host"
    return _POPLAR_BACKEND


def set_poplar_backend(name: str) -> None:
    if name not in POPLAR_BACKENDS:
        raise ValueError(f"unknown poplar backend {name!r}")
    global _POPLAR_BACKEND
    _POPLAR_BACKEND = name


def aes128_ecb_encryptor(key: bytes, backend: str = None):
    """An AES-128-ECB encryptor behind the ``poplar_backend: jax|host``
    seam.  ``backend`` None resolves the process default.  Host prefers
    `cryptography` (AES-NI) when its Cipher is importable AND functional,
    the numpy fallback otherwise — the functional probe matters: the
    dev-container crypto shim imports fine but raises at Cipher
    construction.  "jax" returns the jitted-kernel duck-type (bit-exact,
    FIPS-anchored at ops/aes_jax import) and degrades to host if the jax
    stack is unavailable — a missing accelerator dep must never take the
    Poplar1 tier down."""
    if (backend or poplar_backend()) == "jax":
        try:
            from ..ops.aes_jax import JaxAes128Ecb

            return JaxAes128Ecb(key)
        except Exception:  # pragma: no cover - jax-less host
            import logging

            logging.getLogger("janus_tpu.softaes").warning(
                "poplar_backend=jax but the jax AES kernel is unavailable; "
                "serving the host path",
                exc_info=True,
            )
    try:
        from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

        return Cipher(algorithms.AES(key), modes.ECB()).encryptor()
    except Exception:
        return SoftAes128Ecb(key)


# -- import-time anchor (FIPS-197 C.1) ---------------------------------------
_vec = SoftAes128Ecb(bytes(range(16))).update(
    bytes.fromhex("00112233445566778899aabbccddeeff")
)
if _vec != bytes.fromhex("69c4e0d86a7b0430d8cdb78070b4c55a"):  # pragma: no cover
    raise AssertionError("softaes self-test failed (table corruption)")
del _vec
