"""Shared utilities."""
