"""Extendable output functions (XOFs) for Prio3 — bit-exact CPU oracle.

Implements draft-irtf-cfrg-vdaf-08 §6.2:

* ``XofTurboShake128`` — TurboSHAKE128 (Keccak-p[1600,12], rate 168, domain
  separation byte 0x01), seed size 16.
* ``XofHmacSha256Aes128`` — libprio-rs's non-standard XOF used by the custom
  multiproof VDAF (reference: core/src/vdaf.rs:178-195,
  VERIFY_KEY_LENGTH_HMACSHA256_AES128 = 32 at core/src/vdaf.rs:24), seed size
  32: HMAC-SHA256 over (len(dst) || dst || binder) keyed by the seed yields
  (aes_key, iv); the stream is AES128-CTR over zeros.

The Keccak permutation here is the reference for the vmapped TPU version in
``janus_tpu.ops.keccak``.  Its sponge/padding path is cross-validated against
``hashlib.shake_128`` by running the same code with 24 rounds and domain 0x1F
(see tests/test_xof.py).
"""

from __future__ import annotations

from typing import List

from .fields import next_power_of_2

_M64 = (1 << 64) - 1

# Standard Keccak-f[1600] round constants; Keccak-p[1600,12] (TurboSHAKE) uses
# the final 12.
ROUND_CONSTANTS = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# Rotation offsets, generated from the rho step schedule (index = x + 5*y).
_RHO = [0] * 25
_x, _y = 1, 0
for _t in range(24):
    _RHO[_x + 5 * _y] = ((_t + 1) * (_t + 2) // 2) % 64
    _x, _y = _y, (2 * _x + 3 * _y) % 5


def _rotl(v: int, r: int) -> int:
    return ((v << r) | (v >> (64 - r))) & _M64


def keccak_p(lanes: List[int], rounds: int) -> List[int]:
    """Keccak-p[1600, rounds] permutation on 25 u64 lanes (index = x + 5*y)."""
    a = list(lanes)
    for rc in ROUND_CONSTANTS[24 - rounds :]:
        # theta
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        a = [a[i] ^ d[i % 5] for i in range(25)]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(a[x + 5 * y], _RHO[x + 5 * y])
        # chi
        a = [
            b[i] ^ ((b[(i % 5 + 1) % 5 + 5 * (i // 5)] ^ _M64) & b[(i % 5 + 2) % 5 + 5 * (i // 5)])
            for i in range(25)
        ]
        # iota
        a[0] ^= rc
    return a


class _Sponge:
    """Keccak sponge in absorb-then-squeeze mode with TurboSHAKE padding."""

    def __init__(self, rate: int, rounds: int, domain: int):
        self.rate = rate
        self.rounds = rounds
        self.domain = domain
        self._buf = bytearray()
        self._state = [0] * 25
        self._squeezing = False
        self._out = bytearray()

    def update(self, data: bytes) -> None:
        assert not self._squeezing, "cannot absorb after squeezing"
        self._buf += data
        while len(self._buf) >= self.rate:
            self._absorb_block(bytes(self._buf[: self.rate]))
            del self._buf[: self.rate]

    def _absorb_block(self, block: bytes) -> None:
        for i in range(self.rate // 8):
            self._state[i] ^= int.from_bytes(block[8 * i : 8 * i + 8], "little")
        self._state = keccak_p(self._state, self.rounds)

    def _pad_and_finish(self) -> None:
        block = bytearray(self._buf)
        del self._buf[:]
        block.append(self.domain)
        block += b"\x00" * (self.rate - len(block))
        block[self.rate - 1] ^= 0x80
        for i in range(self.rate // 8):
            self._state[i] ^= int.from_bytes(block[8 * i : 8 * i + 8], "little")
        self._squeezing = True

    def squeeze(self, n: int) -> bytes:
        if not self._squeezing:
            self._pad_and_finish()
        while len(self._out) < n:
            self._state = keccak_p(self._state, self.rounds)
            for i in range(self.rate // 8):
                self._out += self._state[i].to_bytes(8, "little")
        out = bytes(self._out[:n])
        del self._out[:n]
        return out


def turboshake128(message: bytes, domain: int, length: int) -> bytes:
    """One-shot TurboSHAKE128 (rate 168, 12 rounds)."""
    s = _Sponge(rate=168, rounds=12, domain=domain)
    s.update(message)
    return s.squeeze(length)


def shake128(message: bytes, length: int) -> bytes:
    """Standard SHAKE128 via the same sponge (24 rounds, domain 0x1F).

    Only used to cross-validate the sponge against hashlib in tests.
    """
    s = _Sponge(rate=168, rounds=24, domain=0x1F)
    s.update(message)
    return s.squeeze(length)


class Xof:
    """Streaming XOF interface per draft-irtf-cfrg-vdaf-08 §6.2."""

    SEED_SIZE: int

    def next(self, length: int) -> bytes:
        raise NotImplementedError

    def next_vec(self, field: type, length: int) -> List[int]:
        """Rejection-sample field elements from the stream (§6.2.1)."""
        mask = next_power_of_2(field.MODULUS) - 1
        vec: List[int] = []
        while len(vec) < length:
            x = int.from_bytes(self.next(field.ENCODED_SIZE), "little") & mask
            if x < field.MODULUS:
                vec.append(x)
        return vec

    @classmethod
    def expand_into_vec(
        cls, field: type, seed: bytes, dst: bytes, binder: bytes, length: int
    ) -> List[int]:
        return cls(seed, dst, binder).next_vec(field, length)


class XofTurboShake128(Xof):
    SEED_SIZE = 16

    def __init__(self, seed: bytes, dst: bytes, binder: bytes):
        if len(seed) != self.SEED_SIZE:
            raise ValueError("bad seed size")
        if len(dst) > 255:
            raise ValueError("dst too long")
        self._sponge = _Sponge(rate=168, rounds=12, domain=0x01)
        self._sponge.update(bytes([len(dst)]))
        self._sponge.update(dst)
        self._sponge.update(seed)
        self._sponge.update(binder)

    def next(self, length: int) -> bytes:
        return self._sponge.squeeze(length)

    @classmethod
    def expand_into_vec(
        cls, field: type, seed: bytes, dst: bytes, binder: bytes, length: int
    ) -> List[int]:
        # Hot path: the native C++ sponge (bit-exact, tests/test_native.py).
        # The C++ kernel hardcodes the two rejection moduli, so gate on the
        # EXACT modulus — a different 8/16-byte field must take the Python
        # path or it would silently sample against the wrong bound.
        if field.MODULUS in (
            2**64 - 2**32 + 1,
            2**128 - 7 * 2**66 + 1,
        ):
            from .native import next_vec as native_next_vec

            out = native_next_vec(seed, dst, binder, field.ENCODED_SIZE, length)
            if out is not None:
                return out
        return cls(seed, dst, binder).next_vec(field, length)


from functools import lru_cache as _lru_cache


@_lru_cache(maxsize=4096)
def _fixed_key_aes128(dst: bytes, binder: bytes) -> bytes:
    return turboshake128(bytes([len(dst)]) + dst + binder, 0x02, 16)


class XofFixedKeyAes128(Xof):
    """Fixed-key AES-128 XOF for the IDPF tree walk (draft-irtf-cfrg-vdaf-08
    §6.2.2): one TurboSHAKE-derived AES key per (dst, binder) context, then
    stream block i = hash_block(seed XOR le128(i)) with the Davies-Meyer-style
    hash_block(x) = AES128(k, sigma(x)) XOR sigma(x),
    sigma(x_L || x_R) = x_R || (x_L XOR x_R).

    Circular-correlation-robust by construction — safe for the DPF extend
    step where seeds are XOR-related across parties.
    """

    SEED_SIZE = 16

    def __init__(self, seed: bytes, dst: bytes, binder: bytes):
        from .utils.softaes import aes128_ecb_encryptor

        if len(seed) != self.SEED_SIZE:
            raise ValueError("bad seed size")
        if len(dst) > 255:
            raise ValueError("dst too long")
        # The fixed key depends only on (dst, binder) — for an IDPF tree walk
        # that is two values per report, so cache the TurboSHAKE derivation.
        # The encryptor resolves to `cryptography` (AES-NI) when available,
        # else the numpy soft-AES fallback — hosts without the lib keep the
        # whole Poplar1 tier instead of losing it to one import.
        fixed_key = _fixed_key_aes128(dst, binder)
        self._enc = aes128_ecb_encryptor(fixed_key)
        self._seed = seed
        self._index = 0
        self._buf = b""

    def _hash_block(self, x: bytes) -> bytes:
        sigma = x[8:] + bytes(a ^ b for a, b in zip(x[:8], x[8:]))
        return bytes(a ^ b for a, b in zip(self._enc.update(sigma), sigma))

    def next(self, length: int) -> bytes:
        while len(self._buf) < length:
            block = bytes(
                a ^ b
                for a, b in zip(self._seed, self._index.to_bytes(16, "little"))
            )
            self._buf += self._hash_block(block)
            self._index += 1
        out, self._buf = self._buf[:length], self._buf[length:]
        return out


class XofHmacSha256Aes128(Xof):
    """libprio-rs XofHmacSha256Aes128 (non-standard; Daphne interop)."""

    SEED_SIZE = 32

    def __init__(self, seed: bytes, dst: bytes, binder: bytes):
        import hmac as _hmac
        import hashlib as _hashlib

        from .utils.softaes import aes128_ctr_encryptor

        if len(seed) != self.SEED_SIZE:
            raise ValueError("bad seed size")
        if len(dst) > 255:
            raise ValueError("dst too long")
        mac = _hmac.new(seed, digestmod=_hashlib.sha256)
        mac.update(bytes([len(dst)]))
        mac.update(dst)
        mac.update(binder)
        key_block = mac.digest()
        # `cryptography`'s AES-NI CTR when functional, the numpy soft-AES
        # fallback otherwise (ISSUE 14 de-shim): HMAC-XOF VDAF instances
        # no longer die on cryptography-less hosts.
        self._enc = aes128_ctr_encryptor(key_block[:16], key_block[16:])

    def next(self, length: int) -> bytes:
        return self._enc.update(b"\x00" * length)
