"""Fleet control plane: replica membership, task routing, live migration.

Janus scales by running N stateless replicas that coordinate only through
the shared datastore (PAPER.md: "all communication between components
happens implicitly through the database"), but without routing every
replica re-derives every task's compile cache, warmup ledger, and
device-resident accumulator.  This module is the missing tier (ROADMAP
direction 2): each driver binary registers a replica id with a heartbeat
row in ``fleet_members``, rendezvous-hashes ``task_id -> replica`` over
the live member set, and the acquisition path filters to owned tasks —
so each replica compiles and warms only its own tasks' shapes, and adding
a replica shrinks every replica's working set instead of duplicating it.

Design points:

- **Rendezvous (highest-random-weight) hashing** rather than a ring:
  deterministic from (member set, task_id) alone — no state to agree on
  beyond the membership table — and a membership change moves only the
  tasks whose highest-weight member changed (minimal reshuffle).
- **Membership = heartbeat rows.**  A member is live iff its heartbeat is
  within ``heartbeat_ttl_s`` of tx-time.  Liveness is judged per-reader;
  there is no coordinator.  A replica always counts *itself* live in its
  own view (a wedged local heartbeat must degrade toward too-much work,
  never toward "I own nothing" self-eviction); brief double-ownership
  during disagreement is safe because job leases still serialize.
- **Per-role domains.**  Aggregation and collection drivers register with
  distinct roles and hash over same-role members only — a collection
  replica must never absorb *ownership* of aggregation acquisition (the
  jobs would strand: it never acquires them).
- **Migration** is emergent: when an owner's heartbeat goes stale, it
  drops out of every survivor's live set and its tasks re-route.  The
  router counts owner transitions toward itself
  (``janus_fleet_migrations_total``) and applies ``takeover_grace_s``
  before acquiring a freshly-absorbed task, so an owner that was merely
  slow to heartbeat (or whose lease is in flight) gets a window to
  finish/resume before the new owner starts pulling its jobs.
- **Migration-storm suppression.**  Heartbeat liveness is only
  trustworthy while the datastore it lives in is: a brownout makes every
  member's row stale *simultaneously*, which is indistinguishable from
  mass death.  When the local datastore tracker (core/db_health.py) is
  suspect, or more than ``mass_staleness_fraction`` of previously-live
  same-role members go stale in one refresh, the router FREEZES its
  last-known ownership view — no takeovers, no releases — and counts
  ``janus_fleet_migration_suppressed_total``; it thaws only after the
  tracker heals and a full heartbeat TTL confirms the staleness was
  real.  See README "Datastore brownout tolerance".
- **Fleet-shared suspects.**  Each heartbeat republishes the origins this
  replica's peer-health tracker currently holds SUSPECT onto its member
  row; ``shared_suspects`` unions fresh advertisements from *other* live
  members so a replica that never talked to a partitioned peer also skips
  its tasks.  A healed peer un-publishes by advertising the empty set,
  and ``suspect_staleness_s`` bounds how long a stale advertisement is
  honored (a dead advertiser must not suspect-pin a healthy peer forever).

Everything is off unless ``fleet.enabled`` is set in config:
``fleet_router()`` returns None and the drivers' acquisition filter
reduces to the PR 11 suspect filter, bit-for-bit.
"""

from __future__ import annotations

import hashlib
import os
import secrets
import socket
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Set

from ..messages import Duration
from .metrics import GLOBAL_METRICS

#: Separator between member id and task id in the rendezvous digest input —
#: prevents ambiguity between ("ab", "c"||task) and ("a", "bc"||task).
_SEP = b"\x00"


def rendezvous_owner(task_id: bytes, members: Sequence[str]) -> Optional[str]:
    """Highest-random-weight owner of ``task_id`` among ``members``.

    Deterministic in the *set* (order-independent); ties — impossible in
    practice for SHA-256, but defined anyway — break toward the lexically
    larger member id so every caller agrees.
    """
    best: Optional[str] = None
    best_digest = b""
    for member in members:
        digest = hashlib.sha256(member.encode() + _SEP + task_id).digest()
        if best is None or digest > best_digest or (
            digest == best_digest and member > best  # type: ignore[operator]
        ):
            best, best_digest = member, digest
    return best


def default_replica_id() -> str:
    """hostname-pid-nonce: unique per process start, stable within one."""
    host = socket.gethostname().split(".")[0] or "replica"
    return f"{host}-{os.getpid()}-{secrets.token_hex(3)}"


class FleetRouter:
    """One replica's view of the fleet: membership, ownership, migration.

    Instantiable (tests run several routers against one datastore in one
    process); the module-level singleton below is only the binaries'
    default.  All datastore access takes a live Transaction so ownership
    decisions commit atomically with the acquisition they filter.
    """

    #: Rows with a heartbeat older than this many TTLs are pruned
    #: opportunistically during heartbeats — dead replicas that never
    #: deregistered.  Well past any takeover window, so pruning never
    #: races a routing decision.
    PRUNE_TTLS = 10

    def __init__(
        self,
        replica_id: str,
        role: str,
        *,
        heartbeat_ttl_s: float = 10.0,
        takeover_grace_s: float = 5.0,
        suspect_staleness_s: float = 30.0,
        mass_staleness_fraction: float = 0.5,
        enabled: bool = True,
    ):
        self.replica_id = replica_id
        self.role = role
        self.heartbeat_ttl_s = float(heartbeat_ttl_s)
        self.takeover_grace_s = float(takeover_grace_s)
        self.suspect_staleness_s = float(suspect_staleness_s)
        #: migration-storm trigger: if MORE than this fraction of the
        #: previously-live same-role members (excluding self — self is
        #: live by fiat and would dilute the signal) go stale in one
        #: refresh, the staleness is treated as correlated (datastore
        #: brownout, not mass death) and the ownership view freezes.
        self.mass_staleness_fraction = float(mass_staleness_fraction)
        self.enabled = enabled
        self._lock = threading.Lock()
        self._last_owner: Dict[bytes, str] = {}
        self._takeover_at: Dict[bytes, int] = {}
        self._migrations = 0
        self._tasks_owned = 0
        self._last_heartbeat_s: Optional[int] = None
        self._members_snapshot: List[dict] = []
        # -- migration-storm suppression state (ISSUE 17) --------------
        #: live set as of the last UNSUPPRESSED refresh — the baseline
        #: the mass-staleness quorum check compares against
        self._prev_live: Optional[Set[str]] = None
        #: exclusion list as of the last unsuppressed refresh — what a
        #: suppressed refresh serves instead of recomputing ownership
        self._frozen_exclusions: Optional[List[bytes]] = None
        self._suppressed = False
        self._suppress_reason: Optional[str] = None
        self._suppressed_total = 0
        #: tx-time when thaw confirmation began: suppression lifts only
        #: after the datastore tracker is healthy AND a full heartbeat
        #: TTL passes with the trigger still absent — so staleness that
        #: was just brownout shadow (members heartbeat again the moment
        #: the datastore heals) never causes a takeover
        self._thaw_started_s: Optional[int] = None

    # -- membership ----------------------------------------------------

    def heartbeat(self, tx, suspect_origins: Iterable[str] = ()) -> None:
        """Refresh this replica's member row (registering it if absent),
        republish its suspect set, and snapshot the membership view for
        /statusz.  Called on the heartbeat cadence AND once synchronously
        at driver startup (registration must precede warmup so the first
        ownership computation already sees this replica)."""
        if not self.enabled:
            return
        tx.upsert_fleet_member(self.replica_id, self.role, list(suspect_origins))
        tx.prune_fleet_members(
            Duration(int(self.PRUNE_TTLS * self.heartbeat_ttl_s) + 1)
        )
        now = tx._now_s()
        snapshot = []
        live_count = 0
        for m in tx.get_fleet_members():
            age = max(0, now - m.heartbeat.seconds)
            live = m.replica_id == self.replica_id or age <= self.heartbeat_ttl_s
            if live and m.role == self.role:
                live_count += 1
            snapshot.append(
                {
                    "replica_id": m.replica_id,
                    "role": m.role,
                    "heartbeat_age_s": age,
                    "live": live,
                    "suspect_peers": list(m.suspect_peers),
                }
            )
        with self._lock:
            self._last_heartbeat_s = now
            self._members_snapshot = snapshot
        GLOBAL_METRICS.fleet_members.set(live_count)

    def deregister(self, tx) -> None:
        """Graceful shutdown: drop out of the rendezvous domain now
        instead of after the TTL, so survivors re-route immediately."""
        if self.enabled:
            tx.delete_fleet_member(self.replica_id)

    def _live_members(self, tx) -> List[str]:
        now = tx._now_s()
        live = {
            m.replica_id
            for m in tx.get_fleet_members(self.role)
            if now - m.heartbeat.seconds <= self.heartbeat_ttl_s
        }
        live.add(self.replica_id)  # self-eviction is never the right failure mode
        return sorted(live)

    # -- migration-storm suppression (ISSUE 17) ------------------------

    def _suppression_verdict_locked(self, live: Set[str], now: int) -> Optional[str]:
        """Should this refresh be served from the frozen ownership view?
        Returns the reason string, or None to compute live.  Caller holds
        ``self._lock``.

        Triggers: the local datastore tracker says suspect/probing (a
        brownout makes every heartbeat row stale at once — trusting the
        table would start a migration storm), or at least two AND more
        than ``mass_staleness_fraction`` of the previously-live
        same-role members went stale since the last unsuppressed refresh
        (the correlated-staleness signature, caught even when this
        replica's own transactions happened to sail through).

        Thaw: once the tracker is healthy, suppression holds for one
        more full heartbeat TTL — members that were only brownout-shadow
        stale heartbeat again within it and the thawed refresh routes
        exactly as before; members still stale after it are genuinely
        dead and their tasks migrate for real.
        """
        from .db_health import tracker as db_tracker

        if db_tracker().is_suspect():
            self._thaw_started_s = None  # heal restarts the confirmation
            return "datastore_suspect"
        if self._suppressed:
            if self._thaw_started_s is None:
                self._thaw_started_s = now
            if now - self._thaw_started_s < self.heartbeat_ttl_s:
                return self._suppress_reason or "thaw_confirmation"
            return None  # confirmed: thaw this refresh
        prev = self._prev_live
        if prev:
            others = prev - {self.replica_id}
            stale = others - live
            # a storm needs PLURAL simultaneous staleness: one dead peer
            # is the normal single-failure takeover (2-replica fleets
            # rely on the datastore-suspect trigger instead — in a real
            # brownout this replica's own transactions fail too)
            if (
                len(stale) >= 2
                and len(stale) / len(others) > self.mass_staleness_fraction
            ):
                return "mass_staleness"
        return None

    # -- routing -------------------------------------------------------

    def not_owned_task_ids(self, tx) -> Optional[List[bytes]]:
        """Task ids this replica must NOT acquire right now: tasks owned
        by another live member, plus tasks absorbed so recently that the
        takeover grace window is still open.  Returns None (no filter)
        when disabled or when nothing is excluded.

        Also the migration detector: an ownership transition from another
        member to this one increments ``janus_fleet_migrations_total`` and
        opens the grace window.  While migration-storm suppression is
        active the FROZEN exclusion list is returned instead — no
        takeovers, no releases, no ``_last_owner`` churn — and
        ``janus_fleet_migration_suppressed_total`` counts the refresh.
        """
        if not self.enabled:
            return None
        live = self._live_members(tx)
        now = tx._now_s()
        frozen: Optional[List[bytes]] = None
        with self._lock:
            reason = self._suppression_verdict_locked(set(live), now)
            if reason is not None and self._frozen_exclusions is not None:
                self._suppressed = True
                self._suppress_reason = reason
                self._suppressed_total += 1
                frozen = list(self._frozen_exclusions)
            # reason with no frozen view (cold start): nothing useful to
            # freeze to — compute live below, which also seeds the view
        if frozen is not None:
            GLOBAL_METRICS.fleet_migration_suppressed.inc()
            return frozen or None
        excluded: List[bytes] = []
        owned = 0
        migrations = 0
        with self._lock:
            for task_id, _peer in tx.get_task_peer_index():
                owner = rendezvous_owner(task_id, live)
                prev = self._last_owner.get(task_id)
                if owner == self.replica_id:
                    if prev is not None and prev != self.replica_id:
                        migrations += 1
                        self._takeover_at[task_id] = now
                    taken_at = self._takeover_at.get(task_id)
                    if (
                        taken_at is not None
                        and now - taken_at < self.takeover_grace_s
                    ):
                        excluded.append(task_id)
                    else:
                        self._takeover_at.pop(task_id, None)
                        owned += 1
                else:
                    excluded.append(task_id)
                if owner is not None:
                    self._last_owner[task_id] = owner
            self._migrations += migrations
            self._tasks_owned = owned
            # an unsuppressed refresh is the new baseline: what a future
            # suppressed refresh freezes to, and what the mass-staleness
            # check compares against
            self._prev_live = set(live)
            self._frozen_exclusions = list(excluded)
            self._suppressed = False
            self._suppress_reason = None
            self._thaw_started_s = None
        if migrations:
            GLOBAL_METRICS.fleet_migrations.inc(migrations)
        GLOBAL_METRICS.fleet_tasks_owned.set(owned)
        return excluded or None

    def owns(self, tx, task_id: bytes) -> bool:
        """Pure ownership test (no migration/grace bookkeeping)."""
        if not self.enabled:
            return True
        return rendezvous_owner(task_id, self._live_members(tx)) == self.replica_id

    def filter_owned(self, tx, tasks):
        """Warmup filter: of ``tasks`` (AggregatorTask), the ones this
        replica owns — so a replica only compiles/warms its own tasks'
        shapes (the cache-affinity payoff, observable via compile_stats)."""
        if not self.enabled:
            return list(tasks)
        live = self._live_members(tx)
        return [
            t for t in tasks
            if rendezvous_owner(t.task_id.data, live) == self.replica_id
        ]

    # -- fleet-shared suspect set --------------------------------------

    def shared_suspects(self, tx) -> Set[str]:
        """Peer origins advertised suspect by OTHER live members with a
        fresh-enough advertisement.  Consumed beside the in-memory peer
        tracker in ``suspect_task_ids`` — fleet-wide partition awareness
        without every replica having to probe the peer itself."""
        if not self.enabled:
            return set()
        now = tx._now_s()
        out: Set[str] = set()
        for m in tx.get_fleet_members():
            if m.replica_id == self.replica_id:
                continue
            if now - m.heartbeat.seconds > self.heartbeat_ttl_s:
                continue  # dead advertiser: ignore
            if (
                m.suspect_updated_at is None
                or now - m.suspect_updated_at.seconds > self.suspect_staleness_s
            ):
                continue  # stale advertisement: a healed peer un-pins
            out.update(m.suspect_peers)
        return out

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        """/statusz "fleet" section payload."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "replica_id": self.replica_id,
                "role": self.role,
                "heartbeat_ttl_s": self.heartbeat_ttl_s,
                "takeover_grace_s": self.takeover_grace_s,
                "mass_staleness_fraction": self.mass_staleness_fraction,
                "tasks_owned": self._tasks_owned,
                "migrations_total": self._migrations,
                "suppressed": self._suppressed,
                "suppress_reason": self._suppress_reason,
                "suppressed_refreshes_total": self._suppressed_total,
                "thaw_started_s": self._thaw_started_s,
                "last_heartbeat_s": self._last_heartbeat_s,
                "members": list(self._members_snapshot),
            }


# -- process-wide default router (the binaries' singleton; tests build
#    their own FleetRouter instances and never touch this) --------------

_ROUTER: Optional[FleetRouter] = None


def configure_fleet(
    replica_id: str,
    role: str,
    *,
    heartbeat_ttl_s: float = 10.0,
    takeover_grace_s: float = 5.0,
    suspect_staleness_s: float = 30.0,
    mass_staleness_fraction: float = 0.5,
) -> FleetRouter:
    """Install the process-wide router (once, from the driver binary)."""
    global _ROUTER
    _ROUTER = FleetRouter(
        replica_id,
        role,
        heartbeat_ttl_s=heartbeat_ttl_s,
        takeover_grace_s=takeover_grace_s,
        suspect_staleness_s=suspect_staleness_s,
        mass_staleness_fraction=mass_staleness_fraction,
    )
    return _ROUTER


def fleet_router() -> Optional[FleetRouter]:
    """The process-wide router, or None when fleet mode is off."""
    return _ROUTER


def reset_fleet() -> None:
    """Test hook: forget the process-wide router."""
    global _ROUTER
    _ROUTER = None


def fleet_shared_suspects(tx) -> Set[str]:
    """The process router's shared-suspect view; empty when fleet is off.
    Split out so job_driver.suspect_task_ids has no import-time coupling
    to whether a router exists."""
    router = _ROUTER
    if router is None:
        return set()
    return router.shared_suspects(tx)
