"""Clock abstraction and time math.

reference: core/src/time.rs:11 (Clock trait), :42 (MockClock), extension math
for Time/Duration/Interval (:89-270).  The mock clock makes every time-driven
code path deterministic in tests, mirroring the reference's test strategy
(SURVEY.md §4).
"""

from __future__ import annotations

import threading
import time as _time

from ..messages import Duration, Interval, Time


class Clock:
    def now(self) -> Time:
        raise NotImplementedError


class RealClock(Clock):
    def now(self) -> Time:
        return Time(int(_time.time()))


class MockClock(Clock):
    """Settable, advanceable clock (reference: core/src/time.rs:42)."""

    def __init__(self, start: Time = Time(1_600_000_000)):
        self._now = start
        self._lock = threading.Lock()

    def now(self) -> Time:
        with self._lock:
            return self._now

    def advance(self, duration: Duration) -> None:
        with self._lock:
            self._now = Time(self._now.seconds + duration.seconds)

    def set(self, t: Time) -> None:
        with self._lock:
            self._now = t


# --- Time/Interval extension math (reference: core/src/time.rs:89-270) -----


def time_add(t: Time, d: Duration) -> Time:
    return Time(t.seconds + d.seconds)


def time_sub(t: Time, d: Duration) -> Time:
    if t.seconds < d.seconds:
        raise ValueError("time subtraction underflow")
    return Time(t.seconds - d.seconds)


def time_difference(a: Time, b: Time) -> Duration:
    if a.seconds < b.seconds:
        raise ValueError("time difference underflow")
    return Duration(a.seconds - b.seconds)


def time_to_batch_interval_start(t: Time, time_precision: Duration) -> Time:
    """Round down to the nearest multiple of the time precision."""
    if time_precision.seconds == 0:
        raise ValueError("zero time precision")
    return Time(t.seconds - t.seconds % time_precision.seconds)


def time_to_batch_interval(t: Time, time_precision: Duration) -> Interval:
    return Interval(time_to_batch_interval_start(t, time_precision), time_precision)


def time_is_after(t: Time, other: Time) -> bool:
    return t.seconds > other.seconds


def interval_merge(a: Interval, b: Interval) -> Interval:
    """Smallest interval covering both (used for collection intervals)."""
    if a == Interval.EMPTY:
        return b
    if b == Interval.EMPTY:
        return a
    start = min(a.start.seconds, b.start.seconds)
    end = max(a.end().seconds, b.end().seconds)
    return Interval(Time(start), Duration(end - start))


def intervals_overlap(a: Interval, b: Interval) -> bool:
    if a.duration.seconds == 0 or b.duration.seconds == 0:
        return False
    return a.start.seconds < b.end().seconds and b.start.seconds < a.end().seconds


def interval_contains_interval(outer: Interval, inner: Interval) -> bool:
    return (
        outer.start.seconds <= inner.start.seconds
        and inner.end().seconds <= outer.end().seconds
    )
