"""Shared kernel glue: HPKE, auth tokens, checksums, clock, retries.

The analog of the reference's ``janus_core`` crate (reference: core/src/).
"""

from .auth_tokens import (
    DAP_AUTH_HEADER,
    AuthenticationToken,
    AuthenticationTokenHash,
    extract_bearer_token,
)
from .hpke import (
    HpkeApplicationInfo,
    HpkeError,
    HpkeKeypair,
    Label,
    is_hpke_config_supported,
    open_,
    seal,
)
from .report_id import (
    checksum_combined,
    checksum_for_report_id,
    checksum_updated_with,
)
from .time import (
    Clock,
    MockClock,
    RealClock,
    interval_contains_interval,
    interval_merge,
    intervals_overlap,
    time_add,
    time_difference,
    time_is_after,
    time_sub,
    time_to_batch_interval,
    time_to_batch_interval_start,
)

__all__ = [n for n in dir() if not n.startswith("_")]
