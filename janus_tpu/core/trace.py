"""Tracing/logging configuration.

The analog of the reference's ``TraceConfiguration`` (reference:
aggregator/src/trace.rs:36-236): pretty or JSON structured stdout logging
with a runtime-reloadable level filter (the reference exposes this as PUT
``/traceconfigz`` on the health port; our health server does the same).
On-device profiling is the separate ``jax.profiler`` session the bench
harness can enable — host tracing stays here.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from dataclasses import dataclass
from typing import Optional


@dataclass
class TraceConfiguration:
    """reference: trace.rs:36"""

    use_json: bool = False
    level: str = "INFO"


class JsonFormatter(logging.Formatter):
    """One JSON object per line (reference: trace.rs json/stackdriver
    stdout modes)."""

    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": round(time.time(), 3),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            doc["exception"] = self.formatException(record.exc_info)
        return json.dumps(doc)


def install_trace_subscriber(config: Optional[TraceConfiguration] = None) -> None:
    """reference: trace.rs:119 install_trace_subscriber"""
    config = config or TraceConfiguration()
    root = logging.getLogger()
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(sys.stdout)
    if config.use_json:
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s")
        )
    root.addHandler(handler)
    root.setLevel(getattr(logging, config.level.upper(), logging.INFO))


def reload_trace_filter(level: str) -> None:
    """Runtime log-level reload (reference: binary_utils.rs:422-456
    /traceconfigz)."""
    logging.getLogger().setLevel(getattr(logging, level.upper(), logging.INFO))
