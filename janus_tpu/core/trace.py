"""Tracing/logging configuration.

The analog of the reference's ``TraceConfiguration`` (reference:
aggregator/src/trace.rs:36-236): pretty or JSON structured stdout logging
with a runtime-reloadable level filter (the reference exposes this as PUT
``/traceconfigz`` on the health port; our health server does the same).
On-device profiling is the separate ``jax.profiler`` session the bench
harness can enable — host tracing stays here.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import secrets
import sys
import time
from dataclasses import dataclass
from typing import Optional


# -- cross-process trace context ---------------------------------------------
# The fleet-wide correlation layer (reference: trace.rs OTel trace layer +
# the W3C traceparent the OTLP exporter propagates): a trace id is minted
# once per pipeline entity (upload batch / aggregation job / collection
# job), persisted on the job row, carried leader->helper in DAP HTTP
# headers, and bound here — a contextvar, so it follows the asyncio task —
# for every log line and ChromeTracer span to pick up.  That is what makes
# one aggregation job's timeline joinable across replica processes.

#: fields: trace_id (32 hex chars), task_id, job_id — all optional strings
_TRACE_CTX: contextvars.ContextVar[dict] = contextvars.ContextVar(
    "janus_trace_ctx", default={}
)

#: ctx keys stamped onto log records and chrome-trace span args
TRACE_CTX_KEYS = ("trace_id", "task_id", "job_id")


def new_trace_id() -> str:
    """A W3C-traceparent-style 16-byte random trace id (32 hex chars)."""
    return secrets.token_hex(16)


def current_trace() -> dict:
    """The bound trace context ({} when none)."""
    return _TRACE_CTX.get()


def bind_trace(**fields) -> contextvars.Token:
    """Merge ``fields`` (trace_id/task_id/job_id) into the bound context;
    returns a token for :func:`unbind_trace`.  None values are dropped so
    an unset field inherits the enclosing scope's."""
    merged = dict(_TRACE_CTX.get())
    for k, v in fields.items():
        if v is not None:
            merged[k] = str(v)
    return _TRACE_CTX.set(merged)


def unbind_trace(token: contextvars.Token) -> None:
    _TRACE_CTX.reset(token)


@contextlib.contextmanager
def trace_scope(**fields):
    """``with trace_scope(trace_id=..., task_id=..., job_id=...):`` — the
    scoped form of bind/unbind used by job steppers and HTTP handlers."""
    token = bind_trace(**fields)
    try:
        yield
    finally:
        unbind_trace(token)


def current_traceparent() -> Optional[str]:
    """The bound context as a W3C ``traceparent`` header value
    (``00-<trace-id>-<span-id>-01``), or None when no trace id is bound.
    The span id is minted per call: each outbound hop is its own span."""
    trace_id = _TRACE_CTX.get().get("trace_id")
    if not trace_id:
        return None
    return f"00-{trace_id}-{secrets.token_hex(8)}-01"


def inject_traceparent(headers: dict) -> None:
    """Stamp the bound context's ``traceparent`` onto outbound request
    ``headers`` (no-op when no trace id is bound) — the one place every
    peer-HTTP path calls so cross-process correlation cannot be forgotten
    by a new client."""
    traceparent = current_traceparent()
    if traceparent:
        headers["traceparent"] = traceparent


def parse_traceparent(value: Optional[str]) -> Optional[str]:
    """Extract the trace id from a ``traceparent`` header (None on any
    malformation — a bad peer header must never break request handling)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 4 or len(parts[1]) != 32:
        return None
    trace_id = parts[1].lower()
    # strict per-char hex: int(x, 16) would accept '+'/'-'/'_' and
    # whitespace, adopting ids W3C-strict peers will drop downstream
    if any(c not in "0123456789abcdef" for c in trace_id):
        return None
    if trace_id == "0" * 32:
        return None
    return trace_id


@dataclass
class TraceConfiguration:
    """reference: trace.rs:36"""

    use_json: bool = False
    level: str = "INFO"


class TraceContextFilter(logging.Filter):
    """Stamps the bound trace context onto every log record, so formatters
    (and ad-hoc ``%(trace_id)s`` format strings) can render it."""

    def filter(self, record: logging.LogRecord) -> bool:
        ctx = _TRACE_CTX.get()
        for key in TRACE_CTX_KEYS:
            setattr(record, key, ctx.get(key))
        return True


class JsonFormatter(logging.Formatter):
    """One JSON object per line (reference: trace.rs json/stackdriver
    stdout modes), carrying the bound trace context when present."""

    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": round(time.time(), 3),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        for key in TRACE_CTX_KEYS:
            value = getattr(record, key, None)
            if value is not None:
                doc[key] = value
        if record.exc_info:
            doc["exception"] = self.formatException(record.exc_info)
        return json.dumps(doc)


def install_trace_subscriber(config: Optional[TraceConfiguration] = None) -> None:
    """reference: trace.rs:119 install_trace_subscriber"""
    config = config or TraceConfiguration()
    root = logging.getLogger()
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(sys.stdout)
    handler.addFilter(TraceContextFilter())
    if config.use_json:
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s")
        )
    root.addHandler(handler)
    root.setLevel(getattr(logging, config.level.upper(), logging.INFO))


def reload_trace_filter(level: str) -> None:
    """Runtime log-level reload (reference: binary_utils.rs:422-456
    /traceconfigz)."""
    logging.getLogger().setLevel(getattr(logging, level.upper(), logging.INFO))


# -- span sinks --------------------------------------------------------------
# Secondary consumers of closed spans (the OTLP exporter, core/otlp.py):
# callables ``sink(name, cat, epoch_start_s, dur_s, args)``.  Spans reach
# sinks whether or not chrome tracing is configured — the ChromeTracer
# forwards from emit(), and the module-level span helpers forward directly
# when no tracer exists.  Sink errors are swallowed: an export problem must
# never break the traced code path.

_SPAN_SINKS: list = []


def register_span_sink(sink) -> None:
    if sink not in _SPAN_SINKS:
        _SPAN_SINKS.append(sink)


def unregister_span_sink(sink) -> None:
    try:
        _SPAN_SINKS.remove(sink)
    except ValueError:
        pass


def _forward_span(name: str, cat: str, epoch_start_s: float, dur_s: float, args: dict) -> None:
    for sink in list(_SPAN_SINKS):
        try:
            sink(name, cat, epoch_start_s, dur_s, args)
        except Exception:
            pass


# -- chrome-trace export -----------------------------------------------------
# The analog of the reference's chrome tracing layer (trace.rs:145-156
# ChromeLayer): spans around job steps / device launches, written in the
# Trace Event Format chrome://tracing and Perfetto load directly.


class ChromeTracer:
    """Incremental Trace-Event-Format writer (JSON array of "X" events).

    Thread-safe; events are appended as they close, so a crash loses at most
    the open spans (the format tolerates a missing closing bracket).

    Cross-process merging (tools/trace_merge.py): events carry the real OS
    pid, every span inherits the bound trace context (trace_id/task_id/
    job_id) into its args, and a ``clock_sync`` metadata event records the
    wall-clock epoch of this process's monotonic t0 so per-replica files
    can be rebased onto one shared timeline.  A restarted replica pointed
    at the same path APPENDS (its new pid gets its own clock_sync) instead
    of truncating the dead incarnation's events.
    """

    def __init__(self, path: str):
        import threading

        self.path = path
        self._lock = threading.Lock()
        self._closed = False
        append = os.path.exists(path) and os.path.getsize(path) > 0
        self._f = open(path, "a" if append else "w")
        if not append:
            self._f.write("[\n")
        else:
            # the dead incarnation may have been SIGKILLed mid-write: start
            # on a fresh line so its partial trailing line cannot swallow
            # our clock_sync event (trace_merge needs it to rebase us)
            self._f.write("\n")
        self.pid = os.getpid()
        self._t0 = time.monotonic()
        self._epoch_t0 = time.time()
        self._write_event(
            {
                "name": "clock_sync",
                "ph": "M",
                "pid": self.pid,
                "tid": 0,
                "args": {"epoch_t0": self._epoch_t0},
            }
        )
        self._write_event(
            {
                "name": "process_name",
                "ph": "M",
                "pid": self.pid,
                "tid": 0,
                "args": {"name": f"{os.path.basename(sys.argv[0] or 'python')}:{self.pid}"},
            }
        )

    def _write_event(self, ev: dict) -> None:
        line = json.dumps(ev) + ",\n"
        with self._lock:
            if self._closed:
                return
            self._f.write(line)
            self._f.flush()

    def emit(self, name: str, cat: str, start_s: float, dur_s: float, **args) -> None:
        import threading

        # Concurrent spans must land on distinct tracks: same-track
        # overlapping "X" events render as bogus nesting in trace viewers.
        # Thread identity separates executor/launch spans; same-thread
        # asyncio concurrency (job steps) additionally keys on the running
        # task so parallel steps get their own rows.
        tid = threading.get_ident() % 100000
        try:
            import asyncio

            task = asyncio.current_task()
            if task is not None:
                tid = 100000 + id(task) % 100000
        except RuntimeError:
            pass
        ctx = _TRACE_CTX.get()
        for key in TRACE_CTX_KEYS:
            if key not in args and ctx.get(key) is not None:
                args[key] = ctx[key]
        ev = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "pid": self.pid,
            "tid": tid,
            "ts": round((start_s - self._t0) * 1e6, 1),
            "dur": round(dur_s * 1e6, 1),
        }
        if args:
            ev["args"] = args
        self._write_event(ev)
        if _SPAN_SINKS:
            _forward_span(
                name, cat, self._epoch_t0 + (start_s - self._t0), dur_s, dict(args)
            )

    def span(self, name: str, cat: str = "job", **args):
        return _Span(self, name, cat, args)

    def close(self) -> None:
        """Flush and close; idempotent (the graceful-shutdown path and an
        atexit/teardown race may both call it)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._f.write("{}]\n")  # sentinel keeps the array valid JSON
            self._f.close()


class _Span:
    def __init__(self, tracer: ChromeTracer, name: str, cat: str, args):
        self.tracer, self.name, self.cat, self.args = tracer, name, cat, args

    def __enter__(self):
        self.start = time.monotonic()
        return self

    def __exit__(self, exc_type, *_):
        self.tracer.emit(
            self.name,
            self.cat,
            self.start,
            time.monotonic() - self.start,
            ok=exc_type is None,
            **self.args,
        )
        return False


_GLOBAL_TRACER: Optional[ChromeTracer] = None


def configure_chrome_trace(path: Optional[str]) -> Optional[ChromeTracer]:
    """Enable (or disable with None) process-wide chrome-trace output."""
    global _GLOBAL_TRACER
    if _GLOBAL_TRACER is not None:
        _GLOBAL_TRACER.close()
        _GLOBAL_TRACER = None
    if path:
        _GLOBAL_TRACER = ChromeTracer(path)
    return _GLOBAL_TRACER


def close_chrome_trace() -> None:
    """Flush/close the global tracer WITHOUT dropping the configuration
    handle — the binaries' graceful-shutdown (SIGTERM) hook, so soak traces
    are never truncated mid-event.  Safe to call when tracing is off."""
    if _GLOBAL_TRACER is not None:
        _GLOBAL_TRACER.close()


def chrome_trace_path() -> Optional[str]:
    """The active chrome-trace output path (None when tracing is off) —
    surfaced by /statusz."""
    return _GLOBAL_TRACER.path if _GLOBAL_TRACER is not None else None


class _NullSpan:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _SinkSpan:
    """Span measured for the registered sinks only (OTLP configured while
    chrome tracing is off) — mirrors _Span's context inheritance."""

    def __init__(self, name: str, cat: str, args: dict):
        self.name, self.cat, self.args = name, cat, args

    def __enter__(self):
        self.start = time.monotonic()
        return self

    def __exit__(self, exc_type, *_):
        _sink_emit(
            self.name,
            self.cat,
            self.start,
            time.monotonic() - self.start,
            dict(self.args, ok=exc_type is None),
        )
        return False


def _sink_emit(name: str, cat: str, start_mono_s: float, dur_s: float, args: dict) -> None:
    """Forward a monotonic-timed span to the sinks with the bound trace
    context merged in (the ChromeTracer-less twin of ChromeTracer.emit)."""
    ctx = _TRACE_CTX.get()
    for key in TRACE_CTX_KEYS:
        if key not in args and ctx.get(key) is not None:
            args[key] = ctx[key]
    epoch_start = time.time() - (time.monotonic() - start_mono_s)
    _forward_span(name, cat, epoch_start, dur_s, args)


def tracing_active() -> bool:
    """True when SOME span consumer exists (chrome tracer or a sink) —
    the cheap guard for span producers whose data gathering is itself
    expensive (e.g. a datastore query feeding a link span)."""
    return _GLOBAL_TRACER is not None or bool(_SPAN_SINKS)


def trace_span(name: str, cat: str = "job", **args):
    """Span against the global tracer (and any registered span sinks);
    free no-op when both are off."""
    t = _GLOBAL_TRACER
    if t is not None:
        return t.span(name, cat, **args)
    if _SPAN_SINKS:
        return _SinkSpan(name, cat, args)
    return _NULL_SPAN


def emit_span(name: str, cat: str, start_s: float, dur_s: float, **args) -> None:
    """Emit an already-measured span directly (no context manager) —
    retroactive CHILD spans whose interval is known only after the parent
    closed, e.g. the per-submission shares of one executor mega-batch
    flush.  Explicit trace_id/task_id/job_id args override the calling
    context's, so a flush running on the executor's loop can stamp each
    child with ITS submitter's identity.  Free no-op when tracing is off."""
    t = _GLOBAL_TRACER
    if t is not None:
        t.emit(name, cat, start_s, dur_s, **args)
    elif _SPAN_SINKS:
        _sink_emit(name, cat, start_s, dur_s, dict(args))


def start_profiler_server(port: int) -> bool:
    """Opt-in on-device profiling: a jax.profiler server an operator can
    capture from at any time (the analog of the reference's tokio-console /
    OTLP always-on observability sockets, trace.rs:158-236).  Returns False
    when jax is unavailable in this process (control-plane binaries — the
    GATE PROBE, logged quietly: a jax-less process is a deployment shape,
    not an error) or when the server fails to start (logged with the
    traceback; the binary continues — a dead profiler socket must never
    take a replica down)."""
    log = logging.getLogger("janus_tpu.trace")
    try:
        import jax
    except ImportError:
        log.info(
            "jax unavailable in this process; profiler server not started"
        )
        return False
    except Exception:
        # import jax can die with RuntimeError/OSError on a broken device
        # runtime (libtpu init) — still logs-and-continues, never fatal
        log.exception("jax import failed; profiler server not started")
        return False
    try:
        jax.profiler.start_server(port)
        return True
    except Exception:
        log.exception("could not start jax profiler server")
        return False
