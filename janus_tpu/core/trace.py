"""Tracing/logging configuration.

The analog of the reference's ``TraceConfiguration`` (reference:
aggregator/src/trace.rs:36-236): pretty or JSON structured stdout logging
with a runtime-reloadable level filter (the reference exposes this as PUT
``/traceconfigz`` on the health port; our health server does the same).
On-device profiling is the separate ``jax.profiler`` session the bench
harness can enable — host tracing stays here.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from dataclasses import dataclass
from typing import Optional


@dataclass
class TraceConfiguration:
    """reference: trace.rs:36"""

    use_json: bool = False
    level: str = "INFO"


class JsonFormatter(logging.Formatter):
    """One JSON object per line (reference: trace.rs json/stackdriver
    stdout modes)."""

    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": round(time.time(), 3),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            doc["exception"] = self.formatException(record.exc_info)
        return json.dumps(doc)


def install_trace_subscriber(config: Optional[TraceConfiguration] = None) -> None:
    """reference: trace.rs:119 install_trace_subscriber"""
    config = config or TraceConfiguration()
    root = logging.getLogger()
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(sys.stdout)
    if config.use_json:
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s")
        )
    root.addHandler(handler)
    root.setLevel(getattr(logging, config.level.upper(), logging.INFO))


def reload_trace_filter(level: str) -> None:
    """Runtime log-level reload (reference: binary_utils.rs:422-456
    /traceconfigz)."""
    logging.getLogger().setLevel(getattr(logging, level.upper(), logging.INFO))


# -- chrome-trace export -----------------------------------------------------
# The analog of the reference's chrome tracing layer (trace.rs:145-156
# ChromeLayer): spans around job steps / device launches, written in the
# Trace Event Format chrome://tracing and Perfetto load directly.


class ChromeTracer:
    """Incremental Trace-Event-Format writer (JSON array of "X" events).

    Thread-safe; events are appended as they close, so a crash loses at most
    the open spans (the format tolerates a missing closing bracket).
    """

    def __init__(self, path: str):
        import threading

        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "w")
        self._f.write("[\n")
        self._t0 = time.monotonic()

    def emit(self, name: str, cat: str, start_s: float, dur_s: float, **args) -> None:
        import threading

        # Concurrent spans must land on distinct tracks: same-track
        # overlapping "X" events render as bogus nesting in trace viewers.
        # Thread identity separates executor/launch spans; same-thread
        # asyncio concurrency (job steps) additionally keys on the running
        # task so parallel steps get their own rows.
        tid = threading.get_ident() % 100000
        try:
            import asyncio

            task = asyncio.current_task()
            if task is not None:
                tid = 100000 + id(task) % 100000
        except RuntimeError:
            pass
        ev = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "pid": 1,
            "tid": tid,
            "ts": round((start_s - self._t0) * 1e6, 1),
            "dur": round(dur_s * 1e6, 1),
        }
        if args:
            ev["args"] = args
        line = json.dumps(ev) + ",\n"
        with self._lock:
            self._f.write(line)
            self._f.flush()

    def span(self, name: str, cat: str = "job", **args):
        return _Span(self, name, cat, args)

    def close(self) -> None:
        with self._lock:
            self._f.write("{}]\n")  # sentinel keeps the array valid JSON
            self._f.close()


class _Span:
    def __init__(self, tracer: ChromeTracer, name: str, cat: str, args):
        self.tracer, self.name, self.cat, self.args = tracer, name, cat, args

    def __enter__(self):
        self.start = time.monotonic()
        return self

    def __exit__(self, exc_type, *_):
        self.tracer.emit(
            self.name,
            self.cat,
            self.start,
            time.monotonic() - self.start,
            ok=exc_type is None,
            **self.args,
        )
        return False


_GLOBAL_TRACER: Optional[ChromeTracer] = None


def configure_chrome_trace(path: Optional[str]) -> Optional[ChromeTracer]:
    """Enable (or disable with None) process-wide chrome-trace output."""
    global _GLOBAL_TRACER
    if _GLOBAL_TRACER is not None:
        _GLOBAL_TRACER.close()
        _GLOBAL_TRACER = None
    if path:
        _GLOBAL_TRACER = ChromeTracer(path)
    return _GLOBAL_TRACER


class _NullSpan:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def trace_span(name: str, cat: str = "job", **args):
    """Span against the global tracer; free no-op when tracing is off."""
    t = _GLOBAL_TRACER
    return t.span(name, cat, **args) if t is not None else _NULL_SPAN


def start_profiler_server(port: int) -> bool:
    """Opt-in on-device profiling: a jax.profiler server an operator can
    capture from at any time (the analog of the reference's tokio-console /
    OTLP always-on observability sockets, trace.rs:158-236).  Returns False
    when jax is unavailable in this process (control-plane binaries)."""
    try:
        import jax

        jax.profiler.start_server(port)
        return True
    except Exception:
        logging.getLogger("janus_tpu.trace").exception(
            "could not start jax profiler server"
        )
        return False
