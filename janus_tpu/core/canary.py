"""The canary plane: continuous black-box end-to-end probing (ISSUE 20).

Every degradation mode the fleet has grown — brownout suppression,
journal replay, quarantine bisection, breaker-driven oracle fallback —
is observable only by interpreting a dozen metric families after the
fact.  This module closes the loop from the OUTSIDE: a prober drives
known-plaintext synthetic reports through dedicated, auto-provisioned
canary tasks (one per VDAF family) against the real upload → aggregate
→ collect path of a live fleet, then verifies the collected aggregate
against the exact expected sum.  A replica can hold leases, heartbeat,
and serve 200s while producing garbage; only a known answer catches it.

Outcome taxonomy (the ``janus_canary_verdict_total{task,outcome}``
counter):

    ok       upload + collection succeeded AND the aggregate matched
    error    a stage failed loudly (HTTP error, collection rejected)
    timeout  the collection poll never completed within the budget
    corrupt  the fleet ANSWERED, but wrongly — the collected aggregate
             failed HPKE open / field decode, or decoded to a value
             different from the known plaintext sum.  No other signal
             in the system can express this.

Per-stage latency attribution reuses the trace plane: each probe report
carries a minted traceparent, and ``probe_stage_latencies`` extracts
upload→commit and upload→first-prepare deltas from the replicas' merged
chrome traces (tools/trace_merge.py), the same way
``loadgen.first_prepare_percentiles`` does.  Stages the prober can time
from its own clock (upload-ack, collection, e2e) are always recorded.

Degradation-aware backoff: the canary must never add pressure to a
browning-out fleet.  When the process-wide datastore tracker is in
strict SUSPECT, or an upload is shed with 503, the probe cycle is
SUPPRESSED — counted (``janus_canary_backoffs_total{reason}``), never
alerting, and the verdict state machine does not move.  Two fences keep
suppression from masking a hard outage: a 503 whose body names the
datastore-unavailable path (retries exhausted — infrastructure down,
not admission control) is a LOUD upload error, and an unbroken streak
of shed suppressions past ``shed_escalate_after`` escalates to one —
the fleet refusing work forever is indistinguishable from the fleet
being down, and a black-box prober must page on it.

Batch strategy: each probe cycle aggregates its own already-closed time
bucket, allocated monotonically backward per task (``_alloc_bucket``) so
no two cycles ever share or re-query a batch interval — and a collect
rejected with ``batchQueriedTooManyTimes`` (a restarted prober
re-walking ground covered before its crash) is a suppressed
``bucket_collision`` backoff, not a failure.

The rolled-up fleet verdict (healthy / degraded / failing + last-good
timestamp + failing stage) renders in the ``/statusz`` ``canary``
section and the ``janus_canary_verdict_state{task}`` gauge.
"""

from __future__ import annotations

import asyncio
import logging
import os
import secrets
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

logger = logging.getLogger("janus_tpu.canary")

VERDICT_HEALTHY = "healthy"
VERDICT_DEGRADED = "degraded"
VERDICT_FAILING = "failing"
_VERDICT_LEVEL = {VERDICT_HEALTHY: 0, VERDICT_DEGRADED: 1, VERDICT_FAILING: 2}

#: Stage labels on janus_canary_probe_seconds.  upload_ack / collection /
#: e2e come from the prober's own clock; commit / first_prepare are
#: trace-attributed (present only when a trace glob is configured).
STAGES = ("upload_ack", "commit", "first_prepare", "collection", "e2e")


# ---------------------------------------------------------------------------
# Known-plaintext probe families


@dataclass(frozen=True)
class CanaryFamily:
    """One VDAF family's fixed probe: measurements and their exact sum."""

    name: str
    vdaf_instance: dict
    measurements: tuple
    expected: object


#: The registry ``canary.families`` names resolve through.  Measurements
#: are FIXED so the expected aggregate is a compile-time constant — the
#: whole point is that the verifier knows the answer before asking.
FAMILIES: Dict[str, CanaryFamily] = {
    "prio3_sum": CanaryFamily(
        name="prio3_sum",
        vdaf_instance={"type": "Prio3Sum", "bits": 8},
        measurements=(13, 42, 7),
        expected=62,
    ),
    "prio3_histogram": CanaryFamily(
        name="prio3_histogram",
        vdaf_instance={"type": "Prio3Histogram", "length": 4, "chunk_length": 2},
        measurements=(0, 2, 2),
        expected=[1, 0, 2, 0],
    ),
}


def _matches(actual, expected) -> bool:
    """Exact-sum comparison, tolerant of list/tuple/np-array shapes."""
    try:
        if isinstance(expected, (list, tuple)):
            return list(actual) == list(expected)
        return int(actual) == int(expected)
    except (TypeError, ValueError):
        return False


# ---------------------------------------------------------------------------
# Trace-plane stage attribution


def _trace_merge_module():
    """Import tools/trace_merge.py (the repo's merged-trace reader); None
    when the tools tree is absent — attribution then degrades to the
    prober's own clock, never fails a probe."""
    try:
        tools_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            "tools",
        )
        if tools_dir not in sys.path:
            sys.path.insert(0, tools_dir)
        import trace_merge

        return trace_merge
    except Exception:
        return None


def probe_stage_latencies(
    trace_paths: Sequence[str], sampled_ids: Sequence[str]
) -> Dict[str, List[float]]:
    """Per-stage latency samples (seconds) for the sampled probe uploads,
    read from merged chrome traces — the ``first_prepare_percentiles``
    extraction generalized to every stage boundary trace_stats exposes:
    ``commit`` = upload span start → upload_commit end, ``first_prepare``
    = upload span start → first flush-family span.  ``trace_paths`` may
    contain globs.  Empty lists when nothing resolves (tracing off,
    offsetless pids dropped, ids not found)."""
    import glob as globmod

    out: Dict[str, List[float]] = {"commit": [], "first_prepare": []}
    tm = _trace_merge_module()
    if tm is None:
        return out
    paths: List[str] = []
    for pat in trace_paths:
        hits = sorted(globmod.glob(pat))
        paths.extend(hits if hits else ([pat] if os.path.exists(pat) else []))
    sampled = set(sampled_ids)
    if not paths or not sampled:
        return out
    try:
        events = tm.merge_events(paths)
        # each sampled id's OWN earliest upload-span start (a merged group
        # may carry many probes; the group minimum would skew them all)
        upload_ts: Dict[str, float] = {}
        for ev in events:
            if ev.get("ph") == "X" and ev.get("name") == "upload":
                tid = ev.get("args", {}).get("trace_id")
                if tid in sampled:
                    ts = ev.get("ts", 0)
                    if tid not in upload_ts or ts < upload_ts[tid]:
                        upload_ts[tid] = ts
        for g in tm.trace_stats(events)["merged_traces"]:
            stage_ts = g["stages_ts_us"]
            ids = set(g["trace_ids"]) & sampled
            if not ids:
                continue
            for stage, key in (("commit", "commit"), ("first_prepare", "first_flush")):
                ts = stage_ts.get(key)
                if ts is None:
                    continue
                for tid in ids:
                    t0 = upload_ts.get(tid)
                    if t0 is not None and ts >= t0:
                        out[stage].append((ts - t0) / 1e6)
    except Exception:
        logger.exception("trace stage attribution failed (probe still counted)")
    return out


def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


# ---------------------------------------------------------------------------
# Probe results and per-family verdict state


@dataclass
class ProbeResult:
    """One family's probe cycle outcome."""

    family: str
    outcome: str  # ok | error | timeout | corrupt | suppressed
    stage: Optional[str] = None  # failing stage (non-ok outcomes)
    reason: Optional[str] = None  # backoff reason (suppressed only)
    stages_s: Dict[str, float] = field(default_factory=dict)
    expected: object = None
    actual: object = None
    detail: str = ""

    @property
    def ok(self) -> bool:
        return self.outcome == "ok"

    @property
    def suppressed(self) -> bool:
        return self.outcome == "suppressed"


class _FamilyState:
    """Consecutive-failure verdict machine for one canary task.
    Suppressed probes deliberately do not move it — a browning-out fleet
    is degraded, not WRONG, and db_health already tells that story."""

    def __init__(self):
        self.probes = 0
        self.suppressed = 0
        #: consecutive upload-shed suppressions with no completed probe in
        #: between — the escalation fence against a permanent 503 wall
        self.shed_streak = 0
        self.consecutive_failures = 0
        self.last_outcome: Optional[str] = None
        self.failing_stage: Optional[str] = None
        self.last_good_unix: Optional[float] = None
        self.last_detail = ""

    def verdict(self, fail_threshold: int) -> str:
        if self.consecutive_failures >= max(1, fail_threshold):
            return VERDICT_FAILING
        if self.consecutive_failures > 0:
            return VERDICT_DEGRADED
        return VERDICT_HEALTHY


class _CanaryTask:
    """One provisioned canary task: the identity + keys the prober holds."""

    def __init__(self, family: CanaryFamily, task_id, vdaf, collector_keypair,
                 collector_token, leader_hpke_config=None, helper_hpke_config=None):
        self.family = family
        self.task_id = task_id
        self.vdaf = vdaf
        self.collector_keypair = collector_keypair
        self.collector_token = collector_token
        self.leader_hpke_config = leader_hpke_config
        self.helper_hpke_config = helper_hpke_config
        #: completed-probe counter (stats only)
        self.seq = 0
        #: next time bucket to probe — allocated monotonically BACKWARD
        #: from the most recent closed bucket at first use, so no two
        #: probes ever share (or re-query) a batch interval even when a
        #: precision boundary crosses between cycles (deriving the walk
        #: from the live wall clock instead collides exactly then: "now"
        #: advances one precision while the sequence advances one step)
        self.next_bucket: Optional[int] = None


# ---------------------------------------------------------------------------
# The prober


class CanaryPlane:
    """Black-box prober over the real DAP path.

    ``cfg`` is duck-typed (binaries.config.CanaryConfig in production,
    any namespace in tests): leader_endpoint, helper_endpoint,
    leader_task_api, helper_task_api, task_api_auth_token, families,
    probe_interval_s, collect_timeout_s, poll_interval_s,
    fail_threshold, time_precision_s, trace_globs."""

    def __init__(self, cfg, *, metrics=None, wall_fn=time.time, mono_fn=time.monotonic):
        self.cfg = cfg
        self._metrics = metrics
        self._wall = wall_fn
        self._mono = mono_fn
        self._lock = threading.Lock()
        self._tasks: Dict[str, _CanaryTask] = {}
        self._states: Dict[str, _FamilyState] = {}
        self._backoffs: Dict[str, int] = {}
        #: recent per-stage samples for the /statusz p50/p99 rollup
        self._stage_samples: Dict[str, deque] = {s: deque(maxlen=256) for s in STAGES}
        for name in cfg.families:
            if name not in FAMILIES:
                raise ValueError(
                    f"canary: unknown family {name!r} (known: {sorted(FAMILIES)})"
                )
            self._states[name] = _FamilyState()

    @property
    def metrics(self):
        if self._metrics is not None:
            return self._metrics
        from .metrics import GLOBAL_METRICS

        return GLOBAL_METRICS

    # -- provisioning ----------------------------------------------------
    def adopt_task(self, family_name: str, task_id, vdaf, collector_keypair,
                   collector_token, leader_hpke_config=None, helper_hpke_config=None):
        """Directly install an already-provisioned canary task (in-process
        harnesses; production goes through ensure_provisioned)."""
        fam = FAMILIES[family_name]
        with self._lock:
            self._tasks[family_name] = _CanaryTask(
                fam, task_id, vdaf, collector_keypair, collector_token,
                leader_hpke_config, helper_hpke_config,
            )

    async def ensure_provisioned(self, session) -> None:
        """Create the canary tasks through both aggregators' management
        APIs (aggregator_api.py POST /tasks): the same task_id, verify
        key, and aggregator auth token land as role Leader on the leader
        and role Helper on the helper; the prober keeps the collector
        keypair and token.  Idempotent per family; raises on API failure
        so the caller can retry next cycle."""
        from ..core.auth_tokens import AuthenticationToken
        from ..core.hpke import HpkeKeypair
        from ..messages import TaskId
        from ..messages.dap import _b64url

        for idx, name in enumerate(self.cfg.families):
            with self._lock:
                if name in self._tasks:
                    continue
            fam = FAMILIES[name]
            from ..vdaf.instances import vdaf_from_instance

            vdaf = vdaf_from_instance(fam.vdaf_instance)
            task_id = TaskId.random()
            vk = secrets.token_bytes(16)
            collector_kp = HpkeKeypair.generate(200 + idx)
            agg_token = secrets.token_urlsafe(24)
            col_token = secrets.token_urlsafe(24)
            common = {
                "task_id": _b64url(task_id.data),
                "query_type": {"kind": "TimeInterval"},
                "vdaf": fam.vdaf_instance,
                "vdaf_verify_key": _b64url(vk),
                # the whole probe must be collectable: one cycle's reports
                # exactly fill a batch
                "min_batch_size": len(fam.measurements),
                "time_precision": int(self.cfg.time_precision_s),
                "aggregator_auth_token": agg_token,
                "collector_hpke_config": _b64url(collector_kp.config.get_encoded()),
            }
            for api, body in (
                (
                    self.cfg.leader_task_api,
                    dict(
                        common,
                        role="Leader",
                        peer_aggregator_endpoint=self.cfg.helper_endpoint,
                        collector_auth_token=col_token,
                    ),
                ),
                (
                    self.cfg.helper_task_api,
                    dict(
                        common,
                        role="Helper",
                        peer_aggregator_endpoint=self.cfg.leader_endpoint,
                    ),
                ),
            ):
                url = api.rstrip("/") + "/tasks"
                headers = {
                    "Authorization": f"Bearer {self.cfg.task_api_auth_token}",
                    "Content-Type": "application/json",
                }
                async with session.post(url, json=body, headers=headers) as resp:
                    if resp.status != 201:
                        raise RuntimeError(
                            f"canary task provisioning failed at {url}: "
                            f"{resp.status} {await resp.text()}"
                        )
            self.adopt_task(
                name,
                task_id,
                vdaf,
                collector_kp,
                AuthenticationToken.new_bearer(col_token),
            )
            logger.info(
                "canary task provisioned: family=%s task=%s batch=%d",
                name,
                task_id,
                len(fam.measurements),
            )

    # -- degradation-aware backoff ---------------------------------------
    def _backoff_reason(self) -> Optional[str]:
        """Strict-SUSPECT gate: the SAME predicate the upload shed uses
        (db_health strict state), so the canary stands down exactly when
        the fleet starts refusing work."""
        try:
            from .db_health import DB_SUSPECT, tracker

            if tracker().state() == DB_SUSPECT:
                return "db_suspect"
        except Exception:
            pass
        return None

    def _count_backoff(self, family: str, reason: str) -> None:
        metrics = self.metrics
        with self._lock:
            self._backoffs[reason] = self._backoffs.get(reason, 0) + 1
            self._states[family].suppressed += 1
        if metrics.registry is not None:
            metrics.canary_backoffs.labels(reason=reason).inc()

    # -- the probe cycle -------------------------------------------------
    async def probe_once(self, session) -> List[ProbeResult]:
        """One full cycle: every provisioned family probed in turn."""
        results = []
        for name in list(self.cfg.families):
            task = self._tasks.get(name)
            if task is None:
                continue
            results.append(await self._probe_task(task, session))
        return results

    def _alloc_bucket(self, task: _CanaryTask, precision: int) -> int:
        """Allocate the probe's time bucket: distinct, already closed, and
        never re-queried.  The walk starts at the most recent closed
        bucket and steps monotonically backward PER TASK — it must not be
        re-derived from the live wall clock each cycle, because when a
        precision boundary crosses between two probes "now" advances one
        precision while the sequence advances one step and the two cancel
        into the SAME bucket (the leader then rejects the second collect
        with batchQueriedTooManyTimes)."""
        with self._lock:
            task.seq += 1
            nb = task.next_bucket
            if nb is None:
                nb = (int(self._wall()) // precision) * precision - precision
            task.next_bucket = nb - precision
        return nb

    async def _probe_task(self, task: _CanaryTask, session) -> ProbeResult:
        from ..client import prepare_report
        from ..messages import Duration, Interval, Report, Time

        name = task.family.name
        reason = self._backoff_reason()
        if reason is not None:
            self._count_backoff(name, reason)
            return ProbeResult(family=name, outcome="suppressed", reason=reason)

        precision = int(self.cfg.time_precision_s)
        bucket_start = self._alloc_bucket(task, precision)
        report_time = Time(bucket_start)

        if task.leader_hpke_config is None or task.helper_hpke_config is None:
            try:
                task.leader_hpke_config = await self._fetch_hpke_config(
                    session, self.cfg.leader_endpoint, task.task_id
                )
                task.helper_hpke_config = await self._fetch_hpke_config(
                    session, self.cfg.helper_endpoint, task.task_id
                )
            except Exception as e:
                return self._finish(
                    task, "error", "upload", detail=f"hpke_config fetch: {e}"
                )

        # -- upload stage ------------------------------------------------
        t0 = self._mono()
        sampled_ids: List[str] = []
        upload_url = (
            self.cfg.leader_endpoint.rstrip("/") + f"/tasks/{task.task_id}/reports"
        )
        for m in task.family.measurements:
            report = prepare_report(
                task.vdaf,
                task.task_id,
                task.leader_hpke_config,
                task.helper_hpke_config,
                Duration(precision),
                m,
                time=report_time,
            )
            tid = secrets.token_hex(16)
            headers = {
                "Content-Type": Report.MEDIA_TYPE,
                "traceparent": f"00-{tid}-{secrets.token_hex(8)}-01",
            }
            try:
                async with session.put(
                    upload_url, data=report.get_encoded(), headers=headers
                ) as resp:
                    if resp.status == 503:
                        return self._classify_503(task, (await resp.text())[:200])
                    if resp.status not in (200, 201):
                        return self._finish(
                            task,
                            "error",
                            "upload",
                            detail=f"upload {resp.status}: {(await resp.text())[:200]}",
                        )
            except asyncio.CancelledError:
                raise
            except Exception as e:
                return self._finish(task, "error", "upload", detail=f"upload: {e}")
            sampled_ids.append(tid)
        upload_ack_s = self._mono() - t0

        # -- collection stage --------------------------------------------
        from ..collector import Collector, CollectorError
        from ..messages import Query

        collector = Collector(
            task_id=task.task_id,
            leader_endpoint=self.cfg.leader_endpoint,
            vdaf=task.vdaf,
            auth_token=task.collector_token,
            hpke_keypair=task.collector_keypair,
            poll_interval=float(getattr(self.cfg, "poll_interval_s", 0.5)),
            max_poll_time=float(getattr(self.cfg, "collect_timeout_s", 60.0)),
        )
        query = Query.new_time_interval(
            Interval(Time(bucket_start), Duration(precision))
        )
        t1 = self._mono()
        try:
            result = await collector.collect(query, session=session)
        except asyncio.CancelledError:
            raise
        except CollectorError as e:
            if "batchQueriedTooManyTimes" in str(e):
                # This bucket was already collected — a restarted prober
                # re-walking ground it covered before its crash.  The
                # allocator has already moved past it; stand down this
                # cycle instead of paging on our own bookkeeping.
                self._count_backoff(name, "bucket_collision")
                return ProbeResult(
                    family=name, outcome="suppressed", reason="bucket_collision"
                )
            timed_out = "timed out" in str(e)
            stage = (
                self._attribute_timeout_stage(sampled_ids)
                if timed_out
                else "collection"
            )
            return self._finish(
                task,
                "timeout" if timed_out else "error",
                stage,
                stages_s={"upload_ack": upload_ack_s},
                sampled_ids=sampled_ids,
                detail=str(e)[:200],
            )
        except Exception as e:
            # The fleet RETURNED an aggregate, but it would not open or
            # decode — a wrong answer, not an outage.
            return self._finish(
                task,
                "corrupt",
                "verify",
                stages_s={"upload_ack": upload_ack_s},
                sampled_ids=sampled_ids,
                detail=f"decrypt/decode: {e}"[:200],
            )
        collection_s = self._mono() - t1
        e2e_s = self._mono() - t0

        # -- verify stage ------------------------------------------------
        if not _matches(result.aggregate_result, task.family.expected):
            return self._finish(
                task,
                "corrupt",
                "verify",
                stages_s={"upload_ack": upload_ack_s, "collection": collection_s},
                sampled_ids=sampled_ids,
                expected=task.family.expected,
                actual=result.aggregate_result,
                detail="aggregate mismatch",
            )
        return self._finish(
            task,
            "ok",
            None,
            stages_s={
                "upload_ack": upload_ack_s,
                "collection": collection_s,
                "e2e": e2e_s,
            },
            sampled_ids=sampled_ids,
            expected=task.family.expected,
            actual=result.aggregate_result,
        )

    def _classify_503(self, task: _CanaryTask, body: str) -> ProbeResult:
        """503 taxonomy: an intentional shed (admission control, brownout
        suppression) means STAND DOWN — the fleet is refusing work on
        purpose and canary pressure would make it worse.  But the
        datastore-unavailable 503 (tx retries exhausted behind the
        handler) is infrastructure failure wearing a retryable status,
        and an unbroken shed streak past ``shed_escalate_after`` is a
        front door that never reopened — both are LOUD upload failures."""
        name = task.family.name
        if "datastore unavailable" in body:
            return self._finish(
                task, "error", "upload", detail=f"upload 503: {body}"
            )
        limit = int(getattr(self.cfg, "shed_escalate_after", 3))
        with self._lock:
            streak = self._states[name].shed_streak
        if streak >= limit:
            # once declared an outage the wall STAYS loud — only a probe
            # that actually gets past upload resets the streak
            return self._finish(
                task,
                "error",
                "upload",
                detail=f"upload shed {streak + 1} cycles running: {body}",
                keep_shed_streak=True,
            )
        self._count_backoff(name, "upload_shed")
        with self._lock:
            self._states[name].shed_streak += 1
        return ProbeResult(family=name, outcome="suppressed", reason="upload_shed")

    async def _fetch_hpke_config(self, session, endpoint: str, task_id):
        from ..core.hpke import is_hpke_config_supported
        from ..messages import HpkeConfigList

        url = endpoint.rstrip("/") + "/hpke_config?task_id=" + str(task_id)
        async with session.get(url) as resp:
            if resp.status != 200:
                raise RuntimeError(f"hpke_config fetch failed: {resp.status}")
            body = await resp.read()
        for config in HpkeConfigList.get_decoded(body).hpke_configs:
            if is_hpke_config_supported(config):
                return config
        raise RuntimeError("no supported HPKE config advertised")

    def _attribute_timeout_stage(self, sampled_ids: List[str]) -> str:
        """Attribute a poll timeout from traces: a first-prepare span for
        our reports means the pipeline prepared but never collected;
        their absence means they never reached prepare."""
        globs = list(getattr(self.cfg, "trace_globs", ()) or ())
        if not globs:
            return "collection"
        stages = probe_stage_latencies(globs, sampled_ids)
        if stages.get("first_prepare"):
            return "collection"
        return "prepare"

    # -- outcome recording -----------------------------------------------
    def _finish(
        self,
        task: _CanaryTask,
        outcome: str,
        stage: Optional[str],
        stages_s: Optional[Dict[str, float]] = None,
        sampled_ids: Optional[List[str]] = None,
        expected=None,
        actual=None,
        detail: str = "",
        keep_shed_streak: bool = False,
    ) -> ProbeResult:
        name = task.family.name
        stages_s = dict(stages_s or {})
        # trace-plane attribution: commit + first-prepare deltas for this
        # probe's reports (best-effort; off when no trace glob configured)
        globs = list(getattr(self.cfg, "trace_globs", ()) or ())
        if globs and sampled_ids:
            for stage_name, samples in probe_stage_latencies(globs, sampled_ids).items():
                if samples:
                    stages_s[stage_name] = max(samples)
        metrics = self.metrics
        have = metrics.registry is not None
        ok = outcome == "ok"
        with self._lock:
            st = self._states[name]
            st.probes += 1
            if not keep_shed_streak:
                st.shed_streak = 0  # a probe got past upload: wall is open
            st.last_outcome = outcome
            st.last_detail = detail
            if ok:
                st.consecutive_failures = 0
                st.failing_stage = None
                st.last_good_unix = self._wall()
            else:
                st.consecutive_failures += 1
                st.failing_stage = stage
            verdict = st.verdict(int(getattr(self.cfg, "fail_threshold", 2)))
            for stage_name, seconds in stages_s.items():
                if stage_name in self._stage_samples:
                    self._stage_samples[stage_name].append(seconds)
        if have:
            metrics.canary_verdicts.labels(task=name, outcome=outcome).inc()
            metrics.canary_probe_outcome.observe(0.0 if ok else 2.0)
            metrics.canary_verdict_state.labels(task=name).set(_VERDICT_LEVEL[verdict])
            for stage_name, seconds in stages_s.items():
                metrics.canary_probe_seconds.labels(stage=stage_name).observe(seconds)
            if ok and "e2e" in stages_s:
                metrics.canary_e2e.observe(stages_s["e2e"])
        if not ok:
            logger.warning(
                "canary probe %s: outcome=%s stage=%s %s", name, outcome, stage, detail
            )
        return ProbeResult(
            family=name,
            outcome=outcome,
            stage=stage,
            stages_s=stages_s,
            expected=expected,
            actual=actual,
            detail=detail,
        )

    # -- rollup ----------------------------------------------------------
    def fleet_verdict(self) -> str:
        """Worst family verdict — the one pageable signal."""
        threshold = int(getattr(self.cfg, "fail_threshold", 2))
        with self._lock:
            verdicts = [st.verdict(threshold) for st in self._states.values()]
        if not verdicts:
            return VERDICT_HEALTHY
        return max(verdicts, key=lambda v: _VERDICT_LEVEL[v])

    def stats(self) -> dict:
        """The /statusz ``canary`` section."""
        threshold = int(getattr(self.cfg, "fail_threshold", 2))
        with self._lock:
            families = {
                name: {
                    "verdict": st.verdict(threshold),
                    "probes": st.probes,
                    "suppressed": st.suppressed,
                    "shed_streak": st.shed_streak,
                    "consecutive_failures": st.consecutive_failures,
                    "last_outcome": st.last_outcome,
                    "failing_stage": st.failing_stage,
                    "last_good_unix": st.last_good_unix,
                    "last_detail": st.last_detail,
                    "provisioned": name in self._tasks,
                }
                for name, st in self._states.items()
            }
            stage_latency = {}
            for stage, samples in self._stage_samples.items():
                vals = sorted(samples)
                stage_latency[stage] = {
                    "samples": len(vals),
                    "p50": _percentile(vals, 0.50),
                    "p99": _percentile(vals, 0.99),
                }
            backoffs = dict(self._backoffs)
        return {
            "enabled": True,
            "verdict": self.fleet_verdict(),
            "fail_threshold": threshold,
            "families": families,
            "stage_latency_s": stage_latency,
            "backoffs": backoffs,
        }


# ---------------------------------------------------------------------------
# Process-wide plane (the /statusz + binaries seam)

_PLANE: Optional[CanaryPlane] = None


def configure_canary(cfg, metrics=None, **kwargs) -> Optional[CanaryPlane]:
    """Install (or clear, with a falsy config) the process-wide prober."""
    global _PLANE
    if not cfg:
        _PLANE = None
        return None
    _PLANE = CanaryPlane(cfg, metrics=metrics, **kwargs)
    return _PLANE


def canary_plane() -> Optional[CanaryPlane]:
    return _PLANE


def canary_stats() -> dict:
    """The /statusz ``canary`` section (explicit disabled marker when no
    prober runs in this process)."""
    if _PLANE is None:
        return {"enabled": False}
    return _PLANE.stats()
