"""Authentication tokens for aggregator-to-aggregator and collector requests.

reference: core/src/auth_tokens.rs:26 (AuthenticationToken), :335
(AuthenticationTokenHash — SHA-256 digests compared in constant time).
"""

from __future__ import annotations

import hashlib
import hmac
import os
from dataclasses import dataclass, field

from ..messages.dap import _b64url, _unb64url

DAP_AUTH_HEADER = "DAP-Auth-Token"
AUTHORIZATION_HEADER = "Authorization"

_MAX_DAP_AUTH_TOKEN_LEN = 256


def _is_bearer_token_char(c: str) -> bool:
    return c.isalnum() or c in "-._~+/"


@dataclass(frozen=True)
class AuthenticationToken:
    """Bearer ("Authorization: Bearer x") or DapAuth ("DAP-Auth-Token: x")."""

    BEARER = "Bearer"
    DAP_AUTH = "DapAuth"

    kind: str
    # Secret hygiene (reference: aggregator_core/src/lib.rs:28 SecretBytes
    # redacts Debug output): the token never reaches logs through repr.
    token: str = field(repr=False)

    def __post_init__(self):
        if self.kind == self.BEARER:
            # RFC 6750 §2.1 token68 charset, with optional trailing '='.
            stripped = self.token.rstrip("=")
            if not stripped or not all(_is_bearer_token_char(c) for c in stripped):
                raise ValueError("invalid bearer token")
        elif self.kind == self.DAP_AUTH:
            raw = self.token.encode()
            if not raw or len(raw) > _MAX_DAP_AUTH_TOKEN_LEN:
                raise ValueError("invalid DAP auth token length")
            if any(b == 0x25 or b < 0x21 or b > 0x7E for b in raw):
                raise ValueError("DAP auth token must be visible ASCII without %")
        else:
            raise ValueError(f"unknown token kind {self.kind}")

    @classmethod
    def new_bearer(cls, token: str) -> "AuthenticationToken":
        return cls(cls.BEARER, token)

    @classmethod
    def new_dap_auth(cls, token: str) -> "AuthenticationToken":
        return cls(cls.DAP_AUTH, token)

    @classmethod
    def random_bearer(cls) -> "AuthenticationToken":
        return cls.new_bearer(_b64url(os.urandom(16)))

    @classmethod
    def from_str(cls, s: str) -> "AuthenticationToken":
        """Parse "bearer:value" / "dap:value" flag syntax
        (reference: core/src/auth_tokens.rs FromStr)."""
        if s.startswith("bearer:"):
            return cls.new_bearer(s[len("bearer:") :])
        if s.startswith("dap:"):
            return cls.new_dap_auth(s[len("dap:") :])
        raise ValueError("bad or missing prefix on authentication token value")

    def request_authentication(self) -> tuple:
        """(header, value) pair for outgoing requests."""
        if self.kind == self.BEARER:
            return (AUTHORIZATION_HEADER, f"Bearer {self.token}")
        return (DAP_AUTH_HEADER, self.token)

    def as_bytes(self) -> bytes:
        return self.token.encode()

    def hash(self) -> "AuthenticationTokenHash":
        return AuthenticationTokenHash(self.kind, hashlib.sha256(self.as_bytes()).digest())


@dataclass(frozen=True)
class AuthenticationTokenHash:
    """Stored digest validated in constant time
    (reference: core/src/auth_tokens.rs:335)."""

    kind: str
    digest: bytes

    def validate(self, presented: AuthenticationToken) -> bool:
        if presented.kind != self.kind:
            return False
        return hmac.compare_digest(
            hashlib.sha256(presented.as_bytes()).digest(), self.digest
        )

    def to_dict(self) -> dict:
        return {"type": self.kind, "hash": _b64url(self.digest)}

    @classmethod
    def from_dict(cls, d: dict) -> "AuthenticationTokenHash":
        return cls(d["type"], _unb64url(d["hash"]))


def extract_bearer_token(headers) -> "AuthenticationToken | None":
    """Pull a bearer or DAP auth token from a request-header mapping."""
    auth = headers.get(AUTHORIZATION_HEADER) or headers.get(AUTHORIZATION_HEADER.lower())
    if auth and auth.startswith("Bearer "):
        try:
            return AuthenticationToken.new_bearer(auth[len("Bearer ") :])
        except ValueError:
            return None
    dap = headers.get(DAP_AUTH_HEADER) or headers.get(DAP_AUTH_HEADER.lower())
    if dap:
        try:
            return AuthenticationToken.new_dap_auth(dap)
        except ValueError:
            return None
    return None
