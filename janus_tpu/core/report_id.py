"""Report-ID checksums: XOR of SHA-256 digests of report IDs, used for
cross-aggregator batch consistency checks.

reference: core/src/report_id.rs:7-34 (ReportIdChecksumExt).
"""

from __future__ import annotations

import hashlib

from ..messages import ReportId, ReportIdChecksum


def checksum_for_report_id(report_id: ReportId) -> ReportIdChecksum:
    return ReportIdChecksum(hashlib.sha256(report_id.data).digest())


def checksum_combined(a: ReportIdChecksum, b: ReportIdChecksum) -> ReportIdChecksum:
    return ReportIdChecksum(bytes(x ^ y for x, y in zip(a.data, b.data)))


def checksum_updated_with(
    checksum: ReportIdChecksum, report_id: ReportId
) -> ReportIdChecksum:
    return checksum_combined(checksum, checksum_for_report_id(report_id))
