"""Batched HPKE open: the upload front door's crypto as ONE wide kernel.

``Aggregator.handle_upload`` used to HPKE-open every report inline, one
at a time, on the handler's event loop.  The AEAD body of an HPKE open
is exactly the batch-crypto shape this repo accelerates — per-report
keys, a handful of blocks each, huge N — so this module re-expresses a
BATCH of concurrent uploads' opens as:

1. per-report KEM decap + HKDF key schedule (X25519 / P-256 DH — serial
   math, host territory, run off the event loop by the caller's thread
   pool), then
2. ONE vectorized AES-GCM pass over every AES-128-GCM body in the batch:
   the AES-CTR keystream (plus each report's GHASH key H = E(K, 0) and
   tag mask E(K, J0)) via the existing multikey AES kernel
   (``ops/aes_jax.encrypt_blocks_multikey_padded`` — per-report round
   keys, both axes pow2-padded), and GHASH as a vectorized carryless
   GF(2^128) multiply over u64 half-words (numpy), LEFT-zero-padding
   each report's block sequence so one unmasked Horner loop serves
   ragged lengths (leading zero blocks are GHASH no-ops).

Suites the wide kernel does not cover (AES-256-GCM, ChaCha20-Poly1305)
open per-report through core/hpke.py inside the same batch call, so the
caller's contract is uniform.  Robustness contract: a malformed
ciphertext rejects ONLY its own report (per-item error slots), and any
batch-LEVEL failure falls back to per-report inline opens — the batched
path can never reject a report the inline path would accept.
Bit-exactness is anchored by running the vendored RFC 9180 vectors and a
batched-vs-inline fuzz (tests/test_hpke_batch.py) through this path.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..messages import HpkeAeadId
from .hpke import (
    _AEAD_PARAMS,
    _KEMS,
    HpkeApplicationInfo,
    HpkeError,
    HpkeKeypair,
    _key_schedule,
    is_hpke_config_supported,
)

__all__ = ["OpenRequest", "open_batch", "aesgcm_open_batch", "vector_pass_preferred"]

#: An open request: (recipient keypair, application info, ciphertext, aad).
OpenRequest = Tuple[HpkeKeypair, HpkeApplicationInfo, object, bytes]

#: Below this many AES-128-GCM bodies the vectorized pass is dispatch
#: overhead, not a win — open per-report instead.
MIN_VECTOR_BATCH = 2

#: memoized backend probe for vector_pass_preferred (None = unprobed)
_VECTOR_PREFERRED: Optional[bool] = None


def vector_pass_preferred() -> bool:
    """Should AES-128-GCM bodies take the wide table-AES kernel?

    The vectorized pass is the right tool exactly where it was built for:
    hosts whose jax backend is a real accelerator (table gathers on
    TPU are data-independent wide vector ops), and hosts with NO
    functional `cryptography` (nothing constant-time exists to prefer).
    On a plain-CPU host WITH a working `cryptography`, per-report AES-NI
    is both constant-time and faster than table lookups — the soft
    kernels must never be a production preference there (the
    utils/gcm.py invariant).  ``JANUS_TPU_UPLOAD_VECTOR_GCM=1|0``
    overrides (tests force both paths)."""
    global _VECTOR_PREFERRED
    import os

    force = os.environ.get("JANUS_TPU_UPLOAD_VECTOR_GCM", "")
    if force in ("0", "1"):
        return force == "1"
    if _VECTOR_PREFERRED is None:
        from ..utils.gcm import HAVE_FUNCTIONAL_CRYPTOGRAPHY

        if not HAVE_FUNCTIONAL_CRYPTOGRAPHY:
            _VECTOR_PREFERRED = True
        else:
            try:
                import jax

                _VECTOR_PREFERRED = jax.default_backend() != "cpu"
            except Exception:  # pragma: no cover - jax-less host
                _VECTOR_PREFERRED = False
    return _VECTOR_PREFERRED

_R_HI = np.uint64(0xE100000000000000)  # GCM reduction poly, high u64


# -- vectorized GHASH ---------------------------------------------------------


def _ghash_batch(h_blocks: np.ndarray, datas: Sequence[bytes]) -> np.ndarray:
    """GHASH_H(data) per report, vectorized across the batch.

    ``h_blocks`` is (B, 16) u8 (each report's H = E(K, 0)); each
    ``datas[i]`` must already be a block multiple (the caller appends the
    GCM length block).  Ragged lengths are LEFT-padded with zero blocks
    to the common maximum — a leading zero block leaves the Horner
    accumulator at 0, so padding changes nothing.  Returns (B, 16) u8.

    Field elements ride as (hi, lo) u64 pairs in string order (bit 0 of
    the GCM spec = the integer's MSB); multiply-by-H is the SP 800-38D
    right-shift construction: per report, precompute V_t = H * x^t for
    t in [0, 128), then each Horner step XOR-selects the V_t rows whose
    corresponding bit of (Y ^ X_j) is set."""
    b = len(h_blocks)
    # H as u64 halves
    h = h_blocks.reshape(b, 2, 8).astype(np.uint64)
    weights = (np.uint64(256) ** np.arange(7, -1, -1, dtype=np.uint64)).reshape(1, 1, 8)
    h64 = (h * weights).sum(axis=2, dtype=np.uint64)  # (B, 2): hi, lo
    # Vpow[:, t] = H * x^t (128 sequential shift-reduce steps, vectorized
    # over the batch)
    vhi = np.empty((b, 128), dtype=np.uint64)
    vlo = np.empty((b, 128), dtype=np.uint64)
    chi, clo = h64[:, 0].copy(), h64[:, 1].copy()
    one = np.uint64(1)
    s63 = np.uint64(63)
    for t in range(128):
        vhi[:, t] = chi
        vlo[:, t] = clo
        lsb = clo & one
        clo = (clo >> one) | ((chi & one) << s63)
        chi = (chi >> one) ^ (lsb * _R_HI)
    # left-pad block streams to the common length
    nblocks = [len(d) // 16 for d in datas]
    m = max(nblocks) if nblocks else 0
    padded = np.zeros((b, m * 16), dtype=np.uint8)
    for i, d in enumerate(datas):
        if d:
            padded[i, (m - nblocks[i]) * 16 :] = np.frombuffer(d, dtype=np.uint8)
    blocks = padded.reshape(b, m, 2, 8).astype(np.uint64)
    blocks64 = (blocks * weights.reshape(1, 1, 1, 8)).sum(axis=3, dtype=np.uint64)
    # Horner: Y <- (Y ^ X_j) * H per block position
    yhi = np.zeros(b, dtype=np.uint64)
    ylo = np.zeros(b, dtype=np.uint64)
    shifts = np.arange(63, -1, -1, dtype=np.uint64)
    for j in range(m):
        xhi = yhi ^ blocks64[:, j, 0]
        xlo = ylo ^ blocks64[:, j, 1]
        # bit t of the STRING order = integer bit (127 - t): hi's MSB first
        bits_hi = ((xhi[:, None] >> shifts) & one).astype(bool)  # t = 0..63
        bits_lo = ((xlo[:, None] >> shifts) & one).astype(bool)  # t = 64..127
        bits = np.concatenate([bits_hi, bits_lo], axis=1)  # (B, 128)
        yhi = np.bitwise_xor.reduce(np.where(bits, vhi, np.uint64(0)), axis=1)
        ylo = np.bitwise_xor.reduce(np.where(bits, vlo, np.uint64(0)), axis=1)
    out = np.empty((b, 16), dtype=np.uint8)
    for k in range(8):
        sh = np.uint64(8 * (7 - k))
        out[:, k] = (yhi >> sh).astype(np.uint8)
        out[:, 8 + k] = (ylo >> sh).astype(np.uint8)
    return out


# -- vectorized AES-128-GCM open ---------------------------------------------


def _encrypt_blocks_multikey(round_keys: np.ndarray, blocks: np.ndarray) -> np.ndarray:
    """(B, K, 16) AES blocks under per-report (B, 11, 16) round keys: the
    jitted multikey kernel (pow2-padded) when the jax stack is up, a
    per-report numpy soft-AES loop otherwise."""
    try:
        from ..ops.aes_jax import encrypt_blocks_multikey_padded

        return np.asarray(encrypt_blocks_multikey_padded(round_keys, blocks))
    except Exception:  # pragma: no cover - jax-less host
        from ..utils.softaes import encrypt_blocks

        return np.stack(
            [encrypt_blocks(rk, blk) for rk, blk in zip(round_keys, blocks)]
        )


def aesgcm_open_batch(
    keys: Sequence[bytes],
    nonces: Sequence[bytes],
    ciphertexts: Sequence[bytes],
    aads: Sequence[bytes],
) -> List[Optional[bytes]]:
    """Open B AES-128-GCM one-shot messages as one vectorized pass.

    Returns a plaintext per slot, or None where authentication failed
    (tag mismatch / truncated input) — per-report isolation is the
    contract.  All nonces must be 12 bytes (the only length RFC 9180
    produces)."""
    from ..utils.softaes import _expand_key

    b = len(keys)
    cts, tags, ok = [], [], []
    for ct in ciphertexts:
        if len(ct) < 16:
            cts.append(b"")
            tags.append(b"")
            ok.append(False)
        else:
            cts.append(ct[:-16])
            tags.append(ct[-16:])
            ok.append(True)
    nblocks = [(len(c) + 15) // 16 for c in cts]
    kmax = 2 + max(nblocks, default=0)
    round_keys = np.stack([_expand_key(bytes(k)) for k in keys])
    blocks = np.zeros((b, kmax, 16), dtype=np.uint8)
    for i in range(b):
        j0 = nonces[i] + b"\x00\x00\x00\x01"
        blocks[i, 1] = np.frombuffer(j0, dtype=np.uint8)
        for c in range(nblocks[i]):
            ctr = nonces[i] + struct.pack(">I", 2 + c)
            blocks[i, 2 + c] = np.frombuffer(ctr, dtype=np.uint8)
    out = _encrypt_blocks_multikey(round_keys, blocks)
    h = np.ascontiguousarray(out[:, 0])  # E(K, 0): the GHASH key
    tag_mask = out[:, 1]  # E(K, J0)
    ghash_in = [
        aad
        + b"\x00" * (-len(aad) % 16)
        + ct
        + b"\x00" * (-len(ct) % 16)
        + struct.pack(">QQ", 8 * len(aad), 8 * len(ct))
        for aad, ct in zip(aads, cts)
    ]
    s = _ghash_batch(h, ghash_in)
    tags_got = s ^ tag_mask
    results: List[Optional[bytes]] = []
    for i in range(b):
        if not ok[i] or tags_got[i].tobytes() != tags[i]:
            results.append(None)
            continue
        stream = out[i, 2 : 2 + nblocks[i]].tobytes()
        ct = cts[i]
        pt = np.frombuffer(ct, dtype=np.uint8) ^ np.frombuffer(
            stream[: len(ct)], dtype=np.uint8
        )
        results.append(pt.tobytes())
    return results


# -- the batch face -----------------------------------------------------------


def _open_one(keypair, info, ciphertext, aad):
    """Per-report inline open, errors as values."""
    from .hpke import open_

    try:
        return open_(keypair, info, ciphertext, aad)
    except HpkeError as e:
        return e
    except Exception as e:  # pragma: no cover - defensive
        return HpkeError(f"HPKE open failed: {type(e).__name__}")


def open_batch(requests: Sequence[OpenRequest]) -> List[object]:
    """Open a batch of HPKE ciphertexts; one result slot per request —
    plaintext bytes on success, an :class:`HpkeError` value on failure
    (never raised: a malformed row must reject only itself).

    Per-report KEM decap + key schedule run here (the caller is expected
    to be on a worker thread); all AES-128-GCM bodies then open as ONE
    vectorized pass, other suites per-report.  Any batch-level error in
    the vectorized pass falls back to per-report inline opens."""
    results: List[object] = [None] * len(requests)
    gcm_idx: List[int] = []
    gcm_keys: List[bytes] = []
    gcm_nonces: List[bytes] = []
    gcm_cts: List[bytes] = []
    gcm_aads: List[bytes] = []
    for i, (keypair, info, ciphertext, aad) in enumerate(requests):
        config = keypair.config
        if not is_hpke_config_supported(config):
            results[i] = HpkeError("unsupported HPKE configuration")
            continue
        kem = _KEMS[config.kem_id]
        try:
            shared_secret = kem.decap(
                ciphertext.encapsulated_key,
                keypair.private_key,
                pk_r=config.public_key.raw,
            )
            key, base_nonce = _key_schedule(
                config.kem_id, config.kdf_id, config.aead_id, shared_secret, info.raw
            )
        except Exception as e:
            results[i] = HpkeError(f"HPKE open failed: {type(e).__name__}")
            continue
        if config.aead_id == HpkeAeadId.AES_128_GCM:
            gcm_idx.append(i)
            gcm_keys.append(key)
            gcm_nonces.append(base_nonce)
            gcm_cts.append(ciphertext.payload)
            gcm_aads.append(aad)
        else:
            _nk, _nn, aead_factory = _AEAD_PARAMS[config.aead_id]
            try:
                results[i] = aead_factory(key).decrypt(
                    base_nonce, ciphertext.payload, aad
                )
            except Exception as e:
                results[i] = HpkeError(f"HPKE open failed: {type(e).__name__}")
    if gcm_idx:
        if len(gcm_idx) < MIN_VECTOR_BATCH or not vector_pass_preferred():
            # per-report AEAD with the ALREADY-derived keys (the KEM work
            # above is never repeated): the path for tiny batches and for
            # CPU hosts where `cryptography`'s constant-time AES-NI beats
            # — and must be preferred over — the table kernels
            _nk, _nn, aead_factory = _AEAD_PARAMS[HpkeAeadId.AES_128_GCM]
            for i, key, nonce, ct, aad in zip(
                gcm_idx, gcm_keys, gcm_nonces, gcm_cts, gcm_aads
            ):
                try:
                    results[i] = aead_factory(key).decrypt(nonce, ct, aad)
                except Exception as e:
                    results[i] = HpkeError(f"HPKE open failed: {type(e).__name__}")
        else:
            try:
                opened = aesgcm_open_batch(gcm_keys, gcm_nonces, gcm_cts, gcm_aads)
                for i, pt in zip(gcm_idx, opened):
                    results[i] = (
                        pt if pt is not None else HpkeError("HPKE open failed: InvalidTag")
                    )
            except Exception:
                # batch-LEVEL failure (kernel import, shape bug): per-report
                # fallback so one pass's trouble can never reject the batch
                for i in gcm_idx:
                    keypair, info, ciphertext, aad = requests[i]
                    results[i] = _open_one(keypair, info, ciphertext, aad)
    return results
