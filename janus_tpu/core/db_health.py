"""Process-wide datastore health: brownout detection for the database.

Janus is database-centric — every component coordinates implicitly
through the datastore, and PR 16 made it the fleet's membership and
routing substrate too.  That makes a datastore *brownout* (slow disk,
sqlite writer contention, Postgres failover) a correlated failure: every
replica's heartbeat goes stale simultaneously, which without a local
verdict is indistinguishable from "everyone died" and triggers a
fleet-wide migration storm at the worst possible moment.

This module is ``core/peer_health.py``'s state machine pointed at the
one datastore instead of N peers: a single process-wide
healthy→suspect→probing tracker fed from the ``run_tx`` retry loop.
Only TRANSIENT failures count (SQLITE_BUSY / "database is locked",
psycopg OperationalError / serialization failures, injected tx faults):
schema and integrity errors are bugs, stay loud, and never mark the
datastore unhealthy.

States (exported as the ``janus_datastore_health{state}`` state-set
gauge and the /statusz "datastore" section):

    healthy  transactions are committing; everything flows
    suspect  >= ``failure_threshold`` consecutive transient tx failures;
             consumers degrade — the fleet router freezes its ownership
             view, the upload front door sheds with 503 before burning
             HPKE work, the janitors skip their sweeps
    probing  suspect past its dwell: traffic probes the datastore again;
             the first commit restores healthy, the first transient
             failure re-suspects (and restarts the dwell)

Consumers gate on two predicates with different strictness:
``is_suspect()`` (state != healthy — used where acting on a possibly
stale view is dangerous, e.g. fleet takeovers) and ``state() ==
DB_SUSPECT`` (strict — used by the upload shed, because probing traffic
IS the probe that heals the tracker).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Optional

DB_HEALTHY, DB_SUSPECT, DB_PROBING = "healthy", "suspect", "probing"
_STATES = (DB_HEALTHY, DB_SUSPECT, DB_PROBING)

logger = logging.getLogger("janus_tpu.db_health")


def backoff_s(
    attempt: int,
    *,
    initial: float = 0.025,
    cap: float = 0.5,
    rng: Optional[random.Random] = None,
) -> float:
    """Full-jitter exponential backoff for the ``run_tx`` retry loop:
    ``min(cap, initial * 2**attempt)`` scaled by a uniform [0.5, 1.0)
    factor, so N replicas retrying the same contended writer spread out
    instead of stampeding in lockstep.  ``rng`` is the determinism hook
    (tests seed it); None uses the module-level PRNG."""
    base = min(cap, initial * (2.0 ** max(0, attempt)))
    r = rng if rng is not None else random
    return base * (0.5 + 0.5 * r.random())


class DbHealthTracker:
    """The datastore's transport-health state machine; one per process
    (module singleton below), thread-safe — ``run_tx`` records from any
    thread, /statusz reads from the health server."""

    def __init__(self, failure_threshold: int = 3, suspect_dwell_s: float = 5.0):
        self.failure_threshold = failure_threshold
        self.suspect_dwell_s = suspect_dwell_s
        self.consecutive_failures = 0
        self.tx_failures_total = 0
        self.suspected = False
        self.suspected_at = 0.0
        #: suspect transitions (a flapping disk shows up as a high count)
        self.suspect_transitions = 0
        #: when the tracker last transitioned non-healthy -> healthy (0 =
        #: never suspected): the job drivers' heal-grace signal — a lease
        #: whose attempt count was inflated by the brownout gets its
        #: post-heal attempt instead of an entry abandonment
        self.healed_at = 0.0
        self._lock = threading.Lock()

    def configure(
        self,
        failure_threshold: Optional[int] = None,
        suspect_dwell_s: Optional[float] = None,
    ) -> None:
        with self._lock:
            if failure_threshold is not None:
                self.failure_threshold = failure_threshold
            if suspect_dwell_s is not None:
                self.suspect_dwell_s = suspect_dwell_s

    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if not self.suspected:
            return DB_HEALTHY
        if time.monotonic() - self.suspected_at >= self.suspect_dwell_s:
            return DB_PROBING
        return DB_SUSPECT

    def is_suspect(self) -> bool:
        """True while suspect OR probing — the tracker currently believes
        the datastore is (or may still be) browning out.  The fleet
        router and janitors gate on this; the upload shed uses the
        strict ``state() == DB_SUSPECT`` instead (probing uploads are
        the probe)."""
        return self.state() != DB_HEALTHY

    def record_tx_success(self) -> None:
        was = False
        with self._lock:
            self.consecutive_failures = 0
            was = self.suspected
            self.suspected = False
            if was:
                self.healed_at = time.monotonic()
        if was:
            self._publish()
            logger.info("datastore HEALTHY again (transaction committed)")

    def record_tx_failure(self) -> None:
        """One TRANSIENT (retryable) transaction failure.  Permanent
        errors — schema, integrity, bugs — must NOT be fed here: they
        stay loud and say nothing about datastore availability."""
        transitioned = False
        with self._lock:
            self.consecutive_failures += 1
            self.tx_failures_total += 1
            if self.failure_threshold > 0 and (
                self.consecutive_failures >= self.failure_threshold
            ):
                if not self.suspected:
                    self.suspect_transitions += 1
                    transitioned = True
                # a failing probe (or further failures while suspect)
                # restarts the dwell: the datastore earns its way back
                # only with a real commit
                self.suspected = True
                self.suspected_at = time.monotonic()
        self._publish(count_failure=True)
        if transitioned:
            logger.warning(
                "datastore SUSPECT after %d consecutive transient tx "
                "failure(s); degrading for %.1fs before probing",
                self.consecutive_failures,
                self.suspect_dwell_s,
            )

    def recently_healed(self, window_s: float) -> bool:
        with self._lock:
            return (
                self.healed_at > 0
                and time.monotonic() - self.healed_at < window_s
            )

    def brownout_signal(self, window_s: float) -> bool:
        """Is the datastore non-healthy now, or healed within
        ``window_s``?  The job drivers' ceiling guards use this to
        excuse attempt counts inflated by a brownout."""
        return self.is_suspect() or self.recently_healed(window_s)

    def _publish(self, count_failure: bool = False) -> None:
        from .metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is None:
            return
        if count_failure:
            GLOBAL_METRICS.datastore_tx_retries.inc()
        current = self.state()
        for state in _STATES:
            GLOBAL_METRICS.datastore_health.labels(state=state).set(
                1.0 if state == current else 0.0
            )

    def republish_metrics(self) -> None:
        """Refresh the state-set gauge: the suspect -> probing transition
        happens purely by time passing, so with no transactions flowing
        the gauge would report suspect=1 forever — the status sampler
        calls this each tick so alerts match live state."""
        self._publish()

    def stats(self) -> dict:
        with self._lock:
            state = self._state_locked()
            out = {
                "state": state,
                "consecutive_failures": self.consecutive_failures,
                "tx_failures_total": self.tx_failures_total,
                "suspect_transitions": self.suspect_transitions,
                "failure_threshold": self.failure_threshold,
                "suspect_dwell_s": self.suspect_dwell_s,
            }
            if self.suspected:
                out["suspected_age_s"] = round(
                    time.monotonic() - self.suspected_at, 3
                )
        return out

    def reset(self) -> None:
        with self._lock:
            self.consecutive_failures = 0
            self.tx_failures_total = 0
            self.suspected = False
            self.suspected_at = 0.0
            self.suspect_transitions = 0
            self.healed_at = 0.0


# -- process-wide instance ---------------------------------------------------

_TRACKER = DbHealthTracker()


def tracker() -> DbHealthTracker:
    return _TRACKER


def reset_db_health() -> None:
    """Test hook: drop all state (thresholds keep their last configured
    values — reconfigure explicitly if a test needs defaults)."""
    _TRACKER.reset()


def janitor_skip(component: str) -> bool:
    """Shared janitor gate: True when background sweeps (GC, key
    rotation) should no-op because the tracker is non-healthy.  Deletes
    and key-state flips are the worst traffic to aim at a browning-out
    datastore — they contend with the latency-sensitive upload/step
    writes and none of them are urgent.  Counted per component in
    ``janus_janitor_skips_total`` so a stuck-suspect tracker shows up as
    a climbing skip count, not silently stalled maintenance."""
    if not _TRACKER.is_suspect():
        return False
    from .metrics import GLOBAL_METRICS

    if GLOBAL_METRICS.registry is not None:
        GLOBAL_METRICS.janitor_skips.labels(component=component).inc()
    logger.warning(
        "%s sweep skipped: datastore is %s (no-op until it heals)",
        component,
        _TRACKER.state(),
    )
    return True
