"""Declarative SLO evaluation plane: the fleet judges its own freshness.

PR 5 gave the fleet freshness histograms (report commit age, job age at
acquire, collection end-to-end); until now they were numbers a human had
to eyeball.  This module closes the loop: ``common.slos`` declares
objectives over those histograms and a burn-rate evaluator — driven by
the same status-sampler tick that publishes the backlog gauges — computes
multi-window burn rates from histogram snapshots, emits
``janus_slo_burn_rate{slo,window}`` / ``janus_slo_breach_total{slo}``,
and renders its verdicts in ``/statusz``.

The math is the standard multi-window, multi-burn-rate SLO alert (SRE
workbook shape): an SLO is "P of events complete within T seconds".
From a latency histogram, good = samples <= T (rounded DOWN to the
nearest bucket bound — the effective threshold is reported), bad = the
rest.  Over a trailing window W the error rate is bad_delta/total_delta
between snapshots, and the burn rate is error_rate / (1 - objective):
1.0 spends the error budget exactly at the sustainable pace.  A BREACH
is the transition into (fast-window burn >= fast threshold AND
slow-window burn >= slow threshold) — the fast window catches the page,
the slow window keeps a blip from paging.

Declarative config (``common.slos``; signal defaults to the SLO name)::

    slos:
      commit_age:        {objective: 0.99, threshold_s: 60}
      collection_e2e:    {objective: 0.95, threshold_s: 600, fast_burn: 10}
      job_age_at_acquire: {objective: 0.99, threshold_s: 30}
      first_flush:       {objective: 0.9,  threshold_s: 1.0}
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional

#: SLO signal name -> histogram metric family.  Freshness histograms from
#: PR 5 plus the upload-to-commit latency and the executor's submission
#: wait (the "warm first-flush latency" an operator actually feels).
SIGNALS = {
    "commit_age": "janus_report_commit_age_seconds",
    "upload_to_commit": "janus_report_upload_to_commit_seconds",
    "job_age_at_acquire": "janus_job_age_at_acquire_seconds",
    "collection_e2e": "janus_collection_e2e_seconds",
    "first_flush": "janus_executor_wait_duration_seconds",
    # Canary plane (core/canary.py): black-box probe end-to-end latency
    # and probe success rate (the outcome histogram observes 0.0 on
    # success / 2.0 on failure, so any threshold_s in [0.5, 2) makes
    # good == successes under the standard histogram_totals math).
    "canary_e2e_latency": "janus_canary_e2e_seconds",
    "canary_success": "janus_canary_probe_outcome",
}


def _known_histogram_families() -> set:
    """Histogram family names from the live metric catalog — the set a
    raw ``janus_*`` SLO signal must resolve into.  A signal naming a
    family that does not exist (or is not a histogram) would silently
    evaluate over zero events forever; better to fail startup."""
    from .metrics import GLOBAL_METRICS

    out = set()
    for line in GLOBAL_METRICS.catalog():
        name, kind, _labels = line.split("|", 2)
        if kind == "histogram":
            out.add(name)
    return out


@dataclass
class SloTarget:
    """One declarative objective over a latency histogram."""

    name: str
    threshold_s: float
    objective: float = 0.99
    signal: str = ""  # defaults to name; raw janus_* family names allowed
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    #: burn-rate thresholds per window (GCP/SRE-workbook defaults for a
    #: 2%-budget fast page and a sustained slow leak)
    fast_burn: float = 14.0
    slow_burn: float = 2.0

    def __post_init__(self):
        if not self.signal:
            self.signal = self.name
        if not (0.0 < self.objective < 1.0):
            raise ValueError(f"slo {self.name}: objective must be in (0, 1)")
        if self.threshold_s <= 0:
            raise ValueError(f"slo {self.name}: threshold_s must be positive")
        if self.fast_window_s >= self.slow_window_s:
            raise ValueError(
                f"slo {self.name}: fast_window_s must be < slow_window_s"
            )

    @property
    def family(self) -> str:
        fam = SIGNALS.get(self.signal)
        if fam is not None:
            return fam
        if self.signal.startswith("janus_"):
            if self.signal in _known_histogram_families():
                return self.signal
            raise ValueError(
                f"slo {self.name}: raw signal {self.signal!r} is not a "
                f"histogram family in the metric catalog — a typo'd SLO "
                f"must fail startup, not silently evaluate zero events"
            )
        raise ValueError(
            f"slo {self.name}: unknown signal {self.signal!r} "
            f"(known: {sorted(SIGNALS)} or a raw janus_* histogram name)"
        )


def targets_from_config(cfg: dict) -> List[SloTarget]:
    """``common.slos`` (name -> spec mapping) -> validated targets.
    Strict on unknown keys: a typo'd burn threshold must fail startup,
    not silently evaluate defaults."""
    targets = []
    known = {
        "signal",
        "objective",
        "threshold_s",
        "fast_window_s",
        "slow_window_s",
        "fast_burn",
        "slow_burn",
    }
    for name, spec in (cfg or {}).items():
        if not isinstance(spec, dict):
            raise ValueError(f"slo {name}: expected a mapping, got {spec!r}")
        unknown = set(spec) - known
        if unknown:
            raise ValueError(f"slo {name}: unknown keys {sorted(unknown)}")
        if "threshold_s" not in spec:
            raise ValueError(f"slo {name}: threshold_s is required")
        tgt = SloTarget(name=name, **{k: spec[k] for k in spec})
        tgt.family  # validate the signal eagerly
        targets.append(tgt)
    return targets


def histogram_totals(families: dict, family: str, threshold_s: float):
    """(total, good, effective_threshold) summed across every label set of
    ``family`` in a snapshot_metric_families() view.  ``good`` counts
    samples <= the largest bucket bound <= threshold_s (None when the
    threshold undercuts every bound — everything is then "bad")."""
    fam = families.get(family)
    if fam is None or fam["kind"] != "histogram":
        return 0, 0, None
    total = good = 0
    effective = None
    for _labels, h in fam["series"]:
        total += h["count"]
        bounds = h["bounds"]
        idx = None
        for i, b in enumerate(bounds):
            if b <= threshold_s:
                idx = i
            else:
                break
        if idx is not None:
            effective = bounds[idx]
            good += sum(h["bucket_counts"][: idx + 1])
    return total, good, effective


class SloEvaluator:
    """Multi-window burn-rate evaluator over the process's histograms.

    ``tick()`` is driven by the binaries' status-sampler loop; everything
    it reads is an in-memory registry snapshot, so a tick is cheap and a
    wedged datastore cannot stall SLO evaluation."""

    def __init__(self, targets: List[SloTarget], metrics=None, time_fn=time.monotonic):
        self.targets = list(targets)
        self._metrics = metrics
        self._time = time_fn
        self._lock = threading.Lock()
        #: per-slo deque of (t, total, good) snapshots
        self._history: Dict[str, deque] = {t.name: deque() for t in self.targets}
        self._breaching: Dict[str, bool] = {t.name: False for t in self.targets}
        self._breaches: Dict[str, int] = {t.name: 0 for t in self.targets}
        self._last: Dict[str, dict] = {}
        self._ticks = 0

    @property
    def metrics(self):
        if self._metrics is not None:
            return self._metrics
        from .metrics import GLOBAL_METRICS

        return GLOBAL_METRICS

    # -- the tick --------------------------------------------------------
    def tick(self) -> Dict[str, dict]:
        from .otlp import snapshot_metric_families

        metrics = self.metrics
        now = self._time()
        families = {f["name"]: f for f in snapshot_metric_families(metrics)}
        have = metrics.registry is not None
        with self._lock:
            self._ticks += 1
            for tgt in self.targets:
                total, good, effective = histogram_totals(
                    families, tgt.family, tgt.threshold_s
                )
                hist = self._history[tgt.name]
                hist.append((now, total, good))
                # keep exactly one snapshot at/behind the slow window edge
                # (the slow baseline); everything older is dead weight
                while len(hist) >= 2 and hist[1][0] <= now - tgt.slow_window_s:
                    hist.popleft()
                fast = self._burn_rate(hist, now, tgt.fast_window_s, tgt.objective)
                slow = self._burn_rate(hist, now, tgt.slow_window_s, tgt.objective)
                breaching = (
                    fast > 0
                    and fast >= tgt.fast_burn
                    and slow >= tgt.slow_burn
                )
                if breaching and not self._breaching[tgt.name]:
                    self._breaches[tgt.name] += 1
                    if have:
                        metrics.slo_breaches.labels(slo=tgt.name).inc()
                self._breaching[tgt.name] = breaching
                if have:
                    metrics.slo_burn_rate.labels(slo=tgt.name, window="fast").set(fast)
                    metrics.slo_burn_rate.labels(slo=tgt.name, window="slow").set(slow)
                self._last[tgt.name] = {
                    "signal": tgt.signal,
                    "family": tgt.family,
                    "objective": tgt.objective,
                    "threshold_s": tgt.threshold_s,
                    "effective_threshold_s": effective,
                    "events_total": total,
                    "good_total": good,
                    "burn_rate": {"fast": round(fast, 4), "slow": round(slow, 4)},
                    "windows_s": {"fast": tgt.fast_window_s, "slow": tgt.slow_window_s},
                    "burn_thresholds": {"fast": tgt.fast_burn, "slow": tgt.slow_burn},
                    "breaching": breaching,
                    "breaches": self._breaches[tgt.name],
                }
            return dict(self._last)

    @staticmethod
    def _burn_rate(hist, now: float, window_s: float, objective: float) -> float:
        """Burn rate over the trailing window: deltas between the current
        snapshot and the newest snapshot at/behind the window edge (the
        oldest available when history is younger than the window)."""
        cutoff = now - window_s
        base = hist[0]
        for snap in hist:
            if snap[0] <= cutoff:
                base = snap
            else:
                break
        _t0, base_total, base_good = base
        _t1, cur_total, cur_good = hist[-1]
        d_total = cur_total - base_total
        if d_total <= 0:
            return 0.0
        d_bad = (cur_total - cur_good) - (base_total - base_good)
        error_rate = min(1.0, max(0.0, d_bad / d_total))
        return error_rate / max(1e-9, 1.0 - objective)

    # -- introspection ---------------------------------------------------
    def status(self) -> dict:
        """The /statusz "slo" section."""
        with self._lock:
            return {
                "targets": len(self.targets),
                "ticks": self._ticks,
                "slos": dict(self._last)
                or {t.name: {"signal": t.signal} for t in self.targets},
            }


# -- process-wide evaluator ---------------------------------------------------

_EVALUATOR: Optional[SloEvaluator] = None


def configure_slos(cfg, metrics=None) -> Optional[SloEvaluator]:
    """Install (or clear, with a falsy config) the process-wide evaluator.
    ``cfg`` is either the ``common.slos`` mapping or a prebuilt target
    list."""
    global _EVALUATOR
    if not cfg:
        _EVALUATOR = None
        return None
    targets = (
        list(cfg)
        if cfg and isinstance(next(iter(cfg), None), SloTarget)
        else targets_from_config(cfg)
    )
    _EVALUATOR = SloEvaluator(targets, metrics=metrics)
    return _EVALUATOR


def slo_evaluator() -> Optional[SloEvaluator]:
    return _EVALUATOR


def evaluate_tick() -> None:
    """One status-sampler-driven evaluation pass; no-op when unconfigured."""
    if _EVALUATOR is not None:
        _EVALUATOR.tick()


def slo_status() -> dict:
    """The /statusz "slo" section (an explicit disabled marker when no
    targets are configured)."""
    if _EVALUATOR is None:
        return {"targets": 0, "ticks": 0, "slos": {}}
    return _EVALUATOR.status()
