"""Blast-radius isolation for vectorized passes and durable journal rows.

Vectorized planes (batched HPKE open, executor mega-batch prep_init/combine,
the journal materializer fold) fail at *cohort* granularity: one poison row
fails the whole batch, the batch re-enters the retry path, and the pipeline
wedges (or the breaker trips globally) forever.  This module restores the
per-report failure semantics of the reference system on top of those
vectorized planes:

- ``bisect_batch`` retries a failed cohort in halves to isolate the poison
  row(s) within a per-report retry budget — O(log B) extra passes per poison
  row, and the healthy remainder proceeds.
- ``QuarantineRecorder`` records offenders (report id, task, stage, error
  class, payload digest) in memory for /statusz and — when a datastore sink is
  configured — durably in the ``quarantined_reports`` table via a
  failure-tolerant background writer.
- ``crc32c`` / ``chain_crc`` provide the Castagnoli checksum used to detect
  torn/bit-flipped ``report_journal`` and ``accumulator_journal`` rows.
"""

from __future__ import annotations

import hashlib
import logging
import queue
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli).  zlib.crc32 implements the plain CRC32 (0xEDB88320)
# polynomial; durable-storage checksums conventionally use Castagnoli
# (0x82F63B78, reflected), so we carry a small table-driven implementation.
# ---------------------------------------------------------------------------

_CRC32C_POLY = 0x82F63B78


def _build_crc32c_table() -> Tuple[int, ...]:
    table = []
    for byte in range(256):
        crc = byte
        for _ in range(8):
            crc = (crc >> 1) ^ _CRC32C_POLY if crc & 1 else crc >> 1
        table.append(crc)
    return tuple(table)


_CRC32C_TABLE = _build_crc32c_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    """Castagnoli CRC32 of ``data``, optionally chained from ``crc``."""
    crc ^= 0xFFFFFFFF
    table = _CRC32C_TABLE
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def chain_crc(*parts: Optional[bytes]) -> int:
    """CRC32C over a length-prefixed concatenation of ``parts``.

    Length-prefixing (and an explicit marker for NULL columns) makes the
    checksum sensitive to column boundaries: ``(b"ab", b"c")`` and
    ``(b"a", b"bc")`` hash differently, as do ``(None,)`` and ``(b"",)``.
    """
    crc = 0
    for part in parts:
        if part is None:
            crc = crc32c(b"\xff\xff\xff\xff\xff", crc)
            continue
        crc = crc32c(len(part).to_bytes(4, "big"), crc)
        crc = crc32c(part, crc)
    return crc


def payload_digest(payload: object) -> str:
    """Short stable digest of an offending payload for the quarantine record."""
    if isinstance(payload, (bytes, bytearray, memoryview)):
        raw = bytes(payload)
    else:
        raw = repr(payload).encode("utf-8", "replace")
    return hashlib.sha256(raw).hexdigest()[:16]


# ---------------------------------------------------------------------------
# Batch bisection.
# ---------------------------------------------------------------------------


@dataclass
class BisectionOutcome:
    """Result of ``bisect_batch`` over a cohort of ``total`` items."""

    total: int
    results: Dict[int, object] = field(default_factory=dict)
    offenders: List[Tuple[int, Exception]] = field(default_factory=list)
    attempts: int = 0
    exhausted: bool = False

    @property
    def attributable(self) -> bool:
        """True when failure was isolated to a strict subset of the cohort.

        An all-offenders outcome means every singleton failed — that is not a
        poison row, it is the pass itself failing (device lost, bad build) and
        must take the legacy breaker path instead of quarantining the cohort.
        """
        return 0 < len(self.offenders) < self.total

    @property
    def offender_indices(self) -> List[int]:
        return [i for i, _ in self.offenders]


def bisect_batch(
    items: Sequence[object],
    attempt: Callable[[Sequence[object]], Sequence[object]],
    per_item_budget: int = 16,
) -> BisectionOutcome:
    """Isolate poison rows in ``items`` by retrying failing halves.

    ``attempt(subset)`` must either return one result per subset element (in
    order) or raise; it must never partially succeed.  The full cohort is
    retried once first so a transient batch-level failure costs a single extra
    pass and quarantines nothing.  Each item is charged one attempt per pass
    it participates in; when an item's charge reaches ``per_item_budget`` its
    remaining range is marked offender wholesale (``exhausted=True``) rather
    than retried forever.
    """
    outcome = BisectionOutcome(total=len(items))
    if not items:
        return outcome
    charges = [0] * len(items)

    def run(lo: int, hi: int) -> None:
        # Budget fence: the most-charged item in the range pays for each pass.
        if max(charges[lo:hi]) >= per_item_budget:
            outcome.exhausted = True
            for i in range(lo, hi):
                outcome.offenders.append(
                    (i, BudgetExhausted(f"retry budget exhausted at index {i}"))
                )
            return
        outcome.attempts += 1
        for i in range(lo, hi):
            charges[i] += 1
        try:
            sub = attempt(items[lo:hi])
        except Exception as exc:  # noqa: BLE001 - bisection is an error sieve
            if hi - lo == 1:
                outcome.offenders.append((lo, exc))
                return
            mid = (lo + hi) // 2
            run(lo, mid)
            run(mid, hi)
            return
        for i, result in zip(range(lo, hi), sub):
            outcome.results[i] = result

    run(0, len(items))
    return outcome


class BudgetExhausted(Exception):
    """Raised (as an offender error) when bisection hits its retry budget."""


# ---------------------------------------------------------------------------
# Quarantine recorder: in-memory stats for /statusz plus an optional durable
# sink into the quarantined_reports table.  Recording must never take down a
# serving path, so the durable write happens on a background thread and all
# failures are logged-and-counted instead of raised.
# ---------------------------------------------------------------------------

_RECENT_LIMIT = 64


class QuarantineRecorder:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stage_counts: Dict[str, int] = {}
        self._bisections = 0
        self._corrupt_rows = 0
        self._recent: List[Dict[str, object]] = []
        self._sink = None
        self._queue: "queue.Queue[Optional[dict]]" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._sink_errors = 0

    # -- configuration ----------------------------------------------------

    def configure_sink(self, datastore) -> None:
        """Point durable quarantine writes at ``datastore`` (last call wins)."""
        with self._lock:
            self._sink = datastore

    def reset(self) -> None:
        self.drain(timeout=1.0)
        with self._lock:
            self._stage_counts.clear()
            self._recent.clear()
            self._bisections = 0
            self._corrupt_rows = 0
            self._sink = None
            self._sink_errors = 0

    # -- recording --------------------------------------------------------

    def record(
        self,
        stage: str,
        task: Optional[str] = None,
        report_id: Optional[bytes] = None,
        error: Optional[BaseException] = None,
        payload: object = None,
        durable: bool = True,
    ) -> None:
        error_class = type(error).__name__ if error is not None else "unknown"
        digest = payload_digest(payload) if payload is not None else None
        entry = {
            "stage": stage,
            "task": task,
            "report_id": report_id.hex() if report_id else None,
            "error_class": error_class,
            "payload_digest": digest,
        }
        with self._lock:
            self._stage_counts[stage] = self._stage_counts.get(stage, 0) + 1
            self._recent.append(entry)
            del self._recent[:-_RECENT_LIMIT]
            sink = self._sink
        self._bump_metric(stage)
        logger.warning(
            "quarantined report stage=%s task=%s report_id=%s error=%s",
            stage,
            task,
            entry["report_id"],
            error_class,
        )
        if durable and sink is not None:
            self._queue.put(
                {
                    "task": task,
                    "report_id": bytes(report_id) if report_id else None,
                    "stage": stage,
                    "error_class": error_class,
                    "payload_digest": digest,
                }
            )
            self._ensure_worker()

    def note_bisection(self) -> None:
        with self._lock:
            self._bisections += 1
        try:
            from .metrics import GLOBAL_METRICS

            if GLOBAL_METRICS.registry is not None:
                GLOBAL_METRICS.batch_bisections.inc()
        except Exception:  # pragma: no cover - metrics must never break serving
            logger.exception("failed to record bisection metric")

    def note_corrupt_row(self, stage: str = "journal") -> None:
        """Count a checksum-failed durable row (already quarantined in-tx)."""
        with self._lock:
            self._corrupt_rows += 1
            self._stage_counts[stage] = self._stage_counts.get(stage, 0) + 1
        self._bump_metric(stage)
        try:
            from .metrics import GLOBAL_METRICS

            if GLOBAL_METRICS.registry is not None:
                GLOBAL_METRICS.journal_corrupt_rows.inc()
        except Exception:  # pragma: no cover
            logger.exception("failed to record corrupt-row metric")

    def _bump_metric(self, stage: str) -> None:
        try:
            from .metrics import GLOBAL_METRICS

            if GLOBAL_METRICS.registry is not None:
                GLOBAL_METRICS.quarantined_reports.labels(stage=stage).inc()
        except Exception:  # pragma: no cover
            logger.exception("failed to record quarantine metric")

    # -- background sink writer ------------------------------------------

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._worker is not None and self._worker.is_alive():
                return
            self._worker = threading.Thread(
                target=self._drain_loop, name="quarantine-writer", daemon=True
            )
            self._worker.start()

    def _drain_loop(self) -> None:
        while True:
            try:
                row = self._queue.get(timeout=5.0)
            except queue.Empty:
                return
            if row is None:
                self._queue.task_done()
                return
            try:
                with self._lock:
                    sink = self._sink
                if sink is not None:
                    sink.run_tx(
                        "put_quarantined_report",
                        lambda tx: tx.put_quarantined_report(
                            task=row["task"],
                            report_id=row["report_id"],
                            stage=row["stage"],
                            error_class=row["error_class"],
                            payload_digest=row["payload_digest"],
                        ),
                    )
            except Exception:  # noqa: BLE001 - the sink must never crash us
                with self._lock:
                    self._sink_errors += 1
                logger.exception("failed to persist quarantined report")
            finally:
                self._queue.task_done()

    def drain(self, timeout: float = 5.0) -> bool:
        """Block until all queued durable writes have been attempted.

        Test/shutdown helper; returns False on timeout.
        """
        deadline = threading.Event()
        done = threading.Event()

        def waiter() -> None:
            self._queue.join()
            done.set()

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        ok = done.wait(timeout)
        deadline.set()
        return ok

    # -- introspection ----------------------------------------------------

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "stages": dict(sorted(self._stage_counts.items())),
                "total": sum(self._stage_counts.values()),
                "bisections": self._bisections,
                "corrupt_rows": self._corrupt_rows,
                "pending_writes": self._queue.qsize(),
                "sink_errors": self._sink_errors,
                "sink_configured": self._sink is not None,
                "recent": list(self._recent[-8:]),
            }


_RECORDER = QuarantineRecorder()


def recorder() -> QuarantineRecorder:
    return _RECORDER


def configure_sink(datastore) -> None:
    _RECORDER.configure_sink(datastore)


def record(stage: str, **kwargs) -> None:
    _RECORDER.record(stage, **kwargs)


def note_bisection() -> None:
    _RECORDER.note_bisection()


def note_corrupt_row(stage: str = "journal") -> None:
    _RECORDER.note_corrupt_row(stage)


def quarantine_stats() -> Dict[str, object]:
    return _RECORDER.stats()


def reset() -> None:
    _RECORDER.reset()
