"""/statusz snapshot: one JSON document of live fleet state.

The thing an operator curls when a soak wedges (ISSUE 5 tentpole): served
by every binary's health server (binaries/main.py), it assembles the
process-local runtime state (executor buckets, accumulator occupancy,
circuit breakers, fault-registry arm state, trace configuration) plus the
datastore's shared view (outstanding accumulator-journal rows, lease
occupancy, acquirable backlog) into one consistent snapshot.  Everything
here is read-only and cheap — indexed COUNTs and in-memory stats — so
hitting it against a wedged replica never makes things worse.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

logger = logging.getLogger("janus_tpu.statusz")

_PROCESS_START = time.monotonic()


def runtime_status() -> dict:
    """Process-local sections (no datastore): safe to call anywhere."""
    from . import faults
    from .otlp import otlp_health
    from .slo import slo_status
    from .trace import chrome_trace_path, current_trace

    doc: dict = {
        "pid": os.getpid(),
        "uptime_s": round(time.monotonic() - _PROCESS_START, 1),
        "log_level": logging.getLevelName(logging.getLogger().level),
        "trace": {
            "chrome_trace_path": chrome_trace_path(),
            "context": current_trace() or None,
        },
        # OTLP export health (ISSUE 9): queued/dropped/last-export-age, or
        # the explicit unavailable marker when the SDK is absent
        "otlp": otlp_health(),
        # SLO evaluation plane (ISSUE 9): per-objective burn rates and
        # breach state from the sampler-driven evaluator
        "slo": slo_status(),
        "faults": faults.snapshot(),
        # Peer transport health (ISSUE 11): per-peer suspect/probing
        # state + failure counts — the first thing to check when a soak
        # quiesces (partition pressure vs a bug)
        "peers": _peer_stats(),
        # Datastore health (ISSUE 17): the process-wide brownout tracker
        # — state, consecutive/total transient tx failures, suspect
        # transitions — what separates "the fleet froze on purpose" from
        # "the fleet wedged"
        "datastore": _datastore_stats(),
        # Fleet control plane (ISSUE 16): this replica's membership view,
        # owned-task count, and migration total — disabled marker when no
        # router is installed
        "fleet": _fleet_stats(),
        # Upload front door (ISSUE 14): batched-open queue depth, shed
        # counts per reason, and batch/open totals — the overload story
        # at a glance (None on binaries that serve no uploads)
        "upload": _upload_stats(),
        # Zero-copy ingest plane (ISSUE 18): journal-writer depth/sheds,
        # staged-cohort occupancy, and materializer totals — None in
        # synchronous mode or on binaries that serve no uploads
        "ingest": _ingest_stats(),
        # Blast-radius isolation (ISSUE 19): per-stage quarantine counts,
        # bisection sieves run, checksum-failed journal rows, and the most
        # recent offenders — what the operator reads when the quarantine
        # alert fires
        "quarantine": _quarantine_stats(),
        # Canary plane (ISSUE 20): rolled-up fleet verdict, per-family
        # probe state + failing stage, stage-latency percentiles, and
        # counted backoffs — the one pageable signal; disabled marker on
        # binaries that run no prober
        "canary": _canary_stats(),
    }

    from ..executor import peek_global_executor

    ex = peek_global_executor()
    if ex is None:
        doc["executor"] = {"enabled": False}
        doc["accumulator"] = None
    else:
        doc["executor"] = {
            "enabled": True,
            "buckets": ex.stats(),
            "circuits": ex.circuit_stats(),
            # per-shape compile ledger (ISSUE 8): cold / warming / warm
            # (+ last compile_s) / failed, each with the age of its state
            # — the first thing to curl when a fresh task's flushes look
            # slow
            "compile": ex.compile_stats(),
            # why shapes kept exact-shape compiles (ISSUE 9 satellite):
            # pow2-canonicalization plan outcomes, counted per reason
            "canonicalization": _canonicalization_stats(),
            # flight recorder (ISSUE 12): the last flushes' black-box
            # records + dump counters — what the operator reads when a
            # breaker tripped or a flush went anomalously slow
            "flights": ex.flight_stats(),
            # per-task cost-attribution ledger occupancy: tracked labels
            # vs the cardinality cap, and how much landed on "other"
            "cost_attribution": _cost_stats(),
            # shape buckets quarantined to the oracle (ISSUE 19) + the
            # per-shape failure streaks feeding the quarantine gate
            "bucket_quarantine": ex.bucket_quarantine_stats(),
        }
        doc["accumulator"] = (
            ex.accumulator.stats() if ex.accumulator is not None else None
        )
    return doc


def _peer_stats() -> dict:
    """Per-peer transport health (core/peer_health.py); failure-tolerant
    like every other section — introspection must never take /statusz
    down."""
    try:
        from .peer_health import tracker

        return tracker().stats()
    except Exception:
        logger.exception("peer-health stats unavailable")
        return {"error": "unavailable"}


def _datastore_stats() -> dict:
    """Process-wide datastore brownout tracker (core/db_health.py);
    failure-tolerant like every other section — and deliberately
    process-local, so it renders even while the datastore itself is the
    thing that's down."""
    try:
        from .db_health import tracker

        return tracker().stats()
    except Exception:
        logger.exception("datastore-health stats unavailable")
        return {"error": "unavailable"}


def _fleet_stats() -> dict:
    """Fleet router view (core/fleet.py); failure-tolerant like every
    other section."""
    try:
        from .fleet import fleet_router

        router = fleet_router()
        if router is None:
            return {"enabled": False}
        return router.stats()
    except Exception:
        logger.exception("fleet stats unavailable")
        return {"error": "unavailable"}


def _upload_stats():
    """Front-door open-batcher stats (aggregator/report_writer.py);
    failure-tolerant like every other section."""
    try:
        from ..aggregator.report_writer import frontdoor_stats

        return frontdoor_stats()
    except Exception:
        logger.exception("upload front-door stats unavailable")
        return {"error": "unavailable"}


def _ingest_stats():
    """Ingest-plane stats (core/ingest.py); failure-tolerant like every
    other section."""
    try:
        from .ingest import ingest_stats

        return ingest_stats()
    except Exception:
        logger.exception("ingest stats unavailable")
        return {"error": "unavailable"}


def _quarantine_stats() -> dict:
    """Poison/corruption quarantine stats (core/quarantine.py);
    failure-tolerant like every other section."""
    try:
        from .quarantine import quarantine_stats

        return quarantine_stats()
    except Exception:
        logger.exception("quarantine stats unavailable")
        return {"error": "unavailable"}


def _canary_stats() -> dict:
    """Canary-plane verdict rollup (core/canary.py); failure-tolerant
    like every other section."""
    try:
        from .canary import canary_stats

        return canary_stats()
    except Exception:
        logger.exception("canary stats unavailable")
        return {"error": "unavailable"}


def _cost_stats() -> dict:
    """Per-task cost-attribution occupancy (core/costs.py); failure-
    tolerant like every other section."""
    try:
        from .costs import cost_model

        return cost_model().stats()
    except Exception:
        logger.exception("cost-attribution stats unavailable")
        return {"error": "unavailable"}


def _canonicalization_stats() -> dict:
    """Counted canonicalization-plan outcomes (vdaf/canonical.py); lazy
    and failure-tolerant — control-plane binaries may never import the
    vdaf layer, and /statusz must not force (or break on) it."""
    try:
        from ..vdaf.canonical import plan_stats

        return plan_stats()
    except Exception:
        logger.exception("canonicalization stats unavailable")
        return {"error": "unavailable"}


async def statusz_snapshot(datastore=None, clock=None) -> dict:
    """The full document; ``datastore`` adds the shared-state sections
    (journal, leases, acquirable backlog)."""
    doc = runtime_status()
    if datastore is None:
        doc["journal"] = None
        doc["leases"] = None
        return doc

    def q(tx):
        count, oldest = tx.accumulator_journal_stats()
        r_count, r_oldest = tx.report_journal_stats()
        # lease_summary carries the per-type 'acquirable' counts — it is
        # the single read-side source for the acquisition predicate
        return {
            "journal_rows": count,
            "journal_oldest": oldest,
            "report_journal_rows": r_count,
            "report_journal_oldest": r_oldest,
            "quarantined_rows": tx.count_quarantined_reports(),
            "leases": tx.lease_summary(),
        }

    try:
        shared = await datastore.run_tx_async("statusz", q)
    except Exception:
        # a wedged datastore must not take /statusz down with it — the
        # process-local sections are exactly what the operator needs then
        logger.exception("statusz datastore sections unavailable")
        doc["journal"] = {"error": "datastore unavailable"}
        doc["report_journal"] = {"error": "datastore unavailable"}
        doc["leases"] = {"error": "datastore unavailable"}
        return doc
    now_s = (clock or datastore.clock).now().seconds
    oldest = shared["journal_oldest"]
    doc["journal"] = {
        "outstanding_rows": shared["journal_rows"],
        "oldest_age_s": max(0, now_s - oldest) if oldest is not None else None,
    }
    # report journal (ISSUE 18): ACKed-but-unmaterialized reports.  A
    # rising oldest-age means the materializer stopped (or a journaled
    # replica died and nothing has replayed its rows yet).
    r_oldest = shared["report_journal_oldest"]
    doc["report_journal"] = {
        "outstanding_rows": shared["report_journal_rows"],
        "oldest_age_s": (
            max(0, now_s - r_oldest) if r_oldest is not None else None
        ),
    }
    # durable offender ledger row count rides on the process-local
    # quarantine section (the in-memory stats cover this process only)
    if isinstance(doc.get("quarantine"), dict):
        doc["quarantine"]["durable_rows"] = shared["quarantined_rows"]
    doc["leases"] = shared["leases"]
    return doc


def sample_status_metrics(datastore, clock=None) -> None:
    """One status-sampler tick (synchronous; run from an executor thread):
    publish the sampled queue-depth/freshness gauges and retire idle
    executor buckets.  Driven by the binaries' main loops on
    ``common.status_sample_interval_s``."""
    from .metrics import GLOBAL_METRICS

    # BEFORE the datastore query: peer-health gauges must refresh (the
    # time-driven suspect->probing transition has no traffic to publish
    # it) even while the datastore is wedged
    try:
        from .peer_health import tracker

        tracker().republish_metrics()
    except Exception:
        logger.exception("peer-health republish failed")
    # same story for the datastore tracker: suspect->probing is purely
    # time-driven, and during a brownout there may be no committing
    # transaction to republish the gauge
    try:
        from .db_health import tracker as db_tracker

        db_tracker().republish_metrics()
    except Exception:
        logger.exception("datastore-health republish failed")

    def q(tx):
        count, oldest = tx.accumulator_journal_stats()
        return count, oldest, tx.lease_summary()

    count, oldest, leases = datastore.run_tx("status_sample", q)
    if GLOBAL_METRICS.registry is not None:
        now_s = (clock or datastore.clock).now().seconds
        GLOBAL_METRICS.journal_outstanding_rows.set(count)
        GLOBAL_METRICS.journal_oldest_age.set(
            max(0, now_s - oldest) if oldest is not None else 0
        )
        for job_type, summary in leases.items():
            GLOBAL_METRICS.acquirable_jobs.labels(job_type=job_type).set(
                summary["acquirable"]
            )


def retire_idle_executor_buckets(max_idle_s: float) -> int:
    """Sampler-tick companion: cap bucket-gauge cardinality (ISSUE 5
    satellite).  No-op when no executor exists in this process.  The
    per-task cost series (ISSUE 12) retire on the same tick and the same
    idle threshold — their cardinality cap depends on it."""
    from .costs import retire_idle_task_series

    try:
        retire_idle_task_series(max_idle_s)
    except Exception:
        logger.exception("cost-series retirement failed")
    from ..executor import peek_global_executor

    ex = peek_global_executor()
    if ex is None or max_idle_s <= 0:
        return 0
    return ex.retire_idle_buckets(max_idle_s)
