"""The zero-copy ingest plane (ISSUE 18): upload -> staging handoff with a
write-behind report journal.

The synchronous pipeline re-materializes every report at each hop:
batched HPKE open (ISSUE 14) -> ``put_client_report`` commit -> creator
claim/read-back -> driver -> executor.  This module collapses the middle:
the upload front door hands freshly opened, validated shares DIRECTLY to
the aggregation pipeline's staging side — pre-bucketed by (task, vdaf
shape) the way the executor's stage/launch split buckets device work —
while the authoritative client_reports write becomes a WRITE-BEHIND
journal flushed by a bounded background writer.

Durability contract (the non-negotiable half): a report is ACKed to its
client only after its journal row is durable.  The journal-flush
transaction is the durability ACK *and* the only place report_success is
counted; everything downstream — materialization into client_reports,
direct staged-cohort packing, crash replay — consumes the row without
touching counters.  Write-behind applies to the *aggregation visibility*
path only, never to the ACK.

Exactly-once across the reordering hangs on one linearization point, the
same one the accumulator journal uses (executor/accumulator.py):
``delete_report_journal_row`` returns whether THIS transaction consumed
the row, and the loser of a consume race MUST NOT write anything for the
report.  Every consumer follows it:

- the background materializer moves rows into client_reports (a pure
  ciphertext column copy — the share is encrypted under the
  client_reports AAD precisely so this hop never decrypts);
- the staged-cohort consumer (aggregation_job_creator.run_staged_once)
  deletes the row and inserts a born-scrubbed client_reports tombstone in
  the same transaction that packs the report into a job;
- crash replay (a restarted replica, or any creator's pre-pass over rows
  older than a grace) is just the materializer under another scheduler —
  which is also the migration handoff: a cohort staged on replica A is
  collectable after A dies because its journal rows are global state.

Backpressure composes with ISSUE 14 admission control: the journal
writer's queue is bounded, and :meth:`IngestPlane.admit` sheds 503 +
Retry-After (reason="journal") past the bound — a wedged journal writer
degrades to counted sheds, never unbounded memory.  The staging buffer is
bounded separately and OVERFLOWS TO THE JOURNAL, not to the client:
reports that do not fit simply reach aggregation through the
materializer's read-back path (counted path="readback").
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import random
import time
import zlib
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("janus_tpu.ingest")

#: The process's ingest plane, registered at construction so /statusz can
#: render journal depth / staged occupancy without holding the Aggregator
#: (the UploadOpenBatcher._FRONTDOOR pattern; one serving plane per
#: process, tests that build several see the most recent).
_INGEST: Optional["IngestPlane"] = None


def ingest_stats() -> Optional[dict]:
    """The /statusz "ingest" section (None when no plane exists —
    synchronous mode, or binaries that serve no uploads)."""
    return _INGEST.stats() if _INGEST is not None else None


def _shape_digest(shape_key) -> str:
    """Stable 6-hex digest of a vdaf shape key — the executor's bucket
    labeling scheme (executor/service.py _shape_digest), imported when the
    executor is present so the two label spaces cannot drift, recomputed
    identically when it is not (control-plane binaries never pay the
    executor import)."""
    try:
        from ..executor.service import _shape_digest as ex_digest

        return ex_digest(shape_key)
    except Exception:
        return "%06x" % (zlib.crc32(repr(shape_key).encode()) & 0xFFFFFF)


class IngestPlane:
    """The journaled ingest mode's moving parts: the bounded write-behind
    journal writer (the ReportWriteBatcher size/delay shape, flush-
    generation guard included), the bounded in-memory staging buffer, and
    the background materializer.

    ``submit()`` is the upload handler's write seam: it resolves when the
    report's journal row is DURABLE (the ACK point).  On each committed
    flush the fresh reports are handed to the staging buffer, bucketed by
    (task, vdaf shape); ``take_staged()`` is the in-process job creator's
    consumption point.  ``materialize_once()`` drains journal rows into
    client_reports for everything that did not go direct."""

    def __init__(
        self,
        datastore,
        max_batch_size: int = 100,
        max_write_delay: float = 0.05,
        queue_max: int = 2048,
        counter_shard_count: int = 8,
        stage_direct: bool = True,
        stage_max_reports: int = 4096,
    ):
        self.datastore = datastore
        self.max_batch_size = max_batch_size
        self.max_write_delay = max_write_delay
        self.queue_max = queue_max
        self.counter_shard_count = counter_shard_count
        self.stage_direct = stage_direct
        self.stage_max_reports = stage_max_reports
        #: (report, shape_key, waiter, enqueue-monotonic)
        self._queue: List[Tuple[object, object, asyncio.Future, float]] = []
        #: detached-but-uncommitted flushes: seq -> row count.  The
        #: admission bound must count these (the ISSUE 14 lesson: the
        #: staging queue drains into flight at batch granularity, so on
        #: its own it never reaches a real bound while a slow writer
        #: piles work up).
        self._inflight: Dict[int, int] = {}
        self._flush_seq = 0
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        #: flush generation (the ReportWriteBatcher stale-timer guard): an
        #: armed timer carries the generation it was armed for, and a
        #: fired flush whose generation has moved on is a no-op.
        self._flush_gen = 0
        self._lock = asyncio.Lock()
        #: (task_id.data, shape digest) -> staged reports awaiting direct
        #: consumption.  Bounded by stage_max_reports; overflow reports
        #: are simply not staged (their journal rows reach aggregation
        #: through the materializer's read-back path).
        self._staged: Dict[Tuple[bytes, str], List[object]] = {}
        self._staged_count = 0
        self._sheds = 0
        self._flushes = 0
        self._journaled = 0
        self._staged_total = 0
        self._overflow_total = 0
        self._materialized_total = 0
        global _INGEST
        _INGEST = self

    # -- admission control ------------------------------------------------
    def queue_depth(self) -> int:
        """Reports pending anywhere before durability: staged for flush +
        detached into an in-flight flush transaction."""
        return len(self._queue) + sum(self._inflight.values())

    def admit(self) -> None:
        """Raise :class:`UploadShed` when the journal writer is past its
        depth budget — counted as reason="journal" in
        janus_upload_shed_total.  Composes with (runs after) the front
        door's open-queue admission gate."""
        if self.queue_max <= 0 or self.queue_depth() < self.queue_max:
            return
        from ..aggregator.error import UploadShed
        from .metrics import GLOBAL_METRICS

        self._sheds += 1
        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.upload_sheds.labels(reason="journal").inc()
        raise UploadShed("report-journal writer over depth budget; retry")

    # -- the write-behind ACK path ---------------------------------------
    async def submit(self, report, shape_key=None) -> None:
        """Enqueue a validated report; resolves when its journal row is
        durable — the client's ACK point.  Mirrors
        ReportWriteBatcher.write_report's trace adoption so every
        journaled report carries a 32-hex upload trace.

        ``shape_key`` is the staging bucket identity (the task's vdaf
        shape); None marks the report journal-only — it is never staged
        and reaches aggregation through the materializer (agg-param and
        FixedSize tasks, whose jobs the direct path cannot create)."""
        if report.trace_id is None:
            from .trace import current_trace, new_trace_id

            report = dataclasses.replace(
                report,
                trace_id=current_trace().get("trace_id") or new_trace_id(),
            )
        fut = asyncio.get_running_loop().create_future()
        async with self._lock:
            self._queue.append((report, shape_key, fut, time.monotonic()))
            self._publish_depth()
            if len(self._queue) >= self.max_batch_size:
                await self._flush_locked()
            elif self._flush_handle is None:
                loop = asyncio.get_running_loop()
                gen = self._flush_gen
                self._flush_handle = loop.call_later(
                    self.max_write_delay,
                    lambda: asyncio.ensure_future(self._flush(gen)),
                )
        await fut

    async def _flush(self, gen: Optional[int] = None) -> None:
        async with self._lock:
            if gen is not None and gen != self._flush_gen:
                return  # stale timer (see ReportWriteBatcher._flush)
            await self._flush_locked()

    async def _flush_locked(self) -> None:
        """Detach the pending cohort and run its journal transaction
        off-lock, so flushes overlap the way open batches do."""
        self._flush_gen += 1
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        batch, self._queue = self._queue, []
        if not batch:
            self._publish_depth()
            return
        seq = self._flush_seq
        self._flush_seq += 1
        self._inflight[seq] = len(batch)
        self._publish_depth()
        asyncio.ensure_future(self._run_flush(batch, seq))

    async def _run_flush(self, batch, seq: int) -> None:
        from ..datastore import TaskUploadCounter, TxConflict
        from . import faults
        from .metrics import GLOBAL_METRICS

        # In-batch dedup by (task, report id): first wins, dups succeed as
        # idempotent uploads (the ReportWriteBatcher contract).
        seen: Dict[bytes, int] = {}
        unique: List[Tuple[object, object, List[asyncio.Future], float]] = []
        for report, shape_key, fut, enqueued in batch:
            key = report.task_id.data + report.report_id.data
            if key in seen:
                unique[seen[key]][2].append(fut)
            else:
                seen[key] = len(unique)
                unique.append((report, shape_key, [fut], enqueued))

        def tx_fn(tx):
            fresh = []
            shard = random.randrange(self.counter_shard_count)
            for report, _shape, _futs, _enq in unique:
                # A report already materialized in client_reports is a
                # cross-path duplicate (synchronous-mode replica, retried
                # upload after its row was consumed): idempotent success,
                # and CRITICALLY no counter — report_success was settled
                # when it was first journaled/committed.
                if tx.check_client_report_exists(report.task_id, report.report_id):
                    fresh.append(False)
                    continue
                try:
                    tx.put_report_journal_row(report)
                    tx.increment_task_upload_counter(
                        report.task_id,
                        shard,
                        TaskUploadCounter(report.task_id, report_success=1),
                    )
                    fresh.append(True)
                except TxConflict:
                    # journal-row duplicate: idempotent success
                    fresh.append(False)
            return fresh

        t0 = time.monotonic()
        try:
            # Failure-domain boundary: an injected ingest.journal fault
            # impersonates a journal-commit failure — fanned to every
            # waiting ACK exactly like a real one (clients retry).
            await faults.fire_async("ingest.journal")
            fresh = await self.datastore.run_tx_async("ingest_journal", tx_fn)
        except BaseException as e:
            # Belt and suspenders (the ISSUE 14 _run_batch contract): a
            # stranded upload handler is the one unacceptable outcome, so
            # even a non-Exception escape fans to every waiter first.
            for _report, _shape, futs, _enq in unique:
                for fut in futs:
                    if not fut.done():
                        fut.set_exception(
                            e if isinstance(e, Exception) else RuntimeError(str(e))
                        )
            if not isinstance(e, Exception):
                raise
            return
        finally:
            self._inflight.pop(seq, None)
            self._publish_depth()

        from .trace import emit_span

        committed = time.monotonic()
        have_metrics = GLOBAL_METRICS.registry is not None
        now_s = self.datastore.now().seconds if have_metrics else 0
        if have_metrics:
            GLOBAL_METRICS.ingest_journal_flush_seconds.observe(committed - t0)
        self._flushes += 1
        accepted = 0
        for (report, shape_key, futs, enqueued), is_fresh in zip(unique, fresh):
            if have_metrics:
                accepted += 1
                # The same SLO inputs the synchronous writer feeds — in
                # journaled mode "commit" means the durability ACK, which
                # is exactly what the client experiences.
                GLOBAL_METRICS.report_commit_age.observe(
                    max(0.0, float(now_s - report.time.seconds))
                )
                GLOBAL_METRICS.upload_to_commit.observe(
                    max(0.0, committed - enqueued)
                )
            emit_span(
                "upload_commit",
                "upload",
                enqueued,
                committed - enqueued,
                trace_id=report.trace_id,
                task_id=str(report.task_id),
            )
            if is_fresh:
                self._journaled += 1
                self._stage(report, shape_key)
            for fut in futs:
                if not fut.done():
                    fut.set_result(None)
        if have_metrics:
            GLOBAL_METRICS.upload_outcomes.labels(decision="accepted").inc(accepted)

    def _publish_depth(self) -> None:
        from .metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.ingest_journal_depth.set(self.queue_depth())

    # -- the staging side -------------------------------------------------
    def _stage(self, report, shape_key) -> None:
        """Hand one durably journaled report to the staging buffer.  Over
        the bound (or with direct staging off, or shape_key None) the
        report is simply not staged: its journal row reaches aggregation
        through the materializer — overflow degrades to read-back, never
        to memory."""
        if (
            shape_key is None
            or not self.stage_direct
            or self._staged_count >= self.stage_max_reports
        ):
            self._overflow_total += 1
            return
        bucket = (report.task_id.data, _shape_digest(shape_key))
        self._staged.setdefault(bucket, []).append(report)
        self._staged_count += 1
        self._staged_total += 1

    def take_staged(self):
        """Detach every staged cohort: [(task_id, shape_digest, reports)].
        The caller (the in-process creator's staged pass) owns consumption
        from here; reports it cannot consume simply stay journaled and
        fall to the materializer."""
        cohorts = []
        staged, self._staged = self._staged, {}
        self._staged_count = 0
        for (task_data, shape), reports in staged.items():
            from ..messages import TaskId

            cohorts.append((TaskId(task_data), shape, reports))
        return cohorts

    # -- the background materializer --------------------------------------
    async def materialize_once(self, limit: int = 256) -> Tuple[int, int]:
        """One bounded write-behind pass: move up to ``limit`` journal
        rows into client_reports (ciphertext column copies, no decrypt)
        and consume them.  Returns (consumed, materialized); materialized
        rows are counted path="readback" — they will reach aggregation
        through the classic creator claim."""
        from .metrics import GLOBAL_METRICS

        consumed, materialized = await self.datastore.run_tx_async(
            "ingest_materialize",
            lambda tx: tx.materialize_report_journal_rows(limit),
        )
        self._materialized_total += materialized
        if materialized and GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.ingest_staged_total.labels(path="readback").inc(
                materialized
            )
        return consumed, materialized

    async def drain(self) -> None:
        """Graceful-shutdown drain: flush whatever is queued, then
        materialize the journal backlog (bounded loop).  Rows that remain
        (e.g. the datastore died too) are exactly what crash replay
        exists for."""
        try:
            await self._flush()
            for _ in range(64):
                consumed, _materialized = await self.materialize_once()
                if consumed == 0:
                    break
        except Exception:
            logger.exception("ingest drain left journal rows for replay")

    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        return {
            "mode": "journaled",
            "queue_depth": self.queue_depth(),
            "staged_flush": len(self._queue),
            "inflight_flush": sum(self._inflight.values()),
            "queue_max": self.queue_max,
            "sheds": self._sheds,
            "flushes": self._flushes,
            "journaled": self._journaled,
            "stage_direct": self.stage_direct,
            "staged_reports": self._staged_count,
            "staged_buckets": len(self._staged),
            "staged_total": self._staged_total,
            "stage_overflow_total": self._overflow_total,
            "materialized_total": self._materialized_total,
        }


async def replay_report_journal(datastore, batch_size: int = 256) -> int:
    """Startup/crash replay: materialize EVERY outstanding journal row
    into client_reports (bounded batches so one huge backlog cannot hold
    a transaction open forever).  Returns rows materialized.  Safe to run
    concurrently with live consumers on any replica — the per-row delete
    is the linearization point, so a row consumed elsewhere mid-replay is
    simply skipped."""
    from .metrics import GLOBAL_METRICS

    total = 0
    while True:
        consumed, materialized = await datastore.run_tx_async(
            "report_journal_replay",
            lambda tx: tx.materialize_report_journal_rows(batch_size),
        )
        total += materialized
        if materialized and GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.ingest_journal_replayed.inc(materialized)
        if consumed < batch_size:
            return total
