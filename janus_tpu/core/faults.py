"""Process-wide deterministic fault injection.

Janus inherits crash-tolerance from its lease machinery — an expired
lease makes any job re-acquirable by any replica (SURVEY.md §5) — but
the TPU port adds failure domains the reference never had: device
launches can fail or hang, the executor can backpressure, and the
datastore/HTTP seams sit under far more concurrent traffic.  This module
makes failure a first-class, *testable* input: named injection points at
every failure-domain boundary, driven by one seeded registry so a chaos
run replays bit-for-bit.

Injection points wired into the tree (the names are a public contract;
tests/test_chaos.py cross-checks them):

    ``datastore.tx.begin``   before BEGIN in ``Datastore.run_tx``
    ``datastore.tx.commit``  after the tx body, before COMMIT
    ``http.request``         before each attempt in ``retry_http_request``
    ``executor.flush``       at the head of a DeviceExecutor flush
    ``backend.launch``       in ``TpuBackend.launch_prep_init_multi``
    ``backend.device_lost``  same site, impersonating a lost mesh device
    ``backend.combine``      in ``TpuBackend.prep_shares_to_prep_batch``
    ``clock.skew``           sampled by ``SkewedClock.now``
    ``upload.open``          head of each batched HPKE-open pass
                             (UploadOpenBatcher worker thread)
    ``report_writer.flush``  before a ReportWriteBatcher batch commit
    ``gc.run``               per-task GC pass (GarbageCollector._gc_task)
    ``key_rotator.run``      at the head of an HpkeKeyRotator tick
    ``accumulator.spill``    before an accumulator bucket's drain readback
    ``accumulator.evict``    before an LRU eviction spills state to host
    ``accumulator.replay``   before a collection-time journal replay

Modes: ``error`` raises :class:`FaultInjectedError`, ``delay`` sleeps
``delay_s``, ``hang`` sleeps ``hang_s`` (long enough to trip whatever
timeout guards the call site), ``skew`` offsets a clock by up to
``skew_s`` seconds in either direction, ``corrupt`` bit-flips or
truncates a durable payload at a ``corrupt_bytes()`` call site (the
data failure domain — ISSUE 19).  Each point draws from its own
``random.Random`` seeded by ``(seed, point)``, so per-point decision
sequences are reproducible regardless of how threads interleave across
points.

Connectivity modes (ISSUE 11 — the network failure domain):

``blackhole``
    The peer never answers: the hook parks until the CALL SITE's own
    deadline cancels it (async contexts — ``asyncio.wait_for`` around
    the attempt cancels the sleep), with ``hang_s`` as a backstop after
    which a :class:`FaultInjectedTransportError` fires (the OS
    eventually giving up on the socket).  Distinct from ``hang``, which
    sleeps a FIXED duration and then lets the call proceed.
``reset``
    Transport failure mid-exchange: raises
    :class:`FaultInjectedTransportError` (a ``ConnectionResetError``
    subclass), so call sites — and the peer-health tracker — classify
    it exactly like a real socket reset.
``flap``
    Seeded on/off connectivity schedule: the link alternates healthy /
    partitioned phases whose durations are drawn deterministically from
    ``(seed, point)`` around ``flap_period_s`` (see
    :class:`FlapSchedule`); while "up" (partitioned) the spec behaves
    like ``reset``, while "down" traffic flows.  Two registries with
    one seed flap identically.

Target scoping: a spec may carry ``target`` — a substring matched
against the context string the call site passes to ``fire()`` (for
``http.request`` that is the request URL, so a partition can be scoped
to ONE direction of the leader<->helper pair by the peer's host:port).
A scoped spec is consulted — and its RNG rolled — only for matching
calls, so per-point decision sequences stay deterministic per traffic
direction.  Specs without a target match every call, scoped or not.

Activation is config-only (``binaries/config.py`` ``fault_injection:``,
default fully off) or programmatic (:func:`configure`, used by tests).
When off, every hook is a module-call + one attribute check — nothing is
sampled, nothing is allocated.
"""

from __future__ import annotations

import asyncio
import bisect
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

#: The points wired into the tree today.  configure() accepts unknown
#: names (new points must not require a lockstep edit here), but the
#: chaos suite asserts this list stays in sync with the call sites.
KNOWN_POINTS = (
    "datastore.tx.begin",
    "datastore.tx.commit",
    "http.request",
    "executor.flush",
    "backend.launch",
    "backend.combine",
    # mesh failure domain (vdaf/backend.py launch path): impersonates a
    # chip dropping out of the mesh mid-launch (ICI link loss, plugin
    # eviction).  Distinct from backend.launch so chaos runs can target
    # "device lost" specifically; the executor's per-MESH breaker (every
    # mesh-backed shape shares one circuit) is what this point exercises.
    "backend.device_lost",
    "clock.skew",
    # upload front door (ISSUE 14): head of each batched HPKE-open pass
    # (UploadOpenBatcher's worker thread) — delay mode backs the bounded
    # queue up into load sheds, error mode exercises the per-report
    # inline fallback
    "upload.open",
    # maintenance loops (ISSUE 3 satellite: ROADMAP chaos follow-on)
    "report_writer.flush",
    "gc.run",
    "key_rotator.run",
    # device-resident accumulator store (executor/accumulator.py): fired
    # at the commit-time/eviction spill boundaries so ./ci.sh chaos
    # exercises mid-spill failures (oracle replay, no double count)
    "accumulator.spill",
    "accumulator.evict",
    # collection-time journal replay (collection_job_driver.py): a
    # survivor re-deriving a dead replica's un-drained shares must itself
    # be crash-safe (the replay tx is the exactly-once point)
    "accumulator.replay",
    # write-behind report journal (core/ingest.py, ISSUE 18): head of each
    # journal-flush transaction — delay mode wedges the writer so the
    # bounded queue backs up into reason="journal" sheds, error mode
    # impersonates a commit failure fanned to every waiting ACK
    "ingest.journal",
    # leader aggregate-share corruption (collection_job_driver.py, ISSUE
    # 20): a corrupt-mode spec here bit-flips or truncates the encoded
    # leader aggregate share as the collection job finishes — the WRONG-
    # ANSWER fault only the canary plane's known-plaintext verification
    # can catch (outcome="corrupt"); every other signal stays green
    "collection.aggregate_share",
    # durable-row corruption (datastore journal writes, ISSUE 19): a
    # corrupt-mode spec here bit-flips or truncates journal payload bytes
    # AFTER the row CRC is computed — impersonating a torn write / media
    # bit rot that the materialize/replay checksum pass must catch
    "journal.corrupt",
)

MODES = ("error", "delay", "hang", "skew", "blackhole", "reset", "flap", "corrupt")


class FaultInjectedError(Exception):
    """An ``error``-mode injection fired.

    Call sites treat it like the transient infrastructure failure it
    impersonates: the datastore retry loop classifies it retryable, the
    HTTP retry loop retries it, and the executor surfaces it as a launch
    failure (counted by the circuit breaker).
    """

    def __init__(self, point: str):
        super().__init__(f"injected fault at {point}")
        self.point = point


class FaultInjectedTransportError(FaultInjectedError, ConnectionResetError):
    """A ``reset``/``flap`` injection (or a ``blackhole`` backstop)
    fired: impersonates a TRANSPORT-layer failure — connection reset by
    peer — so call sites that classify socket errors (the HTTP retry
    loop, the peer-health tracker) treat it exactly like the real thing,
    while chaos harnesses can still catch it as a FaultInjectedError."""


@dataclass
class FaultSpec:
    """One armed fault: fire at ``point`` with ``probability`` per call."""

    point: str
    mode: str = "error"
    probability: float = 1.0
    #: delay-mode sleep
    delay_s: float = 0.01
    #: hang-mode sleep — size it against the call site's timeout guard.
    #: For blackhole mode this is the BACKSTOP: the hook parks until the
    #: call site's deadline cancels it, and only a site with no deadline
    #: at all waits this long before the transport error fires.
    hang_s: float = 3600.0
    #: skew-mode magnitude: offsets sampled uniformly in [-skew_s, +skew_s]
    skew_s: int = 0
    #: target scope: when set, the spec is consulted only for calls whose
    #: target context (e.g. the http.request URL) CONTAINS this substring
    #: — the asymmetric-partition primitive (scope one direction of the
    #: leader<->helper pair by the peer's host:port).  None = every call.
    target: Optional[str] = None
    #: flap-mode mean phase duration: each healthy/partitioned phase
    #: lasts uniform(0.5, 1.5) x this, drawn from the seeded schedule.
    flap_period_s: float = 1.0

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(f"unknown fault mode {self.mode!r} (one of {MODES})")
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError(f"probability {self.probability} outside [0, 1]")
        if self.mode == "flap" and self.flap_period_s <= 0:
            raise ValueError("flap_period_s must be positive")


class FlapSchedule:
    """Deterministic alternating connectivity schedule for ``flap``
    specs: phase 0 is DOWN (healthy — arming a flap must not partition
    the link at t=0), then UP (partitioned), alternating; each phase
    lasts ``uniform(0.5, 1.5) * period_s`` drawn from a Random seeded by
    ``(seed, point)``.  Same seed => identical schedule, which is what
    lets a chaos run replay a flapping link bit-for-bit."""

    def __init__(self, seed: int, point: str, period_s: float, salt: int = 0):
        import random

        # ``salt`` (the spec's index within its point) gives each armed
        # flap spec an INDEPENDENT schedule: two target-scoped flap
        # specs modeling separately flapping directions must not
        # partition/heal in lockstep
        self._r = random.Random(
            (((seed << 32) ^ zlib.crc32(point.encode())) ^ 0x464C4150)  # "FLAP"
            + salt * 0x9E3779B1
        )
        self.period_s = period_s
        #: cumulative phase-end times; index 0 ends the first DOWN phase
        self._toggles: List[float] = [self._next_phase()]
        #: phases pruned off the front (parity bookkeeping): ``up()`` is
        #: called under the registry lock on every fire, so a multi-hour
        #: soak must not grow (or bisect) an unbounded toggle list
        self._dropped = 0

    def _next_phase(self) -> float:
        return self._r.uniform(0.5, 1.5) * self.period_s

    def up(self, elapsed_s: float) -> bool:
        """Is the link partitioned at ``elapsed_s`` since arming?
        Registry call sites pass monotonically nondecreasing elapsed
        times; probes older than the pruned window are not supported."""
        while self._toggles[-1] <= elapsed_s:
            self._toggles.append(self._toggles[-1] + self._next_phase())
        i = bisect.bisect_right(self._toggles, elapsed_s)
        up = (self._dropped + i) % 2 == 1
        if i > 64:
            self._dropped += i - 1
            del self._toggles[: i - 1]
        return up


class FaultRegistry:
    """Seeded spec store + the fire() sampling loop.  One per process."""

    def __init__(self):
        self.active = False
        self._specs: Dict[str, List[FaultSpec]] = {}
        self._rngs: Dict[str, "_PointRng"] = {}
        self._seed = 0
        self._lock = threading.Lock()
        #: point -> number of faults actually injected (not calls checked)
        self.hits: Dict[str, int] = {}
        #: (point, spec index) -> FlapSchedule; epoch anchors elapsed time
        self._flaps: Dict[Tuple[str, int], FlapSchedule] = {}
        self._epoch = 0.0

    # -- arming ---------------------------------------------------------
    def configure(self, specs: Sequence[FaultSpec], seed: int = 0) -> None:
        """Arm ``specs``; replaces any previous configuration."""
        with self._lock:
            self._specs = {}
            for spec in specs:
                self._specs.setdefault(spec.point, []).append(spec)
            self._seed = seed
            self._rngs = {}
            self.hits = {}
            self._flaps = {}
            self._epoch = time.monotonic()
            self.active = bool(self._specs)

    def clear(self) -> None:
        with self._lock:
            self._specs = {}
            self._rngs = {}
            self._flaps = {}
            self.active = False

    def snapshot(self) -> dict:
        """Arm-state introspection for /statusz: armed flag, seed, the
        per-point spec modes, and injection hit counts so an operator can
        see at a glance whether a wedged soak is chaos pressure or a bug."""
        with self._lock:
            return {
                "armed": self.active,
                "seed": self._seed,
                "points": {
                    point: [
                        {
                            "mode": s.mode,
                            "probability": s.probability,
                            # target scope rendered so an operator can see
                            # WHICH direction of a partition is armed
                            **({"target": s.target} if s.target else {}),
                            **(
                                {"flap_period_s": s.flap_period_s}
                                if s.mode == "flap"
                                else {}
                            ),
                        }
                        for s in specs
                    ]
                    for point, specs in sorted(self._specs.items())
                },
                "hits": dict(self.hits),
            }

    # -- sampling -------------------------------------------------------
    def _decide(
        self, point: str, target: Optional[str] = None
    ) -> Optional[FaultSpec]:
        """Roll each of the point's specs in order; first hit wins.
        Per-point RNGs keyed by (seed, point) keep decision sequences
        deterministic even when threads interleave across points.
        Target-scoped specs are skipped — WITHOUT consuming a roll — for
        calls whose target context does not contain their substring, and
        a flap spec whose schedule is in a healthy phase hits nothing."""
        with self._lock:
            specs = self._specs.get(point)
            if not specs:
                return None
            rng = self._rngs.get(point)
            if rng is None:
                rng = _PointRng(self._seed, point)
                self._rngs[point] = rng
            for idx, spec in enumerate(specs):
                if spec.target is not None and (
                    target is None or spec.target not in target
                ):
                    continue
                if rng.roll() >= spec.probability:
                    continue
                if spec.mode == "flap":
                    flap = self._flaps.get((point, idx))
                    if flap is None:
                        flap = FlapSchedule(
                            self._seed, point, spec.flap_period_s, salt=idx
                        )
                        self._flaps[(point, idx)] = flap
                    if not flap.up(time.monotonic() - self._epoch):
                        continue  # healthy phase: the link carries traffic
                self.hits[point] = self.hits.get(point, 0) + 1
                return spec
            return None

    def _record(self, spec: FaultSpec) -> None:
        from .metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.faults_injected.labels(
                point=spec.point, mode=spec.mode
            ).inc()

    def fire(self, point: str, target: Optional[str] = None) -> None:
        """Synchronous hook (thread contexts: datastore, launch pools)."""
        spec = self._decide(point, target)
        if spec is None:
            return
        self._record(spec)
        if spec.mode == "error":
            raise FaultInjectedError(point)
        if spec.mode in ("reset", "flap"):
            raise FaultInjectedTransportError(point)
        if spec.mode == "delay":
            time.sleep(spec.delay_s)
        elif spec.mode == "hang":
            time.sleep(spec.hang_s)
        elif spec.mode == "blackhole":
            # sync contexts have no cancellable deadline: park for the
            # backstop, then surface the never-answered socket
            time.sleep(spec.hang_s)
            raise FaultInjectedTransportError(point)
        # skew-mode specs only apply through skew(); firing one here is a no-op

    async def fire_async(self, point: str, target: Optional[str] = None) -> None:
        """Event-loop hook: delay/hang must not block the loop's peers."""
        spec = self._decide(point, target)
        if spec is None:
            return
        self._record(spec)
        if spec.mode == "error":
            raise FaultInjectedError(point)
        if spec.mode in ("reset", "flap"):
            raise FaultInjectedTransportError(point)
        if spec.mode == "delay":
            await asyncio.sleep(spec.delay_s)
        elif spec.mode == "hang":
            await asyncio.sleep(spec.hang_s)
        elif spec.mode == "blackhole":
            # parked until the CALL SITE's deadline cancels this sleep
            # (asyncio.wait_for around the attempt — the per-attempt
            # timeout retry_http_request applies); hang_s is only the
            # backstop for sites with no deadline at all
            await asyncio.sleep(spec.hang_s)
            raise FaultInjectedTransportError(point)

    def skew(self, point: str = "clock.skew") -> int:
        """Sample a clock offset in seconds (0 when the point is quiet)."""
        spec = self._decide(point)
        if spec is None or spec.mode != "skew" or spec.skew_s <= 0:
            return 0
        self._record(spec)
        with self._lock:
            rng = self._rngs.get(point)  # None if reconfigured mid-call
            return rng.offset(spec.skew_s) if rng is not None else 0

    def corrupt(
        self, point: str, data: bytes, target: Optional[str] = None
    ) -> bytes:
        """Maybe mangle ``data`` (corrupt-mode specs only).

        Returns ``data`` unchanged when the point is quiet.  When a
        corrupt-mode spec fires, the payload is either bit-flipped at a
        deterministically drawn position or truncated (torn write) — both
        drawn from the point's seeded RNG so a corruption soak replays
        bit-for-bit.  Empty payloads pass through untouched.
        """
        if not data:
            return data
        spec = self._decide(point, target)
        if spec is None or spec.mode != "corrupt":
            return data
        self._record(spec)
        with self._lock:
            rng = self._rngs.get(point)  # None if reconfigured mid-call
            if rng is None:
                return data
            flip = rng.roll() < 0.5
            pos_roll = rng.roll()
        if flip or len(data) == 1:
            pos = min(int(pos_roll * len(data) * 8), len(data) * 8 - 1)
            mangled = bytearray(data)
            mangled[pos // 8] ^= 1 << (pos % 8)
            return bytes(mangled)
        # torn write: keep a strict prefix (possibly empty)
        return data[: int(pos_roll * (len(data) - 1))]


class _PointRng:
    """random.Random seeded stably from (seed, point-name)."""

    def __init__(self, seed: int, point: str):
        import random

        self._r = random.Random((seed << 32) ^ zlib.crc32(point.encode()))

    def roll(self) -> float:
        return self._r.random()

    def offset(self, magnitude: int) -> int:
        return self._r.randint(-magnitude, magnitude)


class SkewedClock:
    """Clock wrapper applying registry-driven skew (the clock-skew
    failure domain): each ``now()`` is offset by whatever the
    ``clock.skew`` point samples.  Wrap exactly the replica whose clock
    should drift; everything else keeps the base clock."""

    def __init__(self, base, point: str = "clock.skew"):
        self.base = base
        self.point = point

    def now(self):
        from ..messages import Time

        t = self.base.now()
        offset = skew(self.point)
        if offset == 0:
            return t
        return Time(max(0, t.seconds + offset))

    def __getattr__(self, item):
        # advance()/set() on a wrapped MockClock keep working
        return getattr(self.base, item)


# -- process-wide instance ---------------------------------------------------

_REGISTRY = FaultRegistry()


def registry() -> FaultRegistry:
    return _REGISTRY


def configure(specs: Sequence[FaultSpec], seed: int = 0) -> None:
    _REGISTRY.configure(specs, seed=seed)


def clear() -> None:
    _REGISTRY.clear()


def snapshot() -> dict:
    return _REGISTRY.snapshot()


def active() -> bool:
    return _REGISTRY.active


def fire(point: str, target: Optional[str] = None) -> None:
    """Sync injection hook; no-op (one bool check) when faults are off.
    ``target`` is the call's scope context (e.g. the peer URL) matched
    against target-scoped specs."""
    if _REGISTRY.active:
        _REGISTRY.fire(point, target)


async def fire_async(point: str, target: Optional[str] = None) -> None:
    """Async injection hook; no-op when faults are off."""
    if _REGISTRY.active:
        await _REGISTRY.fire_async(point, target)


def skew(point: str = "clock.skew") -> int:
    return _REGISTRY.skew(point) if _REGISTRY.active else 0


def corrupt_bytes(point: str, data: bytes, target: Optional[str] = None) -> bytes:
    """Corruption hook: passthrough when faults are off, else maybe-mangle.
    Call sites apply this to durable payloads AFTER computing the row CRC,
    so the stored checksum witnesses the original bytes."""
    if _REGISTRY.active:
        return _REGISTRY.corrupt(point, data, target)
    return data
