"""Metrics: Prometheus registry + the reference's domain metrics.

The analog of the reference's OTel metrics stack (reference:
aggregator/src/metrics.rs:222-323): per-route HTTP request counts/latency,
upload outcome counters by rejection reason, aggregate step failures by
type, job acquire/step timing, and per-transaction status/duration.
Exported via a Prometheus scrape endpoint on the health server
(``/metrics``), matching the reference's prometheus exporter mode.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

try:
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Gauge,
        Histogram,
        generate_latest,
    )

    HAVE_PROMETHEUS = True
except ImportError:  # pragma: no cover - baked into the image
    HAVE_PROMETHEUS = False

#: Latency buckets tuned like the reference's custom histogram views
#: (reference: metrics.rs:103-174).
_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: Pipeline-freshness buckets: ages from sub-second commit latencies up to
#: a day-old report landing in an aggregate (SLO alerting range).
_AGE_BUCKETS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1800.0,
    3600.0, 7200.0, 21600.0, 86400.0,
)


# -- pure-Python fallback metric implementation ------------------------------
# When prometheus_client is absent (dev containers without the baked
# image), Metrics used to no-op (registry=None) — which also silenced every
# metric-invariant ASSERTION the chaos suites want to make.  This fallback
# keeps the same Counter/Gauge/Histogram surface (labels/inc/set/observe/
# remove) in plain dicts, exports Prometheus text, and answers
# ``registry.get_sample_value`` exactly like CollectorRegistry does, so
# tests and /metrics behave identically either way.


class _FallbackChild:
    def __init__(self, metric: "_FallbackMetric", key: Tuple[str, ...]):
        self._metric = metric
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        with self._metric._lock:
            self._metric._values[self._key] = (
                self._metric._values.get(self._key, 0.0) + amount
            )

    def set(self, value: float) -> None:
        with self._metric._lock:
            self._metric._values[self._key] = float(value)

    def observe(self, value: float) -> None:
        with self._metric._lock:
            count, total, buckets = self._metric._hist.get(
                self._key, (0, 0.0, [0] * len(self._metric.buckets))
            )
            buckets = list(buckets)
            for i, le in enumerate(self._metric.buckets):
                if value <= le:
                    buckets[i] += 1
            self._metric._hist[self._key] = (count + 1, total + value, buckets)


class _FallbackMetric:
    """One metric family (all label sets) of the fallback registry."""

    def __init__(
        self,
        name: str,
        documentation: str,
        labelnames: Tuple[str, ...] = (),
        registry: Optional["FallbackRegistry"] = None,
        buckets: Tuple[float, ...] = (),
        kind: str = "counter",
    ):
        self.name = name
        self.documentation = documentation
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets)
        self.kind = kind
        self._lock = threading.Lock()
        #: label-value tuple -> scalar (counter/gauge)
        self._values: Dict[Tuple[str, ...], float] = {}
        #: label-value tuple -> (count, sum, per-bucket cumulative counts)
        self._hist: Dict[Tuple[str, ...], Tuple[int, float, List[int]]] = {}
        if registry is not None:
            registry.register(self)
        # an unlabeled metric is usable without .labels()
        if not self.labelnames:
            self._root = _FallbackChild(self, ())

    def labels(self, *values, **kwargs) -> _FallbackChild:
        if kwargs:
            values = tuple(str(kwargs[n]) for n in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got {values}"
            )
        return _FallbackChild(self, values)

    def remove(self, *values) -> None:
        key = tuple(str(v) for v in values)
        with self._lock:
            self._values.pop(key, None)
            self._hist.pop(key, None)

    # unlabeled passthroughs
    def inc(self, amount: float = 1.0) -> None:
        self._root.inc(amount)

    def set(self, value: float) -> None:
        self._root.set(value)

    def observe(self, value: float) -> None:
        self._root.observe(value)


class FallbackRegistry:
    """Dict-of-families registry with CollectorRegistry's read surface."""

    def __init__(self):
        self._metrics: Dict[str, _FallbackMetric] = {}
        self._lock = threading.Lock()

    def register(self, metric: _FallbackMetric) -> None:
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError(f"duplicate metric {metric.name}")
            self._metrics[metric.name] = metric

    def families(self) -> List[_FallbackMetric]:
        with self._lock:
            return list(self._metrics.values())

    @staticmethod
    def _label_str(labelnames, key) -> str:
        if not labelnames:
            return ""
        pairs = ",".join(f'{n}="{v}"' for n, v in zip(labelnames, key))
        return "{" + pairs + "}"

    def get_sample_value(
        self, name: str, labels: Optional[dict] = None
    ) -> Optional[float]:
        """CollectorRegistry-compatible: ``name`` is the SAMPLE name
        (``..._total``, ``..._count``, ``..._sum``, ``..._bucket``)."""
        labels = dict(labels or {})
        for m in self.families():
            with m._lock:
                if m.kind == "counter" and name == m.name + "_total":
                    key = tuple(str(labels.get(n, "")) for n in m.labelnames)
                    return self._maybe(m._values, key, labels, m.labelnames)
                if m.kind == "gauge" and name == m.name:
                    key = tuple(str(labels.get(n, "")) for n in m.labelnames)
                    return self._maybe(m._values, key, labels, m.labelnames)
                if m.kind == "histogram" and name.startswith(m.name + "_"):
                    suffix = name[len(m.name) + 1 :]
                    le = labels.pop("le", None)
                    key = tuple(str(labels.get(n, "")) for n in m.labelnames)
                    entry = m._hist.get(key)
                    if entry is None:
                        return None
                    count, total, buckets = entry
                    if suffix == "count":
                        return float(count)
                    if suffix == "sum":
                        return total
                    if suffix == "bucket":
                        if le in ("+Inf", None):
                            return float(count)
                        for i, b in enumerate(m.buckets):
                            if _le_str(b) == le:
                                return float(buckets[i])
                        return None
        return None

    @staticmethod
    def _maybe(values, key, labels, labelnames) -> Optional[float]:
        if set(labels) - set(labelnames):
            return None
        return values.get(key)

    def generate_text(self) -> bytes:
        """Prometheus exposition text for /metrics scrapes."""
        out: List[str] = []
        for m in self.families():
            out.append(f"# HELP {m.name} {m.documentation}")
            out.append(f"# TYPE {m.name} {m.kind}")
            with m._lock:
                if m.kind == "histogram":
                    for key, (count, total, buckets) in sorted(m._hist.items()):
                        base = list(zip(m.labelnames, key))
                        for i, le in enumerate(m.buckets):
                            lbl = self._label_str(
                                [n for n, _ in base] + ["le"],
                                [v for _, v in base] + [_le_str(le)],
                            )
                            out.append(f"{m.name}_bucket{lbl} {buckets[i]}")
                        lbl = self._label_str(
                            [n for n, _ in base] + ["le"],
                            [v for _, v in base] + ["+Inf"],
                        )
                        out.append(f"{m.name}_bucket{lbl} {count}")
                        plain = self._label_str(m.labelnames, key)
                        out.append(f"{m.name}_count{plain} {count}")
                        out.append(f"{m.name}_sum{plain} {total}")
                else:
                    suffix = "_total" if m.kind == "counter" else ""
                    for key, value in sorted(m._values.items()):
                        lbl = self._label_str(m.labelnames, key)
                        out.append(f"{m.name}{suffix}{lbl} {value}")
        return ("\n".join(out) + "\n").encode()


def _le_str(bound: float) -> str:
    """Render a bucket bound exactly like prometheus_client's
    floatToGoString does for our finite bounds ('5.0', not '5'), so
    ``le`` label values — in scrapes and in get_sample_value lookups —
    agree between backends."""
    return repr(float(bound))


def _fallback_counter(name, doc, labelnames=(), registry=None):
    # prometheus_client strips a declared "_total" suffix from the family
    # name and re-appends it on the sample; mirror that so sample names
    # (and the golden catalog) agree between backends
    if name.endswith("_total"):
        name = name[: -len("_total")]
    return _FallbackMetric(name, doc, labelnames, registry, kind="counter")


def _fallback_gauge(name, doc, labelnames=(), registry=None):
    return _FallbackMetric(name, doc, labelnames, registry, kind="gauge")


def _fallback_histogram(name, doc, labelnames=(), registry=None, buckets=()):
    return _FallbackMetric(
        name, doc, labelnames, registry, buckets=buckets, kind="histogram"
    )


class Metrics:
    """Domain metrics bundle; one per process.

    With ``prometheus_client`` available the bundle is a real
    CollectorRegistry; without it (or with ``force_fallback=True``) the
    pure-Python fallback above keeps every series live so dev-container
    runs still scrape and assert on metrics.
    """

    def __init__(
        self,
        registry: Optional["CollectorRegistry"] = None,
        force_fallback: bool = False,
    ):
        self.fallback = force_fallback or not HAVE_PROMETHEUS
        if self.fallback:
            self.registry = FallbackRegistry()
            Counter = _fallback_counter  # noqa: N806 - mirror prometheus API
            Gauge = _fallback_gauge  # noqa: N806
            Histogram = _fallback_histogram  # noqa: N806
        else:
            self.registry = registry or CollectorRegistry()
            # local bindings: the fallback branch shadows these names, which
            # makes them function-local in BOTH branches
            from prometheus_client import Counter, Gauge, Histogram  # noqa: F811
        self.http_requests = Counter(
            "janus_http_requests_total",
            "DAP HTTP requests by route and status",
            ["route", "status"],
            registry=self.registry,
        )
        self.http_latency = Histogram(
            "janus_http_request_duration_seconds",
            "DAP HTTP request latency by route",
            ["route"],
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        # reference: report_writer.rs:324 upload counters by reason
        self.upload_outcomes = Counter(
            "janus_upload_decision_total",
            "Upload outcomes by decision",
            ["decision"],
            registry=self.registry,
        )
        # reference: metrics.rs:313 janus_aggregate_step_failure
        self.step_failures = Counter(
            "janus_aggregate_step_failure_total",
            "Aggregation step failures by type",
            ["type"],
            registry=self.registry,
        )
        # Oracle-fallback visibility: a device-configured deployment whose
        # task lands on the CPU oracle must say so (VERDICT r3 weak #3).
        self.vdaf_backend_fallbacks = Counter(
            "janus_vdaf_backend_fallback_total",
            "Tasks served by the CPU oracle despite a device backend config",
            ["vdaf_type", "reason"],
            registry=self.registry,
        )
        # Per-outcome step counter at the JobDriver layer: a stuck fleet
        # (timeouts / retryable churn) and a healthy one look identical on
        # wall-time alone (ISSUE 2 satellite); this splits them.
        self.job_steps_total = Counter(
            "janus_job_steps_total",
            "Job driver step outcomes by job type",
            ["job_type", "outcome"],
            registry=self.registry,
        )
        # reference: job_driver.rs:102-113 acquire/step timing
        self.job_steps = Histogram(
            "janus_job_step_duration_seconds",
            "Job step wall time by job type and outcome",
            ["job_type", "outcome"],
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        # reference: datastore.rs:186-224 per-tx status
        self.tx_total = Counter(
            "janus_database_transactions_total",
            "Datastore transactions by name and status",
            ["name", "status"],
            registry=self.registry,
        )
        # Datastore brownout tolerance (core/db_health.py): the database
        # failure domain made observable.  The state-set gauge carries 1
        # on the tracker's current state so alerts can match on
        # janus_datastore_health{state="suspect"} == 1 directly; the
        # retry counter is the brownout's intensity (every transient
        # in-loop failure, before the attempt that eventually commits).
        self.datastore_health = Gauge(
            "janus_datastore_health",
            "Datastore health state-set (1 on the tracker's current "
            "state: healthy|suspect|probing)",
            ["state"],
            registry=self.registry,
        )
        self.datastore_tx_retries = Counter(
            "janus_datastore_tx_retries_total",
            "Transient datastore transaction failures retried by run_tx "
            "(lock contention, serialization failures, connection drops)",
            registry=self.registry,
        )
        # Janitor plane gating on datastore health: sweeps skipped while
        # the tracker is non-healthy, so GC never races a brownout-
        # recovering replay window.
        self.janitor_skips = Counter(
            "janus_janitor_skips_total",
            "Janitor sweeps skipped because the datastore tracker was "
            "non-healthy, by component (gc|key_rotator)",
            ["component"],
            registry=self.registry,
        )
        # batched device launches through the backend seam
        self.device_launches = Counter(
            "janus_device_prepare_launches_total",
            "Batched VDAF prepare launches by backend",
            ["backend"],
            registry=self.registry,
        )
        self.device_reports = Counter(
            "janus_device_prepare_reports_total",
            "Reports prepared through batched launches by backend",
            ["backend"],
            registry=self.registry,
        )
        # Steady-state backend visibility (VERDICT r4 weak #6): reports/s
        # and wall time PER BACKEND on every prepare/combine batch — an
        # oracle-pinned task shows up on a dashboard as a continuously
        # rising oracle series, not just a one-time fallback warning.
        # (reference analog: per-step timing meters, metrics.rs:303-323)
        self.prepare_reports = Counter(
            "janus_vdaf_prepare_reports_total",
            "Reports through VDAF prepare phases by backend",
            ["backend", "phase"],
            registry=self.registry,
        )
        self.prepare_seconds = Histogram(
            "janus_vdaf_prepare_duration_seconds",
            "VDAF prepare batch wall time by backend and phase",
            ["backend", "phase"],
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )

        # Device executor (janus_tpu/executor/): continuous cross-job
        # batching visibility per (circuit, aggregator-side, phase[,
        # agg-param level]) bucket.  The bucket label enumerates the
        # submission KINDS — prep_init / combine (Prio3) and poplar_init
        # (Poplar1 heavy hitters, whose label carries an L{level} segment:
        # one series per IDPF tree level, so a multi-round collection's
        # per-level batching is visible round by round).  flush_rows vs.
        # the per-job submission size is the direct measure of cross-job
        # coalescing; queue_rows + wait/launch seconds expose whether
        # backpressure or the chip is the bottleneck.
        self.executor_queue_rows = Gauge(
            "janus_executor_queue_rows",
            "Report rows queued or in flight per executor bucket "
            "(circuit/side/kind, Poplar1 buckets carry the tree level)",
            ["bucket"],
            registry=self.registry,
        )
        self.executor_flush_rows = Histogram(
            "janus_executor_flush_rows",
            "Mega-batch size (rows) per executor flush "
            "(all submission kinds: prep_init, combine, poplar_init)",
            ["bucket"],
            buckets=(1, 4, 16, 64, 256, 1024, 4096, 16384, 65536),
            registry=self.registry,
        )
        self.executor_wait_seconds = Histogram(
            "janus_executor_wait_duration_seconds",
            "Submission wall time from enqueue to result by bucket",
            ["bucket"],
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        self.executor_launch_seconds = Histogram(
            "janus_executor_launch_duration_seconds",
            "Device launch wall time per executor flush by bucket "
            "(poplar_init flushes include the bulk-AES walk)",
            ["bucket"],
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        self.executor_rejections = Counter(
            "janus_executor_rejections_total",
            "Backpressure rejections by bucket and reason",
            ["bucket", "reason"],
            registry=self.registry,
        )
        # Shape-churn visibility (ISSUE 8): how long each VDAF shape's
        # executables took to compile, and whether the registry-driven
        # background warmup delivered them (outcome=ok) or the shape is
        # serving cold (outcome=error).  compile_s per shape is the number
        # the persistent compile cache should drive to ~0 across restarts.
        self.executor_compile_seconds = Histogram(
            "janus_executor_compile_duration_seconds",
            "Warmup compile wall time per VDAF shape",
            ["shape"],
            buckets=(0.5, 2, 5, 15, 30, 60, 120, 300, 600),
            registry=self.registry,
        )
        self.executor_warmups = Counter(
            "janus_executor_warmup_total",
            "Backend warmup attempts by outcome",
            ["outcome"],
            registry=self.registry,
        )
        # Per-shape circuit breaker (executor/service.py): a sick device
        # path must be visible the moment it trips, and again when the
        # half-open probe restores it.
        self.circuit_state = Gauge(
            "janus_executor_circuit_state",
            "Device circuit state per VDAF shape (0=closed 1=open 2=half-open)",
            ["circuit"],
            registry=self.registry,
        )
        self.circuit_transitions = Counter(
            "janus_executor_circuit_transitions_total",
            "Device circuit state transitions per VDAF shape",
            ["circuit", "state"],
            registry=self.registry,
        )
        # Device-resident accumulator store (executor/accumulator.py): a
        # budgeted cache — occupancy, spill and eviction rates are what an
        # operator tunes byte_budget against.
        self.accumulator_resident_bytes = Gauge(
            "janus_accumulator_resident_bytes",
            "Bytes of out-share state resident on device (flush matrices + bucket buffers)",
            registry=self.registry,
        )
        self.accumulator_buckets = Gauge(
            "janus_accumulator_buckets",
            "Live (task, shape, batch-bucket) resident accumulators",
            registry=self.registry,
        )
        self.accumulator_spills = Counter(
            "janus_accumulator_spills_total",
            "Accumulator drains by reason (commit, discard)",
            ["reason"],
            registry=self.registry,
        )
        self.accumulator_evictions = Counter(
            "janus_accumulator_evictions_total",
            "LRU/memory-pressure evictions of resident accumulator state",
            registry=self.registry,
        )
        # Device-resident IDPF (ops/poplar1_batch.py): which backend walks
        # the Poplar1 AES tree (host AES-NI/soft-AES vs the jax kernel),
        # and how many device-walked rows had their sketch y vectors
        # materialized back to host — the device-resident path keeps the
        # readback at 0 (states carry ResidentRefs; drains read ONE vector
        # per level bucket).
        self.poplar_walk_rows = Counter(
            "janus_poplar_walk_rows_total",
            "Poplar1 IDPF tree-walk rows by AES backend (host|jax)",
            ["backend"],
            registry=self.registry,
        )
        self.poplar_sketch_readback_rows = Counter(
            "janus_poplar_sketch_readback_rows_total",
            "Device-walked Poplar1 rows whose sketch y vectors were read "
            "back to host (0 on the device-resident path)",
            registry=self.registry,
        )
        # Peer-health-aware acquisition (job_driver.suspect_task_ids): jobs
        # of suspect-peer tasks are filtered at the acquire query instead
        # of acquired-then-released, sparing tx churn during partitions.
        self.job_acquisition_suspect_filtered = Counter(
            "janus_job_acquisition_suspect_filtered_total",
            "Job acquisition passes that excluded suspect-peer tasks at "
            "the query, by job type",
            ["job_type"],
            registry=self.registry,
        )
        # Crash recovery: leases that expired WITHOUT release are holders
        # that died or wedged — the reaper (job_driver.py) clears them so
        # redelivery is prompt and the death is visible on a dashboard.
        self.job_leases_expired = Counter(
            "janus_job_leases_expired_total",
            "Job leases that expired without release (holder died/wedged), by job type",
            ["job_type"],
            registry=self.registry,
        )
        # Deferred-drain journal (datastore accumulator_journal table):
        # persisted entries per outcome — 'drain' is the owner's cadence/
        # shutdown spill consuming its own rows, 'replay' is a survivor
        # re-deriving a dead replica's rows on the CPU oracle.
        self.accumulator_journal_entries = Counter(
            "janus_accumulator_journal_entries_total",
            "Accumulator journal rows written (deferred resident drains)",
            registry=self.registry,
        )
        self.accumulator_journal_consumed = Counter(
            "janus_accumulator_journal_consumed_total",
            "Accumulator journal rows consumed, by path (drain|replay)",
            ["path"],
            registry=self.registry,
        )
        # Peer transport health (core/peer_health.py): the partition
        # failure domain made observable — which peer, what state, how
        # many transport-level failures.  The state-set gauge carries 1
        # on the peer's current state so dashboards and alerts can match
        # on janus_peer_health{state="suspect"} == 1 directly.
        self.peer_health = Gauge(
            "janus_peer_health",
            "Peer transport health state-set (1 on the peer's current "
            "state: healthy|suspect|probing)",
            ["peer", "state"],
            registry=self.registry,
        )
        self.peer_transport_failures = Counter(
            "janus_peer_transport_failures_total",
            "Transport-level failures (connect/reset/timeout) per peer; "
            "HTTP responses of any status do not count",
            ["peer"],
            registry=self.registry,
        )
        # Backpressure cooperation: how often the peer's Retry-After hint
        # (503 overload responses) shaped our backoff instead of the
        # blind exponential curve.
        self.http_retry_after_honored = Counter(
            "janus_http_retry_after_honored_total",
            "Retryable HTTP responses whose Retry-After hint set the "
            "backoff sleep (capped at the policy max interval)",
            registry=self.registry,
        )
        # Fault injection (core/faults.py): every injected fault is counted
        # so a chaos run's pressure is itself observable.
        self.faults_injected = Counter(
            "janus_faults_injected_total",
            "Injected faults by point and mode",
            ["point", "mode"],
            registry=self.registry,
        )
        # Fleet control plane (core/fleet.py): membership and routing as
        # seen by THIS replica's router — members it counts live in its
        # own role's rendezvous domain, tasks it currently owns, and how
        # many tasks it has absorbed from dead peers.  A fleet-wide burst
        # of migrations (every replica's counter moving at once) is the
        # migration-storm signature; see README "Fleet routing".
        self.fleet_members = Gauge(
            "janus_fleet_members",
            "Live same-role fleet members in this replica's membership view",
            registry=self.registry,
        )
        self.fleet_tasks_owned = Gauge(
            "janus_fleet_tasks_owned",
            "Tasks the rendezvous router currently assigns to this replica",
            registry=self.registry,
        )
        self.fleet_migrations = Counter(
            "janus_fleet_migrations_total",
            "Tasks this replica took over from a member whose heartbeat "
            "expired (live task migration events)",
            registry=self.registry,
        )
        # Migration-storm suppression: ownership refreshes served from
        # the FROZEN view because mass staleness (or a suspect local
        # datastore) made the membership table untrustworthy.  A nonzero
        # rate here during a brownout is the system working; see README
        # "Datastore brownout tolerance" for the starter alert.
        self.fleet_migration_suppressed = Counter(
            "janus_fleet_migration_suppressed_total",
            "Ownership refreshes served from the frozen view because a "
            "migration storm was suppressed (mass staleness or suspect "
            "datastore)",
            registry=self.registry,
        )

        # -- pipeline freshness / SLO metrics (ISSUE 5 tentpole) ---------
        # The operator question that defines a DAP deployment's SLO: how
        # old is a report by the time it lands where it is going?
        # reference analog: per-step timing meters, metrics.rs:303-323.
        self.report_commit_age = Histogram(
            "janus_report_commit_age_seconds",
            "Report age at upload-batch commit (client timestamp -> writer commit)",
            registry=self.registry,
            buckets=_AGE_BUCKETS,
        )
        self.job_age_at_acquire = Histogram(
            "janus_job_age_at_acquire_seconds",
            "Job age (created_at -> lease acquire) by job type",
            ["job_type"],
            registry=self.registry,
            buckets=_AGE_BUCKETS,
        )
        self.collection_e2e = Histogram(
            "janus_collection_e2e_seconds",
            "Upload->collectable latency: collection finish minus the "
            "batch's earliest client timestamp",
            registry=self.registry,
            buckets=_AGE_BUCKETS,
        )
        # Sampled queue-depth gauges (binaries' status sampler loop):
        # acquirable backlog per job type, and the outstanding deferred-
        # drain journal (rows counted but not yet merged + oldest age —
        # a rising oldest-age is a dead replica whose rows nobody replayed).
        self.acquirable_jobs = Gauge(
            "janus_acquirable_jobs",
            "Jobs currently acquirable (active state, lease expired) by job type",
            ["job_type"],
            registry=self.registry,
        )
        self.journal_outstanding_rows = Gauge(
            "janus_accumulator_journal_outstanding_rows",
            "Outstanding accumulator-journal rows (counted reports whose "
            "shares are not yet merged)",
            registry=self.registry,
        )
        self.journal_oldest_age = Gauge(
            "janus_accumulator_journal_oldest_age_seconds",
            "Age of the oldest outstanding accumulator-journal row",
            registry=self.registry,
        )

        # -- client-ingress observability (ISSUE 9 tentpole) -------------
        # Upload acceptance latency as the CLIENT experiences it: from the
        # handler enqueueing the validated report into the write batcher to
        # the batch transaction committing it.  The front-door half of the
        # freshness story — report_commit_age measures how old the report
        # was, this measures how long WE held it before it was durable.
        self.upload_to_commit = Histogram(
            "janus_report_upload_to_commit_seconds",
            "Upload handler enqueue to batch-commit latency per accepted report",
            registry=self.registry,
            buckets=_LATENCY_BUCKETS,
        )
        # -- upload front door (ISSUE 14 tentpole) -----------------------
        # Load shedding: uploads refused at the bounded front-door queue
        # (503 + Retry-After, the DAP-retryable shape) by reason —
        # queue_full is depth pressure, queue_delay is the oldest pending
        # open blowing its latency budget.  Overload degrades into client
        # retry pressure instead of event-loop collapse; this counter is
        # the alertable signal that it is happening.
        self.upload_sheds = Counter(
            "janus_upload_shed_total",
            "Uploads shed at the front-door queue (503 + Retry-After) by "
            "reason (queue_full|queue_delay|datastore|journal)",
            ["reason"],
            registry=self.registry,
        )
        # Batched HPKE open (core/hpke_batch.py): how many opens each
        # vectorized pass carried (amortization is the whole point), how
        # long the open stage takes per backend, and the live front-door
        # queue depth the shed decision reads.
        self.upload_open_batch_rows = Histogram(
            "janus_upload_open_batch_rows",
            "HPKE opens per batched front-door open pass",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512),
            registry=self.registry,
        )
        self.upload_open_seconds = Histogram(
            "janus_upload_open_duration_seconds",
            "Upload HPKE-open stage wall time by backend "
            "(batched: per batch pass; inline: per report)",
            ["backend"],
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        self.upload_queue_depth = Gauge(
            "janus_upload_queue_depth",
            "Front-door uploads pending in the batched HPKE-open queue",
            registry=self.registry,
        )
        # -- zero-copy ingest plane (core/ingest.py, ISSUE 18) -----------
        # The write-behind report journal: reports waiting on their
        # durability-ACK journal flush (staged + in-flight — the bound the
        # reason="journal" shed reads), how long each flush transaction
        # takes, where staged reports went (direct = handed in-memory to
        # the job creator's staging side; readback = materialized into
        # client_reports and consumed through the classic read path), and
        # rows replayed into client_reports after a crash or migration.
        self.ingest_journal_depth = Gauge(
            "janus_ingest_journal_depth",
            "Reports pending their report-journal durability flush "
            "(staged + in-flight)",
            registry=self.registry,
        )
        self.ingest_journal_flush_seconds = Histogram(
            "janus_ingest_journal_flush_seconds",
            "Report-journal flush transaction wall time per batch",
            registry=self.registry,
            buckets=_LATENCY_BUCKETS,
        )
        self.ingest_staged_total = Counter(
            "janus_ingest_staged_reports_total",
            "Journaled-ingest reports by aggregation-visibility path "
            "(direct: staged cohort packed in-memory; readback: "
            "materialized into client_reports for the classic read path)",
            ["path"],
            registry=self.registry,
        )
        self.ingest_journal_replayed = Counter(
            "janus_ingest_journal_replayed_total",
            "Report-journal rows materialized into client_reports by "
            "replay (startup, creator pre-pass, or migration handoff)",
            registry=self.registry,
        )
        # -- poison/corruption failure domain (core/quarantine.py, -------
        # ISSUE 19).  Vectorized passes fail at cohort granularity; the
        # bisection harness restores per-report failure semantics and
        # these families are its blast-radius ledger: rows pulled out of
        # a cohort (by stage), bisection sieves run, and durable journal
        # rows that failed their CRC32C check at materialize/replay.
        self.quarantined_reports = Counter(
            "janus_quarantined_reports_total",
            "Reports quarantined out of a vectorized cohort, by stage "
            "(upload_open|prep_init|combine|journal|accumulator_journal|"
            "bucket)",
            ["stage"],
            registry=self.registry,
        )
        self.batch_bisections = Counter(
            "janus_batch_bisections_total",
            "Batch-level failures routed through the bisection harness "
            "(each sieve isolates poison rows in O(log B) extra passes)",
            registry=self.registry,
        )
        self.journal_corrupt_rows = Counter(
            "janus_journal_corrupt_rows_total",
            "Durable journal rows (report_journal / accumulator_journal) "
            "that failed CRC32C verification and were quarantined+skipped",
            registry=self.registry,
        )
        # -- SLO evaluation plane (core/slo.py) --------------------------
        # Burn rate = window error rate / error budget: 1.0 means the SLO
        # spends its budget exactly at the sustainable pace, >1 means it
        # will exhaust early.  One sample per (slo, fast|slow) per
        # evaluator tick.
        self.slo_burn_rate = Gauge(
            "janus_slo_burn_rate",
            "Multi-window SLO burn rate (window error rate / error budget)",
            ["slo", "window"],
            registry=self.registry,
        )
        self.slo_breaches = Counter(
            "janus_slo_breach_total",
            "SLO breaches: transitions into fast AND slow burn above threshold",
            ["slo"],
            registry=self.registry,
        )
        # -- OTLP export health (core/otlp.py) ---------------------------
        # The exporter itself must be observable: spans queued vs dropped
        # (lib absent, queue overflow) and export attempts by outcome tell
        # an operator whether the collector is actually receiving data.
        self.otlp_spans = Counter(
            "janus_otlp_spans_total",
            "Spans through the OTLP exporter by outcome (queued|exported|dropped)",
            ["outcome"],
            registry=self.registry,
        )
        self.otlp_exports = Counter(
            "janus_otlp_exports_total",
            "OTLP export attempts by outcome (ok|error|noop)",
            ["outcome"],
            registry=self.registry,
        )
        self.otlp_last_export_age = Gauge(
            "janus_otlp_last_export_age_seconds",
            "Seconds since the last successful OTLP export (-1 when never)",
            registry=self.registry,
        )
        # -- per-task device-plane cost attribution (core/costs.py) ------
        # Which task is burning the chip: each executor flush's measured
        # stage/launch durations split across its submissions by rows, and
        # oracle-path batches attributed whole (phase init|combine).  The
        # path label (device|oracle) makes breaker-driven cost shifts to
        # the CPU oracle visible on the SAME task series.  Cardinality is
        # capped (common.cost_task_cardinality) with a task="other"
        # overflow label; idle task series retire on the sampler tick.
        self.task_device_seconds = Counter(
            "janus_task_device_seconds_total",
            "Attributed device-plane seconds per task by phase "
            "(stage|launch: executor flush shares; init|combine: direct "
            "backend batches; drain: accumulator spill readbacks) and "
            "path (device|oracle)",
            ["task", "phase", "path"],
            registry=self.registry,
        )
        self.task_rows = Counter(
            "janus_task_rows_total",
            "Report rows through the device plane per task by outcome "
            "(ok|rejected|error)",
            ["task", "outcome"],
            registry=self.registry,
        )
        self.task_queue_delay = Histogram(
            "janus_task_queue_delay_seconds",
            "Per-submission executor queue delay (enqueue -> flush "
            "dispatch) by task",
            ["task"],
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        # Pad waste per flush: mesh-tail + pow2-canonicalization padding
        # rows the chip computes and throws away — the direct measure of
        # how much throughput shape canonicalization costs a bucket.
        self.executor_pad_rows = Counter(
            "janus_executor_pad_rows_total",
            "Mask-padded rows launched per executor bucket (pow2 + "
            "mesh-tail padding waste; real rows ride "
            "janus_executor_flush_rows)",
            ["bucket"],
            registry=self.registry,
        )
        # -- canary plane (core/canary.py, ISSUE 20) ---------------------
        # Black-box known-plaintext probes through the real upload ->
        # aggregate -> collect path.  The verdict counter is the only
        # family that can say "the fleet aggregated WRONG" (outcome=
        # corrupt: collected aggregate != the exact expected sum, or the
        # share failed to decrypt/decode); per-stage attribution rides
        # the probe_seconds histogram and the SLO plane reads the e2e +
        # outcome histograms (canary_e2e_latency / canary_success).
        self.canary_verdicts = Counter(
            "janus_canary_verdict_total",
            "Canary probe verdicts by canary task and outcome "
            "(ok|error|timeout|corrupt)",
            ["task", "outcome"],
            registry=self.registry,
        )
        self.canary_probe_seconds = Histogram(
            "janus_canary_probe_seconds",
            "Canary per-stage latency attribution (upload_ack|commit|"
            "first_prepare|collection|e2e)",
            ["stage"],
            buckets=_AGE_BUCKETS,
            registry=self.registry,
        )
        self.canary_e2e = Histogram(
            "janus_canary_e2e_seconds",
            "Canary probe end-to-end latency (first upload to verified "
            "collection)",
            buckets=_AGE_BUCKETS,
            registry=self.registry,
        )
        self.canary_probe_outcome = Histogram(
            "janus_canary_probe_outcome",
            "Canary probe outcomes as an SLO-shaped histogram (observes "
            "0.0 on success, 2.0 on failure; good = samples <= 0.5)",
            buckets=(0.5, 1.0),
            registry=self.registry,
        )
        self.canary_backoffs = Counter(
            "janus_canary_backoffs_total",
            "Canary probes suppressed by degradation-aware backoff, by "
            "reason (db_suspect|upload_shed) — counted, never alerting",
            ["reason"],
            registry=self.registry,
        )
        self.canary_verdict_state = Gauge(
            "janus_canary_verdict_state",
            "Canary rolled-up verdict per task (0 healthy, 1 degraded, "
            "2 failing)",
            ["task"],
            registry=self.registry,
        )

    # -- introspection ---------------------------------------------------
    def get_sample_value(self, name: str, labels: Optional[dict] = None):
        """Read one sample (Prometheus sample naming: ``..._total``,
        ``..._count``, ...) from whichever registry backs this bundle —
        the accessor metric-invariant assertions use."""
        if self.registry is None:
            return None
        return self.registry.get_sample_value(name, labels or {})

    def catalog(self) -> List[str]:
        """``name|type|label,label`` per metric family, sorted — compared
        against tests/metric_manifest.txt so a silent rename/label change
        fails CI.  Built from the metric objects themselves (not scrape
        samples), so zero-traffic families are still listed."""
        out = []
        for obj in vars(self).values():
            if isinstance(obj, _FallbackMetric):
                out.append(f"{obj.name}|{obj.kind}|{','.join(obj.labelnames)}")
            elif hasattr(obj, "_name") and hasattr(obj, "_labelnames"):
                out.append(
                    f"{obj._name}|{obj._type}|{','.join(obj._labelnames)}"
                )
        return sorted(out)

    @staticmethod
    def remove_series(metric, *labelvalues) -> None:
        """Drop one label set from a metric (both backends); quiet when the
        series never existed — bucket retirement calls this to cap gauge
        cardinality."""
        try:
            metric.remove(*labelvalues)
        except Exception:
            pass

    def observe_prepare(self, backend: str, phase: str, reports: int, seconds: float) -> None:
        if self.registry is None:
            return
        self.prepare_reports.labels(backend=backend, phase=phase).inc(reports)
        self.prepare_seconds.labels(backend=backend, phase=phase).observe(seconds)

    # -- helpers --------------------------------------------------------
    def observe_http(self, route: str, status: int, seconds: float) -> None:
        if self.registry is None:
            return
        self.http_requests.labels(route=route, status=str(status)).inc()
        self.http_latency.labels(route=route).observe(seconds)

    def export(self) -> bytes:
        if self.registry is None:
            return b""
        if isinstance(self.registry, FallbackRegistry):
            return self.registry.generate_text()
        return generate_latest(self.registry)


#: Process-wide default bundle (the analog of the reference's global meters).
GLOBAL_METRICS = Metrics()


class Timer:
    def __enter__(self):
        self.start = time.monotonic()
        return self

    def __exit__(self, *exc):
        self.seconds = time.monotonic() - self.start
