"""Metrics: Prometheus registry + the reference's domain metrics.

The analog of the reference's OTel metrics stack (reference:
aggregator/src/metrics.rs:222-323): per-route HTTP request counts/latency,
upload outcome counters by rejection reason, aggregate step failures by
type, job acquire/step timing, and per-transaction status/duration.
Exported via a Prometheus scrape endpoint on the health server
(``/metrics``), matching the reference's prometheus exporter mode.
"""

from __future__ import annotations

import time
from typing import Optional

try:
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Gauge,
        Histogram,
        generate_latest,
    )

    HAVE_PROMETHEUS = True
except ImportError:  # pragma: no cover - baked into the image
    HAVE_PROMETHEUS = False

#: Latency buckets tuned like the reference's custom histogram views
#: (reference: metrics.rs:103-174).
_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class Metrics:
    """Domain metrics bundle; one per process."""

    def __init__(self, registry: Optional["CollectorRegistry"] = None):
        if not HAVE_PROMETHEUS:
            self.registry = None
            return
        self.registry = registry or CollectorRegistry()
        self.http_requests = Counter(
            "janus_http_requests_total",
            "DAP HTTP requests by route and status",
            ["route", "status"],
            registry=self.registry,
        )
        self.http_latency = Histogram(
            "janus_http_request_duration_seconds",
            "DAP HTTP request latency by route",
            ["route"],
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        # reference: report_writer.rs:324 upload counters by reason
        self.upload_outcomes = Counter(
            "janus_upload_decision_total",
            "Upload outcomes by decision",
            ["decision"],
            registry=self.registry,
        )
        # reference: metrics.rs:313 janus_aggregate_step_failure
        self.step_failures = Counter(
            "janus_aggregate_step_failure_total",
            "Aggregation step failures by type",
            ["type"],
            registry=self.registry,
        )
        # Oracle-fallback visibility: a device-configured deployment whose
        # task lands on the CPU oracle must say so (VERDICT r3 weak #3).
        self.vdaf_backend_fallbacks = Counter(
            "janus_vdaf_backend_fallback_total",
            "Tasks served by the CPU oracle despite a device backend config",
            ["vdaf_type", "reason"],
            registry=self.registry,
        )
        # Per-outcome step counter at the JobDriver layer: a stuck fleet
        # (timeouts / retryable churn) and a healthy one look identical on
        # wall-time alone (ISSUE 2 satellite); this splits them.
        self.job_steps_total = Counter(
            "janus_job_steps_total",
            "Job driver step outcomes by job type",
            ["job_type", "outcome"],
            registry=self.registry,
        )
        # reference: job_driver.rs:102-113 acquire/step timing
        self.job_steps = Histogram(
            "janus_job_step_duration_seconds",
            "Job step wall time by job type and outcome",
            ["job_type", "outcome"],
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        # reference: datastore.rs:186-224 per-tx status
        self.tx_total = Counter(
            "janus_database_transactions_total",
            "Datastore transactions by name and status",
            ["name", "status"],
            registry=self.registry,
        )
        # batched device launches through the backend seam
        self.device_launches = Counter(
            "janus_device_prepare_launches_total",
            "Batched VDAF prepare launches by backend",
            ["backend"],
            registry=self.registry,
        )
        self.device_reports = Counter(
            "janus_device_prepare_reports_total",
            "Reports prepared through batched launches by backend",
            ["backend"],
            registry=self.registry,
        )
        # Steady-state backend visibility (VERDICT r4 weak #6): reports/s
        # and wall time PER BACKEND on every prepare/combine batch — an
        # oracle-pinned task shows up on a dashboard as a continuously
        # rising oracle series, not just a one-time fallback warning.
        # (reference analog: per-step timing meters, metrics.rs:303-323)
        self.prepare_reports = Counter(
            "janus_vdaf_prepare_reports_total",
            "Reports through VDAF prepare phases by backend",
            ["backend", "phase"],
            registry=self.registry,
        )
        self.prepare_seconds = Histogram(
            "janus_vdaf_prepare_duration_seconds",
            "VDAF prepare batch wall time by backend and phase",
            ["backend", "phase"],
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )

        # Device executor (janus_tpu/executor/): continuous cross-job
        # batching visibility per (circuit, aggregator-side, phase) bucket.
        # flush_rows vs. the per-job submission size is the direct measure
        # of cross-job coalescing; queue_rows + wait/launch seconds expose
        # whether backpressure or the chip is the bottleneck.
        self.executor_queue_rows = Gauge(
            "janus_executor_queue_rows",
            "Report rows queued or in flight per executor bucket",
            ["bucket"],
            registry=self.registry,
        )
        self.executor_flush_rows = Histogram(
            "janus_executor_flush_rows",
            "Mega-batch size (rows) per executor flush",
            ["bucket"],
            buckets=(1, 4, 16, 64, 256, 1024, 4096, 16384, 65536),
            registry=self.registry,
        )
        self.executor_wait_seconds = Histogram(
            "janus_executor_wait_duration_seconds",
            "Submission wall time from enqueue to result by bucket",
            ["bucket"],
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        self.executor_launch_seconds = Histogram(
            "janus_executor_launch_duration_seconds",
            "Device launch wall time per executor flush by bucket",
            ["bucket"],
            buckets=_LATENCY_BUCKETS,
            registry=self.registry,
        )
        self.executor_rejections = Counter(
            "janus_executor_rejections_total",
            "Backpressure rejections by bucket and reason",
            ["bucket", "reason"],
            registry=self.registry,
        )
        # Per-shape circuit breaker (executor/service.py): a sick device
        # path must be visible the moment it trips, and again when the
        # half-open probe restores it.
        self.circuit_state = Gauge(
            "janus_executor_circuit_state",
            "Device circuit state per VDAF shape (0=closed 1=open 2=half-open)",
            ["circuit"],
            registry=self.registry,
        )
        self.circuit_transitions = Counter(
            "janus_executor_circuit_transitions_total",
            "Device circuit state transitions per VDAF shape",
            ["circuit", "state"],
            registry=self.registry,
        )
        # Device-resident accumulator store (executor/accumulator.py): a
        # budgeted cache — occupancy, spill and eviction rates are what an
        # operator tunes byte_budget against.
        self.accumulator_resident_bytes = Gauge(
            "janus_accumulator_resident_bytes",
            "Bytes of out-share state resident on device (flush matrices + bucket buffers)",
            registry=self.registry,
        )
        self.accumulator_buckets = Gauge(
            "janus_accumulator_buckets",
            "Live (task, shape, batch-bucket) resident accumulators",
            registry=self.registry,
        )
        self.accumulator_spills = Counter(
            "janus_accumulator_spills_total",
            "Accumulator drains by reason (commit, discard)",
            ["reason"],
            registry=self.registry,
        )
        self.accumulator_evictions = Counter(
            "janus_accumulator_evictions_total",
            "LRU/memory-pressure evictions of resident accumulator state",
            registry=self.registry,
        )
        # Crash recovery: leases that expired WITHOUT release are holders
        # that died or wedged — the reaper (job_driver.py) clears them so
        # redelivery is prompt and the death is visible on a dashboard.
        self.job_leases_expired = Counter(
            "janus_job_leases_expired_total",
            "Job leases that expired without release (holder died/wedged), by job type",
            ["job_type"],
            registry=self.registry,
        )
        # Deferred-drain journal (datastore accumulator_journal table):
        # persisted entries per outcome — 'drain' is the owner's cadence/
        # shutdown spill consuming its own rows, 'replay' is a survivor
        # re-deriving a dead replica's rows on the CPU oracle.
        self.accumulator_journal_entries = Counter(
            "janus_accumulator_journal_entries_total",
            "Accumulator journal rows written (deferred resident drains)",
            registry=self.registry,
        )
        self.accumulator_journal_consumed = Counter(
            "janus_accumulator_journal_consumed_total",
            "Accumulator journal rows consumed, by path (drain|replay)",
            ["path"],
            registry=self.registry,
        )
        # Fault injection (core/faults.py): every injected fault is counted
        # so a chaos run's pressure is itself observable.
        self.faults_injected = Counter(
            "janus_faults_injected_total",
            "Injected faults by point and mode",
            ["point", "mode"],
            registry=self.registry,
        )

    def observe_prepare(self, backend: str, phase: str, reports: int, seconds: float) -> None:
        if self.registry is None:
            return
        self.prepare_reports.labels(backend=backend, phase=phase).inc(reports)
        self.prepare_seconds.labels(backend=backend, phase=phase).observe(seconds)

    # -- helpers --------------------------------------------------------
    def observe_http(self, route: str, status: int, seconds: float) -> None:
        if self.registry is None:
            return
        self.http_requests.labels(route=route, status=str(status)).inc()
        self.http_latency.labels(route=route).observe(seconds)

    def export(self) -> bytes:
        if self.registry is None:
            return b""
        return generate_latest(self.registry)


#: Process-wide default bundle (the analog of the reference's global meters).
GLOBAL_METRICS = Metrics()


class Timer:
    def __enter__(self):
        self.start = time.monotonic()
        return self

    def __exit__(self, *exc):
        self.seconds = time.monotonic() - self.start
