"""RFC 9180 HPKE (base mode), host-side crypto shell.

The analog of the reference's wrapper over ``hpke-dispatch`` (reference:
core/src/hpke.rs:167 seal, :192 open, :212 keypair generation, :54-89
application-info labels).  DAP uses one-shot single-message contexts, so seal
creates a fresh context per call.

Supported suite matrix (all combinations):
  KEM:  DHKEM(X25519, HKDF-SHA256) 0x0020, DHKEM(P-256, HKDF-SHA256) 0x0010
  KDF:  HKDF-SHA256/384/512
  AEAD: AES-128-GCM, AES-256-GCM, ChaCha20-Poly1305

Anchored to the CFRG RFC 9180 test vectors in tests/test_hpke.py (vendored
data file: the same test-vectors.json the reference vendors at
core/src/test-vectors.json).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
from dataclasses import dataclass, field
from typing import Optional, Tuple

try:
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM, ChaCha20Poly1305
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        NoEncryption,
        PrivateFormat,
        PublicFormat,
    )

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover - baked into the prod image
    # Import gate for environments without the ``cryptography`` wheel
    # (compute-only containers): this module — and everything that imports
    # it, e.g. the datastore and job drivers — stays importable; any
    # actual KEM/AEAD operation raises ModuleNotFoundError at call time.
    HAVE_CRYPTOGRAPHY = False

    class _MissingCryptography:
        """Defers the missing-dependency error from import to first use."""

        def __init__(self, name: str):
            self._name = name

        def __getattr__(self, item: str):
            if item.startswith("__"):
                raise AttributeError(item)
            return _MissingCryptography(f"{self._name}.{item}")

        def __call__(self, *args, **kwargs):
            raise ModuleNotFoundError(
                f"the 'cryptography' package is required for HPKE "
                f"(tried to call {self._name})"
            )

    ec = _MissingCryptography("ec")
    X25519PrivateKey = _MissingCryptography("X25519PrivateKey")
    X25519PublicKey = _MissingCryptography("X25519PublicKey")
    AESGCM = _MissingCryptography("AESGCM")
    ChaCha20Poly1305 = _MissingCryptography("ChaCha20Poly1305")
    Encoding = _MissingCryptography("Encoding")
    NoEncryption = _MissingCryptography("NoEncryption")
    PrivateFormat = _MissingCryptography("PrivateFormat")
    PublicFormat = _MissingCryptography("PublicFormat")

# De-shim (ISSUE 14): the HPKE tier no longer DIES without a functional
# `cryptography` — pure-Python RFC 7748 X25519 + P-256 ECDH
# (utils/purecurves.py) and soft AES-GCM / ChaCha20-Poly1305
# (utils/gcm.py) carry every supported suite, KAT-anchored by the same
# RFC 9180 vendored vectors.  HAVE_FUNCTIONAL_CRYPTOGRAPHY is a
# known-answer probe, not an import check: dev-container shims that
# import fine but compute garbage land on the fallbacks too.  The real
# library is preferred whenever it actually works (AES-NI, constant-time
# curves); the fallbacks are NOT constant-time and exist for dev/test
# hosts, never as a production preference.
from ..utils import purecurves as _curves
from ..utils.gcm import HAVE_FUNCTIONAL_CRYPTOGRAPHY
from ..utils.gcm import aesgcm as _aesgcm
from ..utils.gcm import chacha20poly1305 as _chacha20poly1305

from ..messages import (
    HpkeAeadId,
    HpkeCiphertext,
    HpkeConfig,
    HpkeKdfId,
    HpkeKemId,
    HpkePublicKey,
    Role,
)


class HpkeError(Exception):
    pass


class Label:
    """Message-specific application-info label (reference: core/src/hpke.rs:54)."""

    INPUT_SHARE = b"dap-09 input share"
    AGGREGATE_SHARE = b"dap-09 aggregate share"


@dataclass(frozen=True)
class HpkeApplicationInfo:
    """label || sender_role || recipient_role (reference: core/src/hpke.rs:75)."""

    raw: bytes

    @classmethod
    def new(cls, label: bytes, sender_role: Role, recipient_role: Role) -> "HpkeApplicationInfo":
        return cls(label + bytes([sender_role.value, recipient_role.value]))


# --- HKDF ------------------------------------------------------------------

_HASHES = {
    HpkeKdfId.HKDF_SHA256: hashlib.sha256,
    HpkeKdfId.HKDF_SHA384: hashlib.sha384,
    HpkeKdfId.HKDF_SHA512: hashlib.sha512,
}


def _hkdf_extract(hash_fn, salt: bytes, ikm: bytes) -> bytes:
    if not salt:
        salt = b"\x00" * hash_fn().digest_size
    return _hmac.new(salt, ikm, hash_fn).digest()


def _hkdf_expand(hash_fn, prk: bytes, info: bytes, length: int) -> bytes:
    out = b""
    t = b""
    i = 1
    while len(out) < length:
        t = _hmac.new(prk, t + info + bytes([i]), hash_fn).digest()
        out += t
        i += 1
    return out[:length]


def _labeled_extract(hash_fn, suite_id: bytes, salt: bytes, label: bytes, ikm: bytes) -> bytes:
    return _hkdf_extract(hash_fn, salt, b"HPKE-v1" + suite_id + label + ikm)


def _labeled_expand(hash_fn, suite_id: bytes, prk: bytes, label: bytes, info: bytes, length: int) -> bytes:
    return _hkdf_expand(
        hash_fn, prk, length.to_bytes(2, "big") + b"HPKE-v1" + suite_id + label + info, length
    )


# --- KEMs ------------------------------------------------------------------


class _X25519Kem:
    ID = HpkeKemId.X25519_HKDF_SHA256
    N_SECRET = 32
    N_PK = 32
    N_SK = 32
    _hash = hashlib.sha256

    @classmethod
    def _suite_id(cls) -> bytes:
        return b"KEM" + cls.ID.value.to_bytes(2, "big")

    @staticmethod
    def _exchange(sk_bytes: bytes, pk_bytes: bytes) -> bytes:
        if HAVE_FUNCTIONAL_CRYPTOGRAPHY:
            sk = X25519PrivateKey.from_private_bytes(sk_bytes)
            return sk.exchange(X25519PublicKey.from_public_bytes(pk_bytes))
        dh = _curves.x25519(sk_bytes, pk_bytes)
        # mirror the real library: an all-zero shared secret (small-order
        # peer point) is rejected, not silently key-scheduled
        if dh == b"\x00" * 32:
            raise ValueError("X25519 produced an all-zero shared secret")
        return dh

    @classmethod
    def generate_keypair(cls) -> Tuple[bytes, bytes]:
        if HAVE_FUNCTIONAL_CRYPTOGRAPHY:
            sk = X25519PrivateKey.generate()
            return (
                sk.private_bytes(Encoding.Raw, PrivateFormat.Raw, NoEncryption()),
                sk.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw),
            )
        sk_bytes = os.urandom(32)
        return sk_bytes, _curves.x25519_public(sk_bytes)

    @classmethod
    def public_from_private(cls, sk_bytes: bytes) -> bytes:
        if HAVE_FUNCTIONAL_CRYPTOGRAPHY:
            sk = X25519PrivateKey.from_private_bytes(sk_bytes)
            return sk.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
        return _curves.x25519_public(sk_bytes)

    @classmethod
    def _extract_and_expand(cls, dh: bytes, kem_context: bytes) -> bytes:
        suite = cls._suite_id()
        eae_prk = _labeled_extract(cls._hash, suite, b"", b"eae_prk", dh)
        return _labeled_expand(cls._hash, suite, eae_prk, b"shared_secret", kem_context, cls.N_SECRET)

    @classmethod
    def encap(cls, pk_r: bytes, ephemeral_sk: Optional[bytes] = None) -> Tuple[bytes, bytes]:
        """Returns (shared_secret, enc).  ephemeral_sk injectable for KATs."""
        sk_e_bytes = ephemeral_sk if ephemeral_sk is not None else os.urandom(32)
        enc = cls.public_from_private(sk_e_bytes)
        dh = cls._exchange(sk_e_bytes, pk_r)
        return cls._extract_and_expand(dh, enc + pk_r), enc

    @classmethod
    def decap(cls, enc: bytes, sk_r: bytes, pk_r: Optional[bytes] = None) -> bytes:
        """``pk_r`` (the recipient public key, which every HpkeKeypair
        already carries) skips re-deriving it from the private scalar —
        one whole ladder per open on the pure-Python path."""
        dh = cls._exchange(sk_r, enc)
        if pk_r is None:
            pk_r = cls.public_from_private(sk_r)
        return cls._extract_and_expand(dh, enc + pk_r)


class _P256Kem:
    ID = HpkeKemId.P256_HKDF_SHA256
    N_SECRET = 32
    N_PK = 65
    N_SK = 32
    _hash = hashlib.sha256
    # evaluated at class-definition time, so guarded by the import gate
    _curve = ec.SECP256R1() if HAVE_CRYPTOGRAPHY else None

    @classmethod
    def _suite_id(cls) -> bytes:
        return b"KEM" + cls.ID.value.to_bytes(2, "big")

    @classmethod
    def generate_keypair(cls) -> Tuple[bytes, bytes]:
        if HAVE_FUNCTIONAL_CRYPTOGRAPHY:
            sk = ec.generate_private_key(cls._curve)
            return (
                sk.private_numbers().private_value.to_bytes(32, "big"),
                sk.public_key().public_bytes(Encoding.X962, PublicFormat.UncompressedPoint),
            )
        while True:
            sk_bytes = os.urandom(32)
            try:
                return sk_bytes, _curves.p256_public(sk_bytes)
            except ValueError:  # pragma: no cover - scalar == 0 mod n
                continue

    @classmethod
    def public_from_private(cls, sk_bytes: bytes) -> bytes:
        if HAVE_FUNCTIONAL_CRYPTOGRAPHY:
            sk = ec.derive_private_key(int.from_bytes(sk_bytes, "big"), cls._curve)
            return sk.public_key().public_bytes(Encoding.X962, PublicFormat.UncompressedPoint)
        return _curves.p256_public(sk_bytes)

    @classmethod
    def _extract_and_expand(cls, dh: bytes, kem_context: bytes) -> bytes:
        suite = cls._suite_id()
        eae_prk = _labeled_extract(cls._hash, suite, b"", b"eae_prk", dh)
        return _labeled_expand(cls._hash, suite, eae_prk, b"shared_secret", kem_context, cls.N_SECRET)

    @classmethod
    def _exchange(cls, sk_bytes: bytes, pk_bytes: bytes) -> bytes:
        if HAVE_FUNCTIONAL_CRYPTOGRAPHY:
            sk = ec.derive_private_key(int.from_bytes(sk_bytes, "big"), cls._curve)
            peer = ec.EllipticCurvePublicKey.from_encoded_point(cls._curve, pk_bytes)
            return sk.exchange(ec.ECDH(), peer)
        return _curves.p256_ecdh(sk_bytes, pk_bytes)

    @classmethod
    def encap(cls, pk_r: bytes, ephemeral_sk: Optional[bytes] = None) -> Tuple[bytes, bytes]:
        if ephemeral_sk is None:
            ephemeral_sk, enc = cls.generate_keypair()
        else:
            enc = cls.public_from_private(ephemeral_sk)
        dh = cls._exchange(ephemeral_sk, pk_r)
        return cls._extract_and_expand(dh, enc + pk_r), enc

    @classmethod
    def decap(cls, enc: bytes, sk_r: bytes, pk_r: Optional[bytes] = None) -> bytes:
        dh = cls._exchange(sk_r, enc)
        if pk_r is None:
            pk_r = cls.public_from_private(sk_r)
        return cls._extract_and_expand(dh, enc + pk_r)


_KEMS = {k.ID: k for k in (_X25519Kem, _P256Kem)}

#: aead_id -> (key len, nonce len, AEAD factory).  The factories are the
#: utils/gcm.py seam: `cryptography`'s implementations when functional,
#: the KAT-anchored soft fallbacks otherwise — either way the returned
#: object answers .encrypt/.decrypt(nonce, data, aad).
_AEAD_PARAMS = {
    HpkeAeadId.AES_128_GCM: (16, 12, _aesgcm),
    HpkeAeadId.AES_256_GCM: (32, 12, _aesgcm),
    HpkeAeadId.CHACHA20_POLY1305: (32, 12, _chacha20poly1305),
}


def is_hpke_config_supported(config: HpkeConfig) -> bool:
    """reference: core/src/hpke.rs:31"""
    return (
        config.kem_id in _KEMS
        and config.kdf_id in _HASHES
        and config.aead_id in _AEAD_PARAMS
    )


def _key_schedule(kem_id, kdf_id, aead_id, shared_secret: bytes, info: bytes):
    """RFC 9180 §5.1 key schedule, base mode.  Returns (key, base_nonce)."""
    hash_fn = _HASHES[kdf_id]
    suite_id = (
        b"HPKE"
        + kem_id.value.to_bytes(2, "big")
        + kdf_id.value.to_bytes(2, "big")
        + aead_id.value.to_bytes(2, "big")
    )
    nk, nn, _cls = _AEAD_PARAMS[aead_id]
    psk_id_hash = _labeled_extract(hash_fn, suite_id, b"", b"psk_id_hash", b"")
    info_hash = _labeled_extract(hash_fn, suite_id, b"", b"info_hash", info)
    ks_context = b"\x00" + psk_id_hash + info_hash  # mode_base = 0x00
    secret = _labeled_extract(hash_fn, suite_id, shared_secret, b"secret", b"")
    key = _labeled_expand(hash_fn, suite_id, secret, b"key", ks_context, nk)
    base_nonce = _labeled_expand(hash_fn, suite_id, secret, b"base_nonce", ks_context, nn)
    return key, base_nonce


@dataclass(frozen=True)
class HpkeKeypair:
    """Public config + private key (reference: core/src/hpke.rs HpkeKeypair)."""

    config: HpkeConfig
    # Secret hygiene: the private key never reaches logs through repr
    # (reference: aggregator_core/src/lib.rs:28).
    private_key: bytes = field(repr=False)

    @classmethod
    def generate(
        cls,
        config_id: int,
        kem_id: HpkeKemId = HpkeKemId.X25519_HKDF_SHA256,
        kdf_id: HpkeKdfId = HpkeKdfId.HKDF_SHA256,
        aead_id: HpkeAeadId = HpkeAeadId.AES_128_GCM,
    ) -> "HpkeKeypair":
        """reference: core/src/hpke.rs:212 generate_hpke_config_and_private_key"""
        kem = _KEMS.get(kem_id)
        if kem is None:
            raise HpkeError(f"unsupported KEM {kem_id}")
        sk, pk = kem.generate_keypair()
        return cls(
            HpkeConfig(config_id, kem_id, kdf_id, aead_id, HpkePublicKey(pk)), sk
        )


def seal(
    recipient_config: HpkeConfig,
    application_info: HpkeApplicationInfo,
    plaintext: bytes,
    associated_data: bytes,
    _ephemeral_sk: Optional[bytes] = None,
) -> HpkeCiphertext:
    """One-shot base-mode seal (reference: core/src/hpke.rs:167)."""
    if not is_hpke_config_supported(recipient_config):
        raise HpkeError("unsupported HPKE configuration")
    kem = _KEMS[recipient_config.kem_id]
    shared_secret, enc = kem.encap(recipient_config.public_key.raw, _ephemeral_sk)
    key, base_nonce = _key_schedule(
        recipient_config.kem_id,
        recipient_config.kdf_id,
        recipient_config.aead_id,
        shared_secret,
        application_info.raw,
    )
    _nk, _nn, aead_cls = _AEAD_PARAMS[recipient_config.aead_id]
    ct = aead_cls(key).encrypt(base_nonce, plaintext, associated_data)  # seq 0
    return HpkeCiphertext(recipient_config.id, enc, ct)


def open_(
    recipient_keypair: HpkeKeypair,
    application_info: HpkeApplicationInfo,
    ciphertext: HpkeCiphertext,
    associated_data: bytes,
) -> bytes:
    """One-shot base-mode open (reference: core/src/hpke.rs:192)."""
    config = recipient_keypair.config
    if not is_hpke_config_supported(config):
        raise HpkeError("unsupported HPKE configuration")
    kem = _KEMS[config.kem_id]
    try:
        shared_secret = kem.decap(
            ciphertext.encapsulated_key,
            recipient_keypair.private_key,
            pk_r=config.public_key.raw,
        )
        key, base_nonce = _key_schedule(
            config.kem_id, config.kdf_id, config.aead_id, shared_secret, application_info.raw
        )
        _nk, _nn, aead_cls = _AEAD_PARAMS[config.aead_id]
        return aead_cls(key).decrypt(base_nonce, ciphertext.payload, associated_data)
    except HpkeError:
        raise
    except Exception as e:
        raise HpkeError(f"HPKE open failed: {type(e).__name__}") from e
