"""HTTP retry policy: exponential backoff over retryable failures.

The analog of ``retry_http_request`` (reference: core/src/retries.rs:102-205):
network errors and retryable status codes (server overload / transient
upstream failures) are retried with capped exponential backoff + jitter;
everything else returns immediately.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Optional, Tuple

from . import faults


def is_retryable_http_status(status: int) -> bool:
    """reference: core/src/retries.rs:205"""
    return status in (408, 429, 500, 502, 503, 504)


@dataclass
class HttpRetryPolicy:
    """reference: core/src/retries.rs:33 backoff parameters"""

    initial_interval: float = 0.1
    max_interval: float = 5.0
    multiplier: float = 2.0
    max_elapsed: float = 30.0
    max_attempts: int = 10

    def for_tests(self) -> "HttpRetryPolicy":
        return HttpRetryPolicy(0.001, 0.01, 2.0, 0.5, 3)


async def retry_http_request(
    session,
    method: str,
    url: str,
    *,
    data: Optional[bytes] = None,
    headers: Optional[dict] = None,
    policy: Optional[HttpRetryPolicy] = None,
) -> Tuple[int, bytes, dict]:
    """Issue a request, retrying retryable outcomes.

    Returns (status, body, headers) — on exhaustion, the last retryable
    response.  Raises the last transport-layer error if the final attempt
    failed before producing a response; never returns ``None``.
    ``max_elapsed`` bounds TOTAL wall time — request duration included,
    not just the backoff sleeps (a peer that burns 29s per hung attempt
    must not get ten of them).
    """
    import aiohttp

    policy = policy or HttpRetryPolicy()
    interval = policy.initial_interval
    start = time.monotonic()
    last: Optional[Tuple[int, bytes, dict]] = None
    last_exc: Optional[BaseException] = None
    for attempt in range(max(1, policy.max_attempts)):
        try:
            await faults.fire_async("http.request")
            async with session.request(
                method, url, data=data, headers=headers
            ) as resp:
                body = await resp.read()
                if not is_retryable_http_status(resp.status):
                    return resp.status, body, dict(resp.headers)
                last_exc = None
                last = (resp.status, body, dict(resp.headers))
        except (
            aiohttp.ClientError,
            asyncio.TimeoutError,
            faults.FaultInjectedError,
        ) as e:
            last_exc = e
        elapsed = time.monotonic() - start
        if elapsed >= policy.max_elapsed or attempt == policy.max_attempts - 1:
            break
        sleep = interval * (0.5 + random.random())
        await asyncio.sleep(sleep)
        interval = min(interval * policy.multiplier, policy.max_interval)
    if last_exc is not None:
        raise last_exc
    assert last is not None  # loop ran >= 1 attempt and didn't raise
    return last
