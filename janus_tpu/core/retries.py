"""HTTP retry policy: exponential backoff over retryable failures.

The analog of ``retry_http_request`` (reference: core/src/retries.rs:102-205):
network errors and retryable status codes (server overload / transient
upstream failures) are retried with capped exponential backoff + jitter;
everything else returns immediately.

Partition hardening (ISSUE 11): every attempt runs under a PER-ATTEMPT
timeout (``policy.attempt_timeout``) so a blackholed peer costs one
timeout, not an open-ended aiohttp default; the whole exchange runs
under an optional monotonic ``deadline`` the job drivers derive from
their lease expiry (a hung peer must release the lease, never pin it
past reap); a retryable response carrying ``Retry-After`` — the
helper's 503 backpressure hint — shapes the next sleep (capped at
``policy.max_interval``) instead of blind exponential backoff; and each
attempt's transport outcome feeds the per-peer health tracker
(core/peer_health.py) that gates future lease work.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Optional, Tuple

from . import faults, peer_health


def is_retryable_http_status(status: int) -> bool:
    """reference: core/src/retries.rs:205"""
    return status in (408, 429, 500, 502, 503, 504)


def is_transport_error(e: BaseException) -> bool:
    """Transport-layer classification shared by the retry loop, the
    peer-health tracker, and the job drivers' partition-pressure check:
    the failure happened below HTTP (connect/reset/timeout), so it says
    nothing about the peer's application health — only its reachability.
    Injected faults count only in their transport-shaped form
    (FaultInjectedTransportError — reset/flap/blackhole-backstop); a
    plain error-mode fault impersonates an APPLICATION failure and must
    not drive a peer suspect.  Likewise only aiohttp's CONNECTION-level
    errors count: InvalidURL (a misconfigured endpoint is an operator
    error, not a partition — suspecting it would mask the misconfig as
    network weather and release its jobs forever) and response/payload
    errors (the peer answered) do not."""
    if isinstance(
        e,
        (
            asyncio.TimeoutError,
            ConnectionError,
            faults.FaultInjectedTransportError,
        ),
    ):
        return True
    try:
        import aiohttp

        return isinstance(e, aiohttp.ClientConnectionError)
    except ImportError:  # pragma: no cover - aiohttp is baked in
        return False


def _parse_retry_after(headers: dict) -> Optional[float]:
    """Seconds form only (the helper emits integers); HTTP-date and junk
    are ignored rather than guessed at."""
    for key, value in headers.items():
        if key.lower() == "retry-after":
            try:
                seconds = float(value)
            except (TypeError, ValueError):
                return None
            return seconds if seconds >= 0 else None
    return None


@dataclass
class HttpRetryPolicy:
    """reference: core/src/retries.rs:33 backoff parameters"""

    initial_interval: float = 0.1
    max_interval: float = 5.0
    multiplier: float = 2.0
    max_elapsed: float = 30.0
    max_attempts: int = 10
    #: per-attempt wall clamp: a single hung/blackholed attempt is cut
    #: off here instead of riding aiohttp's defaults.  <= 0 disables
    #: (the total deadline/max_elapsed still bound the exchange).
    attempt_timeout: float = 0.0

    def for_tests(self) -> "HttpRetryPolicy":
        return HttpRetryPolicy(0.001, 0.01, 2.0, 0.5, 3)


async def retry_http_request(
    session,
    method: str,
    url: str,
    *,
    data: Optional[bytes] = None,
    headers: Optional[dict] = None,
    policy: Optional[HttpRetryPolicy] = None,
    deadline: Optional[float] = None,
) -> Tuple[int, bytes, dict]:
    """Issue a request, retrying retryable outcomes.

    Returns (status, body, headers) — on exhaustion, the last retryable
    response.  Raises the last transport-layer error if the final attempt
    failed before producing a response; never returns ``None``.
    ``max_elapsed`` bounds TOTAL wall time — request duration included,
    not just the backoff sleeps (a peer that burns 29s per hung attempt
    must not get ten of them).  ``deadline`` (``time.monotonic()``
    terms) bounds the exchange harder still: job drivers derive it from
    their lease expiry so a blackholed peer releases the lease instead
    of pinning it past reap.  Each attempt's transport outcome is
    recorded into the process-wide peer-health tracker; ANY response —
    retryable statuses included — counts as transport success.
    """
    import aiohttp

    policy = policy or HttpRetryPolicy()
    interval = policy.initial_interval
    start = time.monotonic()
    tracker = peer_health.tracker()
    peer = peer_health.origin_of(url)
    last: Optional[Tuple[int, bytes, dict]] = None
    last_exc: Optional[BaseException] = None

    async def one_attempt():
        # the injection hook sits INSIDE the per-attempt timeout scope:
        # a blackhole-mode fault parks exactly like a blackholed peer
        # and the same wait_for cancels it; the URL is the target
        # context that lets specs scope a partition to one direction
        await faults.fire_async("http.request", target=url)
        async with session.request(method, url, data=data, headers=headers) as resp:
            body = await resp.read()
            return resp.status, body, dict(resp.headers)

    for attempt in range(max(1, policy.max_attempts)):
        now = time.monotonic()
        if attempt > 0 and (
            now - start >= policy.max_elapsed
            or (deadline is not None and now >= deadline)
        ):
            break
        # the attempt clamp comes from the explicit knobs only — with
        # attempt_timeout off and no deadline, behavior (and the
        # exception surfaced on exhaustion) is exactly the legacy shape
        per_attempt = float("inf")
        if policy.attempt_timeout > 0:
            per_attempt = policy.attempt_timeout
        # An attempt is "unfairly" clamped when the caller's deadline
        # starves it of any real chance — less than 1s (or less than a
        # sub-second attempt_timeout).  A timeout then says nothing
        # about the peer.  Any attempt that got >= 1s and still timed
        # out DOES feed the tracker: a blackholed peer must register
        # even when the lease budget sits below attempt_timeout (e.g. a
        # 20s lease against the 30s default — discounting those would
        # disable partition gating for the whole deployment).
        fair_floor = min(
            per_attempt if per_attempt != float("inf") else 1.0, 1.0
        )
        deadline_clamped = False
        if deadline is not None and deadline - now < per_attempt:
            per_attempt = deadline - now
            deadline_clamped = per_attempt < fair_floor
        retry_after_s: Optional[float] = None
        try:
            if per_attempt != float("inf"):
                status, body, resp_headers = await asyncio.wait_for(
                    one_attempt(), timeout=max(per_attempt, 0.001)
                )
            else:
                status, body, resp_headers = await one_attempt()
        except (
            aiohttp.ClientError,
            asyncio.TimeoutError,
            ConnectionError,
            faults.FaultInjectedError,
        ) as e:
            last_exc = e
            # only transport-SHAPED failures feed peer health: an
            # error-mode injected fault (application-shaped) is retried
            # like before but says nothing about reachability — and a
            # timeout fired by OUR OWN lease-derived deadline (the
            # attempt got less than its fair attempt_timeout) says
            # nothing about the peer either: a step that spent its lease
            # on local work must not drive a healthy-but-not-instant
            # helper suspect process-wide
            if is_transport_error(e) and not (
                deadline_clamped and isinstance(e, asyncio.TimeoutError)
            ):
                tracker.record_transport_failure(peer)
        else:
            tracker.record_success(peer)
            if not is_retryable_http_status(status):
                return status, body, resp_headers
            last_exc = None
            last = (status, body, resp_headers)
            retry_after_s = _parse_retry_after(resp_headers)
        now = time.monotonic()
        if now - start >= policy.max_elapsed or attempt == policy.max_attempts - 1:
            break
        if deadline is not None and now >= deadline:
            break
        if retry_after_s is not None:
            # the peer told us when to come back (503 backpressure):
            # honor it, capped so a hostile/buggy hint cannot park us,
            # with UPWARD jitter — every exchange the helper shed got the
            # same hint, and re-arriving in one synchronized wave would
            # recreate the overload the hint exists to relieve (never
            # jitter below the hint: that violates it)
            sleep = min(retry_after_s, policy.max_interval) * (
                1.0 + 0.25 * random.random()
            )
            from .metrics import GLOBAL_METRICS

            if GLOBAL_METRICS.registry is not None:
                GLOBAL_METRICS.http_retry_after_honored.inc()
        else:
            sleep = interval * (0.5 + random.random())
        if deadline is not None:
            sleep = min(sleep, max(0.0, deadline - time.monotonic()))
        await asyncio.sleep(sleep)
        interval = min(interval * policy.multiplier, policy.max_interval)
    if last_exc is not None:
        raise last_exc
    if last is None:
        # the deadline was exhausted before any attempt produced an
        # outcome (driver handed us an already-spent lease budget)
        raise asyncio.TimeoutError(
            f"deadline exhausted before any attempt to {url}"
        )
    return last
