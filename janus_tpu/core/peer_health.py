"""Per-peer transport health: suspect gating for partitioned peers.

Janus's two aggregators coordinate only through their datastores and DAP
HTTPS calls to the peer, so the failure that matters at fleet scale is a
peer that is *unreachable* — partitioned, blackholed, flapping — while
everything local stays healthy.  Without gating, every job driver burns
a lease (and a slice of its ``max_step_attempts`` budget) per delivery
discovering the same dead link, and a long partition abandons jobs that
would have finished fine after the heal.

This module is the executor circuit breaker's pattern applied to the
HTTP path, with one deliberate difference: past the suspect dwell the
gate goes half-open for ALL comers rather than a single probe slot — a
healed fleet-wide partition should heal fleet-wide, and concurrent
probes against a still-dead peer just re-suspect it (the lease-backoff
jitter in ``job_driver.step_retry_delay`` keeps the probe wave spread).

States (exported as the ``janus_peer_health{peer,state}`` state-set
gauge and the /statusz "peers" section):

    healthy  transport is fine; every request flows
    suspect  >= ``failure_threshold`` consecutive transport failures;
             requests are refused (``allow()`` is False) until the
             dwell elapses — job drivers release their leases with
             retryable backoff instead of attempting the peer
    probing  suspect past its dwell: requests flow again; the first
             success restores healthy, the first transport failure
             re-suspects (and restarts the dwell)

Only TRANSPORT failures count (connect refused/reset, timeouts,
injected transport faults): an HTTP response of any status — 503
backpressure included — proves the peer reachable and resets the
counter.  Fed by ``retry_http_request`` (core/retries.py) per attempt;
consulted by both job drivers before lease work is burned.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional
from urllib.parse import urlsplit

PEER_HEALTHY, PEER_SUSPECT, PEER_PROBING = "healthy", "suspect", "probing"
_STATES = (PEER_HEALTHY, PEER_SUSPECT, PEER_PROBING)


def origin_of(url: str) -> str:
    """Peer identity for tracking/metrics: the URL's host:port authority.
    Falls back to the raw string for non-URL targets (tests)."""
    try:
        netloc = urlsplit(url).netloc
    except ValueError:
        return url
    return netloc or url


class PeerHealth:
    """One peer's transport state machine; thread-safe (the retry loop
    records from event loops, /statusz reads from the health server)."""

    def __init__(self, peer: str, failure_threshold: int, suspect_dwell_s: float):
        self.peer = peer
        self.failure_threshold = failure_threshold
        self.suspect_dwell_s = suspect_dwell_s
        self.consecutive_failures = 0
        self.transport_failures_total = 0
        self.suspected = False
        self.suspected_at = 0.0
        #: suspect transitions (a flapping link shows up as a high count)
        self.suspect_transitions = 0
        #: when the peer last transitioned non-healthy -> healthy (0 =
        #: never suspected): the ceiling guards' heal-grace signal — a
        #: job whose delivery count was inflated by the partition gets
        #: its post-heal attempt instead of an entry abandonment
        self.healed_at = 0.0
        self._lock = threading.Lock()

    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if not self.suspected:
            return PEER_HEALTHY
        if time.monotonic() - self.suspected_at >= self.suspect_dwell_s:
            return PEER_PROBING
        return PEER_SUSPECT

    def allow(self) -> bool:
        """May a request to this peer be attempted right now?  True for
        healthy and probing (dwell elapsed), False inside the dwell."""
        return self.state() != PEER_SUSPECT

    def record_success(self) -> None:
        with self._lock:
            self.consecutive_failures = 0
            was = self.suspected
            self.suspected = False
            if was:
                self.healed_at = time.monotonic()
        if was:
            self._publish()

    def recently_healed(self, window_s: float) -> bool:
        with self._lock:
            return (
                self.healed_at > 0
                and time.monotonic() - self.healed_at < window_s
            )

    def record_transport_failure(self) -> None:
        transitioned = False
        with self._lock:
            self.consecutive_failures += 1
            self.transport_failures_total += 1
            if self.failure_threshold > 0 and (
                self.consecutive_failures >= self.failure_threshold
            ):
                if not self.suspected:
                    self.suspect_transitions += 1
                    transitioned = True
                # a failing probe (or further failures while suspect)
                # restarts the dwell: the peer earns its way back only
                # with a real success
                self.suspected = True
                self.suspected_at = time.monotonic()
        self._publish(count_failure=True)
        if transitioned:
            import logging

            logging.getLogger("janus_tpu.peer_health").warning(
                "peer %s SUSPECT after %d consecutive transport failure(s); "
                "gating requests for %.1fs before probing",
                self.peer,
                self.consecutive_failures,
                self.suspect_dwell_s,
            )

    def _publish(self, count_failure: bool = False) -> None:
        from .metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is None:
            return
        if count_failure:
            GLOBAL_METRICS.peer_transport_failures.labels(peer=self.peer).inc()
        current = self.state()
        for state in _STATES:
            GLOBAL_METRICS.peer_health.labels(peer=self.peer, state=state).set(
                1.0 if state == current else 0.0
            )

    def stats(self) -> dict:
        with self._lock:
            state = self._state_locked()
            out = {
                "state": state,
                "consecutive_failures": self.consecutive_failures,
                "transport_failures_total": self.transport_failures_total,
                "suspect_transitions": self.suspect_transitions,
            }
            if self.suspected:
                out["suspected_age_s"] = round(
                    time.monotonic() - self.suspected_at, 3
                )
        return out


class PeerHealthTracker:
    """Process-wide peer registry (one per process, like the executor):
    every driver in the process shares each peer's verdict, so replica A
    discovering a partition spares replica B the probe."""

    def __init__(self, failure_threshold: int = 3, suspect_dwell_s: float = 10.0):
        self.failure_threshold = failure_threshold
        self.suspect_dwell_s = suspect_dwell_s
        self._peers: Dict[str, PeerHealth] = {}
        self._lock = threading.Lock()

    def configure(
        self,
        failure_threshold: Optional[int] = None,
        suspect_dwell_s: Optional[float] = None,
    ) -> None:
        """Adjust thresholds (driver construction); existing peers adopt
        them — the tracker is process-wide, so the last configured driver
        wins, which is fine because every driver in one binary shares one
        config."""
        with self._lock:
            if failure_threshold is not None:
                self.failure_threshold = failure_threshold
            if suspect_dwell_s is not None:
                self.suspect_dwell_s = suspect_dwell_s
            for p in self._peers.values():
                p.failure_threshold = self.failure_threshold
                p.suspect_dwell_s = self.suspect_dwell_s

    def _peer(self, url: str) -> PeerHealth:
        key = origin_of(url)
        with self._lock:
            p = self._peers.get(key)
            if p is None:
                p = PeerHealth(key, self.failure_threshold, self.suspect_dwell_s)
                self._peers[key] = p
            return p

    def allow(self, url: str) -> bool:
        return self._peer(url).allow()

    def state(self, url: str) -> str:
        return self._peer(url).state()

    def is_suspect(self, url: str) -> bool:
        """True while the peer is suspect OR probing — i.e. the tracker
        currently believes the link is (or may still be) partitioned.
        Job drivers use this to classify a failed exchange as partition
        pressure (release without consuming the attempt budget)."""
        return self._peer(url).state() != PEER_HEALTHY

    def record_success(self, url: str) -> None:
        self._peer(url).record_success()

    def recently_healed(self, url: str, window_s: float) -> bool:
        """Did this peer transition back to healthy within ``window_s``?
        False for a peer that was never suspect — the ceiling guards use
        this to tell partition debris from a genuinely sick job."""
        return self._peer(url).recently_healed(window_s)

    def record_transport_failure(self, url: str) -> None:
        self._peer(url).record_transport_failure()

    def republish_metrics(self) -> None:
        """Refresh every peer's state-set gauge.  The suspect -> probing
        transition happens purely by time passing, so with no traffic
        flowing (a quiesced partition) the gauge would otherwise report
        suspect=1 forever while the tracker is actually probing — the
        status sampler calls this each tick so alerts match live state."""
        with self._lock:
            peers = list(self._peers.values())
        for p in peers:
            p._publish()

    def partition_signal(self, window_s: float) -> bool:
        """Cheap in-memory pre-check for the ceiling guards: is ANY peer
        currently non-healthy, or healed within ``window_s``?  False in
        the overwhelmingly common no-partition case, letting callers
        skip a datastore lookup."""
        with self._lock:
            peers = list(self._peers.values())
        return any(
            p.state() != PEER_HEALTHY or p.recently_healed(window_s)
            for p in peers
        )

    def stats(self) -> Dict[str, dict]:
        with self._lock:
            peers = list(self._peers.items())
        return {key: p.stats() for key, p in sorted(peers)}

    def reset(self) -> None:
        with self._lock:
            self._peers = {}


# -- process-wide instance ---------------------------------------------------

_TRACKER = PeerHealthTracker()


def tracker() -> PeerHealthTracker:
    return _TRACKER


def reset_peer_health() -> None:
    """Test hook: drop every peer's state (thresholds keep their last
    configured values — reconfigure explicitly if a test needs defaults)."""
    _TRACKER.reset()
