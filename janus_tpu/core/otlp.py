"""OTLP export: plug the fleet's spans and metrics into real collectors.

The analog of the reference's ``otlp`` trace/metrics features (reference:
aggregator/src/trace.rs OpenTelemetryConfiguration + metrics.rs otlp
exporter): when ``common.otlp_endpoint`` is set, ChromeTracer spans (via
the span-sink hook in core/trace.py) and the process's metric registry
(prometheus_client or the pure-Python FallbackRegistry — both) are pushed
to an OTLP collector.

IMPORT-GATED on the **opentelemetry-sdk**'s presence.  The bare
``opentelemetry`` API package is not enough (this container ships the API
without the SDK), so the gate probes ``opentelemetry.sdk`` specifically.
Without the SDK the exporter is a FIRST-CLASS no-op: configuring it never
raises, spans offered to it are counted as dropped, export ticks are
no-ops, and ``/statusz`` reports the ``otlp`` section as ``unavailable``
— a binary whose config names a collector starts cleanly anywhere and
says exactly why nothing is arriving.

Span path (SDK present): spans are queued by the trace sink and flushed
on the status-sampler tick through an SDK tracer backed by the OTLP/HTTP
span exporter; the original 32-hex trace id is preserved by parenting
each span under a remote SpanContext carrying it, so the collector's view
joins the same cross-process timeline the chrome-trace merge does.

Metric path (SDK present): each export tick snapshots the registry and
POSTs one OTLP/HTTP JSON resourceMetrics document to
``<endpoint>/v1/metrics`` — counters as monotonic sums, gauges as gauges,
histograms as OTLP histograms with the registry's bucket bounds.
"""

from __future__ import annotations

import json
import logging
import secrets
import threading
import time
import urllib.request
from collections import deque
from dataclasses import dataclass
from typing import Optional

logger = logging.getLogger("janus_tpu.otlp")

try:  # the gate: SDK, not just the API shim
    from opentelemetry.sdk.resources import Resource  # noqa: F401

    HAVE_OTEL_SDK = True
except ImportError:  # pragma: no cover - exercised on this container
    HAVE_OTEL_SDK = False

OTEL_UNAVAILABLE_REASON = "opentelemetry-sdk not installed"


@dataclass
class OtlpConfig:
    """``common.otlp_endpoint`` plus the exporter's local knobs."""

    endpoint: str
    service_name: str = "janus_tpu"
    #: spans buffered between export ticks; beyond it the OLDEST are
    #: dropped (and counted) — export trouble must never grow memory
    max_queue_spans: int = 4096
    timeout_s: float = 5.0


class OtlpExporter:
    """Span queue + metric snapshot pusher with self-reporting health.

    All public methods are safe to call whether or not the SDK is
    installed; ``available`` says which world we are in.
    """

    def __init__(self, config: OtlpConfig):
        self.config = config
        self.available = HAVE_OTEL_SDK
        self._lock = threading.Lock()
        self._queue: deque = deque()
        self._queued_total = 0
        self._dropped_total = 0
        self._exported_total = 0
        self._exports_ok = 0
        self._exports_err = 0
        self._last_export_t: Optional[float] = None
        self._last_error: Optional[str] = None
        self._sdk_tracer = None
        if self.available:
            try:
                self._sdk_tracer = self._build_sdk_tracer()
            except Exception as e:  # SDK present but exporter wiring failed
                self.available = False
                self._last_error = f"otlp sdk setup failed: {e}"
                logger.exception("OTLP exporter setup failed; exporting disabled")

    # -- SDK wiring (never runs on SDK-less containers) -----------------
    def _build_sdk_tracer(self):  # pragma: no cover - needs the SDK
        from opentelemetry.exporter.otlp.proto.http.trace_exporter import (
            OTLPSpanExporter,
        )
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor

        resource = Resource.create({"service.name": self.config.service_name})
        provider = TracerProvider(resource=resource)
        provider.add_span_processor(
            BatchSpanProcessor(
                OTLPSpanExporter(
                    endpoint=self.config.endpoint.rstrip("/") + "/v1/traces",
                    timeout=self.config.timeout_s,
                ),
                # the processor's queue must not undercut our own drain
                # size, or a burst silently drops inside the SDK
                max_queue_size=max(2048, self.config.max_queue_spans),
            )
        )
        self._sdk_provider = provider
        return provider.get_tracer("janus_tpu")

    def shutdown(self) -> None:
        """Tear down the SDK pipeline (flush + stop its export thread).
        configure_otlp calls this on replace/disable so spans never keep
        flowing to an endpoint the operator disconnected; safe to call on
        an unavailable exporter."""
        provider = getattr(self, "_sdk_provider", None)
        if provider is not None:  # pragma: no cover - needs the SDK
            try:
                provider.shutdown()
            except Exception:
                logger.exception("OTLP provider shutdown failed")
            self._sdk_provider = None
            self._sdk_tracer = None
            self.available = False

    # -- span intake (the core/trace.py sink) ---------------------------
    def record_span(
        self, name: str, cat: str, epoch_start_s: float, dur_s: float, args: dict
    ) -> None:
        """Queue one closed span.  Inert (drop + count) without the SDK."""
        from .metrics import GLOBAL_METRICS

        if not self.available:
            with self._lock:
                self._dropped_total += 1
            if GLOBAL_METRICS.registry is not None:
                GLOBAL_METRICS.otlp_spans.labels(outcome="dropped").inc()
            return
        dropped = 0
        with self._lock:
            self._queue.append((name, cat, epoch_start_s, dur_s, dict(args or {})))
            self._queued_total += 1
            while len(self._queue) > self.config.max_queue_spans:
                self._queue.popleft()
                self._dropped_total += 1
                dropped += 1
        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.otlp_spans.labels(outcome="queued").inc()
            if dropped:
                GLOBAL_METRICS.otlp_spans.labels(outcome="dropped").inc(dropped)

    # -- export tick (status-sampler cadence) ---------------------------
    def export_once(self, metrics=None) -> bool:
        """Flush queued spans and push one metric snapshot.  Never raises;
        returns True on a fully successful export.  A no-op (and counted
        as such) when the SDK is absent."""
        from .metrics import GLOBAL_METRICS

        metrics = metrics if metrics is not None else GLOBAL_METRICS
        have = metrics.registry is not None
        if not self.available:
            if have:
                metrics.otlp_exports.labels(outcome="noop").inc()
            return False
        with self._lock:
            spans, self._queue = list(self._queue), deque()
        ok = True
        try:  # pragma: no cover - needs the SDK
            self._export_spans_sdk(spans)
            # BatchSpanProcessor delivers asynchronously: force the flush
            # and only count spans "exported" when it reports success —
            # a broken /v1/traces pipeline must not read as healthy
            if spans and not self._sdk_provider.force_flush(
                int(self.config.timeout_s * 1000)
            ):
                raise RuntimeError("span flush timed out / dropped")
            self._post_metrics_json(metrics)
            with self._lock:
                self._exported_total += len(spans)
                self._exports_ok += 1
                self._last_export_t = time.monotonic()
                self._last_error = None
        except Exception as e:  # pragma: no cover - needs the SDK
            ok = False
            with self._lock:
                self._exports_err += 1
                self._dropped_total += len(spans)
                self._last_error = str(e)[:200]
            logger.warning("OTLP export failed: %s", e)
        if have:
            metrics.otlp_exports.labels(outcome="ok" if ok else "error").inc()
            if ok and spans:
                metrics.otlp_spans.labels(outcome="exported").inc(len(spans))
        return ok

    def _export_spans_sdk(self, spans) -> None:  # pragma: no cover - needs SDK
        import opentelemetry.trace as ot

        for name, cat, epoch_start_s, dur_s, args in spans:
            start_ns = int(epoch_start_s * 1e9)
            end_ns = start_ns + max(0, int(dur_s * 1e9))
            context = None
            trace_id = args.get("trace_id")
            if isinstance(trace_id, str) and len(trace_id) == 32:
                try:
                    # parent the span under a remote context carrying the
                    # fleet's minted trace id, so the collector's trace
                    # view joins the chrome-trace/W3C one
                    parent = ot.NonRecordingSpan(
                        ot.SpanContext(
                            trace_id=int(trace_id, 16),
                            span_id=int(secrets.token_hex(8), 16),
                            is_remote=True,
                            trace_flags=ot.TraceFlags(ot.TraceFlags.SAMPLED),
                        )
                    )
                    context = ot.set_span_in_context(parent)
                except Exception:
                    context = None
            attrs = {"janus.cat": cat}
            for k, v in args.items():
                if isinstance(v, (str, bool, int, float)):
                    attrs[f"janus.{k}"] = v
            span = self._sdk_tracer.start_span(
                name, context=context, start_time=start_ns, attributes=attrs
            )
            span.end(end_time=end_ns)

    # -- metrics as OTLP/HTTP JSON --------------------------------------
    def _post_metrics_json(self, metrics) -> None:  # pragma: no cover - needs SDK
        doc = self._metrics_document(metrics)
        if doc is None:
            return
        req = urllib.request.Request(
            self.config.endpoint.rstrip("/") + "/v1/metrics",
            data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.config.timeout_s):
            pass

    def _metrics_document(self, metrics) -> Optional[dict]:
        """One OTLP/HTTP JSON resourceMetrics doc from the registry
        snapshot (either backend).  Pure and SDK-free, so the mapping is
        unit-testable on this container."""
        now_ns = int(time.time() * 1e9)
        otlp_metrics = []
        for fam in snapshot_metric_families(metrics):
            dps = []
            if fam["kind"] == "histogram":
                for labels, h in fam["series"]:
                    dps.append(
                        {
                            "attributes": _otlp_attrs(labels),
                            "timeUnixNano": now_ns,
                            "count": h["count"],
                            "sum": h["sum"],
                            "bucketCounts": h["bucket_counts"],
                            "explicitBounds": h["bounds"],
                        }
                    )
                body = {"dataPoints": dps, "aggregationTemporality": 2}
                key = "histogram"
            else:
                for labels, value in fam["series"]:
                    dps.append(
                        {
                            "attributes": _otlp_attrs(labels),
                            "timeUnixNano": now_ns,
                            "asDouble": value,
                        }
                    )
                if fam["kind"] == "counter":
                    body = {
                        "dataPoints": dps,
                        "aggregationTemporality": 2,
                        "isMonotonic": True,
                    }
                    key = "sum"
                else:
                    body = {"dataPoints": dps}
                    key = "gauge"
            if dps:
                otlp_metrics.append(
                    {"name": fam["name"], "description": fam["help"], key: body}
                )
        if not otlp_metrics:
            return None
        return {
            "resourceMetrics": [
                {
                    "resource": {
                        "attributes": _otlp_attrs(
                            {"service.name": self.config.service_name}
                        )
                    },
                    "scopeMetrics": [
                        {
                            "scope": {"name": "janus_tpu"},
                            "metrics": otlp_metrics,
                        }
                    ],
                }
            ]
        }

    # -- health ----------------------------------------------------------
    def health(self) -> dict:
        """The /statusz "otlp" section (and the soak's probe)."""
        with self._lock:
            last_age = (
                round(time.monotonic() - self._last_export_t, 1)
                if self._last_export_t is not None
                else None
            )
            # the SDK may be present but mis-wired (__init__ caught a
            # setup error): report THAT, not a missing-SDK message the
            # operator cannot act on
            reason = None
            if not self.available:
                reason = self._last_error or OTEL_UNAVAILABLE_REASON
            return {
                "state": "active" if self.available else "unavailable",
                "reason": reason,
                "endpoint": self.config.endpoint,
                "queued": len(self._queue),
                "queued_total": self._queued_total,
                "exported_total": self._exported_total,
                "dropped_total": self._dropped_total,
                "exports_ok": self._exports_ok,
                "exports_err": self._exports_err,
                "last_export_age_s": last_age,
                "last_error": self._last_error,
            }


def _otlp_attrs(labels: dict) -> list:
    return [{"key": k, "value": {"stringValue": str(v)}} for k, v in labels.items()]


def snapshot_metric_families(metrics) -> list:
    """Uniform registry snapshot: [{name, help, kind, series}] where
    ``series`` is [(labels_dict, value_or_histogram_dict)] — one reader for
    prometheus_client and FallbackRegistry so the OTLP mapping (and the
    SLO evaluator's histogram reads) cannot drift between backends."""
    from .metrics import FallbackRegistry

    registry = metrics.registry
    if registry is None:
        return []
    out = []
    if isinstance(registry, FallbackRegistry):
        for m in registry.families():
            with m._lock:
                if m.kind == "histogram":
                    series = []
                    for key, (count, total, buckets) in m._hist.items():
                        series.append(
                            (
                                dict(zip(m.labelnames, key)),
                                {
                                    "count": count,
                                    "sum": total,
                                    "bounds": list(m.buckets),
                                    # OTLP wants per-bucket (not cumulative)
                                    # counts plus the +Inf overflow bucket
                                    "bucket_counts": _decumulate(buckets, count),
                                },
                            )
                        )
                else:
                    series = [
                        (dict(zip(m.labelnames, key)), value)
                        for key, value in m._values.items()
                    ]
            out.append(
                {
                    "name": m.name,
                    "help": m.documentation,
                    "kind": m.kind,
                    "series": series,
                }
            )
        return out
    # prometheus_client CollectorRegistry
    for fam in registry.collect():
        kind = {"counter": "counter", "gauge": "gauge", "histogram": "histogram"}.get(
            fam.type
        )
        if kind is None:
            continue
        if kind == "histogram":
            # regroup flat samples back into per-labelset histograms
            hists: dict = {}
            for s in fam.samples:
                labels = dict(s.labels)
                le = labels.pop("le", None)
                key = tuple(sorted(labels.items()))
                h = hists.setdefault(
                    key, {"labels": labels, "buckets": [], "count": 0, "sum": 0.0}
                )
                if s.name.endswith("_bucket"):
                    h["buckets"].append((float(le), s.value))
                elif s.name.endswith("_count"):
                    h["count"] = int(s.value)
                elif s.name.endswith("_sum"):
                    h["sum"] = s.value
            series = []
            for h in hists.values():
                buckets = sorted(h["buckets"])
                bounds = [b for b, _ in buckets if b != float("inf")]
                cumulative = [int(v) for b, v in buckets if b != float("inf")]
                series.append(
                    (
                        h["labels"],
                        {
                            "count": h["count"],
                            "sum": h["sum"],
                            "bounds": bounds,
                            "bucket_counts": _decumulate(cumulative, h["count"]),
                        },
                    )
                )
        else:
            series = [
                (dict(s.labels), s.value)
                for s in fam.samples
                if not s.name.endswith(("_created", "_gsum", "_gcount"))
            ]
        out.append(
            {"name": fam.name, "help": fam.documentation, "kind": kind, "series": series}
        )
    return out


def _decumulate(cumulative, total) -> list:
    """Cumulative bucket counts -> per-bucket counts + +Inf overflow."""
    out, prev = [], 0
    for c in cumulative:
        out.append(int(c - prev))
        prev = c
    out.append(int(total - prev))
    return out


# -- process-wide exporter ----------------------------------------------------

_EXPORTER: Optional[OtlpExporter] = None


def configure_otlp(
    endpoint: Optional[str], service_name: str = "janus_tpu"
) -> Optional[OtlpExporter]:
    """Enable (or disable with a falsy endpoint) process-wide OTLP export.
    Registers the span sink with core/trace.py only when the SDK is
    actually present — the unavailable exporter costs the traced paths
    nothing."""
    global _EXPORTER
    from .trace import register_span_sink, unregister_span_sink

    if _EXPORTER is not None:
        unregister_span_sink(_EXPORTER.record_span)
        _EXPORTER.shutdown()
        _EXPORTER = None
    if not endpoint:
        return None
    _EXPORTER = OtlpExporter(OtlpConfig(endpoint=endpoint, service_name=service_name))
    if _EXPORTER.available:
        register_span_sink(_EXPORTER.record_span)
    return _EXPORTER


def otlp_exporter() -> Optional[OtlpExporter]:
    return _EXPORTER


def export_tick() -> None:
    """One status-sampler-driven export pass; no-op when unconfigured."""
    from .metrics import GLOBAL_METRICS

    if _EXPORTER is None:
        return
    _EXPORTER.export_once()
    if GLOBAL_METRICS.registry is not None:
        h = _EXPORTER.health()
        GLOBAL_METRICS.otlp_last_export_age.set(
            h["last_export_age_s"] if h["last_export_age_s"] is not None else -1
        )


def otlp_health() -> dict:
    """The /statusz "otlp" section: exporter health when configured, and
    an explicit disabled/unavailable marker when not."""
    if _EXPORTER is not None:
        return _EXPORTER.health()
    return {
        "state": "disabled" if HAVE_OTEL_SDK else "unavailable",
        "reason": None if HAVE_OTEL_SDK else OTEL_UNAVAILABLE_REASON,
        "endpoint": None,
    }
