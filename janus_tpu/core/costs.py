"""Per-task device-plane cost attribution (ISSUE 12 tentpole).

The executor coalesces many tasks' reports into one mega-batch flush, so
chip-level metrics (``janus_executor_launch_duration_seconds``) can say
the device is saturated without saying WHICH task is burning it — exactly
the per-tenant accelerator-utilization accounting framework-level proof
accelerators name as the scalability bottleneck (ZK-Flex, PAPERS.md).
This module is the attribution ledger:

* :meth:`TaskCostModel.attribute_flush` splits a flush's measured
  stage/launch durations across its submissions **proportionally by
  rows** into ``janus_task_device_seconds_total{task,phase,path}``; the
  split is conservative by construction — the per-task shares sum to the
  measured total (tests/test_cost_attribution.py proves it to 1e-6 for
  multi-task, oracle-fallback and padded-tail flushes).
* The ``path`` label (``device`` | ``oracle``) makes failure-domain cost
  shifts visible: when a breaker opens and jobs degrade to the CPU
  oracle, their seconds MOVE from ``path="device"`` to ``path="oracle"``
  on the same task series.  Oracle-side attribution rides the existing
  ``_observe_prepare`` seam in vdaf/backend.py via a thread-local task
  scope (:func:`run_in_task_scope`) because oracle batches run on worker
  threads where contextvars set on the event loop are invisible.
* ``janus_task_rows_total{task,outcome}`` (ok | rejected | error) and the
  ``janus_task_queue_delay_seconds{task}`` histogram complete the
  per-task picture: throughput, backpressure pain, and scheduling delay.

Cardinality is BOUNDED: at most ``max_tasks`` live task labels; beyond
the cap new tasks attribute to the ``task="other"`` overflow label until
retirement (riding the binaries' status-sampler tick, the same pattern as
``DeviceExecutor.retire_idle_buckets``) frees idle slots and removes
their series.  The model is process-wide — drivers, the helper, and the
executor all feed one ledger, like GLOBAL_METRICS itself.
"""

from __future__ import annotations

import base64
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

#: Overflow label: tasks beyond the cardinality cap attribute here until
#: retirement frees slots.  A rising "other" share on a dashboard is the
#: signal to raise ``common.cost_task_cardinality``.
OVERFLOW_LABEL = "other"
#: Label for rows submitted without a task identity (legacy callers).
UNATTRIBUTED_LABEL = "unattributed"

#: The closed label sets every series of a retired task must be swept
#: from (remove_series is quiet when a combination never fired).
#: stage/launch: executor flush shares; init/combine: direct backend
#: batches (oracle or device); drain: accumulator spill readbacks.
PHASES = ("stage", "launch", "init", "combine", "drain")
PATHS = ("device", "oracle")
ROW_OUTCOMES = ("ok", "rejected", "error")


def task_label(ident) -> str:
    """Render a task identity (the DAP task id bytes the drivers thread as
    ``task_ident``) as a bounded metric label — unpadded base64url, the
    same rendering TaskId.__str__ uses, so /metrics series line up with
    task ids in logs and the task API."""
    if ident is None:
        return UNATTRIBUTED_LABEL
    if isinstance(ident, bytes):
        return base64.urlsafe_b64encode(ident).rstrip(b"=").decode()
    return str(ident)


class _Entry:
    __slots__ = ("label", "last_used")

    def __init__(self, label: str):
        self.label = label
        self.last_used = time.monotonic()


class TaskCostModel:
    """Bounded per-task attribution ledger (one per process)."""

    def __init__(self, max_tasks: int = 64):
        self.max_tasks = max_tasks
        self._entries: "OrderedDict[object, _Entry]" = OrderedDict()
        self._lock = threading.Lock()
        #: attributions that landed on the overflow label (statusz + the
        #: operator's cue to raise the cap)
        self.overflowed = 0

    def configure(self, max_tasks: int) -> None:
        """Applied once at binary bootstrap; a lower cap takes effect on
        the next retirement pass (live entries are never evicted mid-use)."""
        with self._lock:
            self.max_tasks = max_tasks

    # -- label admission -------------------------------------------------
    def label_for(self, ident) -> str:
        """The task's metric label, admitting it into the tracked set
        (LRU-ordered).  Beyond the cap new tasks get the ``other``
        overflow label — cardinality is capped at ``max_tasks + 2``
        (overflow + unattributed) no matter how many tasks churn through."""
        if ident is None:
            return UNATTRIBUTED_LABEL
        key = ident if isinstance(ident, (bytes, str, int)) else repr(ident)
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e.last_used = time.monotonic()
                self._entries.move_to_end(key)
                return e.label
            if len(self._entries) >= max(1, self.max_tasks):
                self.overflowed += 1
                return OVERFLOW_LABEL
            e = _Entry(task_label(ident))
            self._entries[key] = e
            return e.label

    # -- attribution -----------------------------------------------------
    def attribute_flush(
        self,
        parts: Sequence[Tuple[object, int]],
        phase_seconds: Dict[str, float],
        path: str = "device",
    ) -> None:
        """Split each measured phase duration across ``parts`` —
        ``(task_ident, rows)`` per submission — proportionally by rows.
        Conservation invariant: sum over parts of attributed seconds ==
        the measured phase total (floating error only; padding rows are
        the flush's overhead and are attributed WITH the rows that caused
        them, so no time is orphaned on a phantom "padding task")."""
        from .metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is None or not parts:
            return
        total_rows = sum(max(0, r) for _, r in parts)
        if total_rows <= 0:
            return
        # coalesce one submission-set's shares per label first: N
        # submissions of one task in one flush inc its series once
        shares: Dict[str, Dict[str, float]] = {}
        for ident, rows in parts:
            if rows <= 0:
                continue
            label = self.label_for(ident)
            frac = rows / total_rows
            tab = shares.setdefault(label, {})
            for phase, seconds in phase_seconds.items():
                if seconds and seconds > 0:
                    tab[phase] = tab.get(phase, 0.0) + seconds * frac
        for label, tab in shares.items():
            for phase, seconds in tab.items():
                GLOBAL_METRICS.task_device_seconds.labels(
                    task=label, phase=phase, path=path
                ).inc(seconds)

    def attribute_direct(
        self, ident, phase: str, path: str, seconds: float
    ) -> None:
        """Whole-batch attribution to ONE task (the oracle hook: an oracle
        batch serves exactly one task, so the measured duration attributes
        without a proportional split)."""
        from .metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is None or seconds <= 0:
            return
        GLOBAL_METRICS.task_device_seconds.labels(
            task=self.label_for(ident), phase=phase, path=path
        ).inc(seconds)

    def observe_rows(self, ident, outcome: str, rows: int) -> None:
        from .metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is None or rows <= 0:
            return
        GLOBAL_METRICS.task_rows.labels(
            task=self.label_for(ident), outcome=outcome
        ).inc(rows)

    def observe_queue_delay(self, ident, delay_s: float) -> None:
        from .metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is None:
            return
        GLOBAL_METRICS.task_queue_delay.labels(
            task=self.label_for(ident)
        ).observe(max(0.0, delay_s))

    # -- retirement ------------------------------------------------------
    def retire_idle(self, max_idle_s: float) -> int:
        """Drop task labels idle past ``max_idle_s`` and remove EVERY
        series they own (all phase/path/outcome combinations + the
        queue-delay histogram) — the sampler-tick cardinality cap, same
        contract as executor bucket retirement.  Returns labels retired."""
        if max_idle_s <= 0:
            return 0
        now = time.monotonic()
        retired: List[str] = []
        with self._lock:
            for key, e in list(self._entries.items()):
                if now - e.last_used >= max_idle_s:
                    del self._entries[key]
                    retired.append(e.label)
        if retired:
            from .metrics import GLOBAL_METRICS

            if GLOBAL_METRICS.registry is not None:
                for label in retired:
                    for phase in PHASES:
                        for path in PATHS:
                            GLOBAL_METRICS.remove_series(
                                GLOBAL_METRICS.task_device_seconds,
                                label,
                                phase,
                                path,
                            )
                    for outcome in ROW_OUTCOMES:
                        GLOBAL_METRICS.remove_series(
                            GLOBAL_METRICS.task_rows, label, outcome
                        )
                    GLOBAL_METRICS.remove_series(
                        GLOBAL_METRICS.task_queue_delay, label
                    )
        return len(retired)

    def stats(self) -> dict:
        with self._lock:
            return {
                "tracked": len(self._entries),
                "cap": self.max_tasks,
                "overflowed": self.overflowed,
            }


# -- process-wide instance ---------------------------------------------------

_MODEL = TaskCostModel()


def cost_model() -> TaskCostModel:
    return _MODEL


def configure_cost_attribution(max_tasks: int) -> None:
    """Binary bootstrap hook (``common.cost_task_cardinality``)."""
    _MODEL.configure(max_tasks)


def reset_cost_model() -> None:
    """Tests only: drop tracked labels and overflow accounting (metric
    series persist in the registry as every counter does)."""
    with _MODEL._lock:
        _MODEL._entries.clear()
        _MODEL.overflowed = 0


def retire_idle_task_series(max_idle_s: float) -> int:
    """Sampler-tick companion (binaries/main.py) beside
    ``retire_idle_executor_buckets``."""
    return _MODEL.retire_idle(max_idle_s)


# -- thread-local task scope (the oracle-path hook) --------------------------
# Oracle batches run on run_in_executor worker threads, where contextvars
# bound on the event loop are invisible (the PR 5 lesson); a plain
# thread-local set INSIDE the worker callable is the reliable carrier.

_SCOPE = threading.local()


def current_task():
    """The task identity bound on THIS thread (None outside a scope)."""
    return getattr(_SCOPE, "ident", None)


def run_in_task_scope(ident, fn):
    """Run ``fn()`` with the task identity bound for cost attribution —
    wrap the CALLABLE handed to run_in_executor, so the scope is set on
    the worker thread that actually executes the oracle batch."""
    prev = getattr(_SCOPE, "ident", None)
    _SCOPE.ident = ident
    try:
        return fn()
    finally:
        _SCOPE.ident = prev


def attribute_prepare(backend_name: str, phase: str, seconds: float) -> None:
    """The vdaf/backend.py ``_observe_prepare`` hook: attribute a measured
    prepare/combine batch to the thread's bound task.  ``path`` derives
    from the backend name — the oracle is the CPU fallback, everything
    else is a device layout — so a breaker-open window shows as the same
    task's seconds shifting from ``device`` to ``oracle``.  No-op outside
    a task scope (unattributed producers stay invisible rather than
    polluting a catch-all series with double counts: executor flushes
    attribute via attribute_flush, not here)."""
    ident = current_task()
    if ident is None:
        return
    # substring, not equality: the CPU fallbacks are "oracle" (Prio3)
    # AND "poplar1-oracle" — both must land on path="oracle" or the
    # breaker cost shift is invisible for heavy hitters
    path = "oracle" if "oracle" in backend_name else "device"
    _MODEL.attribute_direct(ident, phase, path, seconds)
