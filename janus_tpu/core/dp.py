"""Differential privacy: exact discrete-Gaussian noise for aggregate shares.

The analog of the reference's DP layer (reference: core/src/dp.rs — strategy
types dispatched per VDAF instance; the noise hook is
aggregator/src/aggregator/collection_job_driver.rs:338-344
``add_noise_to_agg_share``, with the distributions provided by the prio
crate's ``ZCdpDiscreteGaussian``).

The sampler is the Canonne–Kamath–Steinke exact discrete Gaussian
(arXiv:2004.00010, Algorithms 1-3), implemented from the paper's
description: all arithmetic is exact rational/integer, randomness comes
from ``secrets``-grade entropy, and there is no floating point anywhere on
the sampling path — so the output distribution is exactly
N_Z(0, sigma^2) with no floating-point privacy leaks.

Budget semantics match prio's ``ZCdpDiscreteGaussian``: a budget epsilon
applied to a query with L2 sensitivity Delta adds noise with
sigma = Delta / epsilon per coordinate, which yields (epsilon^2)/2-zCDP.
Sensitivity bounds per VDAF are the replacement-adjacency L2 bounds of the
truncated measurement vectors.
"""

from __future__ import annotations

import math
import secrets
from dataclasses import dataclass
from fractions import Fraction
from typing import Any, Dict, List, Optional


class DpError(Exception):
    pass


# -- exact sampling primitives (CKS arXiv:2004.00010) -----------------------

def _randbelow(n: int) -> int:
    return secrets.randbelow(n)


def _bernoulli(p: Fraction) -> bool:
    """Exact Bernoulli(p) for rational p in [0, 1]."""
    return _randbelow(p.denominator) < p.numerator


def _bernoulli_exp1(gamma: Fraction) -> bool:
    """Bernoulli(exp(-gamma)) for 0 <= gamma <= 1 (CKS Algorithm 1)."""
    k = 1
    while _bernoulli(gamma / k):
        k += 1
    return k % 2 == 1


def _bernoulli_exp(gamma: Fraction) -> bool:
    """Bernoulli(exp(-gamma)) for gamma >= 0."""
    while gamma > 1:
        if not _bernoulli_exp1(Fraction(1)):
            return False
        gamma -= 1
    return _bernoulli_exp1(gamma)


def _geometric_exp_slow(gamma: Fraction) -> int:
    """Geometric: P[K = k] = (1 - e^-gamma) e^(-gamma k)."""
    k = 0
    while _bernoulli_exp(gamma):
        k += 1
    return k


def _geometric_exp_fast(gamma: Fraction) -> int:
    """Same distribution, O(1 + gamma) expected Bernoulli-exp trials."""
    if gamma == 0:
        return 0
    s, t = gamma.numerator, gamma.denominator
    while True:
        u = _randbelow(t)
        if _bernoulli_exp(Fraction(u, t)):
            break
    v = _geometric_exp_slow(Fraction(1))
    return (v * t + u) // s


def sample_discrete_laplace(scale: Fraction) -> int:
    """Exact discrete Laplace: P[X = x] proportional to exp(-|x|/scale)."""
    if scale <= 0:
        raise DpError("discrete Laplace scale must be positive")
    while True:
        negative = _bernoulli(Fraction(1, 2))
        magnitude = _geometric_exp_fast(1 / scale)
        if negative and magnitude == 0:
            continue
        return -magnitude if negative else magnitude


def sample_discrete_gaussian(sigma: Fraction) -> int:
    """Exact discrete Gaussian N_Z(0, sigma^2) (CKS Algorithm 3)."""
    if sigma <= 0:
        raise DpError("discrete Gaussian sigma must be positive")
    t = math.floor(sigma) + 1
    sigma2 = sigma * sigma
    while True:
        candidate = sample_discrete_laplace(Fraction(t))
        bias = (Fraction(abs(candidate)) - sigma2 / t) ** 2 / (2 * sigma2)
        if _bernoulli_exp(bias):
            return candidate


# -- strategies -------------------------------------------------------------

class NoDifferentialPrivacy:
    """No-op strategy (reference: core/src/dp.rs:38)."""

    def add_noise_to_agg_share(self, vdaf, agg_share: List[int], report_count: int):
        return agg_share

    def to_dict(self) -> Dict[str, Any]:
        return {"dp_mechanism": "NoDifferentialPrivacy"}


def _sqrt_frac_upper(x: Fraction, precision: int = 10**12) -> Fraction:
    """Rational upper bound on sqrt(x): ceil(sqrt(x * p^2)) / p."""
    num = x.numerator * precision * precision
    r = math.isqrt(num // x.denominator) + 1
    return Fraction(r, precision)


def l2_sensitivity(vdaf_instance: Dict[str, Any]) -> Fraction:
    """Replacement-adjacency L2 sensitivity of one report's aggregate
    contribution, as a rational UPPER bound (rounding up never weakens the
    privacy guarantee)."""
    kind = vdaf_instance.get("type")
    if kind == "Prio3Count":
        return Fraction(1)
    if kind == "Prio3Sum":
        return Fraction((1 << vdaf_instance["bits"]) - 1)
    if kind == "Prio3Histogram":
        # one-hot contribution: replacing a report moves two buckets by 1.
        return _sqrt_frac_upper(Fraction(2))
    if kind in ("Prio3SumVec", "Prio3SumVecField64MultiproofHmacSha256Aes128"):
        per_elem = (1 << vdaf_instance["bits"]) - 1
        return per_elem * _sqrt_frac_upper(Fraction(vdaf_instance["length"]))
    if kind == "Prio3FixedPointBoundedL2VecSum":
        # The circuit enforces ||x||_2 <= 1.0 in fixed point with 2^(b-1)
        # integer scale, so replacement moves the aggregate by <= 2 * 2^(b-1)
        # in field units (reference: core/src/vdaf.rs:88-91; the fpvec DP
        # support is the one place the reference wires real noise).
        bits = {16: 16, 32: 32, "BitSize16": 16, "BitSize32": 32}[
            vdaf_instance["bitsize"]
        ]
        return Fraction(1 << bits)
    raise DpError(f"no L2 sensitivity bound for VDAF type {kind!r}")


@dataclass
class ZCdpDiscreteGaussian:
    """Discrete-Gaussian strategy under a zCDP budget.

    sigma = sensitivity / epsilon per coordinate => (epsilon^2)/2-zCDP
    (prio's ZCdpDiscreteGaussian semantics).
    """

    epsilon: Fraction

    def __post_init__(self):
        if self.epsilon <= 0:
            raise DpError("epsilon must be positive")

    def sigma_for(self, vdaf) -> Fraction:
        return l2_sensitivity(getattr(vdaf, "instance", None) or vdaf) / self.epsilon

    def add_noise_to_agg_share(self, vdaf, agg_share: List[int], report_count: int):
        """agg_share: canonical field-element ints; noise is added mod p.

        Matches the reference hook's signature/semantics
        (collection_job_driver.rs:338-344): one independent discrete
        Gaussian per coordinate of the aggregate share.
        """
        p = vdaf.flp.field.MODULUS
        sigma = self.sigma_for(vdaf)
        return [(x + sample_discrete_gaussian(sigma)) % p for x in agg_share]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "dp_mechanism": "ZCdpDiscreteGaussian",
            "epsilon": [self.epsilon.numerator, self.epsilon.denominator],
        }


def dp_strategy_from_dict(d: Optional[Dict[str, Any]]):
    """Parse a task's serialized DP strategy (stored inside the VDAF
    instance JSON, mirroring the reference's per-VdafInstance dp_strategy
    dispatch, aggregator/src/aggregator/collection_job_driver.rs:98)."""
    if isinstance(d, str):  # legacy string tag form
        if d == "NoDifferentialPrivacy":
            return NoDifferentialPrivacy()
        raise DpError(f"unknown dp_strategy tag {d!r}")
    if not d or d.get("dp_mechanism") in (None, "NoDifferentialPrivacy"):
        return NoDifferentialPrivacy()
    if d["dp_mechanism"] == "ZCdpDiscreteGaussian":
        num, den = d["epsilon"]
        return ZCdpDiscreteGaussian(Fraction(num, den))
    raise DpError(f"unknown dp_mechanism {d['dp_mechanism']!r}")
