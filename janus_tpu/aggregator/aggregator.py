"""Per-process aggregator façade and role logic.

The analog of ``Aggregator<C>`` / ``TaskAggregator`` / ``VdafOps``
(reference: aggregator/src/aggregator.rs:133,868,1168): a task cache resolves
each task's VDAF instance and execution backend once; handlers implement the
DAP endpoints.  The helper's aggregate-init pipeline replaces the reference's
per-report rayon loop (aggregator.rs:2101) with ONE batched device launch via
the backend seam (janus_tpu.vdaf.backend) — the north-star hot path.

Handlers are async: datastore transactions run on a worker thread
(run_tx_async) and the batched VDAF launch runs in an executor, so the event
loop is never blocked (the analog of L0's tokio/rayon split, SURVEY.md §1).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.auth_tokens import AuthenticationToken
from ..core.dp import dp_strategy_from_dict
from ..core.hpke import HpkeApplicationInfo, HpkeError, HpkeKeypair, Label, open_, seal
from ..core.time import Clock, interval_merge, time_add, time_to_batch_interval
from ..datastore import (
    AggregateShareJob,
    AggregationJob,
    AggregationJobState,
    AggregatorTask,
    BatchAggregationState,
    CollectionJob,
    CollectionJobState,
    Datastore,
    LeaderStoredReport,
    ReportAggregation,
    ReportAggregationState,
    TaskNotFound,
    TxConflict,
)
from ..datastore.datastore import QUERY_TYPES
from ..datastore.query_type import strategy_for
from ..messages import (
    AggregateShare,
    AggregateShareAad,
    AggregateShareReq,
    AggregationJobContinueReq,
    AggregationJobId,
    AggregationJobInitializeReq,
    AggregationJobResp,
    AggregationJobStep,
    BatchId,
    BatchSelector,
    Collection,
    CollectionJobId,
    CollectionReq,
    Duration,
    FixedSizeQuery,
    HpkeConfigList,
    InputShareAad,
    Interval,
    PartialBatchSelector,
    PlaintextInputShare,
    PrepareError,
    PrepareResp,
    PrepareStepResult,
    Query,
    Report,
    ReportId,
    Role,
    TaskId,
    Time,
)
from ..vdaf import pingpong as pp
from ..vdaf.backend import make_backend
from ..vdaf.prio3 import Prio3, VdafError
from .aggregation_job_writer import AggregationJobWriter
from .aggregate_share import compute_aggregate_share
from .error import (
    AggregatorError,
    BatchInvalid,
    BatchMismatch,
    BatchOverlap,
    BatchQueriedTooManyTimes,
    DeletedCollectionJob,
    ForbiddenMutation,
    InvalidBatchSize,
    InvalidMessage,
    ReportRejection,
    StepMismatch,
    UnauthorizedRequest,
    UnrecognizedAggregationJob,
    UnrecognizedCollectionJob,
    UnrecognizedTask,
    UploadShed,
)
from .report_writer import ReportWriteBatcher

logger = logging.getLogger("janus_tpu.aggregator")


@dataclass
class Config:
    """reference: aggregator/src/aggregator.rs:180 Config"""

    max_upload_batch_size: int = 100
    max_upload_batch_write_delay: float = 0.25
    #: Upload HPKE-open backend (ISSUE 14): "batched" groups concurrent
    #: uploads' opens into one vectorized pass on a worker thread
    #: (core/hpke_batch.py — bit-exact vs inline, per-report fallback on
    #: any batch-level error); "inline" is the legacy per-report open on
    #: the handler's event loop.
    upload_open_backend: str = "batched"
    #: open-batch size/delay (the ReportWriteBatcher pattern)
    upload_open_batch_size: int = 64
    upload_open_batch_delay: float = 0.005
    #: Admission control: shed uploads (503 + Retry-After) once this many
    #: opens are pending (staged + in flight), or once the oldest STAGED
    #: open has waited upload_shed_delay_s.  <= 0 disables either signal.
    upload_queue_max: int = 1024
    upload_shed_delay_s: float = 2.0
    #: Ingest mode (ISSUE 18): "synchronous" commits every report through
    #: the legacy ReportWriteBatcher put_client_report path (the
    #: bit-for-bit default); "journaled" ACKs uploads on the write-behind
    #: report journal and hands opened shares directly to the aggregation
    #: pipeline's staging side (core/ingest.py IngestPlane).
    ingest_mode: str = "synchronous"
    #: journal-writer size/delay/bound (the ReportWriteBatcher pattern;
    #: queue_max is the reason="journal" admission bound)
    ingest_journal_batch_size: int = 100
    ingest_journal_write_delay: float = 0.05
    ingest_journal_queue_max: int = 2048
    #: direct staging: hand journaled cohorts to the in-process creator
    #: (False = journal-only write-behind; everything reaches aggregation
    #: through the materializer's read-back path)
    ingest_stage_direct: bool = True
    ingest_stage_max_reports: int = 4096
    batch_aggregation_shard_count: int = 8
    task_counter_shard_count: int = 8
    task_cache_ttl: float = 30.0
    #: Refresh cadence for the global-HPKE / taskprov-peer config caches
    #: (reference: cache.rs refresh tasks).
    global_hpke_cache_refresh_interval: float = 60.0
    peer_aggregator_cache_refresh_interval: float = 60.0
    #: VDAF execution backend: "oracle", "tpu" (batched device launch), or
    #: "mesh" (SPMD over a device mesh).
    vdaf_backend: str = "oracle"
    #: Device field-arithmetic layout ("vpu" | "mxu"); None = process
    #: default (JANUS_TPU_FIELD_BACKEND or "vpu").
    field_backend: Optional[str] = None
    #: Poplar1 AES-walk backend ("host" | "jax"); None = process default
    #: (JANUS_TPU_POPLAR_BACKEND or "host").
    poplar_backend: Optional[str] = None
    collection_job_retry_after: int = 10
    #: Aggregation-job size for agg-param VDAFs (Poplar1), whose jobs are
    #: created by the collection request (_create_agg_param_jobs) rather
    #: than the periodic creator: one collection's reports split into
    #: ceil(N/this) jobs per level.  Small values + the device executor
    #: mean the split costs nothing at prepare time — the jobs' rows
    #: re-coalesce in the level-keyed poplar_init bucket.
    max_agg_param_job_size: int = 256
    #: Process-wide device executor (executor.ExecutorConfig): when set and
    #: enabled, the HELPER's Prio3 prep_init/combine launches submit
    #: through the same continuous batcher the drivers feed, so the
    #: circuit breaker (and its oracle degradation) guards the helper path
    #: too.  None/disabled = per-request launches (legacy).
    device_executor: Optional[object] = None


class TaskAggregator:
    """A task with its VDAF instance + backend resolved once
    (reference: aggregator.rs:868-1137)."""

    def __init__(
        self,
        task: AggregatorTask,
        backend_name: str,
        field_backend: Optional[str] = None,
        poplar_backend: Optional[str] = None,
    ):
        self.task = task
        self.vdaf = task.vdaf_instance()
        self.backend_name = backend_name
        self.field_backend = field_backend
        self.poplar_backend = poplar_backend
        self._backend = None

    @property
    def backend(self):
        if self._backend is None:
            try:
                self._backend = make_backend(
                    self.vdaf,
                    self.backend_name,
                    field_backend=self.field_backend,
                    poplar_backend=self.poplar_backend,
                )
            except VdafError:
                # e.g. HMAC-XOF instances have no device path yet
                self._backend = make_backend(self.vdaf, "oracle")
        return self._backend

    @property
    def query_class(self):
        return QUERY_TYPES[self.task.query_type.kind]

    def check_aggregator_auth(self, token: Optional[AuthenticationToken]) -> None:
        h = self.task.aggregator_auth_token_hash
        if h is None or token is None or not h.validate(token):
            raise UnauthorizedRequest("invalid aggregator auth token")

    def check_collector_auth(self, token: Optional[AuthenticationToken]) -> None:
        h = self.task.collector_auth_token_hash
        if h is None or token is None or not h.validate(token):
            raise UnauthorizedRequest("invalid collector auth token")

    def hpke_config_list(self) -> HpkeConfigList:
        return HpkeConfigList([self.task.current_hpke_keypair().config])


class Aggregator:
    """reference: aggregator/src/aggregator.rs:133"""

    def __init__(self, datastore: Datastore, clock: Clock, config: Config = None):
        self.datastore = datastore
        self.clock = clock
        self.config = config or Config()
        self._task_cache: Dict[bytes, Tuple[float, TaskAggregator]] = {}
        from .cache import GlobalHpkeKeypairCache, PeerAggregatorCache

        self.global_hpke_cache = GlobalHpkeKeypairCache(
            datastore, self.config.global_hpke_cache_refresh_interval
        )
        self.peer_aggregator_cache = PeerAggregatorCache(
            datastore, self.config.peer_aggregator_cache_refresh_interval
        )
        self.report_writer = ReportWriteBatcher(
            datastore,
            max_batch_size=self.config.max_upload_batch_size,
            max_batch_write_delay=self.config.max_upload_batch_write_delay,
            counter_shard_count=self.config.task_counter_shard_count,
        )
        # Front-door open stage (ISSUE 14): the batched-HPKE pipeline +
        # admission control.  Constructed unconditionally so /statusz and
        # the shed gate exist even under upload_open_backend: inline.
        if self.config.upload_open_backend not in ("batched", "inline"):
            # a typo'd backend must fail construction loudly, not silently
            # serve the legacy path
            raise ValueError(
                f"unknown upload_open_backend "
                f"{self.config.upload_open_backend!r} (batched|inline)"
            )
        from .report_writer import UploadOpenBatcher

        self.upload_opener = UploadOpenBatcher(
            max_batch_size=self.config.upload_open_batch_size,
            max_batch_delay=self.config.upload_open_batch_delay,
            max_queue=self.config.upload_queue_max,
            shed_delay_s=self.config.upload_shed_delay_s,
        )
        # Zero-copy ingest plane (ISSUE 18): in journaled mode the upload
        # write seam becomes the write-behind report journal + direct
        # staging handoff; synchronous keeps the legacy writer bit-for-bit.
        if self.config.ingest_mode not in ("synchronous", "journaled"):
            raise ValueError(
                f"unknown ingest_mode {self.config.ingest_mode!r} "
                f"(synchronous|journaled)"
            )
        self.ingest = None
        if self.config.ingest_mode == "journaled":
            from ..core.ingest import IngestPlane

            self.ingest = IngestPlane(
                datastore,
                max_batch_size=self.config.ingest_journal_batch_size,
                max_write_delay=self.config.ingest_journal_write_delay,
                queue_max=self.config.ingest_journal_queue_max,
                counter_shard_count=self.config.task_counter_shard_count,
                stage_direct=self.config.ingest_stage_direct,
                stage_max_reports=self.config.ingest_stage_max_reports,
            )
        # Quarantine ledger sink (ISSUE 19): poison offenders found by the
        # batched-open / executor bisection sieves persist into this
        # datastore's quarantined_reports table (failure-tolerant,
        # background thread — see core/quarantine.py).
        if datastore is not None:
            from ..core import quarantine

            quarantine.configure_sink(datastore)
        # Helper-side executor routing: share the process-wide continuous
        # batcher (and its per-shape circuit breakers) with the drivers.
        #: canonical keys whose twin backend failed to build (negative
        #: cache — see _executor_backend_for)
        self._canon_build_failed: set = set()
        self._executor = None
        exec_cfg = self.config.device_executor
        if exec_cfg is not None and getattr(exec_cfg, "enabled", False):
            from ..executor import get_global_executor

            self._executor = get_global_executor(exec_cfg)

    async def shutdown(self) -> None:
        """Cancel the config-cache refresh loops (call on service teardown)."""
        await self.global_hpke_cache.stop()
        await self.peer_aggregator_cache.stop()

    # ------------------------------------------------------------------
    # task cache (reference: aggregator.rs:675 task_aggregator_for)

    async def task_aggregator_for(self, task_id: TaskId) -> TaskAggregator:
        import time as _t

        key = task_id.data
        hit = self._task_cache.get(key)
        if hit is not None and hit[0] > _t.monotonic():
            return hit[1]
        task = await self.datastore.run_tx_async(
            "get_task", lambda tx: tx.get_aggregator_task(task_id)
        )
        if task is None:
            raise UnrecognizedTask(str(task_id))
        ta = TaskAggregator(
            task,
            self.config.vdaf_backend,
            self.config.field_backend,
            poplar_backend=self.config.poplar_backend,
        )
        self._task_cache[key] = (_t.monotonic() + self.config.task_cache_ttl, ta)
        return ta

    # ------------------------------------------------------------------
    # taskprov opt-in (reference: aggregator.rs:722)

    async def ensure_taskprov_task(
        self,
        task_id: TaskId,
        encoded_task_config: Optional[bytes],
        auth_token: Optional[AuthenticationToken],
        require_peer_auth: bool = True,
    ) -> None:
        """Provision a task advertised in-band, if the advertising peer is
        configured, AUTHENTICATED, and the id matches SHA-256 of the config
        (reference: aggregator.rs:722 opt-in + :813 taskprov request
        authorization — the peer must present its pre-shared token before
        anything is written)."""
        if encoded_task_config is None:
            return
        if task_id.data in self._task_cache or await self.datastore.run_tx_async(
            "taskprov_exists",
            lambda tx: tx.get_aggregator_task(task_id) is not None,
        ):
            return
        from .taskprov import taskprov_task, taskprov_task_id

        if taskprov_task_id(encoded_task_config) != task_id:
            raise InvalidMessage("taskprov task id mismatch")
        from ..messages.taskprov import TaskConfig

        config = TaskConfig.get_decoded(encoded_task_config)
        if config.task_expiration.seconds <= self.clock.now().seconds:
            raise InvalidMessage("taskprov advertisement already expired")

        # Peer + global-key lookups come from the refreshed caches; only the
        # task write needs a transaction (reference: cache.rs consumers).
        peers = await self.peer_aggregator_cache.peers()
        own_role = peer = None
        for p in peers:
            if (
                p.role == Role.LEADER
                and p.endpoint == str(config.leader_aggregator_endpoint)
            ):
                own_role, peer = Role.HELPER, p
                break
            if (
                p.role == Role.HELPER
                and p.endpoint == str(config.helper_aggregator_endpoint)
            ):
                own_role, peer = Role.LEADER, p
                break
        if peer is None:
            raise UnrecognizedTask("no taskprov peer for advertised task")
        # authenticate the advertising peer before any write; the upload
        # route is exempt (clients cannot hold the peer token — the
        # reference separates upload opt-in from peer request auth)
        if require_peer_auth:
            h = peer.aggregator_auth_token_hash
            if h is None and peer.aggregator_auth_token is not None:
                h = peer.aggregator_auth_token.hash()
            if h is None or auth_token is None or not h.validate(auth_token):
                raise UnauthorizedRequest("taskprov advertisement not authenticated")
        keys = [
            HpkeKeypair(kp.config, kp.private_key)
            for kp in await self.global_hpke_cache.active_keypairs()
        ]
        if not keys:
            raise UnrecognizedTask("no active global HPKE key for taskprov")

        def tx_fn(tx):
            task = taskprov_task(
                encoded_task_config, peer, own_role, keys, config=config
            )
            try:
                tx.put_aggregator_task(task)
            except TxConflict:
                pass  # concurrent provisioning of the same advertisement

        await self.datastore.run_tx_async("taskprov_opt_in", tx_fn)

    # ------------------------------------------------------------------
    # GET hpke_config (reference: http_handlers.rs "hpke_config" route)

    async def handle_hpke_config(self, task_id: Optional[TaskId]) -> HpkeConfigList:
        if task_id is not None:
            ta = await self.task_aggregator_for(task_id)
            return ta.hpke_config_list()
        # global keys, served from the refreshed cache (no DB hit in the
        # steady state — reference: cache.rs GlobalHpkeKeypairCache)
        active = await self.global_hpke_cache.active_configs()
        if not active:
            raise UnrecognizedTask("no HPKE configuration available")
        return HpkeConfigList(active)

    # ------------------------------------------------------------------
    # upload (reference: aggregator.rs:1522 handle_upload_generic)

    @staticmethod
    def _shed_if_datastore_suspect() -> None:
        """Brownout shed (ISSUE 17): while the datastore tracker is
        SUSPECT every upload would burn HPKE work only to fail at the
        write, so refuse with the retryable 503 up front.  PROBING
        uploads are deliberately admitted — the write attempt IS the
        probe that heals the tracker."""
        from ..core.db_health import DB_SUSPECT, tracker as db_tracker

        if db_tracker().state() != DB_SUSPECT:
            return
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.upload_sheds.labels(reason="datastore").inc()
        raise UploadShed("datastore suspect (brownout); retry shortly")

    async def handle_upload(self, task_id: TaskId, report: Report) -> None:
        from ..core.trace import current_trace, new_trace_id, trace_scope, trace_span

        # Upload trace mint point (ISSUE 9): adopt the client's strict-hex
        # traceparent (bound by http_handlers._route when valid) or mint a
        # fresh 32-hex id.  A malformed header therefore costs the client
        # nothing — parse_traceparent returned None, we mint, the upload
        # proceeds.  The id is bound for the whole handler (validation
        # logs, the upload span) and rides the stored report so job
        # creation can link prepare back to client ingress.
        trace_id = current_trace().get("trace_id") or new_trace_id()
        with trace_scope(trace_id=trace_id), trace_span("upload", cat="upload"):
            # Admission control (ISSUE 14): shed BEFORE any per-upload
            # crypto or datastore work — past the front-door budget the
            # cheapest correct answer is the retryable 503.
            self._shed_if_datastore_suspect()
            self.upload_opener.admit()
            # Journaled-mode backpressure composes here (ISSUE 18): a
            # slow journal writer surfaces as reason="journal" sheds at
            # the same pre-crypto gate, never as unbounded memory.
            if self.ingest is not None:
                self.ingest.admit()
            ta = await self.task_aggregator_for(task_id)
            task = ta.task
            if task.role != Role.LEADER:
                raise UnrecognizedTask("upload to non-leader")
            try:
                keypair, info, aad = self._validate_report_pre_open(ta, report)
            except ReportRejection as rej:
                await self.report_writer.write_rejection(task_id, rej)
                raise rej.to_error()
            # The expensive open: batched (grouped with concurrent
            # uploads, KEM on a worker thread, one vectorized AES-GCM
            # pass) or the legacy inline call.  Either way the SAME
            # plaintext comes back — bit-exactness is the seam contract.
            try:
                if self.config.upload_open_backend == "batched":
                    plaintext = await self.upload_opener.open(
                        keypair,
                        info,
                        report.leader_encrypted_input_share,
                        aad,
                        # report identity for the quarantine ledger, should
                        # bisection isolate this row as poison
                        ident=(
                            task_id.data.hex(),
                            report.metadata.report_id.data,
                        ),
                    )
                else:
                    import time as _time

                    from ..core.metrics import GLOBAL_METRICS

                    t0 = _time.monotonic()
                    plaintext = open_(
                        keypair, info, report.leader_encrypted_input_share, aad
                    )
                    if GLOBAL_METRICS.registry is not None:
                        GLOBAL_METRICS.upload_open_seconds.labels(
                            backend="inline"
                        ).observe(_time.monotonic() - t0)
            except HpkeError:
                rej = ReportRejection(ReportRejection.DECRYPT_FAILURE, "decrypt failed")
                await self.report_writer.write_rejection(task_id, rej)
                raise rej.to_error()
            try:
                stored = self._decode_opened_report(ta, report, plaintext)
            except ReportRejection as rej:
                await self.report_writer.write_rejection(task_id, rej)
                raise rej.to_error()
            if self.ingest is not None:
                # journaled: the ACK resolves when the journal row is
                # durable; the opened share rides to the staging side
                # without a put_client_report round-trip
                await self.ingest.submit(
                    stored, shape_key=self._ingest_shape_key(ta)
                )
            else:
                await self.report_writer.write_report(stored)

    @staticmethod
    def _ingest_shape_key(ta: TaskAggregator):
        """Staging bucket identity for the ingest plane: the task's vdaf
        shape (the executor's bucketing axis), or None for cohorts the
        direct path cannot consume — agg-param VDAFs (jobs come from
        collection requests) and FixedSize tasks (jobs come from
        outstanding-batch filling) journal and reach aggregation through
        the materializer instead."""
        if ta.task.query_type.kind != "TimeInterval":
            return None
        if getattr(ta.vdaf, "REQUIRES_AGG_PARAM", False):
            return None
        return (
            type(ta.vdaf).__name__,
            tuple(sorted((k, repr(v)) for k, v in ta.task.vdaf.items())),
        )

    def _validate_report_pre_open(self, ta: TaskAggregator, report: Report):
        """The CHEAP upload checks, run inline before the open is queued:
        clock skew / expiry / public-share decode / key lookup.  Returns
        (keypair, application info, aad) for the open stage."""
        task = ta.task
        now = self.clock.now()
        t = report.metadata.time
        # clock skew / expiry / GC eligibility (reference: aggregator.rs:1552-1581)
        if t.seconds > time_add(now, task.tolerable_clock_skew).seconds:
            raise ReportRejection(ReportRejection.TOO_EARLY, "report too far in future")
        if task.task_expiration is not None and t.seconds > task.task_expiration.seconds:
            raise ReportRejection(ReportRejection.TASK_EXPIRED, "task expired")
        if (
            task.report_expiry_age is not None
            and t.seconds < now.seconds - task.report_expiry_age.seconds
        ):
            raise ReportRejection(ReportRejection.EXPIRED, "report expired")

        # decode public share (reference: aggregator.rs:1587)
        try:
            ta.vdaf.decode_public_share(report.public_share)
        except Exception:
            raise ReportRejection(ReportRejection.DECODE_FAILURE, "bad public share")

        keypair = task.hpke_keypair_for(report.leader_encrypted_input_share.config_id)
        if keypair is None:
            raise ReportRejection(
                ReportRejection.OUTDATED_KEY,
                f"unknown HPKE config id {report.leader_encrypted_input_share.config_id}",
            )
        aad = InputShareAad(
            task.task_id, report.metadata, report.public_share
        ).get_encoded()
        info = HpkeApplicationInfo.new(Label.INPUT_SHARE, Role.CLIENT, Role.LEADER)
        return keypair, info, aad

    def _decode_opened_report(
        self, ta: TaskAggregator, report: Report, plaintext: bytes
    ) -> LeaderStoredReport:
        """Post-open decode (cheap, inline): plaintext share -> stored row."""
        task = ta.task
        try:
            plain = PlaintextInputShare.get_decoded(plaintext)
            _check_extensions(plain.extensions)
            ta.vdaf.decode_input_share(0, plain.payload)
        except Exception as e:
            raise ReportRejection(ReportRejection.DECODE_FAILURE, f"bad input share: {e}")

        return LeaderStoredReport(
            task_id=task.task_id,
            metadata=report.metadata,
            public_share=report.public_share,
            leader_extensions=list(plain.extensions),
            leader_input_share=plain.payload,
            helper_encrypted_input_share=report.helper_encrypted_input_share,
        )

    def _validate_and_open_report(self, ta: TaskAggregator, report: Report) -> LeaderStoredReport:
        """The legacy single-call inline path (pre-open checks + open +
        decode in one synchronous pass) — kept as the reference the
        batched pipeline is parity-tested against."""
        keypair, info, aad = self._validate_report_pre_open(ta, report)
        try:
            plaintext = open_(keypair, info, report.leader_encrypted_input_share, aad)
        except HpkeError:
            raise ReportRejection(ReportRejection.DECRYPT_FAILURE, "decrypt failed")
        return self._decode_opened_report(ta, report, plaintext)

    # ------------------------------------------------------------------
    # helper aggregate init (reference: aggregator.rs:1720 handle_aggregate_init_generic)

    async def handle_aggregate_init(
        self,
        task_id: TaskId,
        aggregation_job_id: AggregationJobId,
        body: bytes,
        auth_token: Optional[AuthenticationToken],
    ) -> AggregationJobResp:
        ta = await self.task_aggregator_for(task_id)
        task = ta.task
        if task.role != Role.HELPER:
            raise UnrecognizedTask("aggregate-init on non-helper")
        ta.check_aggregator_auth(auth_token)
        req = AggregationJobInitializeReq.get_decoded(body, ta.query_class)
        request_hash = hashlib.sha256(body).digest()

        # replay/idempotency check (reference: aggregator.rs:1748,2173-2209)
        existing = await self.datastore.run_tx_async(
            "agg_init_replay",
            lambda tx: tx.get_aggregation_job(task_id, aggregation_job_id),
        )
        if existing is not None:
            if existing.last_request_hash == request_hash:
                return await self._stored_job_resp(task_id, aggregation_job_id)
            raise ForbiddenMutation("aggregation job replayed with different request")

        # duplicate report IDs in one request are rejected outright
        # (reference: aggregator.rs:1765)
        seen = set()
        for pi in req.prepare_inits:
            rid = pi.report_share.metadata.report_id.data
            if rid in seen:
                raise InvalidMessage("duplicate report id in request")
            seen.add(rid)

        # Per-report validation + HPKE open (host side, async-friendly).
        failed: Dict[int, PrepareError] = {}
        conflict_key = ta.vdaf.agg_param_conflict_key(req.aggregation_parameter)

        def find_replays(tx):
            out = []
            for pi in req.prepare_inits:
                rid = pi.report_share.metadata.report_id
                for param in tx.get_aggregation_params_for_report(
                    task_id, rid, exclude_aggregation_job_id=aggregation_job_id
                ):
                    if ta.vdaf.agg_param_conflict_key(param) == conflict_key:
                        out.append(rid.data)
                        break
            return out

        replay_ids = await self.datastore.run_tx_async(
            "agg_init_conflicts", find_replays
        )
        replay_set = set(replay_ids)
        now = self.clock.now()
        # Batched HPKE open (ROADMAP front-door follow-on): the helper's
        # aggregate-init report-share opens are the same embarrassingly-
        # batchable shape as upload — cheap per-report validation inline,
        # then ONE core/hpke_batch.open_batch call on a worker thread
        # (per-report KEM decap + one vectorized AES-128-GCM pass), with
        # per-report inline fallback on any batch-LEVEL error.
        decoded: List[Tuple[int, tuple]] = []  # (idx, (nonce, public, share, msg))
        to_open: List[Tuple[int, object]] = []  # (idx, OpenRequest)
        for idx, pi in enumerate(req.prepare_inits):
            err = self._helper_validate_report_share(ta, pi, replay_set, now)
            if err is not None:
                failed[idx] = err
                continue
            prepared = self._helper_open_request(ta, pi)
            if isinstance(prepared, PrepareError):
                failed[idx] = prepared
            else:
                to_open.append((idx, prepared))
        if to_open:
            loop = asyncio.get_running_loop()
            if self.config.upload_open_backend == "batched":
                from ..core.hpke_batch import open_batch

                def run_opens():
                    try:
                        return open_batch([r for _i, r in to_open])
                    except Exception:
                        # batch-LEVEL failure: per-report inline opens —
                        # the batched path must never reject a report the
                        # inline path would accept
                        logger.exception(
                            "batched aggregate-init open failed; falling "
                            "back to per-report opens"
                        )
                        from ..core.hpke_batch import _open_one

                        return [_open_one(*r) for _i, r in to_open]

                opened = await loop.run_in_executor(None, run_opens)
            else:
                from ..core.hpke_batch import _open_one

                opened = await loop.run_in_executor(
                    None, lambda: [_open_one(*r) for _i, r in to_open]
                )
            for (idx, _req), plaintext in zip(to_open, opened):
                if isinstance(plaintext, Exception) or plaintext is None:
                    failed[idx] = PrepareError.HPKE_DECRYPT_ERROR
                    continue
                item = self._helper_decode_opened_share(
                    ta, req.prepare_inits[idx], plaintext
                )
                if isinstance(item, PrepareError):
                    failed[idx] = item
                else:
                    decoded.append((idx, item))

        # Batched prepare: ONE device launch for the whole job (north star).
        try:
            agg_param = ta.vdaf.decode_agg_param(req.aggregation_parameter)
        except VdafError:
            raise InvalidMessage("bad aggregation parameter")
        loop = asyncio.get_running_loop()
        if (
            self._executor is not None
            and isinstance(ta.vdaf, Prio3)
            and hasattr(ta.backend, "stage_prep_init_multi")
        ):
            # Helper-side executor routing (ROADMAP item): prep_init and
            # combine submit through the process-wide continuous batcher,
            # so helper requests coalesce with driver traffic and the
            # circuit breaker guards this path too.
            results = await self._helper_prepare_batch_prio3_executor(ta, decoded)
        elif self._executor is not None and hasattr(
            ta.backend, "prep_init_batch_poplar"
        ):
            # Heavy hitters through the same dispatch plane: the request's
            # rows coalesce in the agg-param(level)-keyed poplar_init
            # bucket, breaker + oracle degradation included.
            results = await self._helper_prepare_batch_poplar1_executor(
                ta, decoded, agg_param
            )
        else:
            # direct (non-executor) path: bind the task cost scope on the
            # worker thread so the backend's measured prepare seconds
            # attribute to this task (core/costs.py — path derives from
            # the backend: tpu/mesh -> device, oracle -> oracle)
            from ..core import costs

            _ident = getattr(getattr(ta.task, "task_id", None), "data", None)
            results = await loop.run_in_executor(
                None,
                lambda: costs.run_in_task_scope(
                    _ident,
                    lambda: self._helper_prepare_batch(ta, decoded, agg_param),
                ),
            )

        # Assemble responses + report aggregations in request order.
        ras: List[ReportAggregation] = []
        out_shares: Dict[bytes, Sequence[int]] = {}
        resps: List[PrepareResp] = []
        interval = Interval.EMPTY
        for idx, pi in enumerate(req.prepare_inits):
            rid = pi.report_share.metadata.report_id
            t = pi.report_share.metadata.time
            interval = interval_merge(
                interval, time_to_batch_interval(t, task.time_precision)
            )
            base = dict(
                task_id=task_id,
                aggregation_job_id=aggregation_job_id,
                report_id=rid,
                time=t,
                ord=idx,
            )
            if idx in failed:
                err = failed[idx]
                resp = PrepareResp(rid, PrepareStepResult.reject(err))
                ras.append(
                    ReportAggregation(
                        state=ReportAggregationState.FAILED, error=err,
                        last_prep_resp=resp, **base
                    )
                )
                resps.append(resp)
                continue
            outcome = results[idx]
            if isinstance(outcome, PrepareError):
                resp = PrepareResp(rid, PrepareStepResult.reject(outcome))
                ras.append(
                    ReportAggregation(
                        state=ReportAggregationState.FAILED, error=outcome,
                        last_prep_resp=resp, **base
                    )
                )
                resps.append(resp)
                continue
            kind, payload, outbound = outcome
            resp = PrepareResp(rid, PrepareStepResult.new_continue(outbound))
            if kind == "finished":
                out_shares[rid.data] = payload
                ras.append(
                    ReportAggregation(
                        state=ReportAggregationState.FINISHED,
                        last_prep_resp=resp, **base
                    )
                )
            else:  # continued (multi-round VDAF)
                ras.append(
                    ReportAggregation(
                        state=ReportAggregationState.WAITING_HELPER,
                        helper_prep_state=payload,
                        last_prep_resp=resp, **base
                    )
                )
            resps.append(resp)

        from ..core.trace import current_trace

        job = AggregationJob(
            task_id=task_id,
            aggregation_job_id=aggregation_job_id,
            aggregation_parameter=req.aggregation_parameter,
            partial_batch_identifier=req.partial_batch_selector.batch_identifier
            if task.query_type.kind == "FixedSize"
            else None,
            client_timestamp_interval=interval,
            state=AggregationJobState.FINISHED
            if all(
                ra.state
                in (ReportAggregationState.FINISHED, ReportAggregationState.FAILED)
                for ra in ras
            )
            else AggregationJobState.IN_PROGRESS,
            step=AggregationJobStep(0),
            last_request_hash=request_hash,
            # cross-process correlation: the leader driver's traceparent
            # (bound by the HTTP layer) persists on the helper's job row
            trace_id=current_trace().get("trace_id"),
        )

        # Helper-side retention (ISSUE 4 satellite): finished rows carrying
        # ResidentRefs psum into per-batch device accumulators and drain to
        # ONE vector per batch here, BEFORE the tx — closing the PR 3 gap
        # where the helper read its out shares back per flush.
        decoded_by_rid = {item[0]: item for _idx, item in decoded}
        accumulator_deltas = await self._commit_helper_resident_shares(
            ta, job, ras, out_shares, decoded_by_rid
        )

        from ..executor.accumulator import ResidentRef, StaleAccumulatorDelta

        writer = AggregationJobWriter(
            task,
            ta.vdaf,
            batch_aggregation_shard_count=self.config.batch_aggregation_shard_count,
            initial_write=True,
            backend=ta.backend,
            accumulator_deltas=accumulator_deltas,
        )
        writer.put(job, ras, out_shares)

        def tx_fn(tx):
            return writer.write(tx)

        try:
            failures = await self.datastore.run_tx_async("agg_init_write", tx_fn)
        except TxConflict:
            # racing identical request: return the stored response
            return await self._stored_job_resp(task_id, aggregation_job_id)
        except StaleAccumulatorDelta:
            # A batch was collected between the drain and the tx: the
            # drained delta no longer matches the rows surviving the in-tx
            # check.  The tx aborted with nothing merged; retry ONCE with
            # oracle host vectors — the writer then fails the collected
            # rows properly (BatchCollected) and merges only survivors.
            loop = asyncio.get_running_loop()
            stale = sorted(
                rid for rid, v in out_shares.items() if isinstance(v, ResidentRef)
            )
            replayed = await loop.run_in_executor(
                None,
                lambda: self._helper_oracle_out_shares(ta, stale, decoded_by_rid),
            )
            out_shares.update(replayed)
            writer = AggregationJobWriter(
                task,
                ta.vdaf,
                batch_aggregation_shard_count=self.config.batch_aggregation_shard_count,
                initial_write=True,
                backend=ta.backend,
            )
            writer.put(job, ras, out_shares)
            try:
                failures = await self.datastore.run_tx_async(
                    "agg_init_write", lambda tx: writer.write(tx)
                )
            except TxConflict:
                return await self._stored_job_resp(task_id, aggregation_job_id)
        if failures:
            resps = [
                PrepareResp(r.report_id, PrepareStepResult.reject(failures[r.report_id.data]))
                if r.report_id.data in failures
                else r
                for r in resps
            ]
        return AggregationJobResp(resps)

    def _helper_validate_report_share(
        self, ta: TaskAggregator, pi, replay_set, now
    ) -> Optional[PrepareError]:
        task = ta.task
        meta = pi.report_share.metadata
        if meta.report_id.data in replay_set:
            return PrepareError.REPORT_REPLAYED
        if (
            task.task_expiration is not None
            and meta.time.seconds > task.task_expiration.seconds
        ):
            return PrepareError.TASK_EXPIRED
        if (
            task.report_expiry_age is not None
            and meta.time.seconds < now.seconds - task.report_expiry_age.seconds
        ):
            return PrepareError.REPORT_DROPPED
        if meta.time.seconds > time_add(now, task.tolerable_clock_skew).seconds:
            return PrepareError.REPORT_TOO_EARLY
        return None

    def _helper_open_request(self, ta: TaskAggregator, pi):
        """The pre-open half of a report-share decode: key lookup + AAD
        assembly.  Returns a core/hpke_batch OpenRequest tuple, or the
        PrepareError that rejects the share before any crypto is paid."""
        task = ta.task
        meta = pi.report_share.metadata
        keypair = task.hpke_keypair_for(pi.report_share.encrypted_input_share.config_id)
        if keypair is None:
            return PrepareError.HPKE_UNKNOWN_CONFIG_ID
        aad = InputShareAad(
            task.task_id, meta, pi.report_share.public_share
        ).get_encoded()
        info = HpkeApplicationInfo.new(Label.INPUT_SHARE, Role.CLIENT, Role.HELPER)
        return (keypair, info, pi.report_share.encrypted_input_share, aad)

    def _helper_decode_opened_share(self, ta: TaskAggregator, pi, plaintext):
        """The post-open half: plaintext + wire decode and ping-pong
        variant checks."""
        meta = pi.report_share.metadata
        try:
            plain = PlaintextInputShare.get_decoded(plaintext)
            _check_extensions(plain.extensions)
        except Exception:
            return PrepareError.INVALID_MESSAGE
        try:
            input_share = ta.vdaf.decode_input_share(1, plain.payload)
            public_parts = ta.vdaf.decode_public_share(pi.report_share.public_share)
        except (VdafError, Exception):
            return PrepareError.INVALID_MESSAGE
        if pi.message.variant != pp.PingPongMessage.INITIALIZE:
            return PrepareError.INVALID_MESSAGE
        return (meta.report_id.data, public_parts, input_share, pi.message)

    def _helper_prepare_batch(self, ta: TaskAggregator, decoded, agg_param):
        """Batched helper_initialized over the surviving reports.

        Prio3 rides the backend seam (ONE batched device launch); other
        VDAFs (multi-round test doubles, Poplar1) step per report through
        the generic ping-pong topology (reference mirror:
        aggregator.rs:2022-2040 helper_initialized on rayon)."""
        vdaf = ta.vdaf
        if isinstance(vdaf, Prio3):
            return self._helper_prepare_batch_prio3(ta, decoded)
        if hasattr(ta.backend, "prep_init_batch_poplar"):
            return self._helper_prepare_batch_poplar1(ta, decoded, agg_param)
        results: Dict[int, object] = {}
        vk = ta.task.vdaf_verify_key
        for idx, (nonce, public_parts, input_share, leader_msg) in decoded:
            try:
                trans = pp.helper_initialized(
                    vdaf, vk, agg_param, nonce, public_parts, input_share, leader_msg
                )
                state, outbound = trans.evaluate(vdaf)
            except (VdafError, pp.PingPongError):
                results[idx] = PrepareError.VDAF_PREP_ERROR
                continue
            if isinstance(state, pp.PingPongFinished):
                results[idx] = ("finished", state.out_share, outbound)
            else:
                results[idx] = (
                    "continued",
                    vdaf.ping_pong_encode_state(state.prep_state),
                    outbound,
                )
        return results

    def _helper_prepare_batch_poplar1(
        self, ta: TaskAggregator, decoded, agg_param, backend=None
    ):
        """Heavy hitters through the batched backend: the round-0 IDPF tree
        walk + sketch runs once for the whole job (ops/poplar1_batch.py);
        the per-report remainder is the same combine/transition
        helper_initialized performs (reference: Poplar1 rides the common
        accelerated dispatch, core/src/vdaf.rs:96).  ``backend`` overrides
        ``ta.backend`` — the executor routing passes the per-report CPU
        oracle here while the shape's circuit is open."""
        backend = backend if backend is not None else ta.backend
        vdaf = ta.vdaf
        results, rows = self._helper_decode_poplar_rows(vdaf, decoded)
        if not rows:
            return results
        prep_out = backend.prep_init_batch_poplar(
            ta.task.vdaf_verify_key,
            1,
            agg_param,
            [(n, p, s) for (_, n, p, s, _) in rows],
        )
        return self._helper_finish_poplar1(vdaf, agg_param, results, rows, prep_out)

    @staticmethod
    def _helper_decode_poplar_rows(vdaf, decoded):
        """Decode the leader's round-0 sketch shares; (errors, rows)."""
        results: Dict[int, object] = {}
        rows = []
        for idx, (nonce, public_parts, input_share, leader_msg) in decoded:
            try:
                if leader_msg.variant != pp.PingPongMessage.INITIALIZE:
                    raise pp.PingPongError("expected initialize message")
                leader_share = vdaf.ping_pong_decode_prep_share(
                    leader_msg.prep_share, round=0
                )
            except (VdafError, pp.PingPongError):
                results[idx] = PrepareError.VDAF_PREP_ERROR
                continue
            rows.append((idx, nonce, public_parts, input_share, leader_share))
        return results, rows

    @staticmethod
    def _helper_finish_poplar1(vdaf, agg_param, results, rows, prep_out):
        """Combine sketch shares + evaluate the transition per report (the
        cheap sigma math the executor path runs after its mega-batch)."""
        for (idx, _n, _p, _s, leader_share), outcome in zip(rows, prep_out):
            if isinstance(outcome, VdafError):
                results[idx] = PrepareError.VDAF_PREP_ERROR
                continue
            prep_state, helper_share = outcome
            try:
                prep_msg = vdaf.ping_pong_prep_shares_to_prep(
                    agg_param, [leader_share, helper_share], round=0
                )
                trans = pp.PingPongTransition(prep_state, prep_msg, 0)
                state, outbound = trans.evaluate(vdaf)
            except (VdafError, pp.PingPongError):
                results[idx] = PrepareError.VDAF_PREP_ERROR
                continue
            if isinstance(state, pp.PingPongFinished):
                results[idx] = ("finished", state.out_share, outbound)
            else:
                results[idx] = (
                    "continued",
                    vdaf.ping_pong_encode_state(state.prep_state),
                    outbound,
                )
        return results

    async def _helper_prepare_batch_poplar1_executor(
        self, ta: TaskAggregator, decoded, agg_param
    ):
        """Helper Poplar1 prep through the process-wide device executor:
        the request's rows submit into the agg-param-keyed ``poplar_init``
        bucket (agg_id=1, level discriminant), coalescing with every other
        helper request at the same tree level.  A co-resident driver's
        leader traffic keeps its own agg_id=0 bucket (the sides' walks
        differ) but shares the per-shape circuit breaker.  Failure-domain
        parity with the Prio3 helper path: an open circuit degrades the
        request to the bit-exact per-report CPU oracle, and executor
        backpressure surfaces as a retryable 503 to the leader."""
        from ..executor import KIND_POPLAR_INIT
        from ..executor.service import CircuitOpenError, ExecutorOverloadedError
        from ..vdaf.backend import oracle_backend_for, vdaf_shape_key

        vdaf = ta.vdaf
        shape_key = vdaf_shape_key(vdaf)
        # shape-keyed cache: every request (and any driver in-process)
        # shares one batched backend per Poplar1 `bits` shape
        backend = self._executor.backend_for(shape_key, lambda: ta.backend)
        task_ident = getattr(getattr(ta.task, "task_id", None), "data", None)
        loop = asyncio.get_running_loop()

        def oracle_path():
            from ..core import costs

            oracle = oracle_backend_for(backend, vdaf) or backend
            return costs.run_in_task_scope(
                task_ident,
                lambda: self._helper_prepare_batch_poplar1(
                    ta, decoded, agg_param, backend=oracle
                ),
            )

        if self._executor.circuit_open(shape_key):
            return await loop.run_in_executor(None, oracle_path)
        results, rows = await loop.run_in_executor(
            None, lambda: self._helper_decode_poplar_rows(vdaf, decoded)
        )
        if not rows:
            return results
        prep_in = [(nonce, public, share) for (_, nonce, public, share, _) in rows]
        try:
            prep_out = await self._executor.submit(
                shape_key,
                KIND_POPLAR_INIT,
                (ta.task.vdaf_verify_key, agg_param, prep_in),
                backend=backend,
                agg_id=1,
                task_ident=task_ident,
                agg_param_key=getattr(agg_param, "level", None),
            )
        except CircuitOpenError:
            # re-enter past the decode: (results, rows) are already built
            from ..core import costs

            oracle = oracle_backend_for(backend, vdaf) or backend

            def finish_on_oracle():
                out = costs.run_in_task_scope(
                    task_ident,
                    lambda: oracle.prep_init_batch_poplar(
                        ta.task.vdaf_verify_key, 1, agg_param, prep_in
                    ),
                )
                return self._helper_finish_poplar1(
                    vdaf, agg_param, results, rows, out
                )

            return await loop.run_in_executor(None, finish_on_oracle)
        except ExecutorOverloadedError as e:
            from .error import ServiceUnavailable

            raise ServiceUnavailable(f"device executor overloaded: {e}")
        return await loop.run_in_executor(
            None,
            lambda: self._helper_finish_poplar1(
                vdaf, agg_param, results, rows, prep_out
            ),
        )

    @staticmethod
    def _helper_decode_leader_shares(vdaf, decoded):
        """Decode the leader's round-0 prepare shares; returns
        (per-index errors so far, surviving rows)."""
        results: Dict[int, object] = {}
        rows = []
        for idx, (nonce, public_parts, input_share, leader_msg) in decoded:
            try:
                leader_share = vdaf.ping_pong_decode_prep_share(
                    leader_msg.prep_share, round=0
                )
            except VdafError:
                results[idx] = PrepareError.VDAF_PREP_ERROR
                continue
            rows.append((idx, nonce, public_parts, input_share, leader_share))
        return results, rows

    @staticmethod
    def _helper_finish_prio3(vdaf, results, combine_rows, combined):
        """Evaluate the combined prepare messages into finished outcomes."""
        for (idx, state, _ls, hs), prep_msg in zip(combine_rows, combined):
            if isinstance(prep_msg, VdafError):
                results[idx] = PrepareError.VDAF_PREP_ERROR
                continue
            try:
                out_share = vdaf.prep_next(state, prep_msg)
            except VdafError:
                results[idx] = PrepareError.VDAF_PREP_ERROR
                continue
            outbound = pp.PingPongMessage(
                pp.PingPongMessage.FINISH, prep_msg=prep_msg or b""
            )
            results[idx] = ("finished", out_share, outbound)
        return results

    def _helper_prepare_batch_prio3(self, ta: TaskAggregator, decoded, backend=None):
        """The north-star path: one batched launch for prep + combine.

        ``backend`` overrides ``ta.backend`` (the executor routing passes
        the bit-exact CPU oracle here while a shape's circuit is open)."""
        backend = backend if backend is not None else ta.backend
        results, rows = self._helper_decode_leader_shares(ta.vdaf, decoded)
        return self._helper_prep_rows_prio3(ta, backend, results, rows)

    def _helper_prep_rows_prio3(self, ta: TaskAggregator, backend, results, rows):
        """Prep + combine + finish over already-decoded rows (the executor
        path's mid-flight oracle fallback re-enters here so the per-report
        wire decode is never paid twice)."""
        vdaf = ta.vdaf
        if not rows:
            return results
        prep_in = [(nonce, public, share) for (_, nonce, public, share, _) in rows]
        prep_out = backend.prep_init_batch(ta.task.vdaf_verify_key, 1, prep_in)
        combine_rows = []
        for (idx, _n, _p, _s, leader_share), outcome in zip(rows, prep_out):
            if isinstance(outcome, VdafError):
                results[idx] = PrepareError.VDAF_PREP_ERROR
                continue
            state, helper_share = outcome
            combine_rows.append((idx, state, leader_share, helper_share))
        combined = backend.prep_shares_to_prep_batch(
            [[ls, hs] for (_, _, ls, hs) in combine_rows]
        )
        return self._helper_finish_prio3(vdaf, results, combine_rows, combined)

    def _executor_backend_for(self, ta: TaskAggregator):
        """(shape key, backend) through the executor's shape-keyed cache:
        tasks sharing one VDAF shape share one backend + compiled graphs,
        and ``device_executor.mesh`` upgrades the helper's single-chip
        backends to the SPMD MeshBackend exactly like the drivers'.  With
        ``canonical_shapes`` on, the key is the CANONICAL shape's
        (vdaf/canonical.py) and the cached backend is the bucket's padded
        twin — a canonical cache entry must always be a genuine canonical
        device backend, so a failed twin build falls back to the task's
        exact key/backend instead of caching."""
        from ..vdaf.backend import make_backend, vdaf_shape_key
        from ..vdaf.canonical import executor_shape

        vdaf = ta.vdaf
        key, canon = executor_shape(
            vdaf, enabled=self._executor.config.canonical_shapes
        )
        if (
            canon is not None
            and ta.backend_name != "oracle"
            and key not in self._canon_build_failed
        ):
            try:
                return key, self._executor.backend_for(
                    key,
                    lambda: make_backend(
                        canon,
                        ta.backend_name,
                        field_backend=ta.field_backend,
                        canonical=True,
                    ),
                )
            except Exception:
                # negative-cached: the request path must not re-pay a
                # doomed twin construction + stack trace per request
                self._canon_build_failed.add(key)
                logger.exception(
                    "canonical helper backend build failed for task %s; "
                    "serving from an exact-shape compile",
                    ta.task.task_id,
                )
        key = vdaf_shape_key(vdaf)
        return key, self._executor.backend_for(key, lambda: ta.backend)

    async def _helper_prepare_batch_prio3_executor(self, ta: TaskAggregator, decoded):
        """Helper prep through the process-wide device executor: prep_init
        (agg_id=1 buckets) and combine submissions coalesce with every
        other producer's, and the per-shape circuit breaker guards this
        path — CircuitOpenError (or a breaker-peek hit before submitting)
        degrades the request to the bit-exact CPU oracle, executor
        backpressure surfaces as a retryable 503 to the leader."""
        from ..executor import (
            KIND_COMBINE,
            KIND_PREP_INIT,
        )
        from ..executor.service import CircuitOpenError, ExecutorOverloadedError

        vdaf = ta.vdaf
        shape_key, backend = self._executor_backend_for(ta)
        # task identity for the per-task fairness quota within the bucket
        task_ident = getattr(getattr(ta.task, "task_id", None), "data", None)
        loop = asyncio.get_running_loop()
        canonical = getattr(backend, "canonical", False)

        def oracle_path():
            # canonical backends must serve fallbacks from the TASK's
            # oracle (the bucket twin's computes a padded circuit); the
            # task cost scope attributes the oracle batch (path="oracle")
            from ..core import costs
            from ..vdaf.backend import oracle_backend_for

            oracle = oracle_backend_for(backend, vdaf) or backend
            return costs.run_in_task_scope(
                task_ident,
                lambda: self._helper_prepare_batch_prio3(
                    ta, decoded, backend=oracle
                ),
            )

        if self._executor.circuit_open(shape_key):
            return await loop.run_in_executor(None, oracle_path)
        if self._executor.warming(shape_key):
            # executable still compiling on the warmup thread: the helper
            # answers on the bit-exact oracle instead of queueing the
            # request behind XLA (the breaker never sees compile-wait)
            return await loop.run_in_executor(None, oracle_path)

        results, rows = await loop.run_in_executor(
            None, lambda: self._helper_decode_leader_shares(vdaf, decoded)
        )
        if not rows:
            return results
        prep_in = [(nonce, public, share) for (_, nonce, public, share, _) in rows]
        prep_out = None
        try:
            prep_out = await self._executor.submit(
                shape_key,
                KIND_PREP_INIT,
                # canonical backends take 3-tuple requests: the task's
                # actual vdaf rides along for bucket-shape marshal
                (ta.task.vdaf_verify_key, prep_in, vdaf)
                if canonical
                else (ta.task.vdaf_verify_key, prep_in),
                backend=backend,
                agg_id=1,
                # Helper-side retention (ISSUE 4 satellite): with the
                # accumulator store attached, the helper's out shares stay
                # ON DEVICE and the writer consumes a drained delta
                # instead of reading every row back.
                retain_out_shares=self._executor.accumulator is not None,
                task_ident=task_ident,
            )
            combine_rows = []
            for (idx, _n, _p, _s, leader_share), outcome in zip(rows, prep_out):
                if isinstance(outcome, VdafError):
                    results[idx] = PrepareError.VDAF_PREP_ERROR
                    continue
                state, helper_share = outcome
                combine_rows.append((idx, state, leader_share, helper_share))
            combined = await self._executor.submit(
                shape_key,
                KIND_COMBINE,
                [[ls, hs] for (_, _, ls, hs) in combine_rows],
                backend=backend,
                agg_id=1,
                task_ident=task_ident,
            )
            results = await loop.run_in_executor(
                None,
                lambda: self._helper_finish_prio3(vdaf, results, combine_rows, combined),
            )
        except CircuitOpenError:
            # re-enter past the decode: (results, rows) are already built;
            # any refs the prep submission minted must free first
            self._release_helper_refs(prep_out)
            from ..core import costs
            from ..vdaf.backend import oracle_backend_for

            oracle = oracle_backend_for(backend, vdaf) or backend
            return await loop.run_in_executor(
                None,
                lambda: costs.run_in_task_scope(
                    task_ident,
                    lambda: self._helper_prep_rows_prio3(
                        ta, oracle, results, rows
                    ),
                ),
            )
        except ExecutorOverloadedError as e:
            from .error import ServiceUnavailable

            self._release_helper_refs(prep_out)
            raise ServiceUnavailable(f"device executor overloaded: {e}")
        except BaseException:
            # anything else — a cancelled request mid-combine, an
            # unclassified executor failure — must not strand the minted
            # refs, or the retained flush matrix never frees (release is
            # idempotent, so rows a flush already released are unaffected)
            self._release_helper_refs(prep_out)
            raise
        # rows whose combine/finish failed keep no out share: release their
        # refs so the retained flush matrix can free
        self._release_unfinished_helper_refs(results, combine_rows)
        return results

    def _release_helper_refs(self, prep_out) -> None:
        from ..executor.accumulator import ResidentRef

        store = self._executor.accumulator if self._executor is not None else None
        if store is None or not prep_out:
            return
        refs = [
            o[0].out_share
            for o in prep_out
            if isinstance(o, tuple) and isinstance(o[0].out_share, ResidentRef)
        ]
        if refs:
            store.release_refs(refs)

    def _release_unfinished_helper_refs(self, results, combine_rows) -> None:
        from ..executor.accumulator import ResidentRef

        store = self._executor.accumulator if self._executor is not None else None
        if store is None:
            return
        refs = []
        for idx, state, _ls, _hs in combine_rows:
            out = results.get(idx)
            if isinstance(out, tuple) and out[0] == "finished":
                continue  # its ref lives on in out_shares; committed later
            ref = getattr(state, "out_share", None)
            if isinstance(ref, ResidentRef):
                refs.append(ref)
        if refs:
            store.release_refs(refs)

    async def _commit_helper_resident_shares(
        self, ta: TaskAggregator, job, ras, out_shares, decoded_by_rid
    ):
        """Helper mirror of the driver's accumulator commit (drain-at-
        commit only: the helper's writer runs in this request, so there is
        no cross-job residency to defer).  On any store/device failure the
        journaled reports are recomputed on the bit-exact CPU oracle from
        the request's decoded shares — host vectors replace the dead refs
        and the poisoned delta is discarded, exactly-once either way."""
        store = self._executor.accumulator if self._executor is not None else None
        if store is None:
            return None
        from ..datastore.query_type import strategy_for
        from ..executor.accumulator import AccumulatorUnavailable, ResidentRef
        from ..vdaf.backend import vdaf_shape_key

        resident = {
            rid: v for rid, v in out_shares.items() if isinstance(v, ResidentRef)
        }
        if not resident:
            return None
        task = ta.task
        vdaf = ta.vdaf
        shape_key = vdaf_shape_key(vdaf)
        # The refs were minted by the EXECUTOR's cached backend (the
        # canonical bucket twin when canonical_shapes is on): commit_rows'
        # accumulate launches must run on THAT backend — its buffer widths
        # match the retained flush matrices; ta.backend's would not.
        from ..vdaf.canonical import clip_drained_vector, executor_shape

        ex_cfg = getattr(self._executor, "config", None)
        ckey, _canon = executor_shape(
            vdaf, enabled=bool(ex_cfg and ex_cfg.canonical_shapes)
        )
        peek = getattr(self._executor, "cached_backend", None)
        commit_backend = None
        if peek is not None:
            # canonical key first; the EXACT key second (a failed twin
            # build makes _executor_backend_for cache the exact-shape —
            # possibly meshified — backend there, and THAT one minted the
            # refs whose buffer layout commit_rows must match)
            commit_backend = peek(ckey) or peek(shape_key)
        commit_backend = commit_backend or ta.backend
        strategy = strategy_for(task)
        ra_by_rid = {ra.report_id.data: ra for ra in ras}
        field = vdaf.field_for_agg_param(
            vdaf.decode_agg_param(job.aggregation_parameter)
        )

        def ident_for(ra):
            if job.partial_batch_identifier is not None:
                return job.partial_batch_identifier.get_encoded()
            return strategy.to_batch_identifier(task, ra.time)

        by_ident: Dict[bytes, List[bytes]] = {}
        for rid in resident:
            by_ident.setdefault(ident_for(ra_by_rid[rid]), []).append(rid)

        loop = asyncio.get_running_loop()
        deltas: Dict[bytes, tuple] = {}
        # Per-REQUEST nonce in the key, not just the job id: two identical
        # init requests for one job can be in flight concurrently (a
        # leader replica redelivers while the first delivery's request is
        # still being served).  Sharing a bucket would let both commits
        # land before either drain — a doubled vector whose report-id set
        # still matches, which the StaleAccumulatorDelta check cannot
        # catch and (unlike the leader) no lease-token fence aborts.  The
        # bucket lives only within this request, so uniqueness costs
        # nothing.
        import secrets as _secrets

        request_nonce = _secrets.token_bytes(8)
        for ident, rids in by_ident.items():
            bucket_key = (
                "helper",
                task.task_id.data,
                shape_key,
                ident,
                job.aggregation_parameter,
                job.aggregation_job_id.data,
                request_nonce,
            )
            refs = [resident[rid] for rid in rids]

            def commit_and_drain(bucket_key=bucket_key, refs=refs, rids=rids):
                store.commit_rows(
                    bucket_key,
                    commit_backend,
                    refs,
                    job_token=job.aggregation_job_id.data,
                    report_ids=rids,
                )
                return store.drain(bucket_key, field)

            try:
                drained = await loop.run_in_executor(None, commit_and_drain)
            except Exception as e:
                if not isinstance(e, AccumulatorUnavailable):
                    logger.exception("helper accumulator commit/drain failed")
                journal = store.discard(bucket_key)
                store.release_refs(refs)
                replay_rids = set(rids)
                for _job_token, ids in journal:
                    replay_rids |= set(ids)
                logger.warning(
                    "helper resident accumulator unavailable for %d "
                    "report(s); replaying through the CPU oracle: %s",
                    len(replay_rids),
                    e,
                )
                replayed = await loop.run_in_executor(
                    None,
                    lambda rids=sorted(replay_rids): self._helper_oracle_out_shares(
                        ta, rids, decoded_by_rid
                    ),
                )
                out_shares.update(replayed)
                continue
            if drained is None:
                continue
            vector, drained_rids = drained
            # canonical buffers are bucket-width; clip the provably-zero
            # pad tail back to the task's OUTPUT_LEN
            deltas[ident] = (clip_drained_vector(vdaf, vector), frozenset(drained_rids))
        return deltas or None

    def _helper_oracle_out_shares(self, ta: TaskAggregator, rids, decoded_by_rid):
        """Bit-exact CPU recompute of the helper's out shares from the
        request's already-decoded input shares (backend contract: oracle
        == device, tests/test_backend.py)."""
        from ..vdaf.backend import OracleBackend

        oracle = getattr(ta.backend, "oracle", None) or OracleBackend(ta.vdaf)
        rows = []
        for rid in rids:
            _rid, public_parts, input_share, _msg = decoded_by_rid[rid]
            rows.append((rid, public_parts, input_share))
        out = {}
        for rid, outcome in zip(
            rids, oracle.prep_init_batch(ta.task.vdaf_verify_key, 1, rows)
        ):
            if isinstance(outcome, VdafError):  # cannot happen for a report
                raise AggregatorError(  # that already prepared successfully
                    f"oracle replay rejected report {rid.hex()}"
                )
            state, _share = outcome
            out[rid] = state.out_share
        return out

    async def _stored_job_resp(
        self, task_id: TaskId, aggregation_job_id: AggregationJobId
    ) -> AggregationJobResp:
        """Reconstruct the last response from stored report aggregations."""
        ras = await self.datastore.run_tx_async(
            "stored_resp",
            lambda tx: tx.get_report_aggregations_for_aggregation_job(
                task_id, aggregation_job_id
            ),
        )
        resps = [ra.last_prep_resp for ra in ras if ra.last_prep_resp is not None]
        return AggregationJobResp(resps)

    # ------------------------------------------------------------------
    # helper aggregate continue (reference: aggregation_job_continue.rs:38)

    async def handle_aggregate_continue(
        self,
        task_id: TaskId,
        aggregation_job_id: AggregationJobId,
        body: bytes,
        auth_token: Optional[AuthenticationToken],
    ) -> AggregationJobResp:
        ta = await self.task_aggregator_for(task_id)
        task = ta.task
        if task.role != Role.HELPER:
            raise UnrecognizedTask("aggregate-continue on non-helper")
        ta.check_aggregator_auth(auth_token)
        req = AggregationJobContinueReq.get_decoded(body)
        if int(req.step) == 0:
            raise InvalidMessage("continue cannot request step 0")

        job = await self.datastore.run_tx_async(
            "agg_cont_load",
            lambda tx: tx.get_aggregation_job(task_id, aggregation_job_id),
        )
        if job is None:
            raise UnrecognizedAggregationJob(str(aggregation_job_id))
        # step skew (reference: aggregation_job_continue.rs:38-286)
        if int(req.step) == int(job.step):
            # replay of the previous request: only an identical body may be
            # answered from cache; a mutated request is a conflict
            if job.last_request_hash == hashlib.sha256(body).digest():
                return await self._stored_job_resp(task_id, aggregation_job_id)
            raise ForbiddenMutation("continue replayed with different request")
        if int(req.step) != int(job.step) + 1:
            raise StepMismatch(
                f"request step {int(req.step)} vs job step {int(job.step)}"
            )

        ras = await self.datastore.run_tx_async(
            "agg_cont_ras",
            lambda tx: tx.get_report_aggregations_for_aggregation_job(
                task_id, aggregation_job_id
            ),
        )
        by_id = {ra.report_id.data: ra for ra in ras}

        loop = asyncio.get_running_loop()
        stepped = await loop.run_in_executor(
            None, lambda: self._helper_continue_batch(ta, job, req, by_id)
        )
        new_ras, out_shares, resps = stepped

        job = job.with_step(AggregationJobStep(int(req.step))).with_last_request_hash(
            hashlib.sha256(body).digest()
        )
        if all(
            ra.state
            in (ReportAggregationState.FINISHED, ReportAggregationState.FAILED)
            for ra in new_ras
        ):
            job = job.with_state(AggregationJobState.FINISHED)

        # Helper-side deferred accumulation (ISSUE 13 satellite): CONTINUE
        # rounds of agg-param VDAFs (Poplar1's round-1 finishers) route
        # their per-request host vectors through the store's deferred
        # buckets like the leader's — N continue requests at one tree
        # level merge as ONE datastore vector write on the cadence drain,
        # with the journal row written in this tx as the exactly-once
        # fence (replayable at aggregate-share time after a crash from
        # the retained helper_prep_state).
        journal_entries = None
        touched: List[tuple] = []
        orig_shares = dict(out_shares)
        store = self._executor.accumulator if self._executor is not None else None
        if (
            store is not None
            and getattr(store.config, "deferred", False)
            and getattr(ta.vdaf, "REQUIRES_AGG_PARAM", False)
            and out_shares
        ):
            (
                journal_entries,
                touched,
                new_ras,
            ) = await self._commit_helper_deferred_host_shares(
                ta, job, by_id, new_ras, out_shares
            )

        from ..executor.accumulator import StaleAccumulatorDelta

        writer = AggregationJobWriter(
            task,
            ta.vdaf,
            batch_aggregation_shard_count=self.config.batch_aggregation_shard_count,
            initial_write=False,
            backend=ta.backend,
            journal_entries=journal_entries,
        )
        writer.put(job, new_ras, out_shares)
        try:
            failures = await self.datastore.run_tx_async(
                "agg_cont_write", lambda tx: writer.write(tx)
            )
        except StaleAccumulatorDelta:
            # a journaled report failed in-tx (its batch was collected
            # under our feet): discard the touched buckets — their journal
            # rows never committed (journal_entries cleared so the metric
            # and the drain scan below never see phantom rows) — and
            # retry once merging this request's vectors directly (no
            # deferral; still exactly-once)
            self._discard_helper_deferred(touched)
            journal_entries = None
            out_shares = orig_shares
            writer = AggregationJobWriter(
                task,
                ta.vdaf,
                batch_aggregation_shard_count=self.config.batch_aggregation_shard_count,
                initial_write=False,
                backend=ta.backend,
            )
            writer.put(job, new_ras, out_shares)
            failures = await self.datastore.run_tx_async(
                "agg_cont_write", lambda tx: writer.write(tx)
            )
        except BaseException:
            self._discard_helper_deferred(touched)
            raise
        if journal_entries:
            from ..core.metrics import GLOBAL_METRICS

            if GLOBAL_METRICS.registry is not None:
                GLOBAL_METRICS.accumulator_journal_entries.inc(len(journal_entries))
            await self._maybe_drain_helper_due(ta)
        if failures:
            resps = [
                PrepareResp(r.report_id, PrepareStepResult.reject(failures[r.report_id.data]))
                if r.report_id.data in failures
                else r
                for r in resps
            ]
        return AggregationJobResp(resps)

    async def _commit_helper_deferred_host_shares(
        self, ta: TaskAggregator, job, by_id, new_ras, out_shares
    ):
        """The helper twin of the driver's ``_commit_deferred_host_shares``:
        per batch bucket, sum this request's finished vectors into the
        store's agg-param-keyed HELPER host mirror (commit_host_rows) and
        hand the writer journal entries instead of shares.  Journaled
        rows' out_shares become sentinel refs so the writer defers them;
        their FINISHED report aggregations RETAIN ``helper_prep_state``
        (the round-1 prepare state whose ``y_flat`` IS the vector) as the
        crash-replay window.  A store failure leaves this request's
        vectors merging directly — exactly-once either way.  Returns
        (journal_entries, touched bucket keys, new_ras)."""
        import dataclasses

        from ..datastore import BatchAggregationState
        from ..datastore.query_type import strategy_for
        from ..executor.accumulator import ResidentRef
        from ..vdaf.backend import vdaf_shape_key

        store = self._executor.accumulator
        task = ta.task
        vdaf = ta.vdaf
        strategy = strategy_for(task)
        shape_key = vdaf_shape_key(vdaf)
        field = vdaf.field_for_agg_param(
            vdaf.decode_agg_param(job.aggregation_parameter)
        )
        ra_by_rid = {ra.report_id.data: ra for ra in new_ras}

        def ident_for(ra):
            if job.partial_batch_identifier is not None:
                return job.partial_batch_identifier.get_encoded()
            return strategy.to_batch_identifier(task, ra.time)

        by_ident: Dict[bytes, List[bytes]] = {}
        for rid in out_shares:
            by_ident.setdefault(ident_for(ra_by_rid[rid]), []).append(rid)

        # Pre-tx collected check (same rationale as the leader's):
        # journaling a report the writer tx will fail guarantees a
        # StaleAccumulatorDelta abort on every retry.
        def check(tx):
            out = set()
            for ident in by_ident:
                bas = tx.get_batch_aggregations_for_batch(
                    task.task_id, ident, job.aggregation_parameter
                )
                if any(
                    ba.state != BatchAggregationState.AGGREGATING for ba in bas
                ):
                    out.add(ident)
            return out

        collected = await self.datastore.run_tx_async(
            "helper_accum_collected_check", check
        )

        loop = asyncio.get_running_loop()
        journal_entries: Dict[bytes, frozenset] = {}
        touched: List[tuple] = []
        for ident, rids in by_ident.items():
            if ident in collected:
                continue  # writer fails these in-tx; vectors merge nowhere
            bucket_key = (
                "helper",
                task.task_id.data,
                shape_key,
                ident,
                job.aggregation_parameter,
            )
            vectors = [out_shares[rid] for rid in rids]

            def commit(bucket_key=bucket_key, vectors=vectors, rids=rids):
                store.commit_host_rows(
                    bucket_key,
                    field,
                    vectors,
                    job_token=job.aggregation_job_id.data,
                    report_ids=rids,
                )

            try:
                await loop.run_in_executor(None, commit)
            except Exception as e:
                logger.warning(
                    "helper deferred accumulator commit failed for bucket "
                    "%r; merging this request's %d vector(s) directly: %s",
                    bucket_key,
                    len(rids),
                    e,
                )
                continue
            journal_entries[ident] = frozenset(rids)
            touched.append(bucket_key)
            for i, rid in enumerate(rids):
                out_shares[rid] = ResidentRef(-1, i)

        if journal_entries:
            # replay window: journaled FINISHED rows keep the stored
            # round-1 prepare state (its y_flat is exactly the deferred
            # vector) — the aggregate-share-time replay decodes it after
            # a crash loses the store's host mirror
            journaled = set().union(*journal_entries.values())
            new_ras = [
                dataclasses.replace(
                    ra, helper_prep_state=by_id[ra.report_id.data].helper_prep_state
                )
                if ra.report_id.data in journaled
                and ra.state == ReportAggregationState.FINISHED
                else ra
                for ra in new_ras
            ]
        return journal_entries or None, touched, new_ras

    def _discard_helper_deferred(self, touched) -> None:
        """Drop helper deferred buckets whose journal rows never committed
        (failed tx); OTHER requests' persisted journal rows stay
        replayable at aggregate-share time."""
        store = self._executor.accumulator if self._executor is not None else None
        if store is None or not touched:
            return
        for key in touched:
            journal = store.discard(key)
            if journal:
                logger.warning(
                    "discarded helper bucket %r with %d journaled "
                    "request(s) after a failed tx; persisted journal rows "
                    "will replay at aggregate-share time",
                    key,
                    len(journal),
                )

    async def _maybe_drain_helper_due(self, ta: TaskAggregator) -> int:
        """Cadence scan for the HELPER's deferred buckets (the helper has
        no driver loop — drains ride request completions and the
        aggregate-share barrier): merge every due bucket's vector into
        batch_aggregations, consuming its journal rows exactly once."""
        store = self._executor.accumulator if self._executor is not None else None
        if store is None or not getattr(store.config, "deferred", False):
            return 0
        task_id = ta.task.task_id
        keys = [
            k
            for k in store.due_buckets(store.config.drain_interval_s)
            if len(k) == 5 and k[0] == "helper" and k[1] == task_id.data
        ]
        for key in keys:
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, self._drain_helper_bucket, ta, key
                )
            except Exception:
                logger.exception("helper deferred drain failed for %r", key)
        return len(keys)

    def _drain_helper_bucket(self, ta: TaskAggregator, key: tuple) -> None:
        from ..executor.accumulator import AccumulatorError

        vdaf = ta.vdaf
        _role, _task_id_b, _shape, ident, param = key
        field = vdaf.field_for_agg_param(vdaf.decode_agg_param(param))
        try:
            out = self._executor.accumulator.drain_with_journal(key, field)
        except AccumulatorError as e:
            journal = self._executor.accumulator.discard(key)
            logger.warning(
                "helper deferred drain failed for bucket %r; %d journal "
                "row(s) stay persisted for the aggregate-share replay: %s",
                key,
                len(journal),
                e,
            )
            return
        if out is None:
            return
        vector, journal = out
        self._merge_helper_drained(ta, field, ident, param, vector, journal)

    def _merge_helper_drained(
        self, ta: TaskAggregator, field, ident, param, vector, journal
    ) -> None:
        """Merge one drained helper vector, consuming its journal rows in
        the same tx (exactly-once: the DELETE decides the winner against
        a concurrent aggregate-share replay)."""
        from ..messages import AggregationJobId
        from .aggregation_job_writer import merge_share_delta

        class _RowMissing(Exception):
            pass

        task = ta.task

        def tx_fn(tx):
            for job_token, _rids in journal:
                if not tx.delete_accumulator_journal_entry(
                    task.task_id, ident, param, AggregationJobId(job_token)
                ):
                    raise _RowMissing(job_token)
            merge_share_delta(
                tx,
                task,
                field,
                ident,
                param,
                vector,
                shard_count=self.config.batch_aggregation_shard_count,
            )

        try:
            self.datastore.run_tx("helper_accumulator_drain", tx_fn)
        except _RowMissing as e:
            logger.warning(
                "helper bucket (%r, %r) journal row %s already consumed "
                "(replayed); dropping the drained vector",
                ident,
                param,
                e,
            )
            return
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.accumulator_journal_consumed.labels(path="drain").inc(
                len(journal)
            )

    async def _flush_helper_deferred(self, ta: TaskAggregator, ident: bytes, param: bytes) -> None:
        """The aggregate-share barrier: before the helper computes a
        batch's share, (1) drain every resident deferred bucket for this
        task (regardless of age — collection is the deadline), then (2)
        replay any journal rows still outstanding for the collection's
        batches (a crashed process's buckets died with it; the rows name
        FINISHED reports whose retained ``helper_prep_state`` carries the
        vector).  Mirrors the leader's collection-time replay fence."""
        store = self._executor.accumulator if self._executor is not None else None
        task = ta.task
        if store is not None:
            keys = [
                k
                for k in store.bucket_keys()
                if len(k) == 5 and k[0] == "helper" and k[1] == task.task_id.data
            ]
            for key in keys:
                try:
                    await asyncio.get_running_loop().run_in_executor(
                        None, self._drain_helper_bucket, ta, key
                    )
                except Exception:
                    logger.exception("helper pre-share drain failed for %r", key)
        # journal rows orphaned by a crash (or lost buckets): replay
        if not await self.datastore.run_tx_async(
            "helper_journal_probe",
            lambda tx: tx.count_accumulator_journal_entries(task.task_id),
        ):
            return
        strategy = strategy_for(task)

        def load(tx):
            entries = []
            for bident in strategy.batch_identifiers_for_collection_identifier(
                task, ident
            ):
                entries.extend(
                    e
                    for e in tx.get_accumulator_journal_entries(task.task_id, bident)
                    if e.aggregation_parameter == param
                )
            return entries

        entries = await self.datastore.run_tx_async("helper_journal_scan", load)
        for entry in entries:
            await self._replay_helper_journal_entry(ta, entry)

    async def _replay_helper_journal_entry(self, ta: TaskAggregator, entry) -> None:
        """Re-derive one orphaned helper journal row's vector from the
        retained round-1 prepare states and merge it, deleting the row in
        the same tx (exactly-once against any concurrent drain)."""
        from ..core import costs

        vdaf = ta.vdaf
        ras = await self.datastore.run_tx_async(
            "helper_replay_load_ras",
            lambda tx: tx.get_report_aggregations_for_aggregation_job(
                ta.task.task_id, entry.aggregation_job_id
            ),
        )
        by_rid = {ra.report_id.data: ra for ra in ras}
        field = vdaf.field_for_agg_param(
            vdaf.decode_agg_param(entry.aggregation_parameter)
        )

        def recompute():
            total = None
            for rid in entry.report_ids:
                ra = by_rid.get(rid)
                if ra is None or ra.helper_prep_state is None:
                    raise RuntimeError(
                        f"helper journal entry for job {entry.aggregation_job_id}"
                        f" names report {rid.hex()} without a retained state"
                    )
                state = vdaf.ping_pong_decode_state(ra.helper_prep_state)
                y = list(state.y_flat)
                total = y if total is None else field.vec_add(total, y)
            return total

        total = await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: costs.run_in_task_scope(ta.task.task_id.data, recompute),
        )
        self._merge_replayed_helper_entry(ta, field, entry, total)

    def _merge_replayed_helper_entry(self, ta, field, entry, total) -> None:
        from .aggregation_job_writer import merge_share_delta

        task = ta.task

        def tx_fn(tx):
            if not tx.delete_accumulator_journal_entry(
                task.task_id,
                entry.batch_identifier,
                entry.aggregation_parameter,
                entry.aggregation_job_id,
            ):
                return False
            if total is not None:
                merge_share_delta(
                    tx,
                    task,
                    field,
                    entry.batch_identifier,
                    entry.aggregation_parameter,
                    total,
                    shard_count=self.config.batch_aggregation_shard_count,
                )
            return True

        merged = self.datastore.run_tx("helper_journal_replay", tx_fn)
        if merged:
            logger.warning(
                "helper oracle-replayed %d report(s) of job %s from the "
                "datastore journal (owner never drained)",
                len(entry.report_ids),
                entry.aggregation_job_id,
            )
            from ..core.metrics import GLOBAL_METRICS

            if GLOBAL_METRICS.registry is not None:
                GLOBAL_METRICS.accumulator_journal_consumed.labels(
                    path="replay"
                ).inc()

    def _helper_continue_batch(self, ta: TaskAggregator, job, req, by_id):
        """Step WaitingHelper reports with the leader's continue messages."""
        vdaf = ta.vdaf
        new_ras: List[ReportAggregation] = []
        out_shares: Dict[bytes, Sequence[int]] = {}
        resps: List[PrepareResp] = []
        for pc in req.prepare_continues:
            ra = by_id.get(pc.report_id.data)
            if ra is None or ra.state != ReportAggregationState.WAITING_HELPER:
                raise InvalidMessage(
                    f"report {pc.report_id} not in WaitingHelper state"
                )
            try:
                agg_param = vdaf.decode_agg_param(job.aggregation_parameter)
                state = vdaf.ping_pong_decode_state(ra.helper_prep_state)
                # the helper's stored state after evaluating round k's
                # transition is at round k; step k+1's continue finds it at
                # round == req.step
                value = pp.continued(
                    vdaf,
                    False,
                    pp.PingPongContinued(state, int(req.step)),
                    pc.message,
                    agg_param,
                )
            except (VdafError, pp.PingPongError):
                resp = PrepareResp(
                    pc.report_id, PrepareStepResult.reject(PrepareError.VDAF_PREP_ERROR)
                )
                new_ras.append(
                    ra.failed(PrepareError.VDAF_PREP_ERROR).with_last_prep_resp(resp)
                )
                resps.append(resp)
                continue
            if value.out_share is not None:
                resp = PrepareResp(pc.report_id, PrepareStepResult.finished())
                new_ras.append(
                    ra.with_state(ReportAggregationState.FINISHED).with_last_prep_resp(resp)
                )
                out_shares[pc.report_id.data] = value.out_share
            else:
                next_state, outbound = value.transition.evaluate(vdaf)
                resp = PrepareResp(
                    pc.report_id, PrepareStepResult.new_continue(outbound)
                )
                if isinstance(next_state, pp.PingPongFinished):
                    new_ras.append(
                        ra.with_state(ReportAggregationState.FINISHED).with_last_prep_resp(resp)
                    )
                    out_shares[pc.report_id.data] = next_state.out_share
                else:
                    new_ras.append(
                        ra.with_state(
                            ReportAggregationState.WAITING_HELPER,
                            helper_prep_state=vdaf.ping_pong_encode_state(
                                next_state.prep_state
                            ),
                        ).with_last_prep_resp(resp)
                    )
            resps.append(resp)
        # reports absent from the request keep their state
        present = {pc.report_id.data for pc in req.prepare_continues}
        for rid, ra in by_id.items():
            if rid not in present and ra.state == ReportAggregationState.WAITING_HELPER:
                new_ras.append(ra.failed(PrepareError.REPORT_DROPPED))
        return new_ras, out_shares, resps

    # ------------------------------------------------------------------
    # helper aggregation job delete

    async def handle_aggregate_delete(
        self,
        task_id: TaskId,
        aggregation_job_id: AggregationJobId,
        auth_token: Optional[AuthenticationToken],
    ) -> None:
        ta = await self.task_aggregator_for(task_id)
        ta.check_aggregator_auth(auth_token)

        def tx_fn(tx):
            job = tx.get_aggregation_job(task_id, aggregation_job_id)
            if job is None:
                raise UnrecognizedAggregationJob(str(aggregation_job_id))
            tx.update_aggregation_job(job.with_state(AggregationJobState.DELETED))

        await self.datastore.run_tx_async("agg_delete", tx_fn)

    # ------------------------------------------------------------------
    # collection jobs (leader; reference: aggregator.rs:2461-2757)

    async def handle_create_collection_job(
        self,
        task_id: TaskId,
        collection_job_id: CollectionJobId,
        body: bytes,
        auth_token: Optional[AuthenticationToken],
    ) -> None:
        ta = await self.task_aggregator_for(task_id)
        task = ta.task
        if task.role != Role.LEADER:
            raise UnrecognizedTask("collection on non-leader")
        ta.check_collector_auth(auth_token)
        req = CollectionReq.get_decoded(body, ta.query_class)
        strategy = strategy_for(task)
        err = strategy.validate_query(task, req.query)
        if err is not None:
            raise BatchInvalid(err)

        # Trace mint point: the collection pipeline (readiness polls,
        # journal replays, helper share exchange) joins on this id.
        # Resolved OUTSIDE the tx closure — contextvars do not cross the
        # datastore's executor thread.
        from ..core.trace import current_trace, new_trace_id

        trace_id = current_trace().get("trace_id") or new_trace_id()

        def tx_fn(tx):
            existing = tx.get_collection_job(
                task_id, collection_job_id, task.query_type.kind
            )
            if existing is not None:
                if (
                    existing.query == req.query
                    and existing.aggregation_parameter == req.aggregation_parameter
                ):
                    return  # idempotent re-PUT
                raise ForbiddenMutation("collection job mutated")

            if task.query_type.kind == "TimeInterval":
                ident = req.query.query_body.get_encoded()
                # batch overlap check (reference: batch queried at most once)
                for other in tx.get_collection_jobs_by_batch_identifier(
                    task_id, ident, task.query_type.kind
                ):
                    if other.aggregation_parameter == req.aggregation_parameter:
                        raise BatchQueriedTooManyTimes("batch already queried")
            else:
                fsq: FixedSizeQuery = req.query.query_body
                if fsq.variant == FixedSizeQuery.BY_BATCH_ID:
                    batch_id = fsq.batch_id
                    for other in tx.get_collection_jobs_by_batch_identifier(
                        task_id, batch_id.get_encoded(), task.query_type.kind
                    ):
                        if other.aggregation_parameter == req.aggregation_parameter:
                            raise BatchQueriedTooManyTimes("batch already queried")
                else:  # current batch
                    batch_id = tx.acquire_filled_outstanding_batch(
                        task_id, task.min_batch_size
                    )
                    if batch_id is None:
                        raise InvalidBatchSize("no batch ready for collection")
                ident = batch_id.get_encoded()

            tx.put_collection_job(
                CollectionJob(
                    task_id=task_id,
                    collection_job_id=collection_job_id,
                    query=req.query,
                    aggregation_parameter=req.aggregation_parameter,
                    batch_identifier=ident,
                    state=CollectionJobState.START,
                    trace_id=trace_id,
                )
            )
            if getattr(ta.vdaf, "REQUIRES_AGG_PARAM", False):
                # Aggregation-parameter VDAFs (Poplar1): the collection
                # request IS what names the parameter, so aggregation jobs
                # are created here, re-reading the (never scrubbed) client
                # reports for each level (the reference gates the analogous
                # path behind test-util, aggregation_job_creator.rs:741).
                self._create_agg_param_jobs(
                    tx, ta, ident, req.aggregation_parameter, trace_id=trace_id
                )

        await self.datastore.run_tx_async("create_collection_job", tx_fn)

    def _create_agg_param_jobs(
        self,
        tx,
        ta: TaskAggregator,
        collection_identifier: bytes,
        agg_param: bytes,
        trace_id: Optional[str] = None,
    ) -> None:
        """Create aggregation jobs for one (batch, aggregation parameter)."""
        from .aggregation_job_writer import AggregationJobWriter

        task = ta.task
        if task.query_type.kind != "TimeInterval":
            raise BatchInvalid(
                "aggregation-parameter VDAFs support TimeInterval tasks"
            )
        interval = Interval.get_decoded(collection_identifier)
        reports = tx.get_client_reports_for_interval(task.task_id, interval, 50000)
        if not reports:
            return
        conflict_key = ta.vdaf.agg_param_conflict_key(agg_param)
        writer = AggregationJobWriter(
            task,
            ta.vdaf,
            batch_aggregation_shard_count=self.config.batch_aggregation_shard_count,
            initial_write=True,
            backend=ta.backend,
        )
        params_by_report = tx.get_aggregation_params_by_report_for_interval(
            task.task_id, interval
        )
        fresh = []
        for report in reports:
            params = params_by_report.get(report.report_id.data, [])
            if any(
                ta.vdaf.agg_param_conflict_key(p) == conflict_key for p in params
            ):
                continue  # already aggregated at this level
            fresh.append(report)
        job_size = max(1, self.config.max_agg_param_job_size)
        for i in range(0, len(fresh), job_size):
            chunk = fresh[i : i + job_size]
            job_id = AggregationJobId.random()
            start = min(r.time.seconds for r in chunk)
            end = max(r.time.seconds for r in chunk) + 1
            job = AggregationJob(
                task_id=task.task_id,
                aggregation_job_id=job_id,
                aggregation_parameter=agg_param,
                partial_batch_identifier=None,
                client_timestamp_interval=Interval(
                    Time(start), Duration(end - start)
                ),
                state=AggregationJobState.IN_PROGRESS,
                step=AggregationJobStep(0),
                # collection-driven jobs inherit the collection's trace id
                trace_id=trace_id,
            )
            ras = [
                ReportAggregation(
                    task_id=task.task_id,
                    aggregation_job_id=job_id,
                    report_id=r.report_id,
                    time=r.time,
                    ord=ord_,
                    state=ReportAggregationState.START_LEADER,
                    public_share=r.public_share,
                    leader_extensions=r.leader_extensions,
                    leader_input_share=r.leader_input_share,
                    helper_encrypted_input_share=r.helper_encrypted_input_share,
                )
                for ord_, r in enumerate(chunk)
            ]
            writer.put(job, ras)
        writer.write(tx)

    async def handle_get_collection_job(
        self,
        task_id: TaskId,
        collection_job_id: CollectionJobId,
        auth_token: Optional[AuthenticationToken],
    ) -> Optional[Collection]:
        """Returns the Collection when finished, None when still running
        (HTTP layer turns None into 202 + Retry-After)."""
        ta = await self.task_aggregator_for(task_id)
        task = ta.task
        ta.check_collector_auth(auth_token)
        job = await self.datastore.run_tx_async(
            "get_collection_job",
            lambda tx: tx.get_collection_job(
                task_id, collection_job_id, task.query_type.kind
            ),
        )
        if job is None:
            raise UnrecognizedCollectionJob(str(collection_job_id))
        if job.state == CollectionJobState.START:
            return None
        if job.state == CollectionJobState.DELETED:
            raise DeletedCollectionJob("collection job deleted")
        if job.state == CollectionJobState.ABANDONED:
            raise AggregatorError("collection job abandoned")

        # Finished: seal the leader share to the collector
        # (reference: aggregator.rs:2648-2757).
        if task.query_type.kind == "TimeInterval":
            batch_selector = BatchSelector.new_time_interval(
                Interval.get_decoded(job.batch_identifier)
            )
            pbs = PartialBatchSelector.new_time_interval()
        else:
            batch_selector = BatchSelector.new_fixed_size(
                BatchId.get_decoded(job.batch_identifier)
            )
            pbs = PartialBatchSelector.new_fixed_size(
                BatchId.get_decoded(job.batch_identifier)
            )
        aad = AggregateShareAad(
            task_id, job.aggregation_parameter, batch_selector
        ).get_encoded()
        leader_encrypted = seal(
            task.collector_hpke_config,
            HpkeApplicationInfo.new(Label.AGGREGATE_SHARE, Role.LEADER, Role.COLLECTOR),
            job.leader_aggregate_share,
            aad,
        )
        return Collection(
            partial_batch_selector=pbs,
            report_count=job.report_count,
            interval=job.client_timestamp_interval,
            leader_encrypted_agg_share=leader_encrypted,
            helper_encrypted_agg_share=job.helper_aggregate_share,
        )

    async def handle_delete_collection_job(
        self,
        task_id: TaskId,
        collection_job_id: CollectionJobId,
        auth_token: Optional[AuthenticationToken],
    ) -> None:
        ta = await self.task_aggregator_for(task_id)
        task = ta.task
        ta.check_collector_auth(auth_token)

        def tx_fn(tx):
            job = tx.get_collection_job(task_id, collection_job_id, task.query_type.kind)
            if job is None:
                raise UnrecognizedCollectionJob(str(collection_job_id))
            if job.state != CollectionJobState.DELETED:
                tx.update_collection_job(job.with_state(CollectionJobState.DELETED))

        await self.datastore.run_tx_async("delete_collection_job", tx_fn)

    # ------------------------------------------------------------------
    # helper aggregate share (reference: aggregator.rs:2878 handle_aggregate_share_generic)

    async def handle_aggregate_share(
        self,
        task_id: TaskId,
        body: bytes,
        auth_token: Optional[AuthenticationToken],
    ) -> AggregateShare:
        ta = await self.task_aggregator_for(task_id)
        task = ta.task
        if task.role != Role.HELPER:
            raise UnrecognizedTask("aggregate-share on non-helper")
        ta.check_aggregator_auth(auth_token)
        req = AggregateShareReq.get_decoded(body, ta.query_class)
        strategy = strategy_for(task)
        ident = req.batch_selector.batch_identifier.get_encoded()

        # Deferred-drain barrier (ISSUE 13 satellite): resident helper
        # buckets drain and orphaned journal rows replay BEFORE the share
        # is computed — the helper twin of the leader's collection-time
        # journal fence.
        if getattr(ta.vdaf, "REQUIRES_AGG_PARAM", False):
            try:
                await self._flush_helper_deferred(
                    ta, ident, req.aggregation_parameter
                )
            except Exception:
                # a failed drain leaves rows journaled; the share below
                # would under-count — fail the request loudly, the leader
                # retries
                logger.exception("helper deferred flush failed")
                raise AggregatorError("deferred share flush failed")

        def tx_fn(tx):
            cached = tx.get_aggregate_share_job(
                task_id, ident, req.aggregation_parameter
            )
            if cached is not None:
                if (
                    cached.report_count != req.report_count
                    or cached.checksum.data != req.checksum.data
                ):
                    raise BatchMismatch("cached aggregate share mismatch")
                return cached.helper_aggregate_share, None

            share, count, checksum, _interval = compute_aggregate_share(
                task, ta.vdaf, tx, ident, req.aggregation_parameter
            )
            # cross-aggregator consistency checks (reference: aggregate_share.rs:21-118)
            if count != req.report_count or checksum.data != req.checksum.data:
                raise BatchMismatch(
                    f"count/checksum mismatch: {count} vs {req.report_count}"
                )
            if count < task.min_batch_size:
                raise InvalidBatchSize(f"batch too small: {count}")
            if share is None:
                raise InvalidBatchSize("empty batch")
            return None, (share, count, checksum)

        encoded_share, computed = await self.datastore.run_tx_async(
            "aggregate_share", tx_fn
        )
        if computed is not None:
            share, count, checksum = computed
            # Helper-side DP noise (reference: aggregator.rs:3005
            # add_noise_to_agg_share): the helper noises its share
            # independently of the leader so the zCDP guarantee holds
            # against a collector colluding with either aggregator.  The
            # exact-rational sampler runs OUTSIDE any transaction (it can
            # take seconds on wide shares) and off the event loop.
            field = ta.vdaf.field_for_agg_param(
                ta.vdaf.decode_agg_param(req.aggregation_parameter)
            )
            strategy_dp = dp_strategy_from_dict(task.vdaf.get("dp_strategy"))
            encoded_share = await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: field.encode_vec(
                    strategy_dp.add_noise_to_agg_share(ta.vdaf, share, count)
                ),
            )

            def tx_store(tx):
                # Re-check the cache: a concurrent request may have stored
                # its (differently-noised) job first — serve THAT share so
                # repeated requests stay byte-identical.
                cached = tx.get_aggregate_share_job(
                    task_id, ident, req.aggregation_parameter
                )
                if cached is not None:
                    if (
                        cached.report_count != req.report_count
                        or cached.checksum.data != req.checksum.data
                    ):
                        raise BatchMismatch("cached aggregate share mismatch")
                    return cached.helper_aggregate_share
                tx.put_aggregate_share_job(
                    AggregateShareJob(
                        task_id=task_id,
                        batch_identifier=ident,
                        aggregation_parameter=req.aggregation_parameter,
                        helper_aggregate_share=encoded_share,
                        report_count=count,
                        checksum=checksum,
                    )
                )
                # Scrub contributing batch aggregations ATOMICALLY with the
                # job insert (reference: :2878-3123): if this transaction
                # fails, the un-scrubbed aggregations still support a clean
                # retry; once it commits, every later request is served
                # from the cache and never recomputes over scrubbed rows.
                for bident in strategy.batch_identifiers_for_collection_identifier(
                    task, ident
                ):
                    for ba in tx.get_batch_aggregations_for_batch(
                        task_id, bident, req.aggregation_parameter
                    ):
                        if ba.state == BatchAggregationState.AGGREGATING:
                            tx.update_batch_aggregation(ba.scrubbed())
                return encoded_share

            encoded_share = await self.datastore.run_tx_async(
                "aggregate_share_store", tx_store
            )
        aad = AggregateShareAad(
            task_id, req.aggregation_parameter, req.batch_selector
        ).get_encoded()
        encrypted = seal(
            task.collector_hpke_config,
            HpkeApplicationInfo.new(Label.AGGREGATE_SHARE, Role.HELPER, Role.COLLECTOR),
            encoded_share,
            aad,
        )
        return AggregateShare(encrypted)


def _check_extensions(extensions) -> None:
    """Duplicate extension types are rejected (reference: aggregator.rs upload
    and init validation)."""
    seen = set()
    for ext in extensions:
        if ext.extension_type in seen:
            raise InvalidMessage("duplicate extension")
        seen.add(ext.extension_type)
