"""Global HPKE key rotation for the aggregator binary.

The analog of the reference aggregator's global-HPKE-key lifecycle
(reference: aggregator/src/binaries/aggregator.rs:31-150 runs the
long-lived maintenance loops beside the server; key states and their cache
propagation are aggregator_core/src/datastore/models.rs HpkeKeyState +
aggregator/src/cache.rs GlobalHpkeKeypairCache).  One rotator tick drives
the state machine inside a single transaction:

  bootstrap:  no keys at all -> insert one directly as ACTIVE
  pre-stage:  newest ACTIVE older than (active_duration - pending_duration)
              -> insert a PENDING key.  The pending window exists so every
              replica's refreshed key cache holds the key BEFORE it is
              advertised/attached to new tasks (cache.py refresh cadence).
  promote:    PENDING key older than pending_duration -> ACTIVE
  retire:     ACTIVE key older than (active_duration + pending_duration),
              while a newer ACTIVE exists -> EXPIRED.  The extra
              pending_duration keeps BOTH keys advertised across the
              promotion, so clients that fetched /hpke_config just before
              it never race the flip.
  reap:       EXPIRED key older than expired_duration -> deleted (task
              copies of the keypair keep decrypting in-flight reports).

Every transition is clock-driven and idempotent, so N replicas may run the
rotator concurrently against the shared datastore: serialization-failure
retries cover state flips, and an insert race on a fresh config id (two
replicas staging the same slot — a unique violation, which run_tx does NOT
retry) is swallowed as success since the losing tick's goal already holds.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from ..core import faults
from ..core.db_health import janitor_skip as _janitor_skip
from ..core.hpke import HpkeKeypair
from ..datastore.datastore import Datastore, TxConflict
from ..datastore.models import HpkeKeyState
from ..messages import Duration

logger = logging.getLogger("janus_tpu.key_rotator")


@dataclass
class KeyRotatorConfig:
    # Defaults mirror a conservative deployment: new key staged a day before
    # rotation, keys live a week, expired keys reaped after another day.
    pending_duration: Duration = Duration(86400)
    active_duration: Duration = Duration(7 * 86400)
    expired_duration: Duration = Duration(86400)


class HpkeKeyRotator:
    def __init__(self, datastore: Datastore, config: KeyRotatorConfig = None):
        self.datastore = datastore
        self.config = config or KeyRotatorConfig()

    async def run(self) -> None:
        if _janitor_skip("key_rotator"):
            return
        try:
            await self.datastore.run_tx_async("key_rotator", self._tick)
        except TxConflict:
            # Another replica's rotator inserted the same config id in a
            # concurrent tick (run_tx does not retry unique violations).
            # The tick's goal — a key exists in that slot — is satisfied.
            logger.info("key rotator tick lost an insert race; treating as done")

    def run_sync(self) -> None:
        if _janitor_skip("key_rotator"):
            return
        try:
            self.datastore.run_tx("key_rotator", self._tick)
        except TxConflict:
            logger.info("key rotator tick lost an insert race; treating as done")

    def _next_config_id(self, keypairs) -> int:
        used = {kp.config.id for kp in keypairs}
        for cid in range(256):
            if cid not in used:
                return cid
        raise RuntimeError("all 256 HPKE config ids in use")

    def _tick(self, tx) -> None:
        # Failure-domain boundary: a rotator tick dying mid-transition must
        # roll back atomically (every transition is clock-driven and
        # idempotent, so the next tick simply redoes it).
        faults.fire("key_rotator.run")
        now = self.datastore.clock.now().seconds
        cfg = self.config
        keypairs = tx.get_global_hpke_keypairs()

        if not keypairs:
            kp = HpkeKeypair.generate(self._next_config_id(keypairs))
            tx.put_global_hpke_keypair(kp)
            tx.set_global_hpke_keypair_state(kp.config.id, HpkeKeyState.ACTIVE)
            logger.info("bootstrapped global HPKE key %d as Active", kp.config.id)
            return

        by_state = {}
        for kp in keypairs:
            by_state.setdefault(kp.state, []).append(kp)
        active = sorted(
            by_state.get(HpkeKeyState.ACTIVE, []), key=lambda k: k.updated_at.seconds
        )
        pending = sorted(
            by_state.get(HpkeKeyState.PENDING, []), key=lambda k: k.updated_at.seconds
        )

        # promote: pending long enough for caches/clients to have seen it.
        for kp in list(pending):
            if now - kp.updated_at.seconds >= cfg.pending_duration.seconds:
                tx.set_global_hpke_keypair_state(kp.config.id, HpkeKeyState.ACTIVE)
                logger.info("promoted global HPKE key %d to Active", kp.config.id)
                active.append(kp)
                pending.remove(kp)

        # pre-stage: newest active approaching rotation and nothing pending.
        if active and not pending:
            newest = max(kp.updated_at.seconds for kp in active)
            if now - newest >= cfg.active_duration.seconds - cfg.pending_duration.seconds:
                kp = HpkeKeypair.generate(self._next_config_id(keypairs))
                tx.put_global_hpke_keypair(kp)  # inserted as Pending
                logger.info("staged global HPKE key %d as Pending", kp.config.id)

        # retire: old actives, but never the most recent one, and only after
        # a pending_duration of overlap with its replacement (clients that
        # fetched /hpke_config just before the promotion keep a valid key).
        if len(active) > 1:
            newest_id = max(active, key=lambda k: k.updated_at.seconds).config.id
            retire_age = cfg.active_duration.seconds + cfg.pending_duration.seconds
            for kp in active:
                if kp.config.id != newest_id and now - kp.updated_at.seconds >= retire_age:
                    tx.set_global_hpke_keypair_state(
                        kp.config.id, HpkeKeyState.EXPIRED
                    )
                    logger.info("expired global HPKE key %d", kp.config.id)

        # reap: expired keys past the decrypt grace period.
        for kp in by_state.get(HpkeKeyState.EXPIRED, []):
            if now - kp.updated_at.seconds >= cfg.expired_duration.seconds:
                tx.delete_global_hpke_keypair(kp.config.id)
                logger.info("deleted expired global HPKE key %d", kp.config.id)
