"""Garbage collection of expired artifacts.

The analog of ``GarbageCollector`` (reference:
aggregator/src/aggregator/garbage_collector.rs:14-204): per task with a
``report_expiry_age``, batched deletion of expired client reports,
aggregation artifacts, and collection artifacts.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

from ..core import faults
from ..core.db_health import janitor_skip as _janitor_skip
from ..core.time import time_sub
from ..datastore import Datastore
from ..messages import Role

logger = logging.getLogger("janus_tpu.garbage_collector")


@dataclass
class GcConfig:
    report_limit: int = 5000
    aggregation_limit: int = 500
    collection_limit: int = 50


class GarbageCollector:
    def __init__(self, datastore: Datastore, config: Optional[GcConfig] = None):
        self.datastore = datastore
        self.config = config or GcConfig()

    async def run_once(self) -> int:
        """One GC pass over every task; returns rows deleted."""
        if _janitor_skip("gc"):
            return 0
        tasks = await self.datastore.run_tx_async(
            "gc_tasks", lambda tx: tx.get_aggregator_tasks()
        )
        deleted = 0
        for task in tasks:
            if task.report_expiry_age is None:
                continue
            try:
                deleted += await self.datastore.run_tx_async(
                    "gc_task", lambda tx, task=task: self._gc_task(tx, task)
                )
            except Exception:
                logger.exception("GC failed for task %s", task.task_id)
        return deleted

    def _gc_task(self, tx, task) -> int:
        # Failure-domain boundary: a GC pass dying mid-task must stay
        # contained (run_once's per-task try logs and moves on).
        faults.fire("gc.run")
        now = self.datastore.now()
        if now.seconds <= task.report_expiry_age.seconds:
            return 0
        expiry = time_sub(now, task.report_expiry_age)
        n = tx.delete_expired_client_reports(
            task.task_id, expiry, self.config.report_limit
        )
        n += tx.delete_expired_aggregation_artifacts(
            task.task_id, expiry, self.config.aggregation_limit
        )
        n += tx.delete_expired_collection_artifacts(
            task.task_id, expiry, self.config.collection_limit
        )
        return n
