"""Aggregation job stepping (leader) — the north-star hot path.

The analog of ``AggregationJobDriver`` (reference:
aggregator/src/aggregator/aggregation_job_driver.rs:59-1046): steps leased
aggregation jobs through init (leader prepare → PUT init request to helper)
and continue (evaluate stored ping-pong transitions → POST continue
request), merges the helper's responses, and commits everything through the
AggregationJobWriter.  The per-report leader prepare loop the reference
ships to rayon (:449) is ONE batched device launch via the backend seam.

Abandonment: after ``maximum_attempts_before_failure`` lease attempts the
job is abandoned with a best-effort DELETE to the helper (reference
:977-1026); errors are classified retryable vs fatal (:1030-1045).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.retries import HttpRetryPolicy, retry_http_request
from ..datastore import (
    AggregationJob,
    AggregationJobState,
    Datastore,
    Lease,
    ReportAggregation,
    ReportAggregationState,
)
from ..datastore.datastore import DatastoreError, DatastoreUnavailable
from ..datastore.task import AggregatorTask
from ..messages import (
    AggregationJobContinueReq,
    AggregationJobInitializeReq,
    AggregationJobResp,
    AggregationJobStep,
    Duration,
    PartialBatchSelector,
    PrepareContinue,
    PrepareError,
    PrepareInit,
    PrepareResp,
    PrepareStepResult,
    ReportShare,
    ReportMetadata,
)
from ..vdaf import pingpong as pp
from ..vdaf.backend import device_supported, make_backend
from ..vdaf.prio3 import Prio3, VdafError
from .aggregation_job_writer import AggregationJobWriter
from .job_driver import helper_request_deadline

logger = logging.getLogger("janus_tpu.aggregation_job_driver")


class JobStepError(Exception):
    def __init__(self, detail: str, retryable: bool, peer_unhealthy: bool = False):
        super().__init__(detail)
        self.retryable = retryable
        #: the failure is PARTITION PRESSURE (the peer-health tracker has
        #: the peer suspect, or the gate refused the attempt outright):
        #: the job releases with retryable backoff WITHOUT consuming the
        #: max_step_attempts budget — a long partition must never abandon
        #: work that will finish fine after the heal.
        self.peer_unhealthy = peer_unhealthy


class _JournalRowMissing(Exception):
    """A deferred drain lost the race with a crash-recovery replay for one
    of its journal rows; the drained vector must not merge (non-retryable
    by construction: run_tx propagates it out of the drain tx)."""


@dataclass
class DriverConfig:
    batch_aggregation_shard_count: int = 8
    #: Delivery-count ceiling, checked at step ENTRY: bounds redeliveries
    #: that never report back (crashed/timed-out holders whose lease
    #: simply expired).  Reported retryable failures are bounded by
    #: max_step_attempts below; both count lease_attempts, so the
    #: effective bound is whichever fires first.
    maximum_attempts_before_failure: int = 10
    #: Retryable-failure budget, checked when a step REPORTS
    #: JobStepError(retryable=True): the lease is released with
    #: exponential backoff until lease_attempts reaches this, then the
    #: job is abandoned — it must not ping-pong forever.
    max_step_attempts: int = 10
    #: Lease-backoff curve for retryable failures (doubling per attempt).
    retry_initial_delay_s: float = 1.0
    retry_max_delay_s: float = 300.0
    #: (Peer-health gating thresholds live on the PROCESS-WIDE tracker,
    #: not here: binaries apply JobDriverConfig.peer_failure_threshold /
    #: peer_suspect_dwell_s once at startup, and test harnesses call
    #: peer_health.tracker().configure() explicitly — a per-driver copy
    #: would either be dead or clobber tuned values.)
    vdaf_backend: str = "oracle"
    #: Field-arithmetic layout for the device backends ("vpu" | "mxu" —
    #: vdaf/backend.py FIELD_BACKENDS); None = process default
    #: (JANUS_TPU_FIELD_BACKEND or "vpu").  The A/B seam for the MXU
    #: limb-plane contraction layer; the oracle ignores it.
    field_backend: Optional[str] = None
    #: Poplar1 AES-walk backend ("host" | "jax"); None = process default
    #: (JANUS_TPU_POPLAR_BACKEND or "host").  The A/B seam for the
    #: device-resident IDPF walk; only the Poplar1 path reads it.
    poplar_backend: Optional[str] = None
    http_retry: HttpRetryPolicy = field(default_factory=HttpRetryPolicy)
    #: Gather window for coalescing same-shape jobs from DIFFERENT tasks
    #: into one device launch (BASELINE configs[4]); 0 disables.  Only
    #: meaningful for device backends — the oracle ignores it.
    multi_task_launch_window_s: float = 0.005
    #: When set and enabled, prepare launches route through the
    #: process-wide device executor (janus_tpu/executor/): continuous
    #: cross-job batching shared by ALL drivers, instead of this driver's
    #: private gather window above.  None/disabled = legacy path.
    device_executor: Optional[object] = None  # executor.ExecutorConfig
    #: While a shape's executable is still WARMING (background compile),
    #: wait up to this long on the compile future before draining the job
    #: through the CPU oracle; 0 (default) = oracle immediately.  Either
    #: way the breaker never counts compile-wait as a launch failure.
    warmup_wait_s: float = 0.0


class AggregationJobDriver:
    def __init__(
        self,
        datastore: Datastore,
        session_factory,
        config: Optional[DriverConfig] = None,
    ):
        self.datastore = datastore
        self._session_factory = session_factory
        self._session = None
        self.config = config or DriverConfig()
        self._backends: Dict[tuple, object] = {}
        #: canonical keys whose twin backend failed to BUILD — negative
        #: cache so the hot path does not re-pay a doomed construction
        #: (bounded by shape count; cleared only by process restart)
        self._canon_build_failed: set = set()
        # key -> [(verify_key, prep_rows, future)] awaiting a coalesced launch
        self._pending_prep: Dict[int, list] = {}
        # Quarantine ledger sink (ISSUE 19): bisection offenders found
        # while this driver's flushes sieve persist durably (last
        # configured datastore wins — one per process in production).
        if self.datastore is not None:
            from ..core import quarantine

            quarantine.configure_sink(self.datastore)
        # Process-wide continuous batcher: every driver in the process
        # feeds ONE executor so concurrent tasks form one saturated
        # pipeline rather than N contending ones.
        self._executor = None
        exec_cfg = self.config.device_executor
        if exec_cfg is not None and getattr(exec_cfg, "enabled", False):
            from ..executor import get_global_executor

            self._executor = get_global_executor(exec_cfg)
            if (
                self._executor.accumulator is not None
                and self.datastore is not None
            ):
                # Durable spill target for graceful shutdown: committed-
                # but-unspilled deferred deltas drain through the journal
                # transaction instead of being discarded.
                self._executor.set_spill_sink(self._spill_sink)

    def _get_session(self):
        """One shared connection-pooled session per driver (the analog of the
        reference's shared reqwest client)."""
        if self._session is None or self._session.closed:
            self._session = self._session_factory()
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    # ------------------------------------------------------------------
    async def step_aggregation_job(self, lease: Lease) -> None:
        """Stepper callback for the JobDriver
        (reference: aggregation_job_driver.rs:126 step_aggregation_job)."""
        from ..core.metrics import GLOBAL_METRICS, Timer

        if lease.lease_attempts > self.config.maximum_attempts_before_failure:
            # Entry-ceiling partition guard: clean peer-unhealthy
            # releases still increment lease_attempts (acquisition
            # counts deliveries), so a long partition inflates the count
            # past the ceiling.  While the peer is STILL unhealthy the
            # job releases; within the heal grace it gets its POST-HEAL
            # delivery (abandoning then would destroy exactly the work
            # the partition tolerance exists to preserve); only a peer
            # that has been healthy past the grace gets the ceiling's
            # normal abandon verdict.  (Stopping the inflation at its
            # source — peer-aware acquisition filtering — is the ROADMAP
            # follow-on.)
            from ..core.db_health import tracker as db_tracker
            from .job_driver import heal_grace_s, peer_partition_state

            # Brownout excuse first (in-memory, no datastore lookup): a
            # datastore brownout inflates lease_attempts exactly like a
            # peer partition does — releases without consumed budget —
            # so the ceiling's abandon verdict must wait out the heal
            # grace here too.
            if db_tracker().brownout_signal(
                heal_grace_s(self.config.retry_max_delay_s)
            ):
                await self._release_ceiling_partition(lease)
                return
            verdict = await peer_partition_state(
                self.datastore,
                lease.leased.task_id,
                heal_grace_s(self.config.retry_max_delay_s),
            )
            if verdict == "suspect":
                await self._release_ceiling_partition(lease)
                return
            if verdict != "healed":
                await self.abandon_aggregation_job(lease)
                return
            # healed: fall through — this delivery is the job's chance
        outcome = "success"
        with Timer() as timer:
            try:
                await self._step(lease)
            except JobStepError as e:
                # Partition pressure (peer suspect) releases WITHOUT
                # consuming the retryable budget: the failure is the
                # network's, not the job's, and a long partition must
                # not march every in-flight job to abandonment.  The
                # delivery ceiling (maximum_attempts_before_failure,
                # checked at entry) still bounds holders that never
                # report back.
                from ..core.db_health import tracker as db_tracker
                from .job_driver import heal_grace_s, partition_excused

                if e.retryable and (
                    lease.lease_attempts < self.config.max_step_attempts
                    or e.peer_unhealthy
                    # attempts inflated by a datastore brownout (still
                    # suspect, or healed within the grace) are the
                    # database's doing — in-memory check, evaluated
                    # before the datastore-lookup excuse below
                    or db_tracker().brownout_signal(
                        heal_grace_s(self.config.retry_max_delay_s)
                    )
                    # attempts inflated by a partition (peer still
                    # unhealthy, or healed within the grace) must not
                    # abandon the post-heal delivery on its first
                    # ordinary hiccup — evaluated lazily, only when the
                    # budget comparison would otherwise abandon
                    or await partition_excused(
                        self.datastore,
                        lease.leased.task_id,
                        self.config.retry_max_delay_s,
                    )
                ):
                    from .job_driver import step_retry_delay

                    outcome = "retried"
                    delay = step_retry_delay(
                        lease.lease_attempts,
                        self.config.retry_initial_delay_s,
                        self.config.retry_max_delay_s,
                        # seeded per-job jitter: jobs released during a
                        # partition re-acquire SPREAD OUT after the heal
                        # instead of thundering-herding the helper
                        jitter_key=lease.leased.aggregation_job_id.data,
                    )
                    logger.warning(
                        "retryable step failure (attempt %d/%d, redeliver in %ds): %s",
                        lease.lease_attempts,
                        self.config.max_step_attempts,
                        delay.seconds,
                        e,
                    )
                    await self.datastore.run_tx_async(
                        "release_agg_job",
                        lambda tx: tx.release_aggregation_job(lease, delay),
                    )
                else:
                    outcome = "abandoned"
                    if e.retryable:
                        logger.error(
                            "retryable step failure exhausted its %d-attempt "
                            "budget; abandoning: %s",
                            self.config.max_step_attempts,
                            e,
                        )
                    else:
                        logger.error("fatal step failure: %s", e)
                    await self.abandon_aggregation_job(lease)
            except DatastoreUnavailable as e:
                # Datastore brownout mid-step: treated exactly like
                # peer_unhealthy — release with jittered backoff, budget
                # untouched (ISSUE 17 tentpole layer 3).
                outcome = "retried"
                await self._release_datastore_brownout(lease, e)
        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.job_steps.labels(
                job_type="aggregation", outcome=outcome
            ).observe(timer.seconds)
            if outcome != "success":
                GLOBAL_METRICS.step_failures.labels(type=outcome).inc()

    async def _step(self, lease: Lease) -> None:
        acq = lease.leased
        # tx1: load task, job, report aggregations (reference :169-220)
        def load(tx):
            task = tx.get_aggregator_task(acq.task_id)
            job = tx.get_aggregation_job(acq.task_id, acq.aggregation_job_id)
            ras = tx.get_report_aggregations_for_aggregation_job(
                acq.task_id, acq.aggregation_job_id
            )
            return task, job, ras

        task, job, ras = await self.datastore.run_tx_async("step_agg_job_1", load)
        if task is None or job is None:
            raise JobStepError("job or task vanished", retryable=False)
        if job.state != AggregationJobState.IN_PROGRESS:
            await self.datastore.run_tx_async(
                "release_done", lambda tx: tx.release_aggregation_job(lease)
            )
            return
        # Peer-health gate (ISSUE 11): a suspect helper inside its dwell
        # means this step WILL end at a dead socket — release now, before
        # any prepare work (device launch, decode) is burned on it.  Past
        # the dwell the gate opens (half-open) and this step is the probe.
        self._gate_peer(task)
        vdaf = task.vdaf_instance()

        start_ras = [ra for ra in ras if ra.state == ReportAggregationState.START_LEADER]
        waiting_ras = [
            ra for ra in ras if ra.state == ReportAggregationState.WAITING_LEADER
        ]
        if start_ras:
            await self._step_init(lease, task, vdaf, job, ras, start_ras)
        elif waiting_ras:
            await self._step_continue(lease, task, vdaf, job, ras, waiting_ras)
        else:
            # nothing to do; close the job out
            job = job.with_state(AggregationJobState.FINISHED)
            writer = AggregationJobWriter(
                task,
                vdaf,
                batch_aggregation_shard_count=self.config.batch_aggregation_shard_count,
                initial_write=False,
            )
            writer.put(job, [], {})

            def tx_fn(tx):
                writer.write(tx)
                tx.release_aggregation_job(lease)

            await self.datastore.run_tx_async("step_agg_job_2", tx_fn)

    # ------------------------------------------------------------------
    async def _release_ceiling_partition(self, lease) -> None:
        """Release a past-ceiling lease with jittered backoff: the
        inflated delivery count is partition/brownout pressure, not a
        sick job."""
        from .job_driver import step_retry_delay

        acq = lease.leased
        delay = step_retry_delay(
            lease.lease_attempts,
            self.config.retry_initial_delay_s,
            self.config.retry_max_delay_s,
            jitter_key=acq.aggregation_job_id.data,
        )
        logger.warning(
            "job %s is past its delivery ceiling (%d attempts) but the "
            "peer or datastore is suspect — releasing for %ds instead of "
            "abandoning pressured work",
            acq.aggregation_job_id,
            lease.lease_attempts,
            delay.seconds,
        )
        await self.datastore.run_tx_async(
            "release_agg_job",
            lambda tx: tx.release_aggregation_job(lease, delay),
        )

    async def _release_datastore_brownout(self, lease, err) -> None:
        """A step that died on ``DatastoreUnavailable`` releases WITHOUT
        consuming the retryable budget — the failure is the database's,
        not the job's (the exact peer_unhealthy treatment, ISSUE 17).
        The release transaction itself runs under a short deadline and
        tolerates failure: mid-brownout it may not commit either, and
        lease expiry + the reaper redeliver the job regardless."""
        from .job_driver import step_retry_delay

        acq = lease.leased
        delay = step_retry_delay(
            lease.lease_attempts,
            self.config.retry_initial_delay_s,
            self.config.retry_max_delay_s,
            jitter_key=acq.aggregation_job_id.data,
        )
        logger.warning(
            "datastore unavailable mid-step for job %s — releasing for "
            "%ds without consuming the attempt budget: %s",
            acq.aggregation_job_id,
            delay.seconds,
            err,
        )
        try:
            await self.datastore.run_tx_async(
                "release_agg_job",
                lambda tx: tx.release_aggregation_job(lease, delay),
                deadline_s=5.0,
            )
        except DatastoreError:
            logger.warning(
                "release of job %s failed too (datastore still browned "
                "out); lease expiry redelivers it",
                acq.aggregation_job_id,
            )

    def _gate_peer(self, task: AggregatorTask) -> None:
        """Refuse to burn lease work on a suspect peer (raises a
        peer-unhealthy retryable JobStepError); no-op while healthy or
        once the suspect dwell has elapsed (the half-open probe)."""
        from ..core import peer_health

        url = task.peer_aggregator_endpoint
        if not peer_health.tracker().allow(url):
            raise JobStepError(
                f"peer {peer_health.origin_of(url)} is suspect (consecutive "
                "transport failures); releasing without an attempt",
                retryable=True,
                peer_unhealthy=True,
            )

    # ------------------------------------------------------------------
    @staticmethod
    def _vdaf_shape_key(vdaf) -> tuple:
        """Backend/bucket key (vdaf_shape_key in vdaf/backend.py — shared
        with the helper aggregator so both protocol sides land in the same
        executor buckets and breaker domains).  Canonical twins are shape
        fixpoints, so calling this on a canonical backend's own vdaf
        yields its cache key."""
        from ..vdaf.backend import vdaf_shape_key

        return vdaf_shape_key(vdaf)

    def _executor_shape(self, vdaf):
        """(cache key, canonical twin or None): with the executor's
        ``canonical_shapes`` on, tasks in one pow2 bucket share a key —
        one backend, one set of compiled graphs, one set of mega-batch
        buckets (vdaf/canonical.py); shapes failing the parity
        preconditions keep their exact key."""
        from ..vdaf.canonical import executor_shape

        return executor_shape(
            vdaf,
            enabled=self._executor is not None
            and self._executor.config.canonical_shapes,
        )

    def _backend_for(self, task: AggregatorTask, vdaf):
        key, canon = self._executor_shape(vdaf)
        b = self._backends.get(key)
        if b is None and isinstance(vdaf, Prio3):
            backend_name = self.config.vdaf_backend
            if backend_name != "oracle":
                ok, reason = device_supported(vdaf)
                if not ok:
                    # LOUD fallback: the task still runs (on the oracle),
                    # but never silently — log + metric on first dispatch
                    # (VERDICT r3 weak #3).
                    vdaf_type = (getattr(vdaf, "instance", None) or {}).get(
                        "type", type(vdaf).__name__
                    )
                    logger.warning(
                        "task %s VDAF %s falls back to the CPU oracle "
                        "(configured backend %r): %s",
                        task.task_id,
                        vdaf_type,
                        backend_name,
                        reason,
                    )
                    from ..core.metrics import GLOBAL_METRICS

                    if GLOBAL_METRICS.registry is not None:
                        GLOBAL_METRICS.vdaf_backend_fallbacks.labels(
                            vdaf_type=vdaf_type, reason=reason[:80]
                        ).inc()
                    backend_name = "oracle"  # don't even attempt the device
            field_backend = self.config.field_backend
            if (
                canon is not None
                and backend_name != "oracle"
                and key not in self._canon_build_failed
            ):
                # Bucket twin (vdaf/canonical.py): graphs compile for the
                # CANONICAL shape and requests carry the task's actual
                # vdaf.  A canonical cache entry must ALWAYS be a genuine
                # canonical device backend — an oracle (or exact-shape)
                # fallback under this key would serve other bucket members
                # a wrong-shaped circuit — so a failed build falls through
                # to the exact-shape resolution below instead of caching
                # (and is negative-cached: the hot path must not re-pay a
                # doomed twin construction + stack trace per job step).
                def canon_factory():
                    return make_backend(
                        canon,
                        backend_name,
                        field_backend=field_backend,
                        canonical=True,
                    )

                try:
                    b = (
                        self._executor.backend_for(key, canon_factory)
                        if self._executor is not None
                        else canon_factory()
                    )
                    self._backends[key] = b
                    return b
                except Exception:
                    self._canon_build_failed.add(key)
                    logger.exception(
                        "canonical backend build failed for task %s; "
                        "serving from an exact-shape compile",
                        task.task_id,
                    )
            if canon is not None:
                # Not serving canonically (oracle config, unsupported
                # device path, or a failed twin build): the canonical
                # bucket key must NEVER hold a non-canonical backend —
                # resolve and cache under the task's EXACT key instead.
                from ..vdaf.backend import vdaf_shape_key

                key = vdaf_shape_key(vdaf)
                b = self._backends.get(key)
                if b is not None:
                    return b

            def factory():
                try:
                    return make_backend(vdaf, backend_name, field_backend=field_backend)
                except (VdafError, NotImplementedError):
                    return make_backend(vdaf, "oracle")

            if self._executor is not None:
                # Shape-keyed cache lives in the process-wide executor:
                # every driver (and its compiled graphs/warmup) shares one
                # backend per VDAF shape.
                b = self._executor.backend_for(key, factory)
            else:
                b = factory()
            self._backends[key] = b
        elif (
            b is None
            and type(vdaf).__name__ == "Poplar1"
            and self.config.vdaf_backend != "oracle"
        ):
            # Heavy hitters ride the same dispatch plane: the batched
            # Poplar1Backend (bulk-AES walk + device sketch) resolves
            # through the executor's shape-keyed cache, so every driver in
            # the process shares one instance per `bits` shape — and its
            # poplar_init submissions share the executor's buckets and
            # breaker domains with the helper's.  A build failure falls
            # back to the per-report ping-pong path (backend None), never
            # fails the job.
            def poplar_factory():
                return make_backend(
                    vdaf,
                    self.config.vdaf_backend,
                    poplar_backend=self.config.poplar_backend,
                )

            try:
                b = (
                    self._executor.backend_for(key, poplar_factory)
                    if self._executor is not None
                    else poplar_factory()
                )
            except Exception:
                logger.exception(
                    "Poplar1 backend build failed for task %s; serving "
                    "per-report ping-pong",
                    task.task_id,
                )
                return None
            self._backends[key] = b
        return b

    async def _coalesced_prep_init(
        self, backend, verify_key: bytes, prep_in, task_ident=None, vdaf=None
    ):
        """Join concurrent same-shape jobs (across tasks) into ONE launch.

        With the device executor enabled, submission routes through the
        PROCESS-WIDE continuous batcher instead: all drivers' same-shape
        jobs coalesce into pow2-padded mega-batches with size/deadline
        flushing, and backpressure rejections surface as retryable
        JobStepErrors (the lease machinery redelivers the job).

        Otherwise the first arrival opens a short gather window; jobs
        landing inside it ride the same ``prep_init_multi`` launch with
        per-row verify keys (BASELINE configs[4]'s 16-task shape).  Window
        0 or a backend without prep_init_multi degrades to a per-job
        launch.
        """
        loop = asyncio.get_running_loop()
        if self._executor is not None and hasattr(backend, "stage_prep_init_multi"):
            from ..executor import CircuitOpenError, ExecutorOverloadedError

            # the executor cache / warmup-ledger / breaker key, derived
            # from the RESOLVED backend (vdaf/canonical.backend_shape_key)
            # so key and backend can never diverge — on the twin-build
            # fallback path the cached backend is exact-shape and must
            # keep submitting under the exact key, never the canonical
            # bucket's (which would bind a wrong-shaped backend to it)
            from ..vdaf.canonical import backend_shape_key

            shape_key = backend_shape_key(backend)
            # Breaker-aware routing (ISSUE 3 satellite): an open circuit is
            # known BEFORE submitting — consult the breaker peek (the
            # programmatic face of circuit_stats()) and serve this job on
            # the oracle directly instead of paying a
            # submit-then-CircuitOpenError round trip per job.
            if self._executor.circuit_open(shape_key):
                return await self._oracle_fallback(
                    backend,
                    verify_key,
                    prep_in,
                    f"circuit for shape {shape_key[0]}/{shape_key[1]} is open",
                    vdaf=vdaf,
                    task_ident=task_ident,
                )
            if self._executor.warming(shape_key):
                # Cold-shape contract (ISSUE 8): the executable is still
                # compiling on the warmup thread.  Optionally wait a
                # bounded moment on the compile future; otherwise (or on
                # timeout) drain this job through the bit-exact CPU
                # oracle.  Either way the breaker never counts the
                # compile-wait as a launch failure and no flush deadline
                # can trip on it.
                wait_s = self.config.warmup_wait_s
                warmed = False
                if wait_s > 0:
                    warmed = await asyncio.get_running_loop().run_in_executor(
                        None,
                        lambda: self._executor.wait_warm(shape_key, timeout=wait_s),
                    )
                if not warmed and self._executor.warming(shape_key):
                    return await self._oracle_fallback(
                        backend,
                        verify_key,
                        prep_in,
                        f"shape {shape_key[0]}/{shape_key[1]} is warming "
                        "(executable compiling off the submit path)",
                        vdaf=vdaf,
                        reason="warming",
                        task_ident=task_ident,
                    )
            try:
                return await self._executor.submit(
                    shape_key,
                    "prep_init",
                    # canonical backends take 3-tuple requests: the task's
                    # actual vdaf rides along so marshal pads its rows to
                    # the bucket shape (vdaf/backend._req_parts)
                    (verify_key, prep_in, vdaf)
                    if getattr(backend, "canonical", False)
                    else (verify_key, prep_in),
                    backend=backend,
                    agg_id=0,
                    retain_out_shares=self._executor.accumulator is not None,
                    task_ident=task_ident,
                )
            except CircuitOpenError as e:
                # Device sick (K consecutive launch failures): degrade to
                # the bit-exact CPU oracle for this job instead of burning
                # the retry budget — the breaker's half-open probes restore
                # device service without any action here.
                return await self._oracle_fallback(
                    backend, verify_key, prep_in, e, vdaf=vdaf,
                    task_ident=task_ident,
                )
            except ExecutorOverloadedError as e:
                raise JobStepError(
                    f"device executor overloaded: {e}", retryable=True
                )
            except JobStepError:
                raise
            except Exception as e:
                # Launch failure: the breaker counted it; the lease
                # machinery redelivers (with backoff) until the breaker
                # verdict flips this shape to the oracle path above.
                raise JobStepError(f"device launch failed: {e}", retryable=True)
        window = self.config.multi_task_launch_window_s
        try:
            if window <= 0 or not hasattr(backend, "prep_init_multi"):
                return await loop.run_in_executor(
                    None, lambda: backend.prep_init_batch(verify_key, 0, prep_in)
                )
            key = id(backend)
            fut = loop.create_future()
            bucket = self._pending_prep.setdefault(key, [])
            bucket.append((verify_key, prep_in, fut))
            if len(bucket) == 1:
                loop.call_later(
                    window,
                    lambda: asyncio.ensure_future(self._flush_prep(backend, key)),
                )
            return await fut
        except Exception as e:
            # Per-row VDAF rejections come back as in-band PrepOutcomes; an
            # exception here is infrastructure (device launch, thread pool)
            # and the lease machinery owns the retry.
            raise JobStepError(f"prepare launch failed: {e}", retryable=True)

    async def _oracle_fallback(
        self,
        backend,
        verify_key: bytes,
        prep_in,
        cause,
        vdaf=None,
        reason="circuit_open",
        task_ident=None,
    ):
        """Serve one job's prepare on the CPU oracle (bit-exact with the
        device path by the backend contract, tests/test_backend.py).
        ``vdaf`` routes canonical (bucket-twin) backends to the TASK's
        oracle — the twin's own oracle computes a padded circuit."""
        return await self._serve_on_oracle(
            backend,
            vdaf,
            cause,
            reason,
            len(prep_in),
            lambda oracle: oracle.prep_init_batch(verify_key, 0, prep_in),
            task_ident=task_ident,
        )

    async def _serve_on_oracle(
        self, backend, vdaf, cause, reason, n_reports, call, task_ident=None
    ):
        """The ONE fallback policy (logging, fallback metric, retryable
        guard, off-loop dispatch) shared by the Prio3 and Poplar1 oracle
        degradations — ``call(oracle)`` runs the VDAF-appropriate batch.
        ``task_ident`` binds the worker-thread task scope so the oracle
        batch's measured seconds attribute to the task with
        ``path="oracle"`` (core/costs.py) — the breaker-open cost shift
        the per-task series exist to show."""
        from ..core import costs
        from ..vdaf.backend import oracle_backend_for

        oracle = oracle_backend_for(backend, vdaf)
        if oracle is None:
            raise JobStepError(f"device unavailable: {cause}", retryable=True)
        logger.warning(
            "serving prepare on the CPU oracle (%d report(s)): %s",
            n_reports,
            cause,
        )
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.vdaf_backend_fallbacks.labels(
                vdaf_type=type(getattr(backend, "vdaf", None)).__name__,
                reason=reason,
            ).inc()
        return await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: costs.run_in_task_scope(task_ident, lambda: call(oracle)),
        )

    async def _coalesced_poplar_init(
        self, backend, verify_key: bytes, agg_param, prep_in, task_ident=None
    ):
        """Poplar1 round-0 prepare through the process-wide executor: the
        submission lands in the agg-param-keyed ``poplar_init`` bucket for
        this shape at ``agg_param.level``, so concurrent jobs at one IDPF
        tree level — the multi-round heavy-hitters steady state — coalesce
        into ONE bulk-AES walk + device sketch launch.  Failure-domain
        parity with Prio3: an open circuit (peeked before submitting, or
        raised by the flush) degrades this job to the bit-exact per-report
        CPU oracle, and backpressure surfaces as a retryable JobStepError
        (the lease machinery redelivers)."""
        loop = asyncio.get_running_loop()
        if self._executor is not None:
            from ..executor import (
                KIND_POPLAR_INIT,
                CircuitOpenError,
                ExecutorOverloadedError,
            )
            from ..vdaf.canonical import backend_shape_key

            shape_key = backend_shape_key(backend)
            if self._executor.circuit_open(shape_key):
                return await self._poplar_oracle_fallback(
                    backend,
                    verify_key,
                    agg_param,
                    prep_in,
                    f"circuit for shape {shape_key[0]} is open",
                    task_ident=task_ident,
                )
            # Device-resident sketches (ISSUE 13): only with DEFERRED
            # drains — the refs cross the WAITING_LEADER persistence hop,
            # and only deferred mode retains the StartLeader payloads that
            # make a dead ref (restart/eviction-past-recall) recoverable
            # via the per-report oracle.
            store = self._executor.accumulator
            retain_sketch = (
                store is not None
                and getattr(store.config, "deferred", False)
                and getattr(backend, "supports_resident_sketch", False)
            )
            try:
                return await self._executor.submit(
                    shape_key,
                    KIND_POPLAR_INIT,
                    (verify_key, agg_param, prep_in),
                    backend=backend,
                    agg_id=0,
                    retain_out_shares=retain_sketch,
                    task_ident=task_ident,
                    agg_param_key=getattr(agg_param, "level", None),
                )
            except CircuitOpenError as e:
                return await self._poplar_oracle_fallback(
                    backend, verify_key, agg_param, prep_in, e,
                    task_ident=task_ident,
                )
            except ExecutorOverloadedError as e:
                raise JobStepError(
                    f"device executor overloaded: {e}", retryable=True
                )
            except JobStepError:
                raise
            except Exception as e:
                raise JobStepError(f"device launch failed: {e}", retryable=True)
        try:
            return await loop.run_in_executor(
                None,
                lambda: backend.prep_init_batch_poplar(
                    verify_key, 0, agg_param, prep_in
                ),
            )
        except Exception as e:
            raise JobStepError(f"prepare launch failed: {e}", retryable=True)

    async def _poplar_oracle_fallback(
        self,
        backend,
        verify_key,
        agg_param,
        prep_in,
        cause,
        reason="circuit_open",
        task_ident=None,
    ):
        """Serve one Poplar1 job's round-0 prepare on the per-report CPU
        oracle (bit-exact with the batched walk, tests/test_poplar1_batch
        + test_poplar_executor assert it)."""
        return await self._serve_on_oracle(
            backend,
            None,
            cause,
            reason,
            len(prep_in),
            lambda oracle: oracle.prep_init_batch_poplar(
                verify_key, 0, agg_param, prep_in
            ),
            task_ident=task_ident,
        )

    async def _flush_prep(self, backend, key: int) -> None:
        bucket = self._pending_prep.pop(key, [])
        if not bucket:
            return
        reqs = [(vk, rows) for vk, rows, _ in bucket]
        loop = asyncio.get_running_loop()
        try:
            results = await loop.run_in_executor(
                None, lambda: backend.prep_init_multi(0, reqs)
            )
            if len(results) != len(bucket):
                raise RuntimeError(
                    f"prep_init_multi returned {len(results)} results for "
                    f"{len(bucket)} requests"
                )
            for (_, _, fut), res in zip(bucket, results):
                if not fut.done():
                    fut.set_result(res)
        except Exception as e:  # surface the launch failure to every job
            for _, _, fut in bucket:
                if not fut.done():
                    fut.set_exception(e)

    async def _leader_prep_init(self, task, vdaf, job, start_ras):
        """Batched leader prepare (device launch for Prio3;
        reference mirror: aggregation_job_driver.rs:397-428 on rayon)."""
        try:
            agg_param = vdaf.decode_agg_param(job.aggregation_parameter)
        except VdafError:
            return {
                ra.report_id.data: PrepareError.INVALID_MESSAGE for ra in start_ras
            }
        outcomes: Dict[bytes, object] = {}  # report_id -> (state, msg) | PrepareError
        loop = asyncio.get_running_loop()

        def decode_rows():
            """Per-report wire decoding is pure-Python field parsing —
            thousands of elements per report — so it stays off the event
            loop (the loop must keep serving lease heartbeats and the
            coalescing gather timers)."""
            good, bad = [], []
            for ra in start_ras:
                try:
                    public_parts = vdaf.decode_public_share(ra.public_share or b"")
                    input_share = vdaf.decode_input_share(0, ra.leader_input_share)
                except (VdafError, Exception):
                    bad.append(ra.report_id.data)
                    continue
                good.append((ra, public_parts, input_share))
            return good, bad

        rows, bad_ids = await loop.run_in_executor(None, decode_rows)
        for rid in bad_ids:
            outcomes[rid] = PrepareError.INVALID_MESSAGE

        backend = self._backend_for(task, vdaf)
        if backend is not None:
            prep_in = [
                (ra.report_id.data, public, share) for ra, public, share in rows
            ]
            if hasattr(backend, "prep_init_batch_poplar"):
                # Heavy hitters: round-0 prep through the executor's
                # agg-param-keyed poplar_init plane (or the direct batched
                # walk when no executor is configured).
                prep_out = await self._coalesced_poplar_init(
                    backend,
                    task.vdaf_verify_key,
                    agg_param,
                    prep_in,
                    task_ident=task.task_id.data,
                )
            else:
                prep_out = await self._coalesced_prep_init(
                    backend,
                    task.vdaf_verify_key,
                    prep_in,
                    # per-task fairness quota: the DRR accounting domain
                    # WITHIN the shared shape bucket
                    # (executor._pick_entry_locked)
                    task_ident=task.task_id.data,
                    vdaf=vdaf,
                )

            def wrap_outcomes():
                out = {}
                for (ra, _pub, _sh), outcome in zip(rows, prep_out):
                    if isinstance(outcome, VdafError):
                        out[ra.report_id.data] = PrepareError.VDAF_PREP_ERROR
                        continue
                    state, share = outcome
                    msg = pp.PingPongMessage(
                        pp.PingPongMessage.INITIALIZE,
                        prep_share=vdaf.ping_pong_encode_prep_share(share),
                    )
                    out[ra.report_id.data] = (pp.PingPongContinued(state, 0), msg)
                return out

            outcomes.update(await loop.run_in_executor(None, wrap_outcomes))
        else:

            def oracle_prep():
                out = {}
                for ra, public, share in rows:
                    try:
                        state, msg = pp.leader_initialized(
                            vdaf,
                            task.vdaf_verify_key,
                            agg_param,
                            ra.report_id.data,
                            public,
                            share,
                        )
                        out[ra.report_id.data] = (state, msg)
                    except (VdafError, pp.PingPongError):
                        out[ra.report_id.data] = PrepareError.VDAF_PREP_ERROR
                return out

            outcomes.update(
                await asyncio.get_running_loop().run_in_executor(None, oracle_prep)
            )
        return outcomes

    async def _step_init(self, lease, task, vdaf, job, all_ras, start_ras):
        outcomes = await self._leader_prep_init(task, vdaf, job, start_ras)
        try:
            await self._step_init_with_outcomes(
                lease, task, vdaf, job, all_ras, start_ras, outcomes
            )
        except BaseException:
            # A failure between prep and commit (helper HTTP, tx, anything)
            # must not pin the flush matrices the step's ResidentRefs hold:
            # redelivery will mint fresh refs.  Release is idempotent, so
            # refs already consumed by a partial commit are unaffected.
            self._release_resident_outcomes(outcomes)
            raise

    def _release_finished_refs(self, finished_now) -> None:
        """Release device-resident out shares held by finished-at-evaluate
        rows (Poplar1 continue steps) after a step failure or a helper
        rejection dropped them short of the commit."""
        store = self._executor.accumulator if self._executor is not None else None
        if store is None or not finished_now:
            return
        from ..executor.accumulator import ResidentRef

        refs = [v for v in finished_now.values() if isinstance(v, ResidentRef)]
        if refs:
            store.release_refs(refs)

    def _release_resident_outcomes(self, outcomes) -> None:
        store = self._executor.accumulator if self._executor is not None else None
        if store is None:
            return
        from ..executor.accumulator import ResidentRef

        refs = []
        for outcome in outcomes.values():
            if isinstance(outcome, PrepareError):
                continue
            state, _msg = outcome
            ref = getattr(getattr(state, "prep_state", None), "out_share", None)
            if not isinstance(ref, ResidentRef):  # Poplar1 carries y_flat
                ref = getattr(getattr(state, "prep_state", None), "y_flat", None)
            if isinstance(ref, ResidentRef):
                refs.append(ref)
        if refs:
            store.release_refs(refs)

    async def _step_init_with_outcomes(
        self, lease, task, vdaf, job, all_ras, start_ras, outcomes
    ):
        prepare_inits = []
        states: Dict[bytes, pp.PingPongContinued] = {}
        failed: Dict[bytes, PrepareError] = {}
        for ra in start_ras:
            outcome = outcomes[ra.report_id.data]
            if isinstance(outcome, PrepareError):
                failed[ra.report_id.data] = outcome
                continue
            state, msg = outcome
            states[ra.report_id.data] = state
            prepare_inits.append(
                PrepareInit(
                    ReportShare(
                        ReportMetadata(ra.report_id, ra.time),
                        ra.public_share or b"",
                        ra.helper_encrypted_input_share,
                    ),
                    msg,
                )
            )

        if task.query_type.kind == "FixedSize":
            pbs = PartialBatchSelector.new_fixed_size(job.partial_batch_identifier)
        else:
            pbs = PartialBatchSelector.new_time_interval()
        req = AggregationJobInitializeReq(
            aggregation_parameter=job.aggregation_parameter,
            partial_batch_selector=pbs,
            prepare_inits=prepare_inits,
        )
        resp = await self._send_to_helper(
            task,
            "PUT",
            f"aggregation_jobs/{job.aggregation_job_id}",
            req.get_encoded(),
            AggregationJobInitializeReq.MEDIA_TYPE,
            lease=lease,
        )
        await self._process_helper_resp(
            lease, task, vdaf, job, all_ras, states, failed, resp
        )

    async def _step_continue(self, lease, task, vdaf, job, all_ras, waiting_ras):
        """Evaluate stored transitions, send continue, process responses
        (reference: :527-626)."""
        states: Dict[bytes, pp.PingPongContinued] = {}
        failed: Dict[bytes, PrepareError] = {}
        finished_now: Dict[bytes, Sequence[int]] = {}
        conts = []
        for ra in waiting_ras:
            try:
                trans = pp.PingPongTransition.decode(vdaf, ra.leader_prep_transition)
                state, msg = trans.evaluate(vdaf)
            except (VdafError, pp.PingPongError):
                failed[ra.report_id.data] = PrepareError.VDAF_PREP_ERROR
                continue
            conts.append(PrepareContinue(ra.report_id, msg))
            if isinstance(state, pp.PingPongFinished):
                finished_now[ra.report_id.data] = state.out_share
            else:
                states[ra.report_id.data] = state

        # The wire step is the leader's CURRENT step: after init the leader
        # job is at step 1 while the helper is at 0, and the helper requires
        # req.step == helper_step + 1 — i.e. exactly the leader's step.
        wire_step = AggregationJobStep(int(job.step))
        req = AggregationJobContinueReq(wire_step, conts)
        try:
            resp = await self._send_to_helper(
                task,
                "POST",
                f"aggregation_jobs/{job.aggregation_job_id}",
                req.get_encoded(),
                AggregationJobContinueReq.MEDIA_TYPE,
                lease=lease,
            )
            await self._process_helper_resp(
                lease,
                task,
                vdaf,
                job,
                all_ras,
                states,
                failed,
                resp,
                finished_now=finished_now,
                next_step=AggregationJobStep(int(wire_step) + 1),
            )
        except BaseException:
            # A failure between evaluate and commit must not pin the flush
            # matrices this step's device-resident rows (Poplar1 y refs
            # riding in finished_now) reference: redelivery re-evaluates
            # the persisted transition, and a then-dead ref fails closed
            # into the per-report oracle replay.  Release is idempotent —
            # rows a partial commit already consumed are unaffected.
            self._release_finished_refs(finished_now)
            raise

    # ------------------------------------------------------------------
    async def _process_helper_resp(
        self,
        lease,
        task,
        vdaf,
        job,
        all_ras,
        states: Dict[bytes, pp.PingPongContinued],
        failed: Dict[bytes, PrepareError],
        resp: AggregationJobResp,
        *,
        finished_now: Optional[Dict[bytes, Sequence[int]]] = None,
        next_step: Optional[AggregationJobStep] = None,
    ) -> None:
        """Merge helper PrepareResps into report aggregations
        (reference: :629-793 process_response_from_helper)."""
        finished_now = finished_now or {}
        by_id = {pr.report_id.data: pr for pr in resp.prepare_resps}
        new_ras: List[ReportAggregation] = []
        out_shares: Dict[bytes, Sequence[int]] = {}
        # Multi-round deferred journaling (Poplar1): a report that will only
        # FINISH at a later round must carry its StartLeader payload through
        # every WAITING round — the payload is the journal's oracle-replay
        # window, and with_state() clears it by default.  Costs storage only
        # while the journal machinery is armed for this VDAF.
        store_cfg = getattr(
            self._executor.accumulator if self._executor is not None else None,
            "config",
            None,
        )
        retain_waiting_payload = (
            store_cfg is not None
            and getattr(store_cfg, "deferred", False)
            and getattr(vdaf, "REQUIRES_AGG_PARAM", False)
        )
        for ra in all_ras:
            rid = ra.report_id.data
            if ra.state in (
                ReportAggregationState.FINISHED,
                ReportAggregationState.FAILED,
            ):
                continue  # already terminal; no update needed
            if rid in failed:
                new_ras.append(ra.failed(failed[rid]))
                continue
            pr = by_id.get(rid)
            if pr is None:
                new_ras.append(ra.failed(PrepareError.REPORT_DROPPED))
                continue
            if pr.result.variant == PrepareStepResult.REJECT:
                new_ras.append(ra.failed(pr.result.error))
                continue
            if rid in finished_now:
                if pr.result.variant != PrepareStepResult.FINISHED:
                    new_ras.append(ra.failed(PrepareError.VDAF_PREP_ERROR))
                    continue
                new_ras.append(ra.with_state(ReportAggregationState.FINISHED))
                out_shares[rid] = finished_now[rid]
                continue
            if pr.result.variant != PrepareStepResult.CONTINUE:
                new_ras.append(ra.failed(PrepareError.VDAF_PREP_ERROR))
                continue
            state = states.get(rid)
            if state is None:
                new_ras.append(ra.failed(PrepareError.VDAF_PREP_ERROR))
                continue
            try:
                value = pp.continued(
                    vdaf, True, state, pr.result.message,
                    vdaf.decode_agg_param(job.aggregation_parameter),
                )
            except (VdafError, pp.PingPongError):
                new_ras.append(ra.failed(PrepareError.VDAF_PREP_ERROR))
                continue
            if value.out_share is not None:
                new_ras.append(ra.with_state(ReportAggregationState.FINISHED))
                out_shares[rid] = value.out_share
            else:
                keep = (
                    dict(
                        public_share=ra.public_share,
                        leader_input_share=ra.leader_input_share,
                    )
                    if retain_waiting_payload
                    else {}
                )
                new_ras.append(
                    ra.with_state(
                        ReportAggregationState.WAITING_LEADER,
                        leader_prep_transition=value.transition.encode(vdaf),
                        **keep,
                    )
                )

        any_waiting = any(
            ra.state == ReportAggregationState.WAITING_LEADER for ra in new_ras
        )
        job = job.with_step(
            next_step if next_step is not None else AggregationJobStep(int(job.step) + 1)
        )
        job = job.with_state(
            AggregationJobState.IN_PROGRESS
            if any_waiting
            else AggregationJobState.FINISHED
        )

        # Device-resident out shares: commit the finished rows' ResidentRefs
        # into per-batch resident accumulators BEFORE the transaction — a
        # tx retry must never replay a device psum.  Drain-at-commit mode
        # spills the delta NOW (one O(OUT) readback per batch bucket);
        # deferred mode leaves it resident and persists a journal row in
        # the tx instead (crash recovery replays from the datastore).
        # finished-at-evaluate rows the helper rejected never reached
        # out_shares: their device-resident refs (Poplar1) must release or
        # the retained sketch matrix never frees
        self._release_finished_refs(
            {
                rid: v
                for rid, v in finished_now.items()
                if rid not in out_shares
            }
        )
        (
            accumulator_deltas,
            journal_entries,
            touched_buckets,
        ) = await self._commit_resident_shares(
            task, vdaf, job, all_ras, states, out_shares,
            # WAITING rows (multi-round VDAFs) keep their refs alive: the
            # next step's transition evaluation finishes them
            waiting_rids={
                ra.report_id.data
                for ra in new_ras
                if ra.state == ReportAggregationState.WAITING_LEADER
            },
        )

        if journal_entries:
            # Deferred drains retain the StartLeader payloads on the
            # FINISHED rows: they are the journal's oracle-replay window —
            # a survivor re-derives the out shares from these columns
            # after this process dies with the delta still on device.
            ra_by_rid = {ra.report_id.data: ra for ra in all_ras}
            journaled_rids = set().union(*journal_entries.values())
            new_ras = [
                self._finished_with_payload(ra_by_rid[ra.report_id.data], ra)
                if ra.report_id.data in journaled_rids
                and ra.state == ReportAggregationState.FINISHED
                else ra
                for ra in new_ras
            ]

        writer = AggregationJobWriter(
            task,
            vdaf,
            batch_aggregation_shard_count=self.config.batch_aggregation_shard_count,
            initial_write=False,
            backend=self._backend_for(task, vdaf),
            accumulator_deltas=accumulator_deltas,
            journal_entries=journal_entries,
        )
        writer.put(job, new_ras, out_shares)

        def tx_fn(tx):
            writer.write(tx)
            tx.release_aggregation_job(lease)

        from ..executor.accumulator import StaleAccumulatorDelta

        try:
            await self.datastore.run_tx_async("step_agg_job_2", tx_fn)
        except StaleAccumulatorDelta as e:
            # A report was failed in-tx (batch collected under our feet)
            # AFTER its row was drained/journaled.  The tx aborted with
            # nothing merged; redelivery re-prepares the job and the in-tx
            # check fails the report properly — exactly-once either way.
            self._discard_touched_buckets(touched_buckets)
            raise JobStepError(
                f"resident delta invalidated in-tx: {e}", retryable=True
            )
        except BaseException:
            # Deferred mode: the bucket now holds THIS job's rows but its
            # journal row never committed — a later drain would merge rows
            # that redelivery will re-prepare (double count).  Discard the
            # bucket; other jobs' persisted journal rows stay replayable.
            self._discard_touched_buckets(touched_buckets)
            raise
        if journal_entries:
            from ..core.metrics import GLOBAL_METRICS

            if GLOBAL_METRICS.registry is not None:
                GLOBAL_METRICS.accumulator_journal_entries.inc(len(journal_entries))
            await self._maybe_drain_due()

    @staticmethod
    def _finished_with_payload(orig, finished_ra):
        """FINISHED, but keeping exactly the columns the oracle replay
        reads (public share + leader input share — the deferred journal's
        replay window); the helper's ciphertext has no replay reader and
        is dropped like any other FINISHED row's.  GC reclaims the rest
        with the job, once its journal row is consumed."""
        return orig.with_state(
            ReportAggregationState.FINISHED,
            public_share=orig.public_share,
            leader_input_share=orig.leader_input_share,
        ).with_last_prep_resp(finished_ra.last_prep_resp)

    def _discard_touched_buckets(self, touched_buckets) -> None:
        """Drop the device deltas of buckets this step committed into
        (deferred mode, after its tx failed).  Journal entries belonging
        to OTHER jobs survive in the datastore and are replayed from
        there; this job's rows redeliver and re-prepare."""
        store = self._executor.accumulator if self._executor is not None else None
        if store is None or not touched_buckets:
            return
        for key in touched_buckets:
            journal = store.discard(key)
            if journal:
                logger.warning(
                    "discarded bucket %r with %d journaled job(s) after a "
                    "failed tx; persisted journal rows will be oracle-"
                    "replayed from the datastore",
                    key,
                    len(journal),
                )

    @staticmethod
    def _batch_ident_for(task, job):
        """ra -> batch identifier, shared by the device- and host-vector
        accumulator commit paths (they must bucket identically)."""
        from ..datastore.query_type import strategy_for

        strategy = strategy_for(task)

        def ident_for(ra):
            if job.partial_batch_identifier is not None:
                return job.partial_batch_identifier.get_encoded()
            return strategy.to_batch_identifier(task, ra.time)

        return ident_for

    async def _collected_idents(self, task, job, idents) -> set:
        """Pre-tx collected check shared by both accumulator commit paths:
        batches already past AGGREGATING must not be accumulated/journaled
        now — the writer tx would fail their reports and every redelivery
        would re-trip the StaleAccumulatorDelta fence."""
        if self.datastore is None or not idents:
            return set()
        from ..datastore import BatchAggregationState

        def check(tx):
            out = set()
            for ident in idents:
                bas = tx.get_batch_aggregations_for_batch(
                    task.task_id, ident, job.aggregation_parameter
                )
                if any(
                    ba.state != BatchAggregationState.AGGREGATING for ba in bas
                ):
                    out.add(ident)
            return out

        return await self.datastore.run_tx_async("accum_collected_check", check)

    async def _commit_resident_shares(
        self, task, vdaf, job, all_ras, states, out_shares, waiting_rids=frozenset()
    ) -> Tuple[
        Optional[Dict[bytes, Tuple[Sequence[int], frozenset]]],
        Optional[Dict[bytes, frozenset]],
        List[tuple],
    ]:
        """Accumulator-store commit path (no-op when the store is off or no
        finished report carries a ResidentRef).

        Per batch bucket: psum the finished rows into the resident
        accumulator (one device launch, no readback).  Drain-at-commit
        mode (default) then drains it to ONE host field vector for the
        writer's sharded merge; deferred mode (drain_interval_s > 0)
        leaves the delta resident and hands back journal entries the
        writer persists in its tx (the cadence drain — or, after a crash,
        the collection-time oracle replay — merges the shares later).
        On AccumulatorUnavailable (launch failure / poisoned bucket /
        injected spill fault) the journaled reports are replayed through
        the bit-exact CPU oracle — host vectors replace the dead refs in
        ``out_shares`` and the poisoned device delta is discarded, so
        accumulation never double-counts or drops.  Leftover refs (reports
        the helper failed) are released so their flush matrices free.

        Returns ``(accumulator_deltas, journal_entries, touched_buckets)``
        — touched_buckets names the deferred buckets this step committed
        into, so a failed tx can discard them (their journal rows never
        committed)."""
        store = self._executor.accumulator if self._executor is not None else None
        if store is None:
            return None, None, []
        from ..executor.accumulator import AccumulatorUnavailable, ResidentRef
        from ..vdaf.canonical import clip_drained_vector

        resident = {
            rid: v for rid, v in out_shares.items() if isinstance(v, ResidentRef)
        }
        # release the never-finished rows' refs regardless of outcome below
        # — but NOT the WAITING rows': a multi-round VDAF's pending rows
        # carry their refs through the persisted transition into the next
        # step (releasing them here would strand every Poplar1 row on the
        # dead-ref oracle path at round 1)
        leftover = []
        for rid, st in states.items():
            if rid in out_shares or rid in waiting_rids:
                continue
            ref = getattr(getattr(st, "prep_state", None), "out_share", None)
            if not isinstance(ref, ResidentRef):  # Poplar1 carries y_flat
                ref = getattr(getattr(st, "prep_state", None), "y_flat", None)
            if isinstance(ref, ResidentRef):
                leftover.append(ref)
        if leftover:
            store.release_refs(leftover)
        if not resident:
            if (
                getattr(vdaf, "REQUIRES_AGG_PARAM", False)
                and getattr(store.config, "deferred", False)
                and out_shares
            ):
                # Agg-param VDAFs (Poplar1): finished out shares are HOST
                # vectors (the sketch y values finish in the ping-pong
                # layer), but the deferred-drain machinery — agg-param-
                # keyed buckets, persisted journal rows, cadence drains,
                # crash replay — applies identically.  Route them through
                # the store's host-vector commit so N jobs at one tree
                # level merge as ONE datastore write with the journal as
                # the exactly-once fence.
                return await self._commit_deferred_host_shares(
                    task, vdaf, job, all_ras, out_shares
                )
            return None, None, []

        ra_by_rid = {ra.report_id.data: ra for ra in all_ras}
        ident_for = self._batch_ident_for(task, job)
        by_ident: Dict[bytes, List[bytes]] = {}
        for rid in resident:
            by_ident.setdefault(ident_for(ra_by_rid[rid]), []).append(rid)

        backend = self._backend_for(task, vdaf)
        shape_key = self._vdaf_shape_key(vdaf)
        agg_param = (
            vdaf.decode_agg_param(job.aggregation_parameter)
            if getattr(vdaf, "REQUIRES_AGG_PARAM", False)
            else None
        )
        field = vdaf.field_for_agg_param(agg_param)
        loop = asyncio.get_running_loop()

        # Pre-tx collected check: reports aimed at an already-collected
        # batch will be FAILED inside the writer tx, so accumulating them
        # now would guarantee a delta/tx mismatch on every redelivery.
        # Route those batches through host vectors instead (the writer
        # pops them harmlessly).  The residual race (collection commits
        # between this check and our tx) still aborts cleanly via
        # StaleAccumulatorDelta -> retryable redelivery.
        collected = await self._collected_idents(task, job, by_ident)

        deferred = getattr(store.config, "deferred", False)
        deltas: Dict[bytes, Tuple[Sequence[int], frozenset]] = {}
        journal_entries: Dict[bytes, frozenset] = {}
        touched: List[tuple] = []
        # Drain-at-commit scopes buckets per STEP ATTEMPT (job id + a
        # fresh nonce): two driver replicas sharing one process (and one
        # store) can deliver the same job concurrently after a lease
        # expiry, and a shared bucket would let both commits land before
        # either drain — a doubled vector whose rid set still matches, so
        # StaleAccumulatorDelta cannot catch it and the surviving lease
        # holder would merge it.  The bucket lives only within this step,
        # so per-attempt uniqueness costs nothing.  Deferred drains
        # accumulate ACROSS jobs by design — there the persisted journal
        # row is the fence (the drain tx only merges if it consumes every
        # contributing row exactly once).
        import secrets as _secrets

        step_nonce = _secrets.token_bytes(8)
        for ident, rids in by_ident.items():
            if deferred:
                bucket_key = (
                    "leader",
                    task.task_id.data,
                    shape_key,
                    ident,
                    job.aggregation_parameter,
                )
            else:
                bucket_key = (
                    "leader",
                    task.task_id.data,
                    shape_key,
                    ident,
                    job.aggregation_parameter,
                    job.aggregation_job_id.data,
                    step_nonce,
                )
            refs = [resident[rid] for rid in rids]

            async def replay(rids, refs, cause, bucket_key=bucket_key):
                """Exactly-once recovery: the device delta (whole or
                partial) is discarded FIRST, then the journaled reports are
                recomputed on the bit-exact CPU oracle.  Deferred entries
                from OTHER jobs have committed journal rows — they are NOT
                replayed here (the datastore replay path owns them)."""
                journal = store.discard(bucket_key)
                store.release_refs(refs)
                replay_rids = set(rids)
                other_jobs = 0
                for job_token, ids in journal:
                    if job_token == job.aggregation_job_id.data:
                        replay_rids |= set(ids)
                    else:
                        other_jobs += 1
                if other_jobs:
                    logger.warning(
                        "discarded bucket %r still journaled %d other "
                        "job(s); their persisted journal rows will be "
                        "oracle-replayed from the datastore",
                        bucket_key,
                        other_jobs,
                    )
                unknown = replay_rids - set(ra_by_rid)
                if unknown:
                    # this job's rows must always be recomputable from the
                    # step's loaded report aggregations; fail loudly and
                    # retryably rather than silently dropping shares
                    raise JobStepError(
                        f"accumulator journal names {len(unknown)} report(s) "
                        f"outside this job; cannot replay: {cause}",
                        retryable=True,
                    )
                if cause is not None:
                    logger.warning(
                        "resident accumulator unavailable for %d report(s); "
                        "replaying through the CPU oracle: %s",
                        len(replay_rids),
                        cause,
                    )
                replayed = await loop.run_in_executor(
                    None,
                    lambda rids=sorted(replay_rids): self._oracle_out_shares(
                        task, vdaf, backend, [ra_by_rid[r] for r in rids],
                        agg_param=agg_param,
                    ),
                )
                out_shares.update(replayed)

            if ident in collected:
                await replay(rids, refs, None)
                continue

            def commit_and_drain(bucket_key=bucket_key, refs=refs, rids=rids):
                store.commit_rows(
                    bucket_key,
                    backend,
                    refs,
                    job_token=job.aggregation_job_id.data,
                    report_ids=rids,
                )
                if deferred:
                    return None  # stays resident; the journal row covers it
                return store.drain(bucket_key, field)

            try:
                drained = await loop.run_in_executor(None, commit_and_drain)
            except JobStepError:
                raise
            except Exception as e:
                # AccumulatorUnavailable, an injected fault, or anything
                # else device-shaped: the same discard-then-replay recovery
                # (a partial commit must never survive to double-count)
                if not isinstance(e, AccumulatorUnavailable):
                    logger.exception("accumulator commit/drain failed")
                await replay(rids, refs, e)
                continue
            if deferred:
                journal_entries[ident] = frozenset(rids)
                touched.append(bucket_key)
                continue
            if drained is None:
                continue
            vector, drained_rids = drained
            # canonical accumulator buffers are bucket-width; clip the
            # provably-zero pad tail back to the task's OUTPUT_LEN
            deltas[ident] = (clip_drained_vector(vdaf, vector), frozenset(drained_rids))
        return deltas or None, journal_entries or None, touched

    async def _commit_deferred_host_shares(
        self, task, vdaf, job, all_ras, out_shares
    ):
        """Deferred accumulation of HOST-vector out shares (agg-param
        VDAFs): per batch bucket, sum this job's finished vectors into the
        store's agg-param-keyed host mirror (commit_host_rows) and hand
        the writer journal entries instead of shares.  The bucket key —
        and the persisted ``accumulator_journal`` row — carry the job's
        encoded aggregation parameter, so two tree levels of one task
        land in DISTINCT buckets and journal rows and can never merge.
        Journaled rows' out_shares are replaced with sentinel refs so the
        writer defers them; a store failure leaves this commit cleanly
        un-applied and the job's vectors merge directly (no deferral, no
        journal row — still exactly-once)."""
        store = self._executor.accumulator
        from ..executor.accumulator import ResidentRef

        ra_by_rid = {ra.report_id.data: ra for ra in all_ras}
        ident_for = self._batch_ident_for(task, job)
        by_ident: Dict[bytes, List[bytes]] = {}
        for rid in out_shares:
            by_ident.setdefault(ident_for(ra_by_rid[rid]), []).append(rid)

        # Pre-tx collected check (same rationale as the ResidentRef path):
        # journaling a report the writer tx will fail guarantees a
        # StaleAccumulatorDelta abort on every redelivery.
        collected = await self._collected_idents(task, job, by_ident)

        shape_key = self._vdaf_shape_key(vdaf)
        field = vdaf.field_for_agg_param(
            vdaf.decode_agg_param(job.aggregation_parameter)
        )
        loop = asyncio.get_running_loop()
        journal_entries: Dict[bytes, frozenset] = {}
        touched: List[tuple] = []
        for ident, rids in by_ident.items():
            if ident in collected:
                continue  # writer fails these in-tx; vectors merge nowhere
            bucket_key = (
                "leader",
                task.task_id.data,
                shape_key,
                ident,
                job.aggregation_parameter,
            )
            vectors = [out_shares[rid] for rid in rids]

            def commit(bucket_key=bucket_key, vectors=vectors, rids=rids):
                store.commit_host_rows(
                    bucket_key,
                    field,
                    vectors,
                    job_token=job.aggregation_job_id.data,
                    report_ids=rids,
                )

            try:
                await loop.run_in_executor(None, commit)
            except Exception as e:
                # commit_host_rows mutates nothing on failure: this job's
                # vectors are still in out_shares and merge directly in
                # the writer tx — exactly-once without the deferral.
                logger.warning(
                    "host-share accumulator commit failed for bucket %r; "
                    "merging this job's %d vector(s) directly: %s",
                    bucket_key,
                    len(rids),
                    e,
                )
                continue
            journal_entries[ident] = frozenset(rids)
            touched.append(bucket_key)
            for i, rid in enumerate(rids):
                # journaled sentinel: the writer must defer these rows to
                # the journal (their vectors now live in the store)
                out_shares[rid] = ResidentRef(-1, i)
        return None, journal_entries or None, touched

    def _oracle_out_shares(self, task, vdaf, backend, ras, agg_param=None):
        """Bit-exact CPU replay of finished reports' out shares (backend
        contract: oracle == device, tests/test_backend.py).  Canonical
        backends replay through the TASK's oracle (oracle_for), never the
        bucket twin's.  Agg-param VDAFs (Poplar1) replay per report at
        the job's OWN parameter — ``prep_init(...).y_flat`` is the value
        vector the FINISHED verdict already vouched for (the sketch
        verified before the ref was minted).  The replay runs inside the
        task's cost scope, so crash-recovery CPU time shows on the task's
        ``path="oracle"`` series like any other oracle work."""
        from ..core import costs
        from ..vdaf.backend import OracleBackend, oracle_backend_for

        rows = []
        for ra in ras:
            rows.append(
                (
                    ra.report_id.data,
                    vdaf.decode_public_share(ra.public_share or b""),
                    vdaf.decode_input_share(0, ra.leader_input_share),
                )
            )
        out = {}
        if getattr(vdaf, "REQUIRES_AGG_PARAM", False):
            def poplar_replay():
                res = {}
                for rid, public, share in rows:  # the report id IS the nonce
                    state, _sh = vdaf.prep_init(
                        task.vdaf_verify_key, 0, agg_param, rid, public, share
                    )
                    res[rid] = list(state.y_flat)
                return res

            return costs.run_in_task_scope(task.task_id.data, poplar_replay)
        oracle = oracle_backend_for(backend, vdaf) or OracleBackend(vdaf)
        replayed = costs.run_in_task_scope(
            task.task_id.data,
            lambda: oracle.prep_init_batch(task.vdaf_verify_key, 0, rows),
        )
        for ra, outcome in zip(ras, replayed):
            if isinstance(outcome, VdafError):  # cannot happen for a report
                raise JobStepError(  # that already prepared successfully
                    f"oracle replay rejected report {ra.report_id}: {outcome}",
                    retryable=True,
                )
            state, _share = outcome
            out[ra.report_id.data] = state.out_share
        return out

    # ------------------------------------------------------------------
    # deferred-drain plumbing (accumulator.drain_interval_s > 0)

    async def run_accumulator_maintenance(self) -> int:
        """The dedicated maintenance pass (binaries background loop,
        ``accumulator.maintenance_interval_s``): drain deferred buckets
        that came due while no driver commit was around to drain them —
        an idle task's resident delta no longer waits for UNRELATED
        traffic to commit — then rebalance resident occupancy (the LRU
        eviction pass, off the hot path).  Returns the number of due
        buckets drained (attempted)."""
        store = self._executor.accumulator if self._executor is not None else None
        if store is None:
            return 0
        drained = await self._maybe_drain_due()
        occupancy = store.rebalance()
        if drained:
            logger.info(
                "accumulator maintenance drained %d due bucket(s); "
                "occupancy: %d bucket(s), %d resident byte(s)",
                drained,
                occupancy.get("buckets", 0),
                occupancy.get("resident_bytes", 0),
            )
        return drained

    async def _maybe_drain_due(self) -> int:
        """Cadence scan: drain every deferred bucket whose oldest delta is
        older than drain_interval_s, merging ONE share-only vector per
        bucket into batch_aggregations and consuming its journal rows.
        Returns the number of due buckets scanned."""
        store = self._executor.accumulator if self._executor is not None else None
        if store is None or not getattr(store.config, "deferred", False):
            return 0
        # the shared store may also hold 7-tuple drain-at-commit keys
        # (helper requests in the same process) and the HELPER's 5-tuple
        # deferred CONTINUE buckets (aggregator.py owns those — it merges
        # into the helper datastore); only this driver's LEADER-role
        # 5-tuple deferred keys are cadence-drainable here
        keys = [
            k
            for k in store.due_buckets(store.config.drain_interval_s)
            if len(k) == 5 and k[0] == "leader"
        ]
        if not keys:
            return 0
        loop = asyncio.get_running_loop()
        for key in keys:
            try:
                await loop.run_in_executor(None, self._drain_due_bucket, key)
            except Exception:
                # the step's own tx already committed — a drain failure
                # (e.g. the drain tx exhausting retries under contention)
                # must not fail the step or strand its lease; whatever was
                # not merged stays journaled for the datastore replay
                logger.exception("deferred cadence drain failed for %r", key)
        return len(keys)

    def _drain_due_bucket(self, key: tuple) -> None:
        store = self._executor.accumulator
        from ..executor.accumulator import AccumulatorError

        task, vdaf, field = self._task_field_for_bucket(key)
        if task is None:
            return
        try:
            out = store.drain_with_journal(key, field)
        except AccumulatorError as e:
            journal = store.discard(key)
            logger.warning(
                "deferred drain failed for bucket %r; %d journal row(s) "
                "stay persisted for the datastore oracle replay: %s",
                key,
                len(journal),
                e,
            )
            return
        if out is not None:
            self._merge_drained(task, field, key, out[0], out[1])

    def _task_field_for_bucket(self, key: tuple):
        """(task, vdaf, field) for a deferred bucket key
        ``(role, task_id, shape_key, batch_identifier, agg_param)``."""
        from ..messages import TaskId

        _role, task_id_b, _shape, _ident, param = key
        task = self.datastore.run_tx(
            "accum_drain_task",
            lambda tx: tx.get_aggregator_task(TaskId(task_id_b)),
        )
        if task is None:
            logger.warning("bucket %r names an unknown task; dropping", key)
            return None, None, None
        vdaf = task.vdaf_instance()
        return task, vdaf, vdaf.field_for_agg_param(vdaf.decode_agg_param(param))

    def _merge_drained(self, task, field, key: tuple, vector, journal) -> None:
        """The deferred-drain transaction: consume every contributing
        job's journal row, then merge the drained vector as a share-only
        batch-aggregation delta.  A missing row means a crash-recovery
        replay already merged that job's shares from the datastore — the
        vector can no longer be applied (it cannot be split per job), so
        the whole drain aborts and the SURVIVING rows stay journaled for
        the same replay path.  Either path merges each row exactly once."""
        from ..messages import AggregationJobId
        from ..vdaf.canonical import clip_drained_vector
        from .aggregation_job_writer import merge_share_delta

        _role, _task_id_b, _shape, ident, param = key
        # canonical accumulator buffers are bucket-width: clip the
        # provably-zero pad tail back to the task's OUTPUT_LEN here, the
        # one chokepoint every journaled-drain merge passes through
        vector = clip_drained_vector(task.vdaf_instance(), vector)

        def tx_fn(tx):
            for job_token, _rids in journal:
                if not tx.delete_accumulator_journal_entry(
                    task.task_id, ident, param, AggregationJobId(job_token)
                ):
                    raise _JournalRowMissing(job_token)
            merge_share_delta(
                tx,
                task,
                field,
                ident,
                param,
                vector,
                shard_count=self.config.batch_aggregation_shard_count,
            )

        try:
            self.datastore.run_tx("accumulator_drain", tx_fn)
        except _JournalRowMissing as e:
            logger.warning(
                "bucket %r journal row %s already consumed (replayed by a "
                "survivor); dropping the drained vector — remaining rows "
                "stay journaled for the datastore replay",
                key,
                e,
            )
            return
        from ..core.metrics import GLOBAL_METRICS

        if GLOBAL_METRICS.registry is not None:
            GLOBAL_METRICS.accumulator_journal_consumed.labels(path="drain").inc(
                len(journal)
            )

    def _spill_sink(self, key: tuple, vector, journal) -> None:
        """shutdown(drain=True) target: spill one committed-but-unspilled
        bucket durably.  Only LEADER deferred buckets (5-tuple keys) with
        persisted journal rows are mergeable here; job-scoped
        drain-at-commit buckets still resident at shutdown belong to
        transactions that never committed — merging them would
        double-count after the lease redelivers — and a co-resident
        HELPER's deferred buckets belong to the helper datastore (its
        journal replay at aggregate-share time re-derives them), so both
        are dropped loudly instead."""
        if len(key) != 5 or key[0] != "leader" or not journal:
            logger.warning(
                "dropping un-journaled resident delta for bucket %r "
                "(%d job(s)); lease redelivery re-derives it",
                key,
                len(journal),
            )
            return
        task, _vdaf, field = self._task_field_for_bucket(key)
        if task is None:
            return
        self._merge_drained(task, field, key, vector, journal)

    async def shutdown(self) -> None:
        """Graceful teardown (SIGTERM path): flush the executor's pending
        mega-batches, spill committed-but-unspilled deferred deltas to the
        datastore through the journal transaction, then stop intake.  The
        crash path is ``executor.shutdown(drain=False)`` — everything it
        drops is re-derived by lease redelivery or the journal replay."""
        if self._executor is not None:
            try:
                await self._executor.drain()
            except Exception:
                logger.exception("executor drain failed during shutdown")
            ex = self._executor
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: ex.shutdown(drain=True)
            )
        await self.close()

    # ------------------------------------------------------------------
    async def abandon_aggregation_job(self, lease: Lease) -> None:
        """reference: :977-1026 (abandon + best-effort helper DELETE)"""
        acq = lease.leased

        def tx_fn(tx):
            task = tx.get_aggregator_task(acq.task_id)
            job = tx.get_aggregation_job(acq.task_id, acq.aggregation_job_id)
            if job is not None and job.state == AggregationJobState.IN_PROGRESS:
                tx.update_aggregation_job(job.with_state(AggregationJobState.ABANDONED))
            tx.release_aggregation_job(lease)
            return task

        task = await self.datastore.run_tx_async("abandon_agg_job", tx_fn)
        if task is not None:
            try:
                await self._send_to_helper(
                    task,
                    "DELETE",
                    f"aggregation_jobs/{acq.aggregation_job_id}",
                    None,
                    None,
                    expect_body=False,
                )
            except Exception:
                logger.warning("best-effort helper DELETE failed", exc_info=True)

    # ------------------------------------------------------------------
    async def _send_to_helper(
        self,
        task: AggregatorTask,
        method: str,
        resource: str,
        body: Optional[bytes],
        media_type: Optional[str],
        expect_body: bool = True,
        lease=None,
    ) -> Optional[AggregationJobResp]:
        """HTTPS to the peer aggregator with retry/backoff
        (reference: aggregator.rs:3200 send_request_to_helper).  The
        exchange runs under a lease-derived deadline (a blackholed peer
        must release the lease, never pin it past reap) and behind the
        peer-health gate; a transport-level failure against a suspect
        peer surfaces as partition pressure (peer_unhealthy), which
        releases without consuming the attempt budget."""
        from ..core import peer_health
        from ..core.retries import is_transport_error

        url = (
            task.peer_aggregator_endpoint.rstrip("/")
            + f"/tasks/{task.task_id}/{resource}"
        )
        tracker = peer_health.tracker()
        # re-gate: a partition detected MID-step (between prepare and
        # send) must not burn the attempt either
        self._gate_peer(task)
        headers = {}
        if media_type:
            headers["Content-Type"] = media_type
        if task.aggregator_auth_token is not None:
            name, value = task.aggregator_auth_token.request_authentication()
            headers[name] = value
        # Cross-process trace propagation: the helper binds this request's
        # trace id so both aggregators' spans/logs join one timeline.
        from ..core.trace import inject_traceparent

        inject_traceparent(headers)
        try:
            status, resp_body, _ = await retry_http_request(
                self._get_session(),
                method,
                url,
                data=body,
                headers=headers,
                policy=self.config.http_retry,
                deadline=helper_request_deadline(lease, self.datastore),
            )
        except Exception as e:
            raise JobStepError(
                f"helper request failed: {e}",
                retryable=True,
                # only a transport failure against a peer the tracker has
                # ALREADY suspected is partition pressure — a one-off
                # blip still consumes budget (a broken-but-reachable path
                # must not ping-pong forever)
                peer_unhealthy=is_transport_error(e)
                and tracker.is_suspect(url),
            )
        if status >= 400:
            # 4xx = fatal (bad request will not heal); 5xx = retryable
            # (reference: aggregation_job_driver.rs:1030 error classification)
            raise JobStepError(
                f"helper returned {status}: {resp_body[:200]!r}",
                retryable=status >= 500,
            )
        if not expect_body:
            return None
        return AggregationJobResp.get_decoded(resp_body)
